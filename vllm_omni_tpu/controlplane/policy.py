"""Control-plane sensor math: role pressure + hysteresis bands.

Pure host-side arithmetic over replica/engine state — no jax, no
locks, no side effects — so the whole decision layer is fake-clock
unit-testable (the same stance as the PR 8 watchdog's ``check_once``).

The pressure model (docs/control_plane.md): a role's pressure is its
queue depth per in-rotation replica plus a weighted phase-saturation
term.  Queue depth is the leading indicator (work already waiting);
``phase_saturation_ratio`` is the coincident one (how close the last
schedule ran to its token-budget ceiling) — a fleet can be saturated
with shallow queues when arrivals exactly match capacity, and queued
with low saturation right after a burst.  Summing both (saturation
scaled into queue-depth units by ``saturation_gain``) makes either
signal sufficient to move the controller.

"TPLA" (PAPERS.md) frames why the prefill:decode pressure RATIO is the
re-roling signal: the right tier split is workload-dependent — long
prompts with short outputs want prefill capacity, chatty decode-heavy
sessions want the opposite — so the ratio must float at runtime and
any static split is wrong for part of a diurnal trace.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class RoleSensors:
    """One role's sensor reading for a tick (JSON-ready)."""

    role: str
    replicas: int          # non-dead replicas in the pool
    in_rotation: int       # healthy, undrained (taking new dispatch)
    queue_depth: int       # waiting+running across the pool
    saturation: float      # mean phase saturation across live engines
    pressure: float

    def as_dict(self) -> dict:
        return {
            "role": self.role,
            "replicas": self.replicas,
            "in_rotation": self.in_rotation,
            "queue_depth": self.queue_depth,
            "saturation": round(self.saturation, 4),
            "pressure": round(self.pressure, 4),
        }


def _replica_saturation(replica, phase: str) -> float:
    """One replica's last-schedule saturation for ``phase``
    (getattr-defensive: fake engines and generation stages report 0)."""
    metrics = getattr(replica.engine, "step_metrics", None)
    sat = getattr(metrics, "saturation", None) or {}
    try:
        return float(sat.get(phase, 0.0))
    except (TypeError, ValueError):
        return 0.0


def role_sensors(pool, role: str, phase: str,
                 saturation_gain: float) -> RoleSensors:
    """Fold a replica pool into one ``RoleSensors`` reading.  Dead
    replicas contribute nothing (their queues are being failed over);
    drained/ejected ones still contribute queue depth — their in-flight
    work is real load — but the per-replica normalization divides by
    the IN-ROTATION count, because that is the capacity new work can
    actually land on."""
    alive = [r for r in pool if not r.dead]
    in_rotation = [r for r in alive if r.in_rotation]
    depth = sum(r.queue_depth for r in alive)
    sats = [_replica_saturation(r, phase) for r in in_rotation]
    sat = sum(sats) / len(sats) if sats else 0.0
    pressure = (depth / max(len(in_rotation), 1)
                + saturation_gain * sat)
    if not in_rotation and depth > 0:
        # a tier with queued work and nothing to serve it is the
        # highest-pressure state there is — never report it as calm
        pressure = max(pressure, depth * 2.0)
    return RoleSensors(role=role, replicas=len(alive),
                       in_rotation=len(in_rotation),
                       queue_depth=depth, saturation=sat,
                       pressure=pressure)


def pressure_ratio(prefill: RoleSensors, decode: RoleSensors,
                   eps: float = 0.25) -> float:
    """prefill:decode pressure ratio, epsilon-smoothed so an idle tier
    doesn't blow the ratio to infinity (eps acts as a quarter-request
    of standing pressure on both sides)."""
    return (prefill.pressure + eps) / (decode.pressure + eps)


class Hysteresis:
    """Consecutive-tick debouncer: ``update`` returns the signal only
    after it has held for ``ticks`` consecutive updates.  A transient
    spike (one hot schedule, one burst arrival) never moves the
    controller; a sustained departure does.  Any change of direction
    resets the count."""

    def __init__(self, ticks: int):
        self.ticks = max(int(ticks), 1)
        self._signal = None
        self._count = 0

    def update(self, signal):
        """``signal`` is any hashable direction (e.g. "up"/"down") or
        None for in-band; returns the debounced signal or None."""
        if signal is None or signal != self._signal:
            self._signal = signal
            self._count = 1 if signal is not None else 0
            return None
        self._count += 1
        return signal if self._count >= self.ticks else None

    def reset(self) -> None:
        self._signal = None
        self._count = 0

    @property
    def pending(self) -> dict:
        return {"signal": self._signal, "count": self._count,
                "needed": self.ticks}
