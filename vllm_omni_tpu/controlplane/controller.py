"""omnictl: the SLO-driven control plane closing the serving loop.

Every sensor and actuator already existed — per-tenant SLO/goodput
accounting and serving curves (PR 7), honest health and
``phase_saturation_ratio`` (PR 8), engine roles, drain/quiesce and the
degradation ladder (PR 9) — but nothing connected them, so a
disaggregated fleet stayed pinned to whatever prefill:decode split and
replica count it booted with.  ``ControlPlane`` is the feedback
controller (docs/control_plane.md):

- **live re-roling** — when the prefill:decode pressure ratio
  (policy.py) departs its band with hysteresis, the least-loaded
  replica of the over-provisioned tier is drained, and once quiesced
  its role flips (``DisaggRouter.set_role`` -> engine KV-transfer
  re-arming) and it re-admits into the starved tier.  In-flight
  streams ride out the drain untouched — bit-identical to the
  colocated oracle (tests/controlplane/test_e2e.py pins it).
- **fleet autoscaling** — sustained pressure above/below thresholds
  scales the in-proc fleet up/down per role through a replica
  factory; a fresh replica enters DRAINED for ``warmup_ticks`` (the
  cold-start model: weight load + XLA warmup means new capacity is
  not instant), and scale-down only ever happens via drain.
- **overload-adaptive admission** rides in the engines themselves (the
  WFQ scheduler, core/scheduler.py) — the controller's job there is
  observability: it polls ``refresh_gauges`` so an idle fleet's
  /metrics stay honest, and records fleet SLO attainment per tick.

Threading contract (omnirace-audited): the router is SINGLE-THREADED
by design, so the controller NEVER touches router/replica mutation
paths from its own thread.  ``tick()`` (controller thread, fake-clock
testable exactly like the PR 8 watchdog) only READS replica/engine
state and appends intents to a locked pending queue; ``actuate()``
(called by the router's stepping thread — DisaggService's engine loop)
drains that queue and applies the mutations.  ``_lock`` guards the
pending queue, the apply-outcome queue, the action ring, and the
applied-action counters — and nothing else; the state machine fields
are controller-thread-private.
The lock is declared in the omnilint LOCK_GUARDS manifest and traced
under OMNI_TPU_LOCK_CHECK=1.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from vllm_omni_tpu.analysis.runtime import traced
from vllm_omni_tpu.controlplane.policy import (
    Hysteresis,
    RoleSensors,
    pressure_ratio,
    role_sensors,
)
from vllm_omni_tpu.disagg.roles import ROLE_DECODE, ROLE_PREFILL
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.resilience.metrics import resilience_metrics
from vllm_omni_tpu.tracing import journey, new_trace_context

logger = init_logger(__name__)

#: action kinds on the ring / controlplane_actions_total{action}
ACTION_DRAIN = "drain"
ACTION_UNDRAIN = "undrain"
ACTION_REROLE = "rerole"
ACTION_SCALE_UP = "scale_up"
ACTION_REMOVE = "remove_replica"
ACTION_ABORT = "abort"


@dataclass
class ControlPlaneConfig:
    """Knobs of the feedback loop.  Tick counts (hysteresis, cooldown,
    warmup) are POLL ticks, not seconds — the fake-clock tests drive
    ``tick()`` directly and real deployments scale them with
    ``poll_interval_s``."""

    poll_interval_s: float = 1.0
    # --- re-roling: the prefill:decode pressure ratio's dead band.
    # Outside [band_low, band_high] for hysteresis_ticks consecutive
    # ticks -> flip one replica toward the starved tier
    rerole_enabled: bool = True
    band_low: float = 0.5
    band_high: float = 2.0
    hysteresis_ticks: int = 3
    # ticks after ANY completed/aborted operation before the next may
    # begin — the anti-flap floor (a flip's effect needs time to show
    # in the sensors before the controller may judge it insufficient)
    cooldown_ticks: int = 5
    min_replicas_per_role: int = 1
    # saturation -> queue-depth-units conversion (policy.py): one
    # fully saturated phase reads like this many queued requests
    saturation_gain: float = 4.0
    # --- autoscaling (off unless a replica factory is installed AND
    # max_replicas is set)
    autoscale_enabled: bool = False
    max_replicas: Optional[int] = None
    scale_up_pressure: float = 8.0
    scale_down_pressure: float = 0.5
    # cold-start model: a scaled-up replica serves nothing for this
    # many ticks (weight load + warmup compile stand-in); it counts
    # toward the DECISION capacity immediately so the controller does
    # not stack scale-ups while one is still warming
    warmup_ticks: int = 3
    # never scale down while fleet SLO attainment sits below this
    # floor (None/no-data = the gate passes)
    slo_scale_down_floor: float = 0.9
    # firing overload alerts (metrics/alerts.py, rules marked
    # overload=True) are an ADVISORY early-shed signal: each adds this
    # many queue-depth-units of pressure to BOTH roles, so scale-up
    # hysteresis integrates sooner and scale-down is held off while
    # the detection layer says the fleet is drowning.  Advisory only —
    # the alert can accelerate the controller, never force an action
    # the sensors themselves would not eventually take
    alert_pressure_bonus: float = 2.0
    # donor selection subtracts cache heat (PR 19 omniaffinity): a
    # replica owning hot radix digests is the fleet's cache, and
    # draining it for a re-role/scale-down evicts every prefix the
    # affinity router converged onto it.  Each HBM-resident digest
    # token adds this many queue-depth units to the replica's donor
    # score; 0 restores the pure least-loaded policy (router._pick).
    donor_cache_penalty: float = 0.02
    # --- structured-action ring (/debug/controlplane)
    ring_capacity: int = 256


@dataclass
class _Op:
    """The one drain-based operation in flight (re-role or scale-down).
    Controller-thread-private.  Re-role stages: "draining" ->
    "flipping" -> "readmitting"; scale-down: "draining" -> "removing".
    A flip the router refuses (the quiesce observation can race the
    scheduler's admission window — popped from waiting, not yet in
    running) RETRIES from "draining" instead of aborting: actuation
    revalidates, the decision layer just re-observes."""

    kind: str                  # "rerole" | "scale_down"
    replica_id: str
    from_role: str
    to_role: Optional[str]     # rerole target; None for scale_down
    stage: str = "draining"
    started_tick: int = 0
    retries: int = 0
    # wall-clock start for the journey span: the whole drain -> quiesce
    # -> flip/remove -> re-admit operation renders as ONE interval on
    # the acted-on replica's trace track (tracing/journey.py)
    started_wall: float = 0.0


@dataclass
class _Action:
    """One intent crossing from the controller thread to the router
    thread."""

    kind: str
    args: dict = field(default_factory=dict)
    seq: int = 0


class ControlPlane:
    """The supervised controller thread + its router-thread actuator.

    ``tick()`` is the whole decision state machine (the thread just
    calls it on an interval) and ``actuate()`` is the whole actuation
    path (the router's stepping thread calls it between router steps)
    — tests drive both synchronously with a fake clock and scripted
    replicas, no threads required.
    """

    def __init__(self, router,
                 config: Optional[ControlPlaneConfig] = None,
                 *,
                 replica_factory: Optional[Callable] = None,
                 alert_engine=None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self.router = router
        self.config = config or ControlPlaneConfig()
        #: optional metrics/alerts.py AlertEngine whose firing
        #: overload alerts feed tick() as advisory pressure
        self.alert_engine = alert_engine
        #: builds a fresh EngineReplica for scale-up:
        #: ``factory(role: str, index: int) -> EngineReplica``
        self.replica_factory = replica_factory
        self._clock = clock
        self._sleep = sleep
        self._lock = traced(threading.Lock(), "ControlPlane._lock")
        # cross-thread queues (guarded by _lock): intents out,
        # apply outcomes back, and the structured-action ring
        self._pending: deque[_Action] = deque()
        self._done: deque[dict] = deque()
        self._ring: deque[dict] = deque(
            maxlen=max(int(self.config.ring_capacity), 8))
        self._seq = 0
        # controller-thread-private state machine
        self._ticks = 0
        self._op: Optional[_Op] = None
        self._scale_up_pending: Optional[str] = None   # role
        self._warming: dict[str, int] = {}  # replica_id -> ready tick
        self._cooldown_until = 0
        self._rerole_hyst = Hysteresis(self.config.hysteresis_ticks)
        self._scale_hyst = {
            ROLE_PREFILL: Hysteresis(self.config.hysteresis_ticks),
            ROLE_DECODE: Hysteresis(self.config.hysteresis_ticks),
        }
        self._replica_counter = len(router.replicas)
        self._last_sensors: dict = {}
        # journey tracing: control-plane operations are fleet-scoped,
        # not request-scoped — they ride one long-lived synthetic
        # context so a fleet Perfetto capture shows drain/flip/re-admit
        # intervals on the acted-on replica's track next to the very
        # requests they displaced.  Ops are rare (cooldown-gated), so
        # the bounded recorder ring absorbs them on untraced deployments
        self._trace_ctx = new_trace_context("controlplane")
        # lifetime ledgers (mirrored into the resilience registry)
        self.reroles = 0
        self.actions: dict[str, int] = {}
        self._thread: Optional[threading.Thread] = None
        self._closed = False

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "ControlPlane":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="controlplane")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._closed = True

    def _loop(self) -> None:
        while not self._closed:
            self._sleep(self.config.poll_interval_s)
            if self._closed:
                return
            try:
                self.tick()
            except Exception:  # the controller must never kill serving
                logger.exception("controlplane tick failed")

    # ------------------------------------------------------------- the tick
    def tick(self) -> dict:
        """One control iteration: read sensors, advance the operation
        state machine, emit intents.  Returns the tick's sensor
        snapshot (tests assert on it)."""
        self._ticks += 1
        router = self.router
        # keep the tier gauges honest even when nothing dispatches
        # (the satellite fix this controller is the second caller of)
        try:
            router.refresh_gauges()
        except Exception:
            logger.exception("refresh_gauges failed")
        sensors = self._read_sensors()
        self._drain_done()
        self._advance_warming()
        if self._op is not None:
            self._advance_op()
        elif self._ticks >= self._cooldown_until:
            self._maybe_rerole(sensors)
            self._maybe_scale(sensors)
        else:
            # decisions are frozen through the cooldown, but the
            # debouncers keep integrating so a genuinely sustained
            # departure acts the moment the cooldown lifts
            self._update_hysteresis(sensors)
        return sensors

    def _read_sensors(self) -> dict:
        import dataclasses

        cfg = self.config
        pre = role_sensors(self.router.prefills, ROLE_PREFILL,
                           "prefill", cfg.saturation_gain)
        dec = role_sensors(self.router.decodes, ROLE_DECODE,
                           "decode", cfg.saturation_gain)
        # advisory early-shed signal: firing overload alerts bias the
        # pressure model symmetrically — scale decisions accelerate,
        # and the symmetric bonus pulls the rerole RATIO toward the
        # dead band (an overload says "too little fleet", not "wrong
        # prefill:decode split"; flipping roles mid-overload just
        # moves the starvation)
        overload_alerts: list[str] = []
        if self.alert_engine is not None:
            try:
                overload_alerts = list(
                    self.alert_engine.firing_overload())
            except Exception:
                logger.exception("alert advisory read failed")
        if overload_alerts:
            bonus = cfg.alert_pressure_bonus * len(overload_alerts)
            pre = dataclasses.replace(pre, pressure=pre.pressure + bonus)
            dec = dataclasses.replace(dec, pressure=dec.pressure + bonus)
        ratio = pressure_ratio(pre, dec)
        attainment = self._fleet_attainment()
        resilience_metrics.set_gauge("controlplane_replicas",
                                     pre.replicas, role=ROLE_PREFILL)
        resilience_metrics.set_gauge("controlplane_replicas",
                                     dec.replicas, role=ROLE_DECODE)
        self._last_sensors = {
            "tick": self._ticks,
            "prefill": pre.as_dict(),
            "decode": dec.as_dict(),
            "pressure_ratio": round(ratio, 4),
            "slo_attainment": attainment,
            "overload_alerts": overload_alerts,
            "_pre": pre, "_dec": dec,  # objects for the decision legs
        }
        return self._last_sensors

    def _fleet_attainment(self) -> Optional[float]:
        """met/finished across every live engine's SLO ledger; None
        before any judged completion (no data must gate nothing)."""
        finished = met = 0
        for r in self.router.replicas:
            if r.dead:
                continue
            metrics = getattr(r.engine, "step_metrics", None)
            for st in (getattr(metrics, "tenants", None) or {}).values():
                finished += getattr(st, "finished", 0)
                met += getattr(st, "met", 0)
        if finished <= 0:
            return None
        return round(met / finished, 4)

    # --------------------------------------------------- operation advance
    def _advance_op(self) -> None:
        op = self._op
        try:
            r = self.router._replica(op.replica_id)
        except KeyError:
            # removed (scale_down completed on the router thread)
            if op.kind == "scale_down":
                self._finish_op("removed")
            else:
                self._abort_op("replica vanished mid-operation")
            return
        if r.dead:
            self._abort_op(f"replica {op.replica_id} died "
                           f"mid-{op.kind}")
            return
        if op.stage == "draining":
            if r.quiesced:
                if op.kind == "rerole":
                    op.stage = "flipping"
                    self._emit(ACTION_REROLE,
                               replica_id=op.replica_id,
                               role=op.to_role)
                else:
                    op.stage = "removing"
                    self._emit(ACTION_REMOVE,
                               replica_id=op.replica_id)
            return
        if op.stage == "flipping":
            if r.role == op.to_role:
                # the flip landed: count it, then re-admit (undrain is
                # a SEPARATE stage so a refused flip never leaves an
                # undrained half-flipped replica behind)
                self.reroles += 1
                resilience_metrics.inc("controlplane_reroles_total",
                                       from_role=op.from_role,
                                       to_role=op.to_role)
                op.stage = "readmitting"
                self._emit(ACTION_UNDRAIN, replica_id=op.replica_id)
            return
        if op.stage == "readmitting":
            if not r.drained:
                self._finish_op("flipped and re-admitted")
            return
        # "removing": completion is observed as the KeyError above

    def _finish_op(self, outcome: str) -> None:
        op = self._op
        logger.info("controlplane: %s of %s %s", op.kind,
                    op.replica_id, outcome)
        if op.started_wall:
            # the whole operation as one interval on the acted-on
            # replica's track (abort paths land here too — the outcome
            # rides the args, so a refused flip is visibly different
            # from a completed one)
            journey.record_journey(
                self._trace_ctx, journey.CP_PREFIX + op.kind,
                op.started_wall, max(time.time() - op.started_wall, 0.0),
                replica_id=op.replica_id,
                role=op.to_role or op.from_role, cat="controlplane",
                args={"from_role": op.from_role, "to_role": op.to_role,
                      "outcome": outcome})
        self._op = None
        self._cooldown_until = self._ticks + self.config.cooldown_ticks
        self._rerole_hyst.reset()
        for h in self._scale_hyst.values():
            h.reset()

    def _abort_op(self, reason: str) -> None:
        op = self._op
        logger.warning("controlplane: aborting %s of %s: %s",
                       op.kind, op.replica_id, reason)
        self._record({"action": ACTION_ABORT, "kind": op.kind,
                      "replica_id": op.replica_id,
                      "reason": reason, "ok": False})
        # a LIVE donor stranded drained by the abort would silently
        # leak capacity forever (nothing else ever undrains it):
        # re-admit it in whatever role it currently holds
        try:
            r = self.router._replica(op.replica_id)
            if not r.dead and r.drained:
                self._emit(ACTION_UNDRAIN, replica_id=op.replica_id)
        except KeyError:
            pass
        self._finish_op("aborted")

    def _advance_warming(self) -> None:
        for rid, ready in list(self._warming.items()):
            try:
                r = self.router._replica(rid)
            except KeyError:
                self._warming.pop(rid, None)
                continue
            if r.dead:
                self._warming.pop(rid, None)
                continue
            if self._ticks >= ready:
                self._warming.pop(rid, None)
                self._emit(ACTION_UNDRAIN, replica_id=rid)
                # fresh capacity needs ticks to absorb queued work
                # before its effect shows in the sensors: freezing
                # decisions through that lag is the anti-flap floor
                self._cooldown_until = max(
                    self._cooldown_until,
                    self._ticks + self.config.cooldown_ticks)
                self._scale_hyst[r.role].reset()

    # -------------------------------------------------------- decisions
    def _rerole_signal(self, ratio: float) -> Optional[str]:
        """Band departure direction, or None in-band.  The ONE
        definition both the cooldown integration and the live decision
        read — a divergence would make the debouncers count different
        signals in the two modes."""
        if ratio > self.config.band_high:
            return "to_prefill"
        if ratio < self.config.band_low:
            return "to_decode"
        return None

    def _scale_signal(self, s: RoleSensors) -> Optional[str]:
        if s.pressure > self.config.scale_up_pressure:
            return "up"
        if s.pressure < self.config.scale_down_pressure:
            return "down"
        return None

    def _update_hysteresis(self, sensors: dict) -> None:
        self._rerole_hyst.update(
            self._rerole_signal(sensors["pressure_ratio"]))
        for role, s in ((ROLE_PREFILL, sensors["_pre"]),
                        (ROLE_DECODE, sensors["_dec"])):
            self._scale_hyst[role].update(self._scale_signal(s))

    def _maybe_rerole(self, sensors: dict) -> None:
        cfg = self.config
        if not cfg.rerole_enabled:
            return
        pre: RoleSensors = sensors["_pre"]
        dec: RoleSensors = sensors["_dec"]
        ratio = sensors["pressure_ratio"]
        fired = self._rerole_hyst.update(self._rerole_signal(ratio))
        if fired is None or self._op is not None:
            return
        donor_pool, donor_sensors, to_role = (
            (self.router.decodes, dec, ROLE_PREFILL)
            if fired == "to_prefill"
            else (self.router.prefills, pre, ROLE_DECODE))
        if donor_sensors.in_rotation <= cfg.min_replicas_per_role:
            # the donor tier is at its floor: re-roling would just
            # swap which tier starves.  (Autoscaling, if enabled, is
            # the lever that can still act.)
            return
        donor = self._pick_donor(donor_pool)
        if donor is None:
            return
        self._op = _Op(kind="rerole", replica_id=donor.replica_id,
                       from_role=donor.role, to_role=to_role,
                       started_tick=self._ticks,
                       started_wall=time.time())
        self._emit(ACTION_DRAIN, replica_id=donor.replica_id,
                   reason=f"rerole {donor.role}->{to_role} "
                          f"(pressure_ratio={ratio:.2f})")

    def _maybe_scale(self, sensors: dict) -> None:
        cfg = self.config
        if not (cfg.autoscale_enabled and cfg.max_replicas):
            # still keep the debouncers warm for the rerole leg's reset
            for role in self._scale_hyst:
                self._scale_hyst[role].update(None)
            return
        total = sum(1 for r in self.router.replicas if not r.dead)
        for role, s in ((ROLE_PREFILL, sensors["_pre"]),
                        (ROLE_DECODE, sensors["_dec"])):
            fired = self._scale_hyst[role].update(self._scale_signal(s))
            if fired is None or self._op is not None \
                    or self._scale_up_pending is not None:
                continue
            if fired == "up":
                if self.replica_factory is None:
                    continue  # nothing can build capacity
                if total >= cfg.max_replicas or self._warming:
                    # capacity already building (the cold-start model:
                    # a warming replica IS the response to this
                    # pressure — stacking another is the flap)
                    continue
                self._scale_up_pending = role
                self._emit(ACTION_SCALE_UP, role=role,
                           index=self._replica_counter)
                self._replica_counter += 1
                self._scale_hyst[role].reset()
            else:
                pool = (self.router.prefills if role == ROLE_PREFILL
                        else self.router.decodes)
                in_rot = sum(1 for r in pool if r.in_rotation)
                att = sensors.get("slo_attainment")
                if in_rot <= cfg.min_replicas_per_role:
                    continue
                if (att is not None
                        and att < cfg.slo_scale_down_floor):
                    # the fleet is missing SLOs: shrinking it now
                    # would be pro-cyclical
                    continue
                donor = self._pick_donor(pool)
                if donor is None:
                    continue
                self._op = _Op(kind="scale_down",
                               replica_id=donor.replica_id,
                               from_role=role, to_role=None,
                               started_tick=self._ticks,
                               started_wall=time.time())
                self._emit(ACTION_DRAIN, replica_id=donor.replica_id,
                           reason=f"scale_down {role} "
                                  f"(pressure={s.pressure:.2f})")

    def _pick_donor(self, pool):
        """Least-loaded in-rotation replica, penalized by cache heat —
        the flip/removal that strands the least in-flight work AND the
        least affinity-converged cache behind a drain.  With the
        penalty at 0 this delegates to the router's own dispatch
        policy (``_pick``) so donor choice can never silently diverge
        from where new work lands; with it on, a replica whose radix
        digest advertises hot HBM-resident prefixes scores worse as a
        donor (queue_depth + penalty * hot_tokens), so the controller
        stops evicting the fleet's cache when a colder donor exists."""
        penalty = float(self.config.donor_cache_penalty)
        if penalty <= 0:
            return self.router._pick(pool)
        candidates = [r for r in pool if r.in_rotation]
        if not candidates:
            return None
        heat = self.router.cache.replica_heat()
        return min(candidates,
                   key=lambda r: (r.queue_depth + penalty
                                  * heat.get(r.replica_id, 0)))

    # ------------------------------------------------------- intent queue
    def _emit(self, kind: str, **args) -> None:
        with self._lock:
            self._seq += 1
            self._pending.append(_Action(kind=kind, args=args,
                                         seq=self._seq))

    def _record(self, doc: dict) -> None:
        doc = dict(doc)
        doc.setdefault("tick", self._ticks)
        doc["t"] = round(self._clock(), 3)
        with self._lock:
            self._seq += 1
            doc.setdefault("seq", self._seq)
            self._ring.append(doc)

    def _drain_done(self) -> None:
        with self._lock:
            done, self._done = self._done, deque()
        for d in done:
            if d.get("action") == ACTION_SCALE_UP:
                if d.get("ok"):
                    self._warming[d["replica_id"]] = (
                        self._ticks + self.config.warmup_ticks)
                self._scale_up_pending = None
                if d.get("ok"):
                    self._cooldown_until = (
                        self._ticks + self.config.cooldown_ticks)
            elif not d.get("ok") and self._op is not None \
                    and d.get("replica_id") == self._op.replica_id:
                op = self._op
                retryable = (
                    (d.get("action") == ACTION_REROLE
                     and op.stage == "flipping")
                    or (d.get("action") == ACTION_REMOVE
                        and op.stage == "removing"))
                if retryable and op.retries < 4:
                    # the quiesce observation raced the scheduler's
                    # admission window and the router refused the
                    # mutation: re-observe and retry (bounded)
                    op.retries += 1
                    op.stage = "draining"
                else:
                    self._abort_op(
                        f"actuation {d.get('action')} failed: "
                        f"{d.get('error')}")

    # ---------------------------------------------------------- actuation
    def actuate(self, router=None) -> int:
        """Apply pending intents — called on the ROUTER THREAD (the
        only thread allowed to mutate router/replica state).  Returns
        the number of actions applied.  Every outcome lands on the
        structured ring and, for the ones the state machine waits on,
        in the done-queue the next ``tick()`` drains."""
        router = router or self.router
        with self._lock:
            pending, self._pending = self._pending, deque()
        applied = 0
        for act in pending:
            outcome = {"action": act.kind, "seq": act.seq,
                       "ok": True, **{k: v for k, v in act.args.items()
                                      if k != "reason"}}
            if act.args.get("reason"):
                outcome["reason"] = act.args["reason"]
            t_a0, w_a0 = time.perf_counter(), time.time()
            try:
                if act.kind == ACTION_DRAIN:
                    # omnilint: disable=OL12 - the escape witness needs
                    # the handler's own error-formatting to raise; real
                    # failures land ok=False on the done-queue and
                    # tick's _drain_done aborts the op, which re-admits
                    # the drained donor (_abort_op)
                    router.drain(act.args["replica_id"])
                elif act.kind == ACTION_UNDRAIN:
                    router.undrain(act.args["replica_id"])
                elif act.kind == ACTION_REROLE:
                    router.set_role(act.args["replica_id"],
                                    act.args["role"])
                elif act.kind == ACTION_SCALE_UP:
                    replica = self.replica_factory(
                        act.args["role"], act.args["index"])
                    replica.drained = True  # warms before admission
                    router.add_replica(replica)
                    outcome["replica_id"] = replica.replica_id
                elif act.kind == ACTION_REMOVE:
                    router.remove_replica(act.args["replica_id"])
                else:
                    raise ValueError(f"unknown action {act.kind!r}")
                applied += 1
                with self._lock:
                    self.actions[act.kind] = \
                        self.actions.get(act.kind, 0) + 1
                resilience_metrics.inc("controlplane_actions_total",
                                       action=act.kind)
            except Exception as e:
                outcome["ok"] = False
                outcome["error"] = f"{type(e).__name__}: {e}"
                logger.warning("controlplane action %s failed: %s",
                               act.kind, outcome["error"])
            # one journey span per applied actuation (drain / undrain /
            # flip / scale) on the acted-on replica's track — the
            # fine-grained marks inside the whole-operation interval
            # recorded at _finish_op
            journey.record_journey(
                self._trace_ctx, journey.CP_PREFIX + act.kind, w_a0,
                time.perf_counter() - t_a0,
                replica_id=str(outcome.get("replica_id")
                               or act.args.get("role") or "fleet"),
                role=str(act.args.get("role") or ""),
                cat="controlplane",
                args={"ok": outcome["ok"], "seq": act.seq})
            self._record(outcome)
            if act.kind in (ACTION_SCALE_UP,) or not outcome["ok"]:
                with self._lock:
                    self._done.append(outcome)
        return applied

    # ------------------------------------------------------ introspection
    def debug_snapshot(self) -> dict:
        """/debug/controlplane: sensors, the in-flight operation,
        warming replicas, cooldown state, and the action-ring tail.
        Read-only host state."""
        with self._lock:
            ring = list(self._ring)
            pending = len(self._pending)
            actions = dict(self.actions)
        sensors = {k: v for k, v in self._last_sensors.items()
                   if not k.startswith("_")}
        op = self._op
        return {
            "enabled": True,
            "ticks": self._ticks,
            "sensors": sensors,
            "operation": (None if op is None else {
                "kind": op.kind, "replica_id": op.replica_id,
                "from_role": op.from_role, "to_role": op.to_role,
                "stage": op.stage, "started_tick": op.started_tick,
            }),
            "warming": dict(self._warming),
            "cooldown_remaining_ticks": max(
                self._cooldown_until - self._ticks, 0),
            "pending_actions": pending,
            "counters": {"reroles": self.reroles,
                         "actions": actions},
            "config": {
                "band": [self.config.band_low, self.config.band_high],
                "hysteresis_ticks": self.config.hysteresis_ticks,
                "cooldown_ticks": self.config.cooldown_ticks,
                "autoscale": self.config.autoscale_enabled,
                "max_replicas": self.config.max_replicas,
            },
            "ring": ring[-64:],
        }


def make_inproc_replica_factory(params, model_cfg, base_config,
                                eos_token_id=None) -> Callable:
    """Replica factory for in-proc autoscaling: builds an
    ``LLMEngine`` of the requested role from the same (params, config)
    family ``build_inproc_router`` uses, so scaled-up replicas are
    indistinguishable from boot-time ones."""
    import dataclasses

    from vllm_omni_tpu.disagg.router import EngineReplica

    def factory(role: str, index: int):
        from vllm_omni_tpu.engine import LLMEngine

        cfg = dataclasses.replace(base_config, engine_role=role)
        eng = LLMEngine(params, model_cfg, cfg,
                        eos_token_id=eos_token_id)
        return EngineReplica(f"{role}{index}", eng, role, index)

    return factory
