"""omnictl — the SLO-driven control plane (docs/control_plane.md).

Closes the serving feedback loop over a disaggregated fleet: the
``ControlPlane`` watches per-role queue depth,
``phase_saturation_ratio``, and per-tenant SLO attainment (the same
snapshot surfaces /debug/z reads) and drives three actuator families —
live prefill<->decode re-roling (drain -> quiesce -> flip -> re-admit
through the PR 9 ``DisaggRouter``), fleet autoscaling with a modeled
cold-start window, and the engines' weighted-fair overload admission
(``core/scheduler.py`` WFQ, ordered by the ``x-omni-priority``
metadata).  Decisions land as structured actions on a bounded ring
served at ``/debug/controlplane``.
"""

from vllm_omni_tpu.controlplane.controller import (  # noqa: F401
    ACTION_DRAIN,
    ACTION_REMOVE,
    ACTION_REROLE,
    ACTION_SCALE_UP,
    ACTION_UNDRAIN,
    ControlPlane,
    ControlPlaneConfig,
    make_inproc_replica_factory,
)
from vllm_omni_tpu.controlplane.policy import (  # noqa: F401
    Hysteresis,
    RoleSensors,
    pressure_ratio,
    role_sensors,
)

__all__ = [
    "ControlPlane", "ControlPlaneConfig",
    "make_inproc_replica_factory", "Hysteresis", "RoleSensors",
    "pressure_ratio", "role_sensors", "ACTION_DRAIN", "ACTION_UNDRAIN",
    "ACTION_REROLE", "ACTION_SCALE_UP", "ACTION_REMOVE",
]
