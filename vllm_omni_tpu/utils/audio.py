"""Host-side audio feature extraction (numpy).

The whisper-style log-mel front end the reference gets from the HF
feature extractor (reference: qwen3_omni_moe_thinker.py:222
``get_feature_extractor``; hop padding ``pad_to_hop_length`` :248).
Pure numpy — runs on the host before features ship to the device.
"""

from __future__ import annotations

import functools

import numpy as np


@functools.lru_cache(maxsize=8)
def _mel_filterbank(sr: int, n_fft: int, n_mels: int) -> np.ndarray:
    """Triangular mel filterbank [n_mels, n_fft//2 + 1] (Slaney-style
    htk mel scale, unit peak)."""
    def hz_to_mel(f):
        return 2595.0 * np.log10(1.0 + np.asarray(f, np.float64) / 700.0)

    def mel_to_hz(m):
        return 700.0 * (10.0 ** (np.asarray(m, np.float64) / 2595.0) - 1.0)

    n_bins = n_fft // 2 + 1
    fft_freqs = np.linspace(0, sr / 2, n_bins)
    mel_pts = mel_to_hz(np.linspace(0, hz_to_mel(sr / 2), n_mels + 2))
    fb = np.zeros((n_mels, n_bins), np.float32)
    for i in range(n_mels):
        lo, ctr, hi = mel_pts[i], mel_pts[i + 1], mel_pts[i + 2]
        up = (fft_freqs - lo) / max(ctr - lo, 1e-10)
        down = (hi - fft_freqs) / max(hi - ctr, 1e-10)
        fb[i] = np.maximum(0.0, np.minimum(up, down))
    return fb


def log_mel_spectrogram(
    waveform: np.ndarray,  # [T] float
    sr: int = 16000,
    n_mels: int = 128,
    n_fft: int = 400,
    hop: int = 160,
) -> np.ndarray:
    """Return log-mel frames [num_frames, n_mels] float32 (whisper
    normalization: log10, clamped to max - 8, scaled to ~[-1, 1])."""
    x = np.asarray(waveform, np.float32)
    pad = (-len(x)) % hop
    if pad:
        x = np.pad(x, (0, pad))
    n_frames = max(1, (len(x) - n_fft) // hop + 1) if len(x) >= n_fft else 1
    if len(x) < n_fft:
        x = np.pad(x, (0, n_fft - len(x)))
    idx = np.arange(n_fft)[None, :] + hop * np.arange(n_frames)[:, None]
    frames = x[idx] * np.hanning(n_fft).astype(np.float32)[None, :]
    power = np.abs(np.fft.rfft(frames, axis=-1)) ** 2  # [F, n_fft//2+1]
    mel = power @ _mel_filterbank(sr, n_fft, n_mels).T  # [F, n_mels]
    logmel = np.log10(np.maximum(mel, 1e-10))
    logmel = np.maximum(logmel, logmel.max() - 8.0)
    return ((logmel + 4.0) / 4.0).astype(np.float32)


def bucket_waveform_to_mel(
    aud: np.ndarray,
    *,
    sr: int,
    n_mels: int,
    max_frames: int,
    samples_per_frame: int = 160,
    min_bucket: int = 1024,
    pad_pow2: bool = True,
) -> np.ndarray:
    """Length-guarded, compile-bounded mel intake shared by the audio
    towers (Qwen2.5-Omni whisper front end, Qwen3-Omni AuT).

    1-D waveforms are padded to a power-of-two sample count so each tower
    compiles once per bucket, not once per clip length (the padding is
    trailing silence).  The bucket is CAPPED at ``max_frames`` worth of
    samples so padding can never push a just-under-the-limit clip past
    the cap the error message promises — the raw-waveform and
    precomputed-mel paths enforce the same limit.  2-D inputs are taken
    as precomputed ``[T, n_mels]`` mels and only validated.

    ``pad_pow2=False`` skips the waveform padding (guard + transform
    only) for towers that bucket FRAME counts themselves and mask the
    padding rather than treating it as silence.
    """
    aud = np.asarray(aud)
    max_samples = max_frames * samples_per_frame
    if aud.ndim == 1:
        n = aud.shape[0]
        if n > max_samples:
            raise ValueError(
                f"audio clip too long ({n} samples > {max_samples}); "
                f"max {max_frames} mel frames")
        if pad_pow2:
            bucket = min_bucket
            while bucket < n:
                bucket *= 2
            bucket = min(bucket, max_samples)
            if bucket != n:
                aud = np.pad(aud, (0, bucket - n))
        return log_mel_spectrogram(aud, sr=sr, n_mels=n_mels)
    if aud.ndim == 2:
        if aud.shape[0] > max_frames:
            raise ValueError(
                f"audio clip has {aud.shape[0]} mel frames > {max_frames}")
        if aud.shape[1] != n_mels:
            raise ValueError(
                f"precomputed mel has {aud.shape[1]} bins; this tower "
                f"expects n_mels={n_mels}")
        return aud
    raise ValueError(
        f"audio must be a 1-D waveform or [T, n_mels] mel; got shape "
        f"{aud.shape}")
