"""Self-contained tokenizers.

``ByteTokenizer`` is the zero-dependency default used when no HF tokenizer
files ship with a model (random-weight pipelines, tests, benches): UTF-8
bytes + special tokens.  When a model directory carries a real HF
tokenizer, ``load_tokenizer`` prefers it (transformers is in the image).
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np


class ByteTokenizer:
    PAD, BOS, EOS = 0, 1, 2
    _SPECIALS = 3

    def __init__(self, vocab_size: int = 512):
        if vocab_size < 256 + self._SPECIALS:
            # byte values collapse modulo the usable range
            self.byte_span = vocab_size - self._SPECIALS
        else:
            self.byte_span = 256
        self.vocab_size = vocab_size
        self.pad_token_id = self.PAD
        self.bos_token_id = self.BOS
        self.eos_token_id = self.EOS

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = [self._SPECIALS + (b % self.byte_span) for b in text.encode()]
        return ([self.BOS] if add_bos else []) + ids

    def decode(self, ids) -> str:
        bs = bytes(
            (int(i) - self._SPECIALS) % max(1, self.byte_span)
            for i in ids
            if int(i) >= self._SPECIALS
        )
        return bs.decode(errors="replace")

    def batch_encode(
        self, texts: list[str], max_len: int
    ) -> tuple[np.ndarray, np.ndarray]:
        """Right-padded [B, max_len] ids + lengths."""
        out = np.full((len(texts), max_len), self.PAD, np.int32)
        lens = np.zeros((len(texts),), np.int32)
        for i, t in enumerate(texts):
            ids = self.encode(t)[:max_len]
            out[i, : len(ids)] = ids
            lens[i] = len(ids)
        return out, lens


def load_tokenizer(model_path: Optional[str], vocab_size: int = 512):
    """HF tokenizer when available, byte fallback otherwise."""
    if model_path and os.path.isdir(model_path):
        for f in ("tokenizer.json", "tokenizer_config.json"):
            if os.path.exists(os.path.join(model_path, f)):
                try:
                    from transformers import AutoTokenizer

                    return AutoTokenizer.from_pretrained(model_path)
                except Exception:
                    break
    return ByteTokenizer(vocab_size)
