"""HF-format Qwen2/Qwen3(-MoE) LM checkpoint loading.

Maps HuggingFace transformer weight names onto the functional param tree of
models/common/transformer.py (reference loads these models through vLLM's
loader; omni-side registration at vllm_omni/engine/arg_utils.py:33-43).

Layout conversions:
- HF linears are [out, in]; ours are [in, out] (transpose).
- HF gate_proj/up_proj pairs fuse into our ``gate_up`` [in, 2*inter]
  (silu_mul splits [gate; up] halves, ops/activation.py:13).
- HF per-expert MLPs stack onto the leading E axis of ``experts.gate_up`` /
  ``experts.down`` (the EP shard axis).

Streaming: shards load one at a time into preallocated numpy buffers, so
peak host memory is params + one shard.
"""

from __future__ import annotations

import json
import os
import re
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.model_loader.safetensors_loader import (
    iter_safetensors,
    np_param_dtype,
)
from vllm_omni_tpu.models.common import transformer as tfm

logger = init_logger(__name__)


def config_from_hf(model_dir: str,
                   hf_config_name: Optional[str] = None) -> tfm.TransformerConfig:
    """Translate an HF config.json into a TransformerConfig.

    ``hf_config_name`` selects a sub-config inside multi-part checkpoints
    (reference: OmniModelConfig.hf_config_name, config/model.py:46-60 —
    e.g. "thinker_config.text_config" for Qwen3-Omni).
    """
    with open(os.path.join(model_dir, "config.json")) as f:
        hf = json.load(f)
    for part in (hf_config_name or "").split("."):
        if part:
            hf = hf[part]
    num_heads = hf["num_attention_heads"]
    moe = "num_experts" in hf or "num_routed_experts" in hf
    model_type = hf.get("model_type", "").lower()
    # Qwen2 family uses q/k/v biases implicitly (no config field); Qwen3
    # exposes attention_bias explicitly (default False)
    attention_bias = hf.get("attention_bias", model_type.startswith("qwen2"))
    return tfm.TransformerConfig(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=num_heads,
        num_kv_heads=hf.get("num_key_value_heads", num_heads),
        head_dim=hf.get("head_dim") or hf["hidden_size"] // num_heads,
        intermediate_size=hf["intermediate_size"],
        rope_theta=hf.get("rope_theta", 1e6),
        rms_eps=hf.get("rms_norm_eps", 1e-6),
        qk_norm="qwen3" in model_type,
        attention_bias=attention_bias,
        tie_word_embeddings=hf.get("tie_word_embeddings", False),
        moe=moe,
        num_experts=hf.get("num_experts", hf.get("num_routed_experts", 8)),
        num_experts_per_tok=hf.get("num_experts_per_tok", 2),
        moe_intermediate_size=hf.get("moe_intermediate_size", 0),
        moe_renormalize=hf.get("norm_topk_prob", True),
        shared_expert_size=hf.get("shared_expert_intermediate_size", 0)
        if moe else 0,
        # multimodal 3-D RoPE sections (thinker/talker text configs carry
        # rope_scaling.mrope_section; positions then come in [B, 3, S])
        mrope_sections=tuple(
            (hf.get("rope_scaling") or {}).get("mrope_section"))
        if (hf.get("rope_scaling") or {}).get("mrope_section") else None,
    )


def _alloc_tree(cfg: tfm.TransformerConfig, dtype) -> dict:
    """Numpy buffers shaped like init_params output, without computing
    random values (jax.eval_shape traces the init)."""
    shapes = jax.eval_shape(
        lambda: tfm.init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    )
    return jax.tree_util.tree_map(
        lambda s: np.zeros(s.shape, dtype), shapes
    )


_LAYER_RE = re.compile(
    r"^(?:(?:model|language_model|thinker\.model|talker\.model)\.)?"
    r"layers\.(\d+)\.(.+?)\.(weight|bias)$"
)
# prefix optional: bare backbone checkpoints (e.g. a Qwen3Model saved as
# a diffusion text_encoder) name tensors layers.N... with no model. root
_PREFIX_RE = re.compile(
    r"^(?:model|language_model|thinker\.model|talker\.model)\."
)

_DIRECT = {
    "input_layernorm": ("input_norm", "w", False),
    "post_attention_layernorm": ("post_norm", "w", False),
    "self_attn.q_proj": ("q_proj", "w", True),
    "self_attn.k_proj": ("k_proj", "w", True),
    "self_attn.v_proj": ("v_proj", "w", True),
    "self_attn.o_proj": ("o_proj", "w", True),
    "self_attn.q_norm": ("q_norm", "w", False),
    "self_attn.k_norm": ("k_norm", "w", False),
    "mlp.down_proj": ("down", "w", True),
}

_EXPERT_RE = re.compile(r"^mlp\.experts\.(\d+)\.(gate_proj|up_proj|down_proj)$")


def load_qwen_lm(
    model_dir: str,
    cfg: Optional[tfm.TransformerConfig] = None,
    dtype=jnp.bfloat16,
    hf_config_name: Optional[str] = None,
    submodel: Optional[str] = None,
):
    """Load an HF Qwen2/Qwen3(-MoE) checkpoint.

    ``submodel`` restricts loading to one component of a composite
    checkpoint ("thinker" / "talker"): only tensors under that prefix
    are consumed — without it, a full Qwen3-Omni checkpoint would write
    both thinker.model.* and talker.model.* into the same tree.

    Returns (params, cfg, eos_token_id) — the model_factory contract.
    """
    from vllm_omni_tpu.model_loader.hub import resolve_model_path

    model_dir = resolve_model_path(model_dir, submodel=submodel)
    if cfg is None:
        cfg = config_from_hf(model_dir, hf_config_name)
    if isinstance(dtype, str):  # YAML model_factory_args pass strings
        from vllm_omni_tpu.config.model import resolve_dtype

        dtype = resolve_dtype(dtype)
    np_dtype = np_param_dtype(dtype)
    params = _alloc_tree(cfg, np_dtype)
    inter = cfg.moe_intermediate_size or cfg.intermediate_size

    # sibling components of a composite checkpoint that OTHER loaders
    # own — skipped at the shard-key level (never decoded, never counted
    # unmapped) so a correct load stays warning-free
    sibling = (f"{submodel}.code_predictor.", f"{submodel}.audio_tower.",
               f"{submodel}.visual.", f"{submodel}.text_projection.",
               f"{submodel}.hidden_projection.") if submodel else ()

    def keep(name):
        if submodel is None:
            return True
        return name.startswith(f"{submodel}.") \
            and not any(name.startswith(p) for p in sibling)

    loaded, unmapped = 0, []
    for name, arr in iter_safetensors(model_dir, keep):
        m = _LAYER_RE.match(name)
        if m:
            li, sub, kind = int(m.group(1)), m.group(2), m.group(3)
            if li >= cfg.num_layers:
                unmapped.append(name)
                continue
            layer = params["layers"][li]
            if kind == "bias":
                key = _DIRECT.get(sub, (None,))[0]
                if key is not None and key in layer and "b" in layer[key]:
                    layer[key]["b"][...] = arr
                    loaded += 1
                else:
                    unmapped.append(name)
                continue
            if sub in _DIRECT:
                key, leaf, transpose = _DIRECT[sub]
                if key not in layer:
                    unmapped.append(name)
                    continue
                layer[key][leaf][...] = arr.T if transpose else arr
                loaded += 1
                continue
            if sub == "mlp.gate_proj":
                layer["gate_up"]["w"][:, : cfg.intermediate_size] = arr.T
                loaded += 1
                continue
            if sub == "mlp.up_proj":
                layer["gate_up"]["w"][:, cfg.intermediate_size:] = arr.T
                loaded += 1
                continue
            if sub == "mlp.gate":  # MoE router [E, hidden]
                layer["router"]["w"][...] = arr.T
                loaded += 1
                continue
            if sub.startswith("mlp.shared_expert") and cfg.moe \
                    and "shared_expert" in layer:
                sse = cfg.shared_expert_size
                if sub == "mlp.shared_expert.gate_proj":
                    layer["shared_expert"]["gate_up"]["w"][:, :sse] = arr.T
                elif sub == "mlp.shared_expert.up_proj":
                    layer["shared_expert"]["gate_up"]["w"][:, sse:] = arr.T
                elif sub == "mlp.shared_expert.down_proj":
                    layer["shared_expert"]["down"]["w"][...] = arr.T
                elif sub == "mlp.shared_expert_gate":
                    layer["shared_gate"]["w"][...] = arr.T
                else:
                    unmapped.append(name)
                    continue
                loaded += 1
                continue
            em = _EXPERT_RE.match(sub)
            if em and cfg.moe:
                e, which = int(em.group(1)), em.group(2)
                if which == "gate_proj":
                    layer["experts"]["gate_up"][e, :, :inter] = arr.T
                elif which == "up_proj":
                    layer["experts"]["gate_up"][e, :, inter:] = arr.T
                else:
                    layer["experts"]["down"][e] = arr.T
                loaded += 1
                continue
            unmapped.append(name)
            continue
        stripped = _PREFIX_RE.sub("", name)
        if stripped in ("embed_tokens.weight", "codec_embedding.weight"):
            # codec_embedding: the talker's code-token table
            # (Qwen3OmniMoeTalkerModel)
            params["embed"]["w"][...] = arr  # embeddings stay [vocab, hidden]
            loaded += 1
        elif stripped == "norm.weight":
            params["final_norm"]["w"][...] = arr
            loaded += 1
        elif name in ("lm_head.weight", "thinker.lm_head.weight",
                      "talker.lm_head.weight", "talker.codec_head.weight",
                      "codec_head.weight"):
            if cfg.tie_word_embeddings:
                unmapped.append(name)
            else:
                params["lm_head"]["w"][...] = arr.T
                loaded += 1
        else:
            unmapped.append(name)
    if unmapped:
        logger.warning("unmapped checkpoint tensors (%d): %s%s",
                       len(unmapped), unmapped[:8],
                       "..." if len(unmapped) > 8 else "")
    logger.info("loaded %d tensors from %s", loaded, model_dir)
    params = jax.tree_util.tree_map(jnp.asarray, params)
    eos = _eos_token_id(model_dir)
    return params, cfg, eos


def _eos_token_id(model_dir: str):
    """Primary eos id or the full list (multi-eos checkpoints like Qwen2.5
    stop on any of them — Request.check_stop accepts both forms)."""
    for fn in ("generation_config.json", "config.json"):
        p = os.path.join(model_dir, fn)
        if os.path.isfile(p):
            with open(p) as f:
                eos = json.load(f).get("eos_token_id")
            if isinstance(eos, list):
                return [int(e) for e in eos] if eos else None
            if eos is not None:
                return int(eos)
    return None


# load_qwen_lm already satisfies the stage model_factory contract directly:
#   engine_args:
#     model_factory: "vllm_omni_tpu.model_loader.hf_qwen:load_qwen_lm"
#     model_factory_args: {model_dir: /path/to/checkpoint}
