from vllm_omni_tpu.model_loader.safetensors_loader import (
    iter_safetensors,
    load_checkpoint_tree,
)
from vllm_omni_tpu.model_loader.hf_qwen import config_from_hf, load_qwen_lm

__all__ = [
    "config_from_hf",
    "iter_safetensors",
    "load_checkpoint_tree",
    "load_qwen_lm",
]
