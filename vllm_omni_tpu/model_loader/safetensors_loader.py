"""Streaming safetensors checkpoint reader.

TPU-native replacement for the reference's weight-loading path (reference:
model loaders in vllm_omni/diffusion/model_loader/diffusers_loader.py and
model_executor/model_loader/weight_utils.py:87
``download_weights_from_hf_specific``).  Tensors stream shard-by-shard —
each shard is opened, its tensors consumed (optionally device_put with a
target sharding), and released before the next opens, bounding host memory
at one shard instead of the whole checkpoint (SURVEY.md §7 hard part 6).

Zero-egress stance: loads from local paths only; HF-hub download is the
caller's concern.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Iterator, Optional

import numpy as np
from safetensors import safe_open

from vllm_omni_tpu.logger import init_logger

logger = init_logger(__name__)


def _shard_files(model_dir: str) -> list[str]:
    """Resolve the safetensors shard list: direct file path, single file,
    HF index json, or every *.safetensors in the directory."""
    if os.path.isfile(model_dir):
        return [model_dir]
    single = os.path.join(model_dir, "model.safetensors")
    if os.path.isfile(single):
        return [single]
    for index_name in ("model.safetensors.index.json",
                       "diffusion_pytorch_model.safetensors.index.json"):
        index = os.path.join(model_dir, index_name)
        if os.path.isfile(index):
            with open(index) as f:
                weight_map = json.load(f)["weight_map"]
            return sorted(
                os.path.join(model_dir, fn) for fn in set(weight_map.values())
            )
    files = sorted(
        os.path.join(model_dir, f) for f in os.listdir(model_dir)
        if f.endswith(".safetensors")
    )
    if not files:
        raise FileNotFoundError(f"no .safetensors under {model_dir}")
    return files


def iter_safetensors(
    model_dir: str,
    name_filter: Optional[Callable[[str], bool]] = None,
) -> Iterator[tuple[str, np.ndarray]]:
    """Yield (hf_name, array) streaming across shards (numpy framework —
    works for bf16 via ml_dtypes, no torch in the loop).

    ``name_filter`` skips tensors at the key level — non-matching names
    are never decoded, so picking a few tensors out of a multi-GB
    composite checkpoint does not read the rest."""
    for path in _shard_files(model_dir):
        logger.info("loading shard %s", os.path.basename(path))
        with safe_open(path, framework="numpy") as f:
            for name in f.keys():
                if name_filter is not None and not name_filter(name):
                    continue
                yield name, f.get_tensor(name)


def np_param_dtype(dtype):
    """numpy-side dtype for preallocated param buffers (bfloat16 has no
    numpy dtype name — ml_dtypes' type object works directly)."""
    import jax.numpy as jnp

    return jnp.bfloat16 if dtype == jnp.bfloat16 \
        else np.dtype(jnp.dtype(dtype).name)


def load_checkpoint_tree(
    model_dir: str,
    name_map: Callable[[str], Optional[tuple]],
    tree: dict,
    transpose_linear: bool = True,
    dtype=None,
    device_put: Optional[Callable] = None,
    transform: Optional[Callable[[str, np.ndarray], np.ndarray]] = None,
    name_filter: Optional[Callable[[str], bool]] = None,
) -> tuple[int, list[str]]:
    """Stream a checkpoint into an existing param tree.

    ``name_map(hf_name)`` returns a path tuple into ``tree`` (or None to
    skip).  HF linears store [out, in]; our layout is [in, out] —
    ``transpose_linear`` flips 2-D "w" leaves.  ``transform(name, arr)``
    (when given) handles layouts the flag can't express, e.g. torch
    OIDHW conv kernels -> DHWIO.  ``name_filter`` skips non-matching
    tensors without decoding them (they are not counted as unmapped).
    Returns (num_loaded, unmapped_names); shape mismatches raise
    immediately.
    """
    n = 0
    unmapped: list[str] = []
    for hf_name, arr in iter_safetensors(model_dir, name_filter):
        path = name_map(hf_name)
        if path is None:
            unmapped.append(hf_name)
            continue
        node = tree
        for key in path[:-1]:
            node = node[int(key)] if isinstance(node, list) else node[key]
        leaf = path[-1]
        if transform is not None:
            arr = transform(hf_name, arr)
        elif transpose_linear and leaf == "w" and arr.ndim == 2:
            arr = arr.T
        expected = node[leaf]
        if tuple(expected.shape) != tuple(arr.shape):
            raise ValueError(
                f"{hf_name} -> {'/'.join(map(str, path))}: shape "
                f"{arr.shape} != expected {tuple(expected.shape)}"
            )
        if dtype is not None:
            arr = arr.astype(dtype)
        node[leaf] = device_put(arr, path) if device_put else arr
        n += 1
    return n, unmapped
