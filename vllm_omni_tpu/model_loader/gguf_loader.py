"""GGUF checkpoint intake for Qwen-family LMs.

Role of the reference's GGUF support (reference: engine/arg_utils.py:96-97
``load_format="gguf"`` / quantized checkpoint intake): parse the GGUF
container (v2/v3), translate the ``general.architecture`` metadata into a
TransformerConfig, and dequantize tensors into the functional param tree
(models/common/transformer.py).  Pure numpy — no gguf-py dependency.

Supported tensor encodings: F32, F16, BF16, and Q8_0 (32-element blocks,
fp16 scale + int8 quants — the llama.cpp 8-bit format).  Other quant
types raise with the type name so the gap is explicit, not silent.
"""

from __future__ import annotations

import struct
from typing import Any, Optional

import numpy as np

from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common import transformer as tfm

logger = init_logger(__name__)

_MAGIC = b"GGUF"

# metadata value readers by GGUF type id
_SCALARS = {
    0: ("<B", 1), 1: ("<b", 1), 2: ("<H", 2), 3: ("<h", 2),
    4: ("<I", 4), 5: ("<i", 4), 6: ("<f", 4), 7: ("<?", 1),
    10: ("<Q", 8), 11: ("<q", 8), 12: ("<d", 8),
}

GGML_F32, GGML_F16, GGML_Q8_0, GGML_BF16 = 0, 1, 8, 30
_TYPE_NAMES = {
    2: "Q4_0", 3: "Q4_1", 6: "Q5_0", 7: "Q5_1", 8: "Q8_0",
    10: "Q2_K", 11: "Q3_K", 12: "Q4_K", 13: "Q5_K", 14: "Q6_K",
}


class _Reader:
    def __init__(self, buf: memoryview):
        self.buf = buf
        self.pos = 0

    def read(self, fmt: str):
        v = struct.unpack_from(fmt, self.buf, self.pos)[0]
        self.pos += struct.calcsize(fmt)
        return v

    def read_string(self) -> str:
        n = self.read("<Q")
        s = bytes(self.buf[self.pos:self.pos + n]).decode("utf-8")
        self.pos += n
        return s

    def read_value(self, vtype: int):
        if vtype in _SCALARS:
            return self.read(_SCALARS[vtype][0])
        if vtype == 8:
            return self.read_string()
        if vtype == 9:
            etype = self.read("<I")
            count = self.read("<Q")
            return [self.read_value(etype) for _ in range(count)]
        raise ValueError(f"unknown GGUF metadata type {vtype}")


def _dequant(raw: np.ndarray, ggml_type: int, shape: tuple) -> np.ndarray:
    n = int(np.prod(shape))
    if ggml_type == GGML_F32:
        return raw.view(np.float32)[:n].reshape(shape)
    if ggml_type == GGML_F16:
        return raw.view(np.float16)[:n].astype(np.float32).reshape(shape)
    if ggml_type == GGML_BF16:
        import ml_dtypes

        return raw.view(ml_dtypes.bfloat16)[:n].astype(
            np.float32).reshape(shape)
    if ggml_type == GGML_Q8_0:
        # 34-byte blocks: fp16 scale + 32 int8 quants
        nblocks = n // 32
        blocks = raw[: nblocks * 34].reshape(nblocks, 34)
        scales = blocks[:, :2].copy().view(np.float16).astype(np.float32)
        quants = blocks[:, 2:].view(np.int8).astype(np.float32)
        return (quants * scales).reshape(shape)
    raise ValueError(
        f"unsupported GGUF tensor type {ggml_type} "
        f"({_TYPE_NAMES.get(ggml_type, '?')}) — supported: F32, F16, "
        "BF16, Q8_0")


def read_gguf(path: str):
    """Parse a GGUF file -> (metadata dict, {name: np.ndarray fp32})."""
    data = np.memmap(path, dtype=np.uint8, mode="r")
    r = _Reader(memoryview(data))
    if bytes(r.buf[:4]) != _MAGIC:
        raise ValueError(f"{path}: not a GGUF file")
    r.pos = 4
    version = r.read("<I")
    if version not in (2, 3):
        raise ValueError(f"{path}: unsupported GGUF version {version}")
    n_tensors = r.read("<Q")
    n_kv = r.read("<Q")
    meta: dict[str, Any] = {}
    for _ in range(n_kv):
        key = r.read_string()
        vtype = r.read("<I")
        meta[key] = r.read_value(vtype)
    infos = []
    for _ in range(n_tensors):
        name = r.read_string()
        n_dims = r.read("<I")
        # ggml dims are innermost-first; numpy wants outermost-first
        dims = [r.read("<Q") for _ in range(n_dims)][::-1]
        ttype = r.read("<I")
        offset = r.read("<Q")
        infos.append((name, tuple(dims), ttype, offset))
    align = int(meta.get("general.alignment", 32))
    base = (r.pos + align - 1) // align * align

    def nbytes(shape, ttype):
        n = int(np.prod(shape))
        if ttype == GGML_F32:
            return n * 4
        if ttype in (GGML_F16, GGML_BF16):
            return n * 2
        if ttype == GGML_Q8_0:
            return n // 32 * 34
        raise ValueError(
            f"unsupported GGUF tensor type {ttype} "
            f"({_TYPE_NAMES.get(ttype, '?')})")

    tensors: dict[str, np.ndarray] = {}
    for name, shape, ttype, offset in infos:
        start = base + offset
        tensors[name] = _dequant(
            np.asarray(data[start:start + nbytes(shape, ttype)]),
            ttype, shape)
    return meta, tensors


def config_from_gguf(meta: dict,
                     vocab_size: int) -> tfm.TransformerConfig:
    arch = meta.get("general.architecture", "qwen2")

    def g(key, default=None):
        return meta.get(f"{arch}.{key}", default)

    heads = int(g("attention.head_count"))
    hidden = int(g("embedding_length"))
    return tfm.TransformerConfig(
        vocab_size=vocab_size,
        hidden_size=hidden,
        num_layers=int(g("block_count")),
        num_heads=heads,
        num_kv_heads=int(g("attention.head_count_kv", heads)),
        head_dim=int(g("attention.key_length", hidden // heads)),
        intermediate_size=int(g("feed_forward_length")),
        rope_theta=float(g("rope.freq_base", 1e6)),
        rms_eps=float(g("attention.layer_norm_rms_epsilon", 1e-6)),
        qk_norm=arch.startswith("qwen3"),
        attention_bias=arch.startswith("qwen2"),
        tie_word_embeddings=False,  # set below from tensor presence
    )


def load_gguf_lm(model_dir: str, dtype="bfloat16",
                 cfg: Optional[tfm.TransformerConfig] = None, **_):
    """model_factory contract: (params, TransformerConfig, eos_id).

    ``model_dir`` is the .gguf file path."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from vllm_omni_tpu.config.model import resolve_dtype

    jdtype = resolve_dtype(dtype) if isinstance(dtype, str) else dtype
    meta, tensors = read_gguf(model_dir)
    vocab = tensors["token_embd.weight"].shape[0]
    if cfg is None:
        cfg = config_from_gguf(meta, vocab)
    tied = "output.weight" not in tensors
    cfg = dataclasses.replace(cfg, tie_word_embeddings=tied)

    shapes = jax.eval_shape(
        lambda: tfm.init_params(jax.random.PRNGKey(0), cfg, jnp.float32))
    tree = jax.tree_util.tree_map(
        lambda s: np.zeros(s.shape, np.float32), shapes)

    def put(dst, src, transpose=True):
        arr = src.T if (transpose and src.ndim == 2) else src
        if dst.shape != arr.shape:
            raise ValueError(f"shape {arr.shape} != {dst.shape}")
        dst[...] = arr

    put(tree["embed"]["w"], tensors["token_embd.weight"],
        transpose=False)
    put(tree["final_norm"]["w"], tensors["output_norm.weight"])
    if not tied:
        put(tree["lm_head"]["w"], tensors["output.weight"])
    inter = cfg.intermediate_size
    for i in range(cfg.num_layers):
        blk = f"blk.{i}"
        layer = tree["layers"][i]
        put(layer["input_norm"]["w"], tensors[f"{blk}.attn_norm.weight"])
        put(layer["post_norm"]["w"], tensors[f"{blk}.ffn_norm.weight"])
        for gg, ours in (("attn_q", "q_proj"), ("attn_k", "k_proj"),
                         ("attn_v", "v_proj"),
                         ("attn_output", "o_proj")):
            put(layer[ours]["w"], tensors[f"{blk}.{gg}.weight"])
            bias = tensors.get(f"{blk}.{gg}.bias")
            if bias is not None and "b" in layer[ours]:
                layer[ours]["b"][...] = bias
        if cfg.qk_norm:
            put(layer["q_norm"]["w"],
                tensors[f"{blk}.attn_q_norm.weight"])
            put(layer["k_norm"]["w"],
                tensors[f"{blk}.attn_k_norm.weight"])
        layer["gate_up"]["w"][:, :inter] = \
            tensors[f"{blk}.ffn_gate.weight"].T
        layer["gate_up"]["w"][:, inter:] = \
            tensors[f"{blk}.ffn_up.weight"].T
        put(layer["down"]["w"], tensors[f"{blk}.ffn_down.weight"])
    params = jax.tree_util.tree_map(
        lambda a: jnp.asarray(a, jdtype), tree)
    eos = meta.get("tokenizer.ggml.eos_token_id")
    logger.info("GGUF load: %s (%s, %d tensors, tied=%s)",
                model_dir, meta.get("general.architecture"),
                len(tensors), tied)
    return params, cfg, (int(eos) if eos is not None else None)
