"""Diffusers-format checkpoint loading for the diffusion stack.

The TPU-native counterpart of the reference's ``DiffusersPipelineLoader``
(reference: vllm_omni/diffusion/model_loader/diffusers_loader.py:1-120):
a diffusers repo directory is a ``model_index.json`` naming per-component
subdirectories (transformer / text_encoder / tokenizer / vae / scheduler),
each with its own ``config.json`` and safetensors shards.

Zero-egress stance: local directories only (HF-hub download is the
caller's concern).  Weight streaming rides
``safetensors_loader.load_checkpoint_tree`` — one shard resident at a
time, with HF [out, in] linears transposed into our [in, out] layout.

Name mapping follows the checkpoint layout the reference's
``QwenImageTransformer2DModel.load_weights`` consumes
(qwen_image_transformer.py:1073-1108): ``transformer_blocks.{i}.attn.to_q``
etc., ``img_mod.1`` (SiLU+Linear Sequential), ``img_mlp.net.0.proj`` /
``net.2`` (approx-GELU FeedForward), ``norm_out.linear``, and the
``time_text_embed.timestep_embedder.linear_{1,2}`` timestep MLP.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Optional

import jax.numpy as jnp

from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.model_loader.safetensors_loader import load_checkpoint_tree
from vllm_omni_tpu.models.qwen_image import transformer as qwen_dit
from vllm_omni_tpu.models.qwen_image.transformer import QwenImageDiTConfig

logger = init_logger(__name__)


def load_model_index(model_dir: str) -> dict[str, Any]:
    """Parse model_index.json -> {component: (library, class) | value}."""
    path = os.path.join(model_dir, "model_index.json")
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no model_index.json under {model_dir}")
    with open(path) as f:
        return json.load(f)


# ------------------------------------------------------------------ DiT
def dit_config_from_diffusers(config: dict) -> QwenImageDiTConfig:
    """QwenImageTransformer2DModel config.json -> QwenImageDiTConfig
    (field names per the diffusers class the reference mirrors,
    qwen_image_transformer.py:818-840)."""
    in_channels = config.get("in_channels", 64)
    return QwenImageDiTConfig(
        patch_size=config.get("patch_size", 2),
        in_channels=in_channels,
        out_channels=config.get("out_channels") or in_channels // 4,
        num_layers=config.get("num_layers", 60),
        num_heads=config.get("num_attention_heads", 24),
        head_dim=config.get("attention_head_dim", 128),
        joint_dim=config.get("joint_attention_dim", 3584),
        axes_dims=tuple(config.get("axes_dims_rope", (16, 56, 56))),
        # checkpoints are trained under the interleaved rotary pairing
        # (reference RotaryEmbedding(is_neox_style=False) on complex
        # polar freqs, qwen_image_transformer.py:553)
        rope_interleaved=True,
    )


_DIT_TOP = {
    "img_in": ("img_in",),
    "txt_in": ("txt_in",),
    "txt_norm": ("txt_norm",),
    "time_text_embed.timestep_embedder.linear_1": ("time_in1",),
    "time_text_embed.timestep_embedder.linear_2": ("time_in2",),
    "norm_out.linear": ("norm_out_mod",),
    "proj_out": ("proj_out",),
}

_DIT_BLOCK = {
    "img_mod.1": "img_mod",
    "txt_mod.1": "txt_mod",
    "attn.to_q": "to_q",
    "attn.to_k": "to_k",
    "attn.to_v": "to_v",
    "attn.add_q_proj": "add_q",
    "attn.add_k_proj": "add_k",
    "attn.add_v_proj": "add_v",
    "attn.norm_q": "norm_q",
    "attn.norm_k": "norm_k",
    "attn.norm_added_q": "norm_added_q",
    "attn.norm_added_k": "norm_added_k",
    "attn.to_out.0": "to_out",
    "attn.to_add_out": "to_add_out",
    "img_mlp.net.0.proj": "img_mlp1",
    "img_mlp.net.2": "img_mlp2",
    "txt_mlp.net.0.proj": "txt_mlp1",
    "txt_mlp.net.2": "txt_mlp2",
}

_LEAF = {"weight": "w", "bias": "b"}

_BLOCK_RE = re.compile(r"^transformer_blocks\.(\d+)\.(.+)\.(weight|bias)$")
_TOP_RE = re.compile(r"^(.+)\.(weight|bias)$")


def qwen_image_dit_name_map(hf_name: str) -> Optional[tuple]:
    """Checkpoint tensor name -> path into our DiT param tree (None for
    unknown names)."""
    m = _BLOCK_RE.match(hf_name)
    if m:
        idx, mod, leaf = m.groups()
        ours = _DIT_BLOCK.get(mod)
        if ours is None:
            return None
        return ("blocks", idx, ours, _LEAF[leaf])
    m = _TOP_RE.match(hf_name)
    if m:
        mod, leaf = m.groups()
        ours = _DIT_TOP.get(mod)
        if ours is None:
            return None
        return ours + (_LEAF[leaf],)
    return None


def load_qwen_image_dit(
    transformer_dir: str,
    dtype=jnp.bfloat16,
    device_put=None,
):
    """Load a diffusers-format Qwen-Image transformer.

    Returns (params, QwenImageDiTConfig).  Raises on shape mismatches;
    logs any unmapped checkpoint tensors.
    """
    import jax
    import numpy as np

    with open(os.path.join(transformer_dir, "config.json")) as f:
        cfg = dit_config_from_diffusers(json.load(f))
    # allocate the target tree without materializing random weights
    shapes = jax.eval_shape(
        lambda: qwen_dit.init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    )
    np_dtype = jnp.bfloat16 if dtype == jnp.bfloat16 else np.dtype(
        jnp.dtype(dtype).name)

    def alloc(t):
        return np.zeros(t.shape, np_dtype)

    tree = jax.tree.map(alloc, shapes)
    n, unmapped = load_checkpoint_tree(
        transformer_dir, qwen_image_dit_name_map, tree,
        transpose_linear=True, dtype=np_dtype, device_put=device_put,
    )
    if unmapped:
        logger.warning("DiT loader: %d unmapped tensors (e.g. %s)",
                       len(unmapped), unmapped[:3])
    n_leaves = len(jax.tree.leaves(tree))
    if n != n_leaves:
        # the tree is pre-allocated zeros: an uncovered leaf would serve
        # silently-garbage outputs (missing shard / renamed tensor)
        raise ValueError(
            f"checkpoint {transformer_dir} covered {n}/{n_leaves} DiT "
            "weights — incomplete or incompatible checkpoint"
        )
    logger.info("DiT loader: %d tensors loaded (%d layers)", n,
                cfg.num_layers)
    return tree, cfg


# ----------------------------------------------------------- text encoder
def text_encoder_config(text_encoder_dir: str):
    """TransformerConfig for the text-encoder component.  Qwen2.5-VL
    checkpoints nest the LM fields under ``text_config`` (newer
    transformers) or keep them at the top level — handle both."""
    from vllm_omni_tpu.model_loader.hf_qwen import config_from_hf

    with open(os.path.join(text_encoder_dir, "config.json")) as f:
        hf = json.load(f)
    sub = "text_config" if "text_config" in hf else None
    return config_from_hf(text_encoder_dir, hf_config_name=sub)


def load_text_encoder(text_encoder_dir: str, dtype=jnp.bfloat16):
    """Load the text-encoder LM (Qwen2/2.5-VL-text style) via the proven
    hf_qwen streaming loader.  Returns (params, TransformerConfig)."""
    from vllm_omni_tpu.model_loader.hf_qwen import load_qwen_lm

    cfg = text_encoder_config(text_encoder_dir)
    params, _, _ = load_qwen_lm(text_encoder_dir, cfg=cfg, dtype=dtype)
    return params, cfg


# ------------------------------------------------------------- causal VAE
def causal_vae_config_from_diffusers(config: dict):
    """AutoencoderKLQwenImage config.json -> CausalVAEConfig (field names
    per the diffusers class the reference mirrors,
    autoencoder_kl_qwenimage.py:679-697)."""
    from vllm_omni_tpu.models.common.causal_vae import CausalVAEConfig

    return CausalVAEConfig(
        z_channels=config.get("z_dim", 16),
        base_dim=config.get("base_dim", 96),
        dim_mult=tuple(config.get("dim_mult", (1, 2, 4, 4))),
        num_res_blocks=config.get("num_res_blocks", 2),
        attn_scales=tuple(config.get("attn_scales", ())),
        temporal_downsample=tuple(
            config.get("temperal_downsample", (False, True, True))),
        latents_mean=tuple(config["latents_mean"])
        if config.get("latents_mean") else None,
        latents_std=tuple(config["latents_std"])
        if config.get("latents_std") else None,
    )


_VAE_RES = {
    "norm1.gamma": ("norm1", "g"),
    "conv1.weight": ("conv1", "w"),
    "conv1.bias": ("conv1", "b"),
    "norm2.gamma": ("norm2", "g"),
    "conv2.weight": ("conv2", "w"),
    "conv2.bias": ("conv2", "b"),
    "conv_shortcut.weight": ("skip", "w"),
    "conv_shortcut.bias": ("skip", "b"),
}

_VAE_ATTN = {
    "norm.gamma": ("norm", "g"),
    "to_qkv.weight": ("qkv", "w"),
    "to_qkv.bias": ("qkv", "b"),
    "proj.weight": ("proj", "w"),
    "proj.bias": ("proj", "b"),
}


def causal_vae_flat_map(cfg) -> dict[str, tuple]:
    """hf_name -> tree-path dict for the Wan-family causal VAE.

    Decoder/mid/up-block names are positional; the encoder's
    ``down_blocks`` is a FLAT ModuleList (resnets, attentions, and
    resamplers interleaved — autoencoder_kl_qwenimage.py:415-429), so the
    flat index is reconstructed from the config here.
    """
    flat: dict[str, tuple] = {}

    def put(prefix: str, table: dict, path: tuple):
        for hf_leaf, ours in table.items():
            flat[f"{prefix}.{hf_leaf}"] = path + ours

    conv = {"weight": "w", "bias": "b"}
    for side in ("decoder", "encoder"):
        put(f"{side}.mid_block.resnets.0", _VAE_RES, (side, "mid", "res0"))
        put(f"{side}.mid_block.attentions.0", _VAE_ATTN,
            (side, "mid", "attn0"))
        put(f"{side}.mid_block.resnets.1", _VAE_RES, (side, "mid", "res1"))
        for leaf, ours in conv.items():
            flat[f"{side}.conv_in.{leaf}"] = (side, "conv_in", ours)
            flat[f"{side}.conv_out.{leaf}"] = (side, "conv_out", ours)
        flat[f"{side}.norm_out.gamma"] = (side, "norm_out", "g")
    for name in ("quant_conv", "post_quant_conv"):
        for leaf, ours in conv.items():
            flat[f"{name}.{leaf}"] = (name, ours)

    n_stages = len(cfg.dim_mult)
    for i in range(n_stages):
        for j in range(cfg.num_res_blocks + 1):
            put(f"decoder.up_blocks.{i}.resnets.{j}", _VAE_RES,
                ("decoder", "ups", i, "res", j))
        up = f"decoder.up_blocks.{i}.upsamplers.0"
        for leaf, ours in conv.items():
            flat[f"{up}.resample.1.{leaf}"] = (
                "decoder", "ups", i, "up", "conv", ours)
            flat[f"{up}.time_conv.{leaf}"] = (
                "decoder", "ups", i, "up", "time", ours)

    k = 0  # encoder down_blocks flat index
    scale = 1.0
    for i in range(n_stages):
        for j in range(cfg.num_res_blocks):
            put(f"encoder.down_blocks.{k}", _VAE_RES,
                ("encoder", "downs", i, "res", j))
            k += 1
            if scale in cfg.attn_scales:
                put(f"encoder.down_blocks.{k}", _VAE_ATTN,
                    ("encoder", "downs", i, "attn", j))
                k += 1
        if i != n_stages - 1:
            for leaf, ours in conv.items():
                flat[f"encoder.down_blocks.{k}.resample.1.{leaf}"] = (
                    "encoder", "downs", i, "down", "conv", ours)
                flat[f"encoder.down_blocks.{k}.time_conv.{leaf}"] = (
                    "encoder", "downs", i, "down", "time", ours)
            k += 1
            scale /= 2.0

    return flat


def causal_vae_name_map(cfg):
    return causal_vae_flat_map(cfg).get


def causal_vae_transform(name: str, arr):
    """torch layouts -> ours: OIDHW conv3d -> DHWIO, OIHW conv2d -> HWIO,
    broadcast-shaped norm gammas -> [C]."""
    if name.endswith("gamma"):
        return arr.reshape(-1)
    if arr.ndim == 5:
        return arr.transpose(2, 3, 4, 1, 0)
    if arr.ndim == 4:
        return arr.transpose(2, 3, 1, 0)
    return arr


def load_causal_vae(
    vae_dir: str,
    dtype=jnp.bfloat16,
    encoder: bool = True,
    decoder: bool = True,
    device_put=None,
):
    """Load a diffusers-format Wan-family causal VAE
    (AutoencoderKLQwenImage / Wan2.1 layout).  Returns (params,
    CausalVAEConfig).  Every leaf of the requested halves must be covered
    by the checkpoint or this raises."""
    import jax
    import numpy as np

    from vllm_omni_tpu.models.common import causal_vae as cv

    with open(os.path.join(vae_dir, "config.json")) as f:
        cfg = causal_vae_config_from_diffusers(json.load(f))
    shapes = jax.eval_shape(
        lambda: cv.init_params(jax.random.PRNGKey(0), cfg, jnp.float32,
                               encoder=encoder, decoder=decoder)
    )
    np_dtype = jnp.bfloat16 if dtype == jnp.bfloat16 else np.dtype(
        jnp.dtype(dtype).name)
    tree = jax.tree.map(lambda t: np.zeros(t.shape, np_dtype), shapes)
    name_map = causal_vae_name_map(cfg)

    def map_requested(hf_name):
        path = name_map(hf_name)
        if path is None:
            return None
        if not encoder and path[0] in ("encoder", "quant_conv"):
            return None
        if not decoder and path[0] in ("decoder", "post_quant_conv"):
            return None
        return path

    n, unmapped = load_checkpoint_tree(
        vae_dir, map_requested, tree,
        dtype=np_dtype, device_put=device_put,
        transform=causal_vae_transform,
    )
    n_leaves = len(jax.tree.leaves(tree))
    if n != n_leaves:
        raise ValueError(
            f"checkpoint {vae_dir} covered {n}/{n_leaves} VAE weights — "
            "incomplete or incompatible checkpoint"
        )
    logger.info("causal VAE loader: %d tensors loaded", n)
    return tree, cfg


# -------------------------------------------------------------- scheduler
def scheduler_config(model_dir: str) -> dict:
    """FlowMatch scheduler knobs from scheduler/scheduler_config.json
    (shift / dynamic shifting — diffusers FlowMatchEulerDiscreteScheduler
    fields consumed by our diffusion/scheduler.py)."""
    path = os.path.join(model_dir, "scheduler", "scheduler_config.json")
    if not os.path.isfile(path):
        return {}
    with open(path) as f:
        sc = json.load(f)
    out = {
        "shift": sc.get("shift", 1.0),
        "use_dynamic_shifting": sc.get("use_dynamic_shifting", False),
    }
    # EDM-family schedulers (StableAudio's CosineDPMSolverMultistep)
    # carry sigma knobs instead of a flow shift
    for k in ("sigma_min", "sigma_max", "sigma_data"):
        if k in sc:
            out[k] = sc[k]
    return out


# --------------------------------------------------------- 2-D image VAE
def image_vae_config_from_diffusers(config: dict):
    """AutoencoderKL config.json -> qwen_image.vae.VAEConfig (the SD3 /
    Flux VAE variant: no quant/post-quant convs)."""
    from vllm_omni_tpu.models.qwen_image.vae import VAEConfig

    blocks = config.get("block_out_channels", (128, 256, 512, 512))
    base = blocks[0]
    mults = []
    for b in blocks:
        if b % base:
            raise ValueError(
                f"block_out_channels {blocks} are not multiples of "
                f"{base}")
        mults.append(b // base)
    if config.get("use_quant_conv", False) \
            or config.get("use_post_quant_conv", False):
        raise ValueError(
            "quant/post-quant conv VAEs (SD1/SDXL layout) are not "
            "supported; SD3/Flux-style AutoencoderKL only")
    return VAEConfig(
        latent_channels=config.get("latent_channels", 16),
        base_channels=base,
        channel_multipliers=tuple(mults),
        layers_per_block=config.get("layers_per_block", 2),
        scaling_factor=config.get("scaling_factor", 1.0),
        shift_factor=config.get("shift_factor", 0.0) or 0.0,
    )


def image_vae_flat_map(cfg, encoder: bool = True,
                       decoder: bool = True) -> dict[str, tuple]:
    """diffusers AutoencoderKL names -> qwen_image.vae tree paths."""
    m: dict[str, tuple] = {}

    def wb(hf: str, *path):
        m[f"{hf}.weight"] = path + ("w",)
        m[f"{hf}.bias"] = path + ("b",)

    def resnet(hf: str, tgt: tuple, cin: int, cout: int):
        wb(f"{hf}.norm1", *tgt, "norm1")
        wb(f"{hf}.conv1", *tgt, "conv1")
        wb(f"{hf}.norm2", *tgt, "norm2")
        wb(f"{hf}.conv2", *tgt, "conv2")
        if cin != cout:
            wb(f"{hf}.conv_shortcut", *tgt, "skip")

    def attn(hf: str, tgt: tuple):
        wb(f"{hf}.group_norm", *tgt, "norm")
        wb(f"{hf}.to_q", *tgt, "q")
        wb(f"{hf}.to_k", *tgt, "k")
        wb(f"{hf}.to_v", *tgt, "v")
        wb(f"{hf}.to_out.0", *tgt, "o")

    chans = [cfg.base_channels * x for x in cfg.channel_multipliers]
    n = len(chans)
    if decoder:
        top = chans[-1]
        wb("decoder.conv_in", "conv_in")
        resnet("decoder.mid_block.resnets.0", ("mid_res1",), top, top)
        attn("decoder.mid_block.attentions.0", ("mid_attn",))
        resnet("decoder.mid_block.resnets.1", ("mid_res2",), top, top)
        cur = top
        for i, ch in enumerate(reversed(chans)):
            blk = f"decoder.up_blocks.{i}"
            for j in range(cfg.layers_per_block + 1):
                resnet(f"{blk}.resnets.{j}", ("ups", i, "res", j),
                       cur, ch)
                cur = ch
            if i < n - 1:
                wb(f"{blk}.upsamplers.0.conv", "ups", i, "up_conv")
        wb("decoder.conv_norm_out", "norm_out")
        wb("decoder.conv_out", "conv_out")
    if encoder:
        wb("encoder.conv_in", "conv_in")
        cur = chans[0]
        for i, ch in enumerate(chans):
            blk = f"encoder.down_blocks.{i}"
            for j in range(cfg.layers_per_block):
                resnet(f"{blk}.resnets.{j}", ("downs", i, "res", j),
                       cur, ch)
                cur = ch
            if i < n - 1:
                wb(f"{blk}.downsamplers.0.conv", "downs", i,
                   "down_conv")
        resnet("encoder.mid_block.resnets.0", ("mid_res1",), cur, cur)
        attn("encoder.mid_block.attentions.0", ("mid_attn",))
        resnet("encoder.mid_block.resnets.1", ("mid_res2",), cur, cur)
        wb("encoder.conv_norm_out", "norm_out")
        wb("encoder.conv_out", "conv_out")
    return m


def image_vae_transform(name: str, arr):
    """torch conv [O, I, kh, kw] -> [kh, kw, I, O] (NHWC); attention
    to_* linears [O, I] -> [I, O]."""
    if arr.ndim == 4:
        return arr.transpose(2, 3, 1, 0)
    if arr.ndim == 2:
        return arr.T
    return arr


def load_image_vae(
    vae_dir: str,
    dtype=jnp.float32,
    encoder: bool = False,
    decoder: bool = True,
):
    """Load a diffusers-format SD3/Flux-style AutoencoderKL directory.
    Returns ((decoder_params?, encoder_params?), VAEConfig) as a dict
    with "decoder"/"encoder" halves; raises unless every leaf of the
    requested halves is covered."""
    import jax
    import numpy as np

    from vllm_omni_tpu.models.qwen_image import vae as iv

    with open(os.path.join(vae_dir, "config.json")) as f:
        cfg = image_vae_config_from_diffusers(json.load(f))
    out: dict = {}
    halves = []
    if decoder:
        halves.append(("decoder", iv.init_decoder, False))
    if encoder:
        halves.append(("encoder", iv.init_encoder, True))
    for name, init_fn, is_enc in halves:
        shapes = jax.eval_shape(
            lambda init_fn=init_fn: init_fn(jax.random.PRNGKey(0), cfg,
                                            jnp.float32))
        tree = jax.tree.map(lambda t: np.zeros(t.shape, np.float32),
                            shapes)
        flat = image_vae_flat_map(cfg, encoder=is_enc,
                                  decoder=not is_enc)
        n, _ = load_checkpoint_tree(
            vae_dir, flat.get, tree, dtype=np.float32,
            transform=image_vae_transform,
            name_filter=lambda nm, flat=flat: nm in flat,
        )
        n_leaves = len(jax.tree.leaves(tree))
        if n < n_leaves:
            raise ValueError(
                f"{vae_dir} covered {n}/{n_leaves} {name} VAE weights")
        out[name] = jax.tree.map(lambda a: jnp.asarray(a, dtype), tree)
    return out, cfg
