"""Pattern-filtered HF-hub checkpoint download.

TPU-native counterpart of the reference's
``download_weights_from_hf_specific`` (reference:
vllm_omni/model_executor/model_loader/weight_utils.py — snapshot
download restricted to the tensor/config patterns a stage actually
needs; per-component savings apply when the repo shards per component,
see _SUBMODEL_PATTERNS).

Zero-egress stance: every loader in this package takes LOCAL paths;
this module is the single place network fetch happens, and only when
the caller passes a repo id that is not a local directory.  Offline
environments (HF_HUB_OFFLINE) get a clear error instead of a hang.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

from vllm_omni_tpu.logger import init_logger

logger = init_logger(__name__)

# submodel -> shard name patterns worth pulling (index + config always).
# NOTE: composite checkpoints sharded as model-XXXXX-of-YYYYY mix all
# submodels in shared files the hub cannot filter — per-component
# savings only materialize for repos that shard per component; pass
# allow_patterns=["*.safetensors"] to force everything
_SUBMODEL_PATTERNS = {
    "thinker": ["*thinker*"],
    "talker": ["*talker*"],
    "code2wav": ["*code2wav*"],
    "token2wav": ["*token2wav*"],
}

_ALWAYS = ["config.json", "*.index.json", "generation_config.json",
           "tokenizer*", "preprocessor_config.json", "model_index.json"]


def resolve_model_path(
    model: str,
    allow_patterns: Optional[Sequence[str]] = None,
    submodel: Optional[str] = None,
    revision: Optional[str] = None,
) -> str:
    """A local directory passes through; anything else snapshot-downloads
    (pattern-filtered) and returns the cache path.

    ``submodel`` picks a predefined pattern set ("talker" etc.);
    ``allow_patterns`` overrides it entirely.
    """
    if os.path.isdir(model) or os.path.isfile(model):
        return model
    offline = os.environ.get("HF_HUB_OFFLINE", "").upper() in (
        "1", "ON", "YES", "TRUE")  # huggingface_hub's env parsing
    try:
        from huggingface_hub import snapshot_download
    except ImportError as e:  # pragma: no cover - baked into the image
        raise FileNotFoundError(
            f"{model!r} is not a local path and huggingface_hub is "
            "unavailable") from e
    if offline:
        # a pre-warmed cache still resolves offline; a MISSING snapshot
        # errors here, but hub cannot verify per-file completeness
        # offline — a half-populated snapshot surfaces later as a
        # missing-shard error in the safetensors loader
        try:
            return snapshot_download(model, revision=revision,
                                     local_files_only=True)
        except Exception as e:
            raise FileNotFoundError(
                f"{model!r} is not a local path, HF_HUB_OFFLINE is set, "
                f"and the local HF cache cannot satisfy it ({e}) — "
                "download the checkpoint out of band and pass its "
                "directory") from e

    patterns = list(allow_patterns) if allow_patterns else list(
        _SUBMODEL_PATTERNS.get(submodel, ["*.safetensors"]))
    if submodel and not allow_patterns:
        # shared-shard composite repos carry no per-component files;
        # include the common shard naming so such repos still resolve
        patterns.append("model*.safetensors")
    patterns = list(dict.fromkeys(patterns + _ALWAYS))
    logger.info("downloading %s (patterns: %s)", model, patterns)
    try:
        return snapshot_download(model, revision=revision,
                                 allow_patterns=patterns)
    except Exception as e:
        raise FileNotFoundError(
            f"could not download {model!r} from the HF hub ({e}); in "
            "zero-egress environments pass a local checkpoint directory"
        ) from e
