"""Per-stage device-memory accounting.

Role of the reference's per-process GPU memory accounting
(vllm_omni/worker/gpu_memory_utils.py:22-124 — NVML per-process usage
feeding gpu_memory_utilization budgeting so co-located stages don't
fight over one device).  The TPU shape: stages that share a chip declare
an HBM fraction (the ``gpu_memory_utilization`` engine arg, kept for
config parity); the orchestrator validates the fractions fit before any
engine allocates, and each stage snapshots allocator stats after its
engine build so over-budget stages are flagged with numbers instead of
surfacing later as opaque RESOURCE_EXHAUSTED errors mid-request.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from vllm_omni_tpu.logger import init_logger

logger = init_logger(__name__)


def device_memory_stats() -> Optional[dict]:
    """Allocator stats of the first local device: {bytes_in_use,
    bytes_limit, peak_bytes_in_use} (None when the backend doesn't
    report — e.g. CPU)."""
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats()
    except (RuntimeError, AttributeError, IndexError):
        return None
    if not stats:
        return None
    return {
        "bytes_in_use": stats.get("bytes_in_use"),
        "bytes_limit": stats.get("bytes_limit"),
        "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
    }


@dataclass
class StageMemoryAccountant:
    """Budget bookkeeping for stages sharing one device."""

    # stage_id -> declared HBM fraction
    fractions: dict[int, float] = field(default_factory=dict)
    # stage_id -> bytes_in_use snapshot after engine build
    usage: dict[int, int] = field(default_factory=dict)
    # allocations that predate the stages (runtime buffers, caller
    # arrays) — captured once so they aren't billed to the first stage
    baseline: Optional[int] = None

    def capture_baseline(self) -> None:
        from vllm_omni_tpu.platforms import current_platform

        stats = current_platform().memory_stats()
        if stats and stats.get("bytes_in_use") is not None:
            self.baseline = stats["bytes_in_use"]

    def register(self, stage_id: int, fraction: float) -> None:
        if not (0.0 < fraction <= 1.0):
            raise ValueError(
                f"stage {stage_id}: hbm fraction must be in (0, 1], "
                f"got {fraction}")
        self.fractions[stage_id] = float(fraction)

    def validate(self) -> None:
        """Co-located stages must fit: sum of fractions <= 1 (the check
        the reference performs against NVML before engine init)."""
        total = sum(self.fractions.values())
        if total > 1.0 + 1e-6:
            raise ValueError(
                "stages sharing one device over-subscribe HBM: "
                f"sum of gpu_memory_utilization = {total:.2f} > 1.0 "
                f"({self.fractions}); lower the per-stage fractions")

    def snapshot(self, stage_id: int) -> Optional[dict]:
        """Record the stage's post-build usage and warn when it exceeds
        its declared share (stats come through the PLATFORM so
        out-of-tree backends' memory_stats overrides are honored)."""
        from vllm_omni_tpu.platforms import current_platform

        stats = current_platform().memory_stats()
        if stats is None or stats.get("bytes_in_use") is None:
            return None
        prev_total = sum(self.usage.values()) + (self.baseline or 0)
        own = max(0, stats["bytes_in_use"] - prev_total)
        self.usage[stage_id] = own
        limit = stats.get("bytes_limit")
        frac = self.fractions.get(stage_id)
        if limit and frac and own > frac * limit:
            logger.warning(
                "stage %d uses %.2f GiB (%.0f%% of device) but declared "
                "gpu_memory_utilization=%.2f — co-located stages may "
                "OOM; raise the fraction or move the stage to its own "
                "device", stage_id, own / 2**30, 100.0 * own / limit,
                frac)
        return {"bytes_in_use": own, "bytes_limit": limit,
                "fraction": frac}
