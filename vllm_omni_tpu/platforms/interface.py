"""Platform interface (reference: vllm_omni/platforms/interface.py:20
``OmniPlatform`` — per-platform worker classes, attention-backend selection,
device ops, default stage-config path)."""

from __future__ import annotations

from abc import ABC, abstractmethod


class OmniPlatform(ABC):
    name: str = "abstract"
    # Whether pallas kernels compile natively (TPU) or must run in
    # interpret mode (CPU tests).
    supports_pallas: bool = False

    @abstractmethod
    def ar_attention_backend(self) -> str:
        """Backend name for AR paged attention ("pallas_paged" | "xla")."""

    @abstractmethod
    def diffusion_attention_backend(self) -> str:
        """Backend name for DiT attention ("pallas_flash" | "xla")."""

    def device_count(self) -> int:
        import jax

        return jax.local_device_count()

    def preferred_dtype(self):
        import jax.numpy as jnp

        return jnp.bfloat16

    def __repr__(self) -> str:  # pragma: no cover
        return f"<OmniPlatform {self.name}>"
