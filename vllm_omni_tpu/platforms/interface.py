"""Platform interface (reference: vllm_omni/platforms/interface.py:20
``OmniPlatform`` — per-platform worker classes, attention-backend selection,
device ops, default stage-config path)."""

from __future__ import annotations

from abc import ABC, abstractmethod


class OmniPlatform(ABC):
    name: str = "abstract"
    # Whether pallas kernels compile natively (TPU) or must run in
    # interpret mode (CPU tests).
    supports_pallas: bool = False

    def initialize(self) -> None:
        """Once-per-process backend bring-up (PJRT plugin registration,
        topology discovery).  No-op by default; out-of-tree platforms
        override (see platforms/template.py for the full override-point
        catalogue)."""

    def memory_stats(self):
        """Allocator stats {bytes_in_use, bytes_limit,
        peak_bytes_in_use} or None (platforms/memory.py budgeting)."""
        from vllm_omni_tpu.platforms.memory import device_memory_stats

        return device_memory_stats()

    @abstractmethod
    def ar_attention_backend(self) -> str:
        """Backend name for AR paged attention ("pallas_paged" | "xla")."""

    @abstractmethod
    def diffusion_attention_backend(self) -> str:
        """Backend name for DiT attention ("pallas_flash" | "xla")."""

    def device_count(self) -> int:
        import jax

        return jax.local_device_count()

    def device_kind(self) -> str:
        import jax

        return jax.devices()[0].device_kind

    def hbm_bytes(self):
        """Per-device memory limit in bytes (None when the backend does
        not report it) — the TPU analogue of the reference's NVML
        per-process accounting (worker/gpu_memory_utils.py:22-124).
        Derived from memory_stats() so there is ONE allocator probe."""
        stats = self.memory_stats()
        return stats.get("bytes_limit") if stats else None

    def peak_tflops_bf16(self) -> float:
        """Peak dense bf16 TFLOP/s of one device (MFU denominators)."""
        return 0.0

    def peak_hbm_gbps(self) -> float:
        """Peak HBM GB/s of one device (MBU denominators for
        bandwidth-bound decode); 0 when unknown."""
        return 0.0

    def stage_device_env(self, devices: str = "all") -> dict:
        """Env applied to a spawned stage worker BEFORE jax import so the
        child binds only its share of the hardware (reference:
        set_stage_devices / CUDA_VISIBLE_DEVICES scoping,
        entrypoints/stage_utils.py)."""
        return {}

    def default_stage_config_dir(self) -> str:
        """Directory of in-tree stage YAMLs (reference:
        get_default_stage_config_path, platforms/interface.py:43-99);
        single source of truth lives in config/stage.py."""
        from vllm_omni_tpu.config.stage import _STAGE_CONFIG_DIR

        return _STAGE_CONFIG_DIR

    def preferred_dtype(self):
        import jax.numpy as jnp

        return jnp.bfloat16

    def __repr__(self) -> str:  # pragma: no cover
        return f"<OmniPlatform {self.name}>"
