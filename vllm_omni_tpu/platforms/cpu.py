"""CPU platform: unit tests + virtual multi-device meshes
(XLA_FLAGS=--xla_force_host_platform_device_count=N).  Mirrors the
reference's "cpu marker" test strategy (tests/conftest.py:10-11 forcing
VLLM_TARGET_DEVICE=cpu)."""

from __future__ import annotations

from vllm_omni_tpu import envs
from vllm_omni_tpu.platforms.interface import OmniPlatform


class CpuPlatform(OmniPlatform):
    name = "cpu"
    supports_pallas = False  # pallas runs in interpret mode only

    def ar_attention_backend(self) -> str:
        override = envs.OMNI_TPU_AR_ATTENTION_BACKEND
        if override != "auto":
            return override
        return "xla"

    def diffusion_attention_backend(self) -> str:
        override = envs.OMNI_TPU_DIFFUSION_ATTENTION_BACKEND
        if override != "auto":
            return override
        return "xla"

    def peak_tflops_bf16(self) -> float:
        return 0.5  # rough host-CPU figure; MFU on CPU is informational

    def peak_hbm_gbps(self) -> float:
        # rough dual-channel DDR figure; like the TFLOP/s peak above,
        # CPU MBU is informational — the gauges must still be finite
        # and nonzero so the metric surface exercises on the test lane
        return 50.0

    def stage_device_env(self, devices: str = "all") -> dict:
        # children must not grab a TPU the parent may hold — nor load
        # ambient TPU PJRT plugins whose sitecustomize hangs at startup
        # when the chip tunnel is unhealthy (scrub_plugin_sitedirs)
        import os

        from vllm_omni_tpu.platforms import scrub_plugin_sitedirs

        env = {"JAX_PLATFORMS": "cpu", "OMNI_TPU_PALLAS_INTERPRET": "1"}
        pp = os.environ.get("PYTHONPATH", "")
        if pp:
            env["PYTHONPATH"] = scrub_plugin_sitedirs(pp)
        return env

    def preferred_dtype(self):
        import jax.numpy as jnp

        return jnp.float32
