"""Platform resolution (reference: vllm_omni/platforms/__init__.py:153-165
``current_omni_platform`` lazy singleton).

On the reference, platform detection probes NVML/amdsmi/torch to pick
CUDA/ROCm/XPU/NPU.  Here the platforms are the JAX backends: TPU when a TPU
is attached, CPU otherwise (used for unit tests with a virtual device mesh).
Entry-point plugins can still override via ``register_platform``.
"""

from __future__ import annotations

from typing import Optional

from vllm_omni_tpu.platforms.interface import OmniPlatform

_current: Optional[OmniPlatform] = None
_registered: dict[str, type[OmniPlatform]] = {}


def register_platform(name: str, cls: type[OmniPlatform]) -> None:
    _registered[name] = cls


def _detect() -> OmniPlatform:
    import jax

    backend = jax.default_backend()
    if backend in _registered:
        return _registered[backend]()
    if backend == "tpu" or backend.startswith("axon"):
        from vllm_omni_tpu.platforms.tpu import TpuPlatform

        return TpuPlatform()
    from vllm_omni_tpu.platforms.cpu import CpuPlatform

    return CpuPlatform()


def current_platform() -> OmniPlatform:
    global _current
    if _current is None:
        # plugins first: a platform plugin registered for the active jax
        # backend must win detection (reference: entry-point override,
        # platforms/__init__.py:118-151)
        from vllm_omni_tpu.plugins import load_plugins

        load_plugins()
        _current = _detect()
        # once-per-process backend bring-up (PJRT plugin registration
        # etc. for out-of-tree platforms — template.py)
        _current.initialize()
    return _current


def reset_platform() -> None:
    """Testing hook."""
    global _current
    _current = None


def scrub_plugin_sitedirs(pythonpath: str) -> str:
    """Drop PYTHONPATH entries whose sitecustomize eagerly initializes a
    hardware backend (they hang CPU-scoped children at interpreter
    startup when the device tunnel is unhealthy).  The entry pattern is
    the OMNI_TPU_STRIP_SITEDIRS env var (substring match on the path
    basename; default "axon" for the TPU tunnel plugin deployment)."""
    import os

    pattern = os.environ.get("OMNI_TPU_STRIP_SITEDIRS", "axon")
    if not pythonpath or not pattern:
        return pythonpath
    keep = [p for p in pythonpath.split(os.pathsep)
            if p and pattern not in os.path.basename(p)]
    return os.pathsep.join(keep)


def default_stage_device_env(devices: str = "all") -> dict:
    """Child-process device scoping WITHOUT initializing jax in the
    caller: the orchestrator parent of an all-process pipeline must never
    touch the TPU runtime itself (acquiring the chips its children need),
    so this sniffs environment variables only.  The per-platform
    ``stage_device_env`` methods remain for callers that already hold a
    platform."""
    import os

    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        from vllm_omni_tpu.platforms.cpu import CpuPlatform

        return CpuPlatform().stage_device_env(devices)
    if devices in ("", "all"):
        return {}
    from vllm_omni_tpu.platforms.tpu import TpuPlatform

    return TpuPlatform().stage_device_env(devices)
