"""Platform resolution (reference: vllm_omni/platforms/__init__.py:153-165
``current_omni_platform`` lazy singleton).

On the reference, platform detection probes NVML/amdsmi/torch to pick
CUDA/ROCm/XPU/NPU.  Here the platforms are the JAX backends: TPU when a TPU
is attached, CPU otherwise (used for unit tests with a virtual device mesh).
Entry-point plugins can still override via ``register_platform``.
"""

from __future__ import annotations

from typing import Optional

from vllm_omni_tpu.platforms.interface import OmniPlatform

_current: Optional[OmniPlatform] = None
_registered: dict[str, type[OmniPlatform]] = {}


def register_platform(name: str, cls: type[OmniPlatform]) -> None:
    _registered[name] = cls


def _detect() -> OmniPlatform:
    import jax

    backend = jax.default_backend()
    if backend in _registered:
        return _registered[backend]()
    if backend == "tpu" or backend.startswith("axon"):
        from vllm_omni_tpu.platforms.tpu import TpuPlatform

        return TpuPlatform()
    from vllm_omni_tpu.platforms.cpu import CpuPlatform

    return CpuPlatform()


def current_platform() -> OmniPlatform:
    global _current
    if _current is None:
        _current = _detect()
    return _current


def reset_platform() -> None:
    """Testing hook."""
    global _current
    _current = None
