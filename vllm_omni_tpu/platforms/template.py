"""Second-backend platform template — every override point, documented.

Role of the reference's out-of-tree platform support (reference:
vllm_omni/platforms/interface.py:20 ``OmniPlatform`` + NPU plugin
platforms resolved through entry points): a new accelerator backend
("NPU-grade" port) subclasses ``OmniPlatform``, overrides the hooks
below, and registers itself — either programmatically
(``platforms.register_platform``) or through the
``vllm_omni_tpu.platforms`` entry-point group — WITHOUT touching
in-tree code.  ``ExamplePlatform`` here is a complete, runnable
instance (it executes on the CPU backend, standing in for a device
whose pallas kernels don't compile), used by the platform-template
tests to prove a third-party backend drives the full engine stack.

Override points and what consumes each:

==========================  =============================================
hook                        consumed by
==========================  =============================================
ar_attention_backend        ops/_dispatch.py — paged-attention impl pick
diffusion_attention_backend ops/_dispatch.py — DiT flash-attention pick
supports_pallas             ops (interpret-mode fallback for kernels)
preferred_dtype             config/model.resolve_dtype ("auto" dtype)
stage_device_env            spawned stage workers' pre-import env
hbm_bytes / memory_stats    platforms/memory.py stage HBM budgeting
peak_tflops_bf16            bench.py MFU denominators
initialize                  once-per-process backend bring-up
==========================  =============================================
"""

from __future__ import annotations

from vllm_omni_tpu.platforms.interface import OmniPlatform


class ExamplePlatform(OmniPlatform):
    """A fully-wired example backend (CPU execution underneath).

    A real port changes: the attention backends to its kernel library,
    ``stage_device_env`` to its device-visibility env vars,
    ``peak_tflops_bf16`` to the chip's spec sheet, and ``initialize``
    to its runtime bring-up (plugin registration, topology discovery).
    """

    name = "example"
    supports_pallas = False  # kernels run via the XLA fallbacks

    def initialize(self) -> None:
        """Once-per-process backend bring-up.  A real device plugin
        would initialize its PJRT client / driver here; the example
        needs nothing."""

    def ar_attention_backend(self) -> str:
        return "xla"

    def diffusion_attention_backend(self) -> str:
        return "xla"

    def preferred_dtype(self):
        import jax.numpy as jnp

        return jnp.float32

    def peak_tflops_bf16(self) -> float:
        return 1.0  # spec-sheet number of the ported device

    def stage_device_env(self, devices: str = "all") -> dict:
        # the env a spawned worker needs to bind only its device share
        # (the CUDA_VISIBLE_DEVICES / TPU_VISIBLE_CHIPS analogue)
        return {"JAX_PLATFORMS": "cpu", "OMNI_TPU_PALLAS_INTERPRET": "1"}
