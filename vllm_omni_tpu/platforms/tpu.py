"""TPU platform (role of reference's platforms/cuda/platform.py:15 — picks
worker classes and attention backends per device capability)."""

from __future__ import annotations

from vllm_omni_tpu import envs
from vllm_omni_tpu.platforms.interface import OmniPlatform


class TpuPlatform(OmniPlatform):
    name = "tpu"
    supports_pallas = True

    def ar_attention_backend(self) -> str:
        override = envs.OMNI_TPU_AR_ATTENTION_BACKEND
        if override != "auto":
            return override
        return "pallas_paged"

    def diffusion_attention_backend(self) -> str:
        override = envs.OMNI_TPU_DIFFUSION_ATTENTION_BACKEND
        if override != "auto":
            return override
        return "pallas_flash"

    # (peak dense bf16 TFLOP/s, peak HBM GB/s) per chip generation —
    # public spec sheet numbers; MFU / MBU denominators.  One table so a
    # new generation cannot land in one metric and not the other.
    _PEAK_TABLE = {
        "v4": (275.0, 1228.0),
        "v5 lite": (197.0, 819.0), "v5e": (197.0, 819.0),
        "v5litepod": (197.0, 819.0), "v5p": (459.0, 2765.0),
        "v6 lite": (918.0, 1640.0), "v6e": (918.0, 1640.0),
    }

    def _peaks(self) -> tuple:
        kind = self.device_kind().lower()
        for k, v in self._PEAK_TABLE.items():
            if k in kind:
                return v
        return (197.0, 819.0)  # unlisted generation: v5e floor

    def peak_tflops_bf16(self) -> float:
        return self._peaks()[0]

    def peak_hbm_gbps(self) -> float:
        return self._peaks()[1]

    def stage_device_env(self, devices: str = "all") -> dict:
        if devices in ("", "all"):
            return {}
        # libtpu chip-scoping recipe (as used for single-host
        # multi-process): visible chips + process bounds + chips-per-
        # process bounds matching the subset size
        n = len([d for d in devices.split(",") if d])
        return {"TPU_VISIBLE_CHIPS": devices,
                "TPU_PROCESS_BOUNDS": "1,1,1",
                "TPU_CHIPS_PER_PROCESS_BOUNDS": f"{n},1,1"}
