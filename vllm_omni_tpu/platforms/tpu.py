"""TPU platform (role of reference's platforms/cuda/platform.py:15 — picks
worker classes and attention backends per device capability)."""

from __future__ import annotations

from vllm_omni_tpu import envs
from vllm_omni_tpu.platforms.interface import OmniPlatform


class TpuPlatform(OmniPlatform):
    name = "tpu"
    supports_pallas = True

    def ar_attention_backend(self) -> str:
        override = envs.OMNI_TPU_AR_ATTENTION_BACKEND
        if override != "auto":
            return override
        return "pallas_paged"

    def diffusion_attention_backend(self) -> str:
        override = envs.OMNI_TPU_DIFFUSION_ATTENTION_BACKEND
        if override != "auto":
            return override
        return "pallas_flash"
