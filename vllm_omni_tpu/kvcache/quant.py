"""Shared absmax int8 quantization for KV caches (resident + cold).

One module owns the scale layout and rounding so the PR 6 cold-offload
quantizer (``kvcache/tiers.py``) and the PR 20 HBM-resident quantized
page pool (``ops/paged_attention.py`` / ``ops/ragged_paged_attention.py``)
can never drift apart:

- rounding:   ``q = clip(round(x / scale), -127, 127)`` as int8,
  ``scale = max(absmax / 127, SCALE_EPS)`` as float32 — symmetric absmax,
  the same stance as ``diffusion/quantization``.
- resident layout: each paged cache half becomes a 2-tuple
  ``(data int8 [Hkv, P, page_size, D], scale f32 [Hkv, P])`` — ONE scale
  per (kv-head, page) so the ragged kernel's page DMA fetches a page's
  bytes plus a single scalar per head and dequantizes in-register.
- wire layout (extract/inject/disagg handoff): per-layer
  ``[((kq, ks), (vq, vs))]`` with ``kq`` int8 ``[Hkv, S, D]`` and ``ks``
  f32 ``[Hkv, ceil(S / page_size)]`` run-relative page scales — the
  resident layout with the page pool indirection flattened out, so an
  int8→int8 handoff round-trips bit-exactly (no re-quantization).
- cold layout (tiers.py dict): per-(layer, tensor, head) scales over the
  whole run; coarser, kept for the ``kv_offload_quant`` path whose
  payloads start dense.

Capacity math lives here too (``page_bytes`` / ``pages_for_budget``):
an int8 page costs ``Hkv*(page_size*D + 4)`` bytes per half vs
``Hkv*page_size*D*itemsize`` for bf16 — ~2x more pages in the same HBM
budget (1.94x at the tiny test dims, 2.0x at D=128/page_size=16).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

QMAX = 127.0
SCALE_EPS = 1e-12


# ------------------------------------------------------- primitives (np)
def quantize_np(a: np.ndarray, axis) -> tuple[np.ndarray, np.ndarray]:
    """Absmax-quantize ``a`` over ``axis`` (kept as size-1 dims).

    Returns (int8 body, float32 scale) with the module's single rounding
    definition; ``dequantize_np`` inverts it up to rounding error."""
    a = np.asarray(a, dtype=np.float32)
    absmax = np.max(np.abs(a), axis=axis, keepdims=True)
    scale = np.maximum(absmax / QMAX, SCALE_EPS).astype(np.float32)
    q = np.clip(np.round(a / scale), -QMAX, QMAX).astype(np.int8)
    return q, scale


def dequantize_np(q: np.ndarray, scale: np.ndarray) -> np.ndarray:
    return q.astype(np.float32) * scale


# ------------------------------------------------- wire-payload helpers
def is_quant_payload(payload) -> bool:
    """True for the quantized wire layout ``[((kq, ks), (vq, vs))]``
    (each half a (data, scale) pair) vs the dense ``[(k, v)]`` layout
    (each half a bare array)."""
    if not payload:
        return False
    return isinstance(payload[0][0], (tuple, list))


def payload_seq_len(payload) -> int:
    """Token-run length of a dense or quantized wire payload."""
    half = payload[0][0]
    return int(half[0].shape[1] if is_quant_payload(payload)
               else half.shape[1])


def payload_num_heads(payload) -> int:
    half = payload[0][0]
    return int(half[0].shape[0] if is_quant_payload(payload)
               else half.shape[0])


def trim_payload(payload, use: int, page_size: int):
    """First ``use`` tokens of a wire payload (either layout).

    Quantized payloads trim the data on the token axis and the scales on
    the run-page axis — scales stay valid because a page scale bounds
    every token it covered, a superset of the kept prefix."""
    if not is_quant_payload(payload):
        return [(k[:, :use], v[:, :use]) for k, v in payload]
    pages = max(1, -(-use // page_size))
    return [((kq[:, :use], ks[:, :pages]), (vq[:, :use], vs[:, :pages]))
            for (kq, ks), (vq, vs) in payload]


def concat_payloads(parts: list, page_size: int) -> Optional[list]:
    """Concatenate per-part wire payloads along the token axis into one
    payload (the radix restore path stitches per-page node payloads).

    All parts must share a layout.  Quantized parts additionally need
    page-aligned token runs (every part but the last a multiple of
    ``page_size``) so the per-page scale axes concatenate without
    splitting a page across parts; radix node payloads are single full
    pages, so this always holds there.  A mixed or misaligned set falls
    back to dense concat via ``dequantize_payload``."""
    if not parts:
        return None
    quant_flags = [is_quant_payload(p) for p in parts]
    if any(quant_flags):
        aligned = all(
            payload_seq_len(p) % page_size == 0 for p in parts[:-1])
        if not (all(quant_flags) and aligned):
            parts = [dequantize_payload(p, page_size)
                     if q else p for p, q in zip(parts, quant_flags)]
            return concat_payloads(parts, page_size)
        n_layers = len(parts[0])
        out = []
        for i in range(n_layers):
            kq = np.concatenate([np.asarray(p[i][0][0]) for p in parts],
                                axis=1)
            ks = np.concatenate([np.asarray(p[i][0][1]) for p in parts],
                                axis=1)
            vq = np.concatenate([np.asarray(p[i][1][0]) for p in parts],
                                axis=1)
            vs = np.concatenate([np.asarray(p[i][1][1]) for p in parts],
                                axis=1)
            out.append(((kq, ks), (vq, vs)))
        return out
    n_layers = len(parts[0])
    return [
        (np.concatenate([np.asarray(p[i][0]) for p in parts], axis=1),
         np.concatenate([np.asarray(p[i][1]) for p in parts], axis=1))
        for i in range(n_layers)
    ]


def _dequant_half(q: np.ndarray, s: np.ndarray,
                  page_size: int) -> np.ndarray:
    """(int8 [Hkv, S, D], f32 [Hkv, n_pages]) -> f32 [Hkv, S, D]."""
    q = np.asarray(q)
    s = np.asarray(s)
    seq = q.shape[1]
    per_tok = np.repeat(s, page_size, axis=1)[:, :seq]
    return q.astype(np.float32) * per_tok[:, :, None]


def dequantize_payload(payload, page_size: int) -> list:
    """Quantized wire payload -> dense float32 ``[(k, v)]`` payload."""
    if not is_quant_payload(payload):
        return payload
    return [(_dequant_half(kq, ks, page_size),
             _dequant_half(vq, vs, page_size))
            for (kq, ks), (vq, vs) in payload]


def quantize_payload(payload, page_size: int) -> list:
    """Dense ``[(k, v)]`` ([Hkv, S, D]) -> quantized wire payload with
    per-(head, run-page) scales — the exact scales an int8-resident pool
    would hold for these tokens, so injecting the result re-quantizes
    nothing."""
    if is_quant_payload(payload):
        return payload
    out = []
    for k, v in payload:
        halves = []
        for arr in (k, v):
            a = np.asarray(arr, dtype=np.float32)
            hkv, seq, d = a.shape
            n_pages = max(1, -(-seq // page_size))
            pad = n_pages * page_size - seq
            ap = np.pad(a, ((0, 0), (0, pad), (0, 0)))
            ap = ap.reshape(hkv, n_pages, page_size, d)
            absmax = np.max(np.abs(ap), axis=(2, 3))
            scale = np.maximum(absmax / QMAX, SCALE_EPS).astype(np.float32)
            q = np.clip(np.round(ap / scale[:, :, None, None]),
                        -QMAX, QMAX).astype(np.int8)
            halves.append((q.reshape(hkv, -1, d)[:, :seq], scale))
        out.append((halves[0], halves[1]))
    return out


def payload_wire_nbytes(payload) -> int:
    """Handoff bytes of a wire payload (either layout)."""
    total = 0
    for layer in payload:
        for half in layer:
            if isinstance(half, (tuple, list)):
                total += sum(np.asarray(a).nbytes for a in half)
            else:
                total += np.asarray(half).nbytes
    return total


# --------------------------------------------------------- capacity math
def page_bytes(num_kv_heads: int, page_size: int, head_dim: int,
               quantized: bool, itemsize: int = 2) -> int:
    """HBM bytes of ONE page (k + v halves) for ONE layer, including the
    per-(head, page) scales on the quantized layout — the unit the page
    pool is sized in and the ledger accounts."""
    if quantized:
        return 2 * num_kv_heads * (page_size * head_dim + 4)
    return 2 * num_kv_heads * page_size * head_dim * itemsize


def bytes_per_token(num_layers: int, num_kv_heads: int, page_size: int,
                    head_dim: int, quantized: bool,
                    itemsize: int = 2) -> float:
    """Amortized HBM bytes per cached token across all layers."""
    return num_layers * page_bytes(
        num_kv_heads, page_size, head_dim, quantized, itemsize
    ) / page_size


def pages_for_budget(budget_bytes: int, num_layers: int,
                     num_kv_heads: int, page_size: int, head_dim: int,
                     quantized: bool, itemsize: int = 2) -> int:
    """Page-pool size that fits ``budget_bytes`` of HBM under the given
    layout; with int8 this lands >=1.8x the bf16 count for the same
    budget (the acceptance floor — exactly 2x minus the scale array)."""
    per_page = num_layers * page_bytes(
        num_kv_heads, page_size, head_dim, quantized, itemsize)
    return max(1, int(budget_bytes) // per_page)
