"""KV tier hierarchy: HBM → pinned host-RAM pool → remote store.

Holds the COLD copies of parked KV payloads and the bytes-moved
discipline around them.  A payload is the per-layer ``[(k, v)]`` numpy
arrays for one radix node (one full page) or one parked request
(arbitrary token run) — always moved as ONE pytree transfer
(``worker/model_runner.py`` batches the device halves), never per-page.

Tiers:

- **host** — an LRU ``OrderedDict`` of payloads in (pinned) host RAM,
  capped by ``host_capacity_bytes``; overflow demotes the oldest
  entries to the remote tier (or drops them when no remote edge is
  configured — the radix index prunes the now-unbacked nodes at the
  next match).
- **remote** — the existing connector/TCP-store layer
  (``distributed/connectors.py`` / ``distributed/tcp.py``), wrapped in
  the PR 3 retry policy + circuit breaker so a flapping remote store
  degrades to recompute instead of wedging the scheduler.

Cold payloads optionally quantize to int8 (per-(layer, head) absmax
scales, the same scale machinery stance as ``diffusion/quantization``):
at ~0.15 GB/s every byte on the tunnel is latency, and int8 halves the
bf16 cold path.  ``quant == "none"`` (default) keeps payloads bit-exact
so restored greedy streams match the never-offloaded oracle.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Optional

import numpy as np

from vllm_omni_tpu.kvcache.quant import (
    dequantize_np,
    is_quant_payload,
    quantize_np,
)
from vllm_omni_tpu.logger import init_logger

logger = init_logger(__name__)

TIER_HBM = "hbm"
TIER_HOST = "host"
TIER_REMOTE = "remote"


# ---------------------------------------------------------- quantization
def quantize_kv_payload(payload: list) -> dict:
    """Dense [(k, v)] float arrays ([Hkv, S, D]) -> int8 bodies + per-head
    float32 absmax scales (rounding shared with ``kvcache/quant.py``).
    Mirrors diffusion/quantization's per-out-channel absmax stance,
    applied per (layer, tensor, head)."""
    layers = []
    for k, v in payload:
        out = []
        for arr in (k, v):
            q, scale = quantize_np(arr, axis=(1, 2))
            out.append((q, scale, str(np.asarray(arr).dtype)))
        layers.append(tuple(out))
    return {"quant": "int8", "layers": layers}


def dequantize_kv_payload(obj: dict) -> list:
    payload = []
    for (kq, ks, kd), (vq, vs, vd) in obj["layers"]:
        payload.append((dequantize_np(kq, ks).astype(kd),
                        dequantize_np(vq, vs).astype(vd)))
    return payload


def _resident_wrap(payload: list) -> dict:
    """Already-quantized wire payload (int8-resident extraction,
    ``kvcache/quant.py`` layout) stored AS-IS — re-quantizing int8 data
    would double the rounding error, and dequantizing to bf16 to satisfy
    the cold format would double the bytes.  ``fetch`` hands the list
    straight back for an exact int8->int8 restore."""
    layers = [((np.asarray(kq), np.asarray(ks)),
               (np.asarray(vq), np.asarray(vs)))
              for (kq, ks), (vq, vs) in payload]
    return {"quant": "int8", "resident": True, "layers": layers}


def payload_nbytes(payload) -> int:
    """Stored size of a payload (raw [(k, v)], quantized wire list, or
    stored dict of either cold flavor)."""
    if isinstance(payload, dict):
        payload = payload["layers"]

    def walk(node) -> int:
        if isinstance(node, (tuple, list)):
            return sum(walk(x) for x in node)
        if isinstance(node, str):
            return 0
        return np.asarray(node).nbytes

    return walk(payload)


class TieredKVStore:
    """Cold-side owner of parked KV payloads, keyed by radix node key
    (shared prefixes) or ``park/{request_id}`` (preempted sessions).

    Counters are cumulative and feed the ``kv_offload_bytes_total
    {tier,dir}`` / ``kv_tier_*_pages`` series on ``/metrics``."""

    def __init__(self, quant: str = "none",
                 host_capacity_bytes: Optional[int] = None,
                 remote: Optional[Any] = None,
                 remote_namespace: str = "kvcache"):
        if quant not in ("none", "int8"):
            raise ValueError(f"unknown kv quant mode {quant!r}")
        self.quant = quant
        self.host_capacity_bytes = host_capacity_bytes
        self._host: "OrderedDict[str, Any]" = OrderedDict()
        self._host_bytes = 0
        # keys known to live remotely (the remote store is write-once
        # per key; this set is the host-side directory)
        self._remote_keys: set[str] = set()
        self._remote = remote
        self._ns = remote_namespace
        if remote is not None:
            from vllm_omni_tpu.resilience.retry import (
                CircuitBreaker,
                RetryPolicy,
            )

            self._retry = RetryPolicy(max_attempts=3, base_delay_s=0.01)
            self._breaker = CircuitBreaker(site="kvcache_remote")
        # bytes moved per (tier, dir) — dir "out" = away from HBM,
        # "in" = back toward it
        self.bytes_moved: dict[tuple[str, str], int] = {}
        self.restored_tokens = 0

    # ------------------------------------------------------------ lookup
    def tier_of(self, key: str) -> Optional[str]:
        if key in self._host:
            return TIER_HOST
        if key in self._remote_keys:
            return TIER_REMOTE
        return None

    def has(self, key: str) -> bool:
        return self.tier_of(key) is not None

    # ------------------------------------------------------------- sizes
    def host_entries(self) -> int:
        return len(self._host)

    def remote_entries(self) -> int:
        return len(self._remote_keys)

    def host_bytes(self) -> int:
        return self._host_bytes

    def _count(self, tier: str, direction: str, n: int) -> None:
        k = (tier, direction)
        self.bytes_moved[k] = self.bytes_moved.get(k, 0) + int(n)

    # --------------------------------------------------------------- put
    def put(self, key: str, payload: list) -> int:
        """Park a payload in the host tier (quantizing per policy);
        returns stored bytes.  Overflow demotes LRU host entries to the
        remote tier, or drops them without one."""
        if is_quant_payload(payload):
            # int8-resident extraction: already quantized once at
            # KV-write time — park it verbatim (never double-quantize)
            stored: Any = _resident_wrap(payload)
        elif self.quant == "int8":
            stored = quantize_kv_payload(payload)
        else:
            stored = [(np.asarray(k), np.asarray(v)) for k, v in payload]
        n = payload_nbytes(stored)
        old = self._host.pop(key, None)
        if old is not None:
            self._host_bytes -= payload_nbytes(old)
        self._host[key] = stored
        self._host_bytes += n
        self._count(TIER_HOST, "out", n)
        self._shed()
        return n

    def _shed(self) -> None:
        if self.host_capacity_bytes is None:
            return
        while (self._host_bytes > self.host_capacity_bytes
               and len(self._host) > 1):
            key, stored = self._host.popitem(last=False)
            n = payload_nbytes(stored)
            self._host_bytes -= n
            if self._remote is not None and self._remote_put(key, stored):
                self._count(TIER_REMOTE, "out", n)
                self._remote_keys.add(key)
            else:
                logger.debug("kv tier store: dropped %s (%d B, no "
                             "remote tier)", key, n)

    # --------------------------------------------------------------- get
    def fetch(self, key: str) -> Optional[list]:
        """Payload for ``key``, promoted back through the tiers:
        remote hits re-park in the host tier (the next restore of a
        popular prefix skips the slow edge).  Returns the DEQUANTIZED
        per-layer [(k, v)] list, or None when the payload is gone."""
        stored = self._host.get(key)
        if stored is not None:
            self._host.move_to_end(key)
            self._count(TIER_HOST, "in", payload_nbytes(stored))
        elif key in self._remote_keys:
            stored = self._remote_get(key)
            if stored is None:
                self._remote_keys.discard(key)
                return None
            n = payload_nbytes(stored)
            self._count(TIER_REMOTE, "in", n)
            # promote: popular prefixes climb back to the faster tier
            self._host[key] = stored
            self._host_bytes += n
            self._shed()
        else:
            return None
        if isinstance(stored, dict):
            if stored.get("resident"):
                # quantized wire payload: hand back as-is — an int8
                # runner re-injects it bit-exactly, a bf16 runner's
                # inject path dequantizes (kvcache/quant.py)
                return [((kq, ks), (vq, vs))
                        for (kq, ks), (vq, vs) in stored["layers"]]
            return dequantize_kv_payload(stored)
        return [(k, v) for k, v in stored]

    def drop(self, key: str) -> None:
        stored = self._host.pop(key, None)
        if stored is not None:
            self._host_bytes -= payload_nbytes(stored)
        if key in self._remote_keys:
            self._remote_keys.discard(key)
            if self._remote is not None:
                try:
                    self._remote.cleanup(self._rkey(key))
                except Exception:  # noqa: BLE001 - best-effort GC
                    pass

    def clear(self) -> None:
        for key in list(self._host) + list(self._remote_keys):
            self.drop(key)

    # ------------------------------------------------------- remote edge
    def _rkey(self, key: str) -> str:
        return f"{self._ns}/{key}"

    def _remote_put(self, key: str, stored: Any) -> bool:
        from vllm_omni_tpu.resilience.retry import call_with_retry

        try:
            call_with_retry(
                lambda: self._remote.put(self._rkey(key), stored),
                policy=self._retry, breaker=self._breaker,
                site="kvcache_remote",
            )
            return True
        except Exception as e:  # noqa: BLE001 - any failure = payload
            # unavailable; callers degrade to recompute.  Transient
            # errors were already retried; a non-transient one (store
            # ST_ERR, serialization) must not kill the engine step
            logger.warning("kv remote tier put failed for %s: %s",
                           key, e)
            return False

    def _remote_get(self, key: str) -> Optional[Any]:
        from vllm_omni_tpu.resilience.retry import call_with_retry

        try:
            stored = call_with_retry(
                lambda: self._remote.get(self._rkey(key), timeout=None),
                policy=self._retry, breaker=self._breaker,
                site="kvcache_remote",
            )
        except Exception as e:  # noqa: BLE001 - any failure = payload
            # unavailable (incl. a corrupt payload failing to decode);
            # the lost-payload path recomputes instead of wedging
            logger.warning("kv remote tier get failed for %s: %s",
                           key, e)
            return None
        if stored is None:
            return None
        # connector get() semantics pop the key on some transports
        # (the TCP store's blocking pop): re-publish so other replicas
        # and a later fall-from-host still find it
        self._remote_put(key, stored)
        return stored

    # ------------------------------------------------------------- stats
    def stats(self) -> dict:
        return {
            "host_entries": self.host_entries(),
            "remote_entries": self.remote_entries(),
            "host_bytes": self._host_bytes,
            "bytes_moved": {
                f"{tier}/{d}": n
                for (tier, d), n in sorted(self.bytes_moved.items())},
            "restored_tokens": self.restored_tokens,
            "quant": self.quant,
        }

    def debug_snapshot(self, key_limit: int = 32) -> dict:
        """stats() plus a BOUNDED sample of resident keys per tier for
        /debug/kv — enough to see which park runs / prefix nodes are
        parked where, without serializing a fleet-sized directory."""
        doc = self.stats()
        doc["host_capacity_bytes"] = self.host_capacity_bytes
        # oldest-first (the LRU's next demotion victims lead the list)
        doc["host_keys"] = list(self._host)[:key_limit]
        doc["remote_keys"] = sorted(self._remote_keys)[:key_limit]
        doc["has_remote"] = self._remote is not None
        return doc
