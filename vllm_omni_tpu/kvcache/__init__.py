"""Fleet-scale KV economics: shared radix prefix index + tiered offload.

The subsystem behind ROADMAP item 2 — serving millions of users means
massive system-prompt / multi-turn prefix overlap and far more live
sessions than one chip's HBM can hold:

- ``radix.py``  — token-keyed radix (page-trie) prefix index over full
  KV pages: reference-counted nodes shared across concurrent requests
  and tenants, longest-prefix match at admission, leaf-first LRU
  eviction (a prefix is never evicted before its extensions, unlike the
  flat chained-hash map it replaces), per-node tier residency.
- ``tiers.py``  — the KV tier hierarchy HBM → pinned host RAM → remote
  store (connector/TCP-store layer with PR 3 retry/breaker policies),
  with optional int8 quantization on the cold path and bytes-moved /
  occupancy counters for ``/metrics``.
- ``policy.py`` — bytes-saved-vs-recompute admission heuristic: on this
  tunnel host↔HBM moves ~0.1–0.2 GB/s, so the cold path must earn its
  transfers.

``core/kv_cache_manager.py`` owns page ids and queues device moves;
``engine/llm_engine.py`` drains those queues between schedule() and
execute() with ONE batched pytree transfer per direction per step.
See docs/kv_cache.md.
"""

from vllm_omni_tpu.kvcache.policy import OffloadPolicy
from vllm_omni_tpu.kvcache.radix import RadixNode, RadixPrefixIndex
from vllm_omni_tpu.kvcache.tiers import (
    TIER_HBM,
    TIER_HOST,
    TIER_REMOTE,
    TieredKVStore,
)

__all__ = [
    "OffloadPolicy",
    "RadixNode",
    "RadixPrefixIndex",
    "TieredKVStore",
    "TIER_HBM",
    "TIER_HOST",
    "TIER_REMOTE",
]
