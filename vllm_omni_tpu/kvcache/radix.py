"""Radix prefix index: a token-keyed page-trie over full KV pages.

Replaces the flat chained-hash dict that ``KVCacheManager`` used for
automatic prefix caching.  Each node covers exactly ONE full page
(``page_size`` tokens); children are keyed by the next page's token
tuple, so a root-to-node path spells out a prompt prefix at page
granularity.  What the tree buys over the flat map:

- **Shape-aware eviction.**  The flat map's LRU could evict a *middle*
  page of a chain, silently orphaning every suffix entry behind it
  (the orphans stay in the dict, can never match again, and still
  occupy pages).  The trie evicts deepest-first: a prefix outlives its
  extensions, so everything the index holds stays reachable and every
  cached page stays adoptable — partial overlap between sessions keeps
  paying off even under pressure.
- **Reference-counted sharing across tenants.**  A node's refcount is
  the number of live request tables adopting its page.  Adoption
  always covers a contiguous root-path prefix, which yields the
  load-bearing invariant ``node.ref >= child.ref`` — an unreferenced
  node's whole subtree is unreferenced, so reclaiming its page can
  never cut a live request's context chain.
- **Tier residency.**  A node records where its KV bytes live —
  ``TIER_HBM`` (its ``page`` id is valid device storage) or parked
  cold (host/remote; payload looked up in the ``TieredKVStore`` by the
  node's ``key``).  A cold node STAYS in the tree: longest-prefix
  match can adopt it, and the manager allocates a fresh page and
  queues a restore.

The index never touches jax: it maps token content to page ids and
tier keys.  Device bytes move in ``worker/model_runner.py``; the
``TieredKVStore`` (tiers.py) holds the cold copies.
"""

from __future__ import annotations

import hashlib
import heapq
from itertools import count
from typing import Iterator, Optional

from vllm_omni_tpu.kvcache.tiers import TIER_HBM


def chain_page_keys(token_ids, page_size: int,
                    max_pages: Optional[int] = None
                    ) -> list[tuple[tuple[int, ...], str]]:
    """[(page token tuple, chain-hash key)] for the FULL pages of
    ``token_ids`` — the chained content address shared by every index
    (a page's key commits to every page before it, so equal keys mean
    equal whole prefixes).  Module-level so consumers that never hold
    an index (the router's cache-economics board computing dispatch
    coverage against exported digests) can derive the same keys."""
    if page_size < 1:
        raise ValueError("page_size must be positive")
    out = []
    prev = b""
    n_full = len(token_ids) // page_size
    if max_pages is not None:
        n_full = min(n_full, max_pages)
    for p in range(n_full):
        chunk = tuple(
            int(t) for t in
            token_ids[p * page_size: (p + 1) * page_size])
        h = hashlib.blake2b(
            prev + b"," + repr(list(chunk)).encode(), digest_size=16
        ).hexdigest()
        out.append((chunk, h))
        prev = h.encode()
    return out


class RadixNode:
    """One full KV page of a shared prompt prefix."""

    __slots__ = ("parent", "children", "tokens", "key", "page", "ref",
                 "tier", "last_use", "hbm_desc")

    def __init__(self, parent: Optional["RadixNode"],
                 tokens: tuple[int, ...], key: str,
                 page: Optional[int], tier: str = TIER_HBM):
        self.parent = parent
        self.children: dict[tuple[int, ...], RadixNode] = {}
        self.tokens = tokens
        # stable content address: chain hash of the root→node token
        # path — doubles as the cold-tier storage key (tiers.py)
        self.key = key
        # device page holding this node's KV; None while parked cold
        self.page = page
        self.ref = 0
        self.tier = tier
        self.last_use = 0
        # HBM pages among strict descendants, maintained incrementally
        # (``_adjust_hbm_desc``): ``hbm_desc == 0`` makes an
        # unreferenced HBM node an eviction candidate without a
        # subtree walk
        self.hbm_desc = 0


class RadixPrefixIndex:
    def __init__(self, page_size: int):
        if page_size < 1:
            raise ValueError("page_size must be positive")
        self.page_size = page_size
        self._root = RadixNode(None, (), "", None)
        # logical LRU clock: ticks on every match/insert touch, so
        # eviction order is deterministic and test-replayable
        self._clock = 0
        # page id -> node, for pin/evict cross-checks and invariants
        self._by_page: dict[int, RadixNode] = {}
        # incrementally maintained count of unreferenced HBM nodes:
        # ``evictable`` sits on the scheduler's per-step hot path
        # (num_free_pages / can_allocate), so it must not walk the
        # tree — check_invariants audits this counter against a
        # recount
        self._unref_hbm = 0
        # lazy min-heap of eviction candidates ("effective leaves":
        # unreferenced HBM nodes with no HBM descendant), keyed by
        # last_use at push time.  Every transition INTO candidacy
        # pushes; pick_victim validates on pop and re-queues entries
        # whose recency went stale — amortized O(log n) per eviction
        # where the full-tree walk was O(n · subtree) per evicted
        # page, i.e. quadratic exactly under the allocation pressure
        # eviction exists for
        self._victims: list[tuple[int, int, RadixNode]] = []
        self._vseq = count()

    # ------------------------------------------------------------- stats
    def __len__(self) -> int:
        """Cached nodes in the index, all tiers."""
        return sum(1 for _ in self._iter_nodes())

    def _iter_nodes(self) -> Iterator[RadixNode]:
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            yield n
            stack.extend(n.children.values())

    def hbm_pages(self) -> int:
        return len(self._by_page)

    def cold_nodes(self) -> int:
        return sum(1 for n in self._iter_nodes() if n.page is None)

    def debug_stats(self) -> dict:
        """Aggregate index state for /debug/kv: node/tier/refcount
        counts plus the eviction machinery's internals (a diverging
        ``unref_hbm`` vs recount is the first sign of a refcount leak
        — check_invariants audits the same pair)."""
        nodes = refs = cold = 0
        by_tier: dict[str, int] = {}
        for n in self._iter_nodes():
            nodes += 1
            refs += n.ref
            by_tier[n.tier] = by_tier.get(n.tier, 0) + 1
            if n.page is None:
                cold += 1
        return {
            "enabled": True,
            "nodes": nodes,
            "hbm_pages": len(self._by_page),
            "cold_nodes": cold,
            "by_tier": by_tier,
            "ref_total": refs,
            "unref_hbm": self._unref_hbm,
            "victim_heap": len(self._victims),
            "clock": self._clock,
        }

    # ------------------------------------------------------------ digest
    def digest(self, max_nodes: int = 64) -> dict:
        """Bounded export of the top of the tree for fleet-wide
        cache-economics aggregation (metrics/cache_economics.py).

        BFS from the root so shallow nodes — the widely shared
        prefixes worth comparing across replicas — always make the cut;
        the walk stops dead at ``max_nodes`` emitted entries
        (``truncated`` marks the cut).  Per-node subtree HBM token
        counts come from the incrementally maintained ``hbm_desc``
        counter: O(1) per node, NO subtree walks, so the whole export
        is O(max_nodes) host work regardless of tree size.  Pure host
        dict/list assembly — zero device syncs (omnilint OL2)."""
        if max_nodes < 1:
            raise ValueError("max_nodes must be positive")
        nodes: list[dict] = []
        truncated = False
        queue: list[tuple[int, RadixNode]] = [
            (1, n) for n in self._root.children.values()]
        head = 0
        while head < len(queue):
            depth, n = queue[head]
            head += 1
            if len(nodes) >= max_nodes:
                truncated = True
                break
            own_hbm = 1 if n.page is not None else 0
            nodes.append({
                "key": n.key,
                "depth": depth,
                "tier": n.tier,
                "ref": n.ref,
                "last_use": n.last_use,
                # tokens resident in HBM in the subtree rooted here
                # (hbm_desc = strict descendants; add the node's own
                # page) — the O(1) counter the eviction path maintains
                "hbm_tokens": (n.hbm_desc + own_hbm) * self.page_size,
            })
            for child in n.children.values():
                queue.append((depth + 1, child))
        return {
            "page_size": self.page_size,
            "clock": self._clock,
            "hbm_pages": len(self._by_page),
            "node_cap": max_nodes,
            "truncated": truncated,
            "nodes": nodes,
        }

    # ----------------------------------------------------------- hashing
    def page_keys(self, token_ids, max_pages: Optional[int] = None
                  ) -> list[tuple[tuple[int, ...], str]]:
        """[(page token tuple, chain-hash key)] for the FULL pages of
        ``token_ids`` — the same chained content address the flat map
        used, so cold-tier payloads stay findable across index
        rebuilds."""
        return chain_page_keys(token_ids, self.page_size, max_pages)

    # ------------------------------------------------------------- match
    def match(self, token_ids=None, max_pages: Optional[int] = None,
              *, keys=None) -> list[RadixNode]:
        """Longest-prefix walk: the chain of nodes covering the leading
        full pages of ``token_ids`` (any tier).  ``keys`` takes
        precomputed ``page_keys`` output instead (the manager memoizes
        them per request — a head-of-queue request re-matches every
        step).  Touches each matched node's LRU clock; takes NO
        references — the caller adopts explicitly via ``acquire`` once
        it commits to the pages."""
        if keys is None:
            keys = self.page_keys(token_ids, max_pages)
        nodes = []
        cur = self._root
        for chunk, _ in keys:
            child = cur.children.get(chunk)
            if child is None:
                break
            nodes.append(child)
            cur = child
        self._clock += 1
        for n in nodes:
            n.last_use = self._clock
        return nodes

    def acquire(self, node: RadixNode) -> None:
        if node.ref == 0 and node.page is not None:
            self._unref_hbm -= 1
        node.ref += 1

    def release(self, node: RadixNode) -> None:
        node.ref -= 1
        if node.ref < 0:
            raise AssertionError(
                f"radix node {node.key} refcount went negative")
        if node.ref == 0 and node.page is not None:
            self._unref_hbm += 1
            self._push_victim(node)

    # --------------------------------------------------- candidate heap
    def _push_victim(self, node: RadixNode) -> None:
        """Queue ``node`` as an eviction candidate if it qualifies
        right now (unreferenced HBM effective leaf).  Call on every
        transition into candidacy; duplicates and entries invalidated
        by later transitions are discarded at pop time."""
        if node.page is None or node.ref or node.hbm_desc:
            return
        heapq.heappush(self._victims,
                       (node.last_use, next(self._vseq), node))

    def _adjust_hbm_desc(self, node: RadixNode, delta: int) -> None:
        """Propagate an HBM page gained/lost at ``node`` into its
        ancestors' descendant counters; a loss can turn an ancestor
        into an effective leaf, i.e. an eviction candidate."""
        n = node.parent
        while n is not None:
            n.hbm_desc += delta
            if delta < 0 and n.hbm_desc == 0:
                self._push_victim(n)
            n = n.parent

    # ------------------------------------------------------------ insert
    def insert(self, token_ids, pages: list[int],
               max_pages: Optional[int] = None) -> set[int]:
        """Register the full pages of ``token_ids`` (KV resident in
        ``pages``, parallel order).  Existing nodes keep their storage
        (the first producer wins, matching the flat map's collision
        rule) and the incoming duplicate page is NOT consumed — except
        a COLD existing node, which re-adopts the incoming hot page
        (same content, already in HBM: strictly better than a restore).
        Returns the set of pages the index took ownership of."""
        consumed: set[int] = set()
        cur = self._root
        self._clock += 1
        for (chunk, key), page in zip(
                self.page_keys(token_ids, max_pages), pages):
            child = cur.children.get(chunk)
            gained = False
            if child is None:
                child = RadixNode(cur, chunk, key, page)
                cur.children[chunk] = child
                self._by_page[page] = child
                consumed.add(page)
                self._unref_hbm += 1
                gained = True
            elif child.page is None:
                child.page = page
                child.tier = TIER_HBM
                self._by_page[page] = child
                consumed.add(page)
                if child.ref == 0:
                    self._unref_hbm += 1
                gained = True
            child.last_use = self._clock
            if gained:
                self._adjust_hbm_desc(child, +1)
                self._push_victim(child)
            cur = child
        return consumed

    # ----------------------------------------------------------- restore
    def rebind_page(self, node: RadixNode, page: int) -> None:
        """Give a cold node fresh HBM storage (restore path)."""
        if node.page is not None:
            raise AssertionError(
                f"rebind of node {node.key} which still owns page "
                f"{node.page}")
        node.page = page
        node.tier = TIER_HBM
        self._by_page[page] = node
        self._adjust_hbm_desc(node, +1)
        if node.ref == 0:
            self._unref_hbm += 1
        self._push_victim(node)

    # ---------------------------------------------------------- eviction
    def evictable(self, pinned: set[int]) -> int:
        """HBM pages reclaimable right now: unreferenced AND unpinned.
        The ref invariant (ancestor.ref >= child.ref) means repeated
        deepest-first eviction reaches all of them.  O(|pinned|), not
        O(tree): the unreferenced count is maintained incrementally and
        pins are few (one snapshot per in-flight transfer)."""
        if not pinned:
            return self._unref_hbm
        pinned_unref = sum(
            1 for p in pinned
            if (n := self._by_page.get(p)) is not None and n.ref == 0)
        return self._unref_hbm - pinned_unref

    def pick_victim(self, pinned: set[int]) -> Optional[RadixNode]:
        """The eviction victim: the least-recently-used unreferenced,
        unpinned HBM node with no HBM descendant ("effectively a
        leaf" — cold descendants don't count, their bytes already left
        the device).  Served from the lazy candidate heap — amortized
        O(log n) instead of a full-tree walk per evicted page.  Such a
        node always exists when ``evictable`` > 0: any unreferenced
        HBM node's deepest HBM descendant qualifies."""
        pinned_back: list[tuple[int, RadixNode]] = []
        victim: Optional[RadixNode] = None
        while self._victims:
            use, _, node = heapq.heappop(self._victims)
            if node.page is None or node.ref or node.hbm_desc:
                continue  # stale: a future candidacy event re-pushes
            if node.last_use != use:
                # touched since push: re-queue at its current recency
                self._push_victim(node)
                continue
            if node.page in pinned:
                # still a candidate — no radix event fires when the
                # pin releases (ack_transfer), so it must stay queued
                pinned_back.append((use, node))
                continue
            victim = node
            break
        for use, node in pinned_back:
            heapq.heappush(self._victims, (use, next(self._vseq), node))
        return victim

    def mark_cold(self, node: RadixNode, tier: str) -> Optional[int]:
        """Offload-evict: the node's KV left HBM for ``tier`` but the
        node STAYS matchable in the tree.  Returns the released page."""
        page = node.page
        if page is not None:
            self._by_page.pop(page, None)
            if node.ref == 0:
                self._unref_hbm -= 1
            self._adjust_hbm_desc(node, -1)
        node.page = None
        node.tier = tier
        return page

    def drop(self, node: RadixNode) -> tuple[Optional[int], list[str]]:
        """Drop-evict: detach the node AND its (necessarily
        unreferenced) subtree — a dropped prefix makes every extension
        unmatchable, so keeping them would recreate exactly the orphan
        garbage the flat map suffered from.  Returns (the node's HBM
        page, cold keys whose tier payloads should be purged).  Any
        HBM descendants' pages are returned via ``extra_pages`` on the
        keys list caller — callers evict deepest-first so in practice
        the subtree holds only cold nodes."""
        purge: list[str] = []
        stack = list(node.children.values())
        while stack:
            n = stack.pop()
            if n.ref > 0:
                raise AssertionError(
                    "drop of a node with a referenced descendant")
            if n.page is not None:
                # deepest-first callers never hit this; keep the audit
                raise AssertionError(
                    "drop of a node with an HBM descendant")
            purge.append(n.key)
            stack.extend(n.children.values())
        page = node.page
        if page is not None:
            self._by_page.pop(page, None)
            if node.ref == 0:
                self._unref_hbm -= 1
            self._adjust_hbm_desc(node, -1)
        if node.parent is not None:
            node.parent.children.pop(node.tokens, None)
        node.parent = None
        node.children = {}
        return page, purge

    # ------------------------------------------------------------- reset
    def reset(self, pinned: set[int]) -> tuple[list[int], list[str]]:
        """Drop every node not protected by a live reference or a pin
        (reference: reset_prefix_cache — weight updates invalidate
        cached KV).  A protected node protects its ancestors (their
        chain is its context).  Returns (freed HBM pages, cold keys to
        purge from the tier store)."""
        keep: set[int] = set()
        for node in self._iter_nodes():
            if node.ref > 0 or (node.page is not None
                                and node.page in pinned):
                n: Optional[RadixNode] = node
                while n is not None and id(n) not in keep:
                    keep.add(id(n))
                    n = n.parent
        freed: list[int] = []
        purged: list[str] = []
        for node in list(self._iter_nodes()):
            if id(node) in keep:
                continue
            if node.page is not None:
                freed.append(node.page)
                self._by_page.pop(node.page, None)
            else:
                purged.append(node.key)
            # unlink from a surviving parent (the root always
            # survives); doomed parents need no unlink — their own
            # topmost doomed ancestor is cut from a survivor here
            if node.parent is self._root or id(node.parent) in keep:
                node.parent.children.pop(node.tokens, None)
        # reset is rare: recount / rebuild rather than threading deltas
        self._unref_hbm = 0
        self._root.hbm_desc = 0
        for n in self._iter_nodes():
            n.hbm_desc = 0
        for n in self._iter_nodes():
            if n.page is None:
                continue
            if n.ref == 0:
                self._unref_hbm += 1
            a = n.parent
            while a is not None:
                a.hbm_desc += 1
                a = a.parent
        self._victims = []
        for n in self._iter_nodes():
            self._push_victim(n)
        return freed, purged

    # -------------------------------------------------------- invariants
    def check_invariants(self) -> list[str]:
        """Structural audit for the property-test harness: returns a
        list of violations (empty = healthy)."""
        errors = []
        seen_pages: set[int] = set()
        for node in self._iter_nodes():
            if node.ref < 0:
                errors.append(f"node {node.key}: negative ref {node.ref}")
            if len(node.tokens) != self.page_size:
                errors.append(
                    f"node {node.key}: tokens len {len(node.tokens)} != "
                    f"page_size {self.page_size}")
            if node.parent is not None \
                    and node.parent.children.get(node.tokens) is not node:
                errors.append(f"node {node.key}: parent link broken")
            for child in node.children.values():
                if child.ref > node.ref:
                    errors.append(
                        f"ref invariant broken: child {child.key} ref "
                        f"{child.ref} > parent {node.key} ref {node.ref}")
            if node.page is not None:
                if node.page in seen_pages:
                    errors.append(f"page {node.page} owned by two nodes")
                seen_pages.add(node.page)
                if self._by_page.get(node.page) is not node:
                    errors.append(
                        f"page {node.page} missing from _by_page")
                if node.tier != TIER_HBM:
                    errors.append(
                        f"node {node.key}: page set but tier {node.tier}")
            elif node.tier == TIER_HBM:
                errors.append(f"node {node.key}: tier hbm but no page")
            actual_desc = 0
            stack = list(node.children.values())
            while stack:
                d = stack.pop()
                if d.page is not None:
                    actual_desc += 1
                stack.extend(d.children.values())
            if node.hbm_desc != actual_desc:
                errors.append(
                    f"node {node.key}: hbm_desc drifted: counter "
                    f"{node.hbm_desc} != recount {actual_desc}")
        if seen_pages != set(self._by_page):
            errors.append("_by_page out of sync with tree")
        recount = sum(1 for n in self._iter_nodes()
                      if n.page is not None and n.ref == 0)
        if recount != self._unref_hbm:
            errors.append(
                f"_unref_hbm drifted: counter {self._unref_hbm} != "
                f"recount {recount}")
        return errors
