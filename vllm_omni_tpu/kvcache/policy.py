"""Bytes-saved-vs-recompute admission for the KV cold path.

The round-5 bench measured host↔HBM at ~0.1–0.2 GB/s on this tunnel, so
parking KV is only a win when moving the bytes (twice: out now, back on
restore) beats recomputing the same tokens through a prefill.  The
break-even (docs/performance.md):

    t_offload + t_restore  <  t_recompute
    2 * (fixed_s + n*Bpt/bw)  <  n / prefill_tps

with ``Bpt`` = 2 (K+V) * layers * kv_heads * head_dim * dtype_bytes per
token, halved under int8 cold-path quantization.  Both sides are linear
in ``n`` past the fixed per-transfer overhead, so the policy reduces to
a per-token comparison plus a minimum-size gate: tiny payloads never
amortize the dispatch + connector round trip.

``mode`` pins the decision for deployments that know better:
``always`` (tests, fast local tunnels), ``never`` (kill switch — the
scheduler degrades to recompute-preemption exactly as before), ``auto``
(the break-even math).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class OffloadPolicy:
    mode: str = "auto"                 # "auto" | "always" | "never"
    # measured tunnel characteristics (overridable per deployment)
    host_bandwidth_bytes_s: float = 0.15e9   # ~0.1–0.2 GB/s (BENCH r5)
    fixed_transfer_s: float = 5e-3           # dispatch + gather overhead
    # what recompute costs: sustained prefill throughput of the engine
    prefill_tokens_per_s: float = 2000.0
    # per-token KV footprint; 0 until bound to a model config
    bytes_per_token: int = 0
    # cold-path storage: "none" keeps bf16/f32 payloads bit-exact
    # (restored greedy streams match the never-offloaded oracle);
    # "int8" halves-to-quarters the moved bytes at a bounded KV error
    quant_mode: str = "none"
    # margin: offload only when the transfer wins by this factor
    safety: float = 1.0

    def __post_init__(self):
        if self.mode not in ("auto", "always", "never"):
            raise ValueError(f"unknown offload policy mode {self.mode!r}")
        if self.quant_mode not in ("none", "int8"):
            raise ValueError(
                f"unknown cold-path quant mode {self.quant_mode!r}")

    @classmethod
    def for_model(cls, num_layers: int, num_kv_heads: int, head_dim: int,
                  dtype_bytes: int, **kw) -> "OffloadPolicy":
        bpt = 2 * num_layers * num_kv_heads * head_dim * dtype_bytes
        return cls(bytes_per_token=bpt, **kw)

    # ------------------------------------------------------------- sizes
    def cold_bytes_per_token(self) -> float:
        """Bytes per token actually moved on the cold path (quantized
        payloads ship int8 values + a float32 scale per head)."""
        if self.quant_mode == "int8" and self.bytes_per_token:
            # int8 body is bytes_per_token / dtype_bytes... the scale
            # overhead is per (layer, head), amortized over page_size
            # tokens — negligible; approximate as a clean ratio
            return self.bytes_per_token / 2.0
        return float(self.bytes_per_token)

    # ---------------------------------------------------------- decision
    def transfer_seconds(self, num_tokens: int) -> float:
        """One direction: fixed overhead + bytes over the tunnel."""
        return (self.fixed_transfer_s
                + num_tokens * self.cold_bytes_per_token()
                / max(self.host_bandwidth_bytes_s, 1.0))

    def recompute_seconds(self, num_tokens: int) -> float:
        return num_tokens / max(self.prefill_tokens_per_s, 1e-9)

    def worth_offloading(self, num_tokens: int) -> bool:
        """Should ``num_tokens`` of KV be parked instead of dropped?
        Counts BOTH directions of the round trip — parked bytes only
        pay off if they come back cheaper than recomputing them."""
        if self.mode == "always":
            return num_tokens > 0
        if self.mode == "never" or num_tokens <= 0:
            return False
        round_trip = 2.0 * self.transfer_seconds(num_tokens)
        return round_trip * self.safety < self.recompute_seconds(
            num_tokens)

    def worth_offloading_page(self, num_tokens: int) -> bool:
        """The per-PAGE eviction decision: like ``worth_offloading``
        but WITHOUT the fixed per-transfer overhead — evicted pages
        ride the step's batched extraction (one device round trip for
        every payload, ``extract_kv_batch``), so the fixed cost
        amortizes across the batch and the benefit scales with the
        whole adopted chain.  Judging one page against the full fixed
        cost would make 'auto' a de-facto 'never' for prefix pages."""
        if self.mode == "always":
            return num_tokens > 0
        if self.mode == "never" or num_tokens <= 0:
            return False
        stream = 2.0 * num_tokens * self.cold_bytes_per_token() \
            / max(self.host_bandwidth_bytes_s, 1.0)
        return stream * self.safety < self.recompute_seconds(num_tokens)

    def report(self, num_tokens: int) -> dict:
        """Break-even report for bench output (kv_reuse scenario)."""
        return {
            "mode": self.mode,
            "quant_mode": self.quant_mode,
            "bytes_per_token": self.bytes_per_token,
            "cold_bytes_per_token": self.cold_bytes_per_token(),
            "transfer_s_one_way": round(
                self.transfer_seconds(num_tokens), 6),
            "recompute_s": round(self.recompute_seconds(num_tokens), 6),
            "worth_offloading": self.worth_offloading(num_tokens),
        }
