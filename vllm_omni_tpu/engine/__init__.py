from vllm_omni_tpu.engine.llm_engine import EngineConfig, LLMEngine

__all__ = ["EngineConfig", "LLMEngine"]
