"""AR engine facade: scheduler + runner step loop.

The TPU-native collapse of the reference's LLMEngine → EngineCore →
worker-process chain (reference call stack SURVEY.md §3.2: OmniARScheduler
.schedule → GPUARModelRunner.execute_model/sample_tokens →
update_from_output).  On TPU the intra-stage fan-out is pjit over a mesh,
so the engine is a single-process object: schedule → jitted step → update.

``worker_type`` selects the scheduler the way the reference's
OmniModelConfig.worker_type picks AR vs generation workers
(reference: config/model.py:46-60).
"""

from __future__ import annotations

import dataclasses
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from vllm_omni_tpu.core.kv_cache_manager import KVCacheManager
from vllm_omni_tpu.kvcache.quant import (
    concat_payloads,
    payload_seq_len,
    trim_payload,
)
from vllm_omni_tpu.introspection import (
    DeviceMemoryLedger,
    FlightRecorder,
    register_engine,
)
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.metrics.stats import EngineStepMetrics
from vllm_omni_tpu.resilience.faults import fault_point
from vllm_omni_tpu.tracing import get_recorder
from vllm_omni_tpu.core.scheduler import (
    ARScheduler,
    GenerationScheduler,
    KVTransferConfig,
    SchedulerConfig,
    SchedulerOutput,
)
from vllm_omni_tpu.models.common import transformer as tfm
from vllm_omni_tpu.outputs import OmniRequestOutput
from vllm_omni_tpu.request import Request, RequestStatus
from vllm_omni_tpu.sampling_params import SamplingParams
from vllm_omni_tpu.worker.model_runner import ARModelRunner

logger = init_logger(__name__)


@dataclass
class EngineConfig:
    num_pages: int = 256
    page_size: int = 16
    max_model_len: int = 4096
    max_num_seqs: int = 8
    max_num_batched_tokens: int = 2048
    worker_type: str = "ar"  # "ar" | "generation"
    # disaggregated prefill/decode serving (docs/disaggregation.md):
    # "prefill" engines run requests to the end of prompt processing
    # and ship the paged KV per-layer to a decode tier (kv_transfer is
    # auto-armed with the prefill_finished trigger); "decode" engines
    # adopt streamed KV into their paged cache and resume as decode
    # (the PR 6 resume-as-decode executable-identity rule — the decode
    # tier's first step runs the SAME decode executable an
    # uninterrupted colocated stream would).  "colocated" is the
    # classic single-engine shape and the degradation target when a
    # peer tier has no healthy replicas (disagg/router.py).
    engine_role: str = "colocated"  # "prefill" | "decode" | "colocated"
    enable_chunked_prefill: bool = False
    # automatic prefix caching: full prompt pages register under a
    # content hash when their producer frees; later requests sharing the
    # prefix skip recomputing it (vLLM-core APC; cached pages stay
    # allocatable via LRU eviction, so capacity is unaffected)
    enable_prefix_caching: bool = True
    # speculative decoding: drafts per step (needs a draft_fn — the MTP
    # head, models/qwen3_omni/mtp.py).  Verify rows are k+1-token ragged
    # rows of the unified dispatch; greedy requests verify by on-device
    # accept-mask, sampled requests by on-device rejection sampling
    num_speculative_tokens: int = 0
    # RETIRED (PR 11): the multi-step lax.scan window is gone — the
    # async pipelined step amortizes the host round trip instead, and
    # it serves the batches the scan never could (mixed, sampled, spec,
    # logprobs).  Accepted as a no-op so existing configs construct;
    # values > 1 log a deprecation warning.
    multi_step_decode: int = 1
    # unified ragged batching POLICY (the execution mechanism is always
    # on since PR 11 — every non-pure-decode step is ONE token-packed
    # dispatch, ops/ragged_paged_attention.py, and the split executor
    # is deleted).  This flag controls the SCHEDULER's packing policy:
    # decodes claim the token budget first, prefill chunks fill the
    # remainder, and chunked prefill becomes the mechanism (implied
    # ON).  Off keeps the classic admission order and prompt-length
    # limits.  See docs/ragged_batching.md.
    unified_batching: bool = False
    # async pipelined step: two-slot pipeline — dispatch step N
    # (forward + ON-DEVICE sampling/verify/logprobs, the sampled tokens
    # stay device-resident and feed step N+1's dispatch directly), then
    # do step N-1's host work (readback, stop checks, metrics) while
    # the device computes.  Host readback lags exactly one step.  Since
    # PR 11 every batch shape pipelines — spec decode, logprobs,
    # collect_hidden, and embeds ride the unified dispatch; only
    # host-synchronous KV movement (cross-stage transfer, tier-offload
    # drains) and streaming chunk intake run a synchronous step.
    # Greedy token streams are bit-identical to sync mode.
    # See docs/async_engine.md.
    async_scheduling: bool = False
    # tiered KV offload (docs/kv_cache.md): evicted prefix-cache pages
    # and preempted requests PARK their KV in a host-RAM pool (and
    # optionally a remote store) instead of dropping it; restores
    # promote the bytes back before the scheduler re-admits.  The
    # radix prefix index tracks which tier each cached node lives in.
    kv_offload: bool = False
    # cold-path storage: "none" keeps parked payloads bit-exact
    # (restored greedy streams match the never-offloaded oracle);
    # "int8" halves the bytes over the ~0.15 GB/s host tunnel
    kv_offload_quant: str = "none"
    # HBM-RESIDENT KV dtype (docs/performance.md): "int8" stores the
    # paged pool as int8 bytes + per-(head, page) absmax scales — the
    # attention kernels dequantize in-register during the page DMA
    # pipeline, and the same HBM budget holds ~2x the pages (more
    # concurrent sessions at fixed p99 TPOT, scripts/kv_quant_bench.py).
    # "auto"/"bf16" keep the dense layout in ``dtype``
    kv_cache_dtype: str = "auto"  # "auto" | "bf16" | "int8"
    # HBM budget the page pool is sized from.  None derives it from
    # ``num_pages`` at the DENSE layout — flipping to int8 then converts
    # the SAME budget into ~2x pages rather than keeping the page count
    kv_hbm_budget_bytes: Optional[int] = None
    # bytes-vs-recompute admission (kvcache/policy.py): "auto" runs the
    # break-even math, "always"/"never" pin the decision
    kv_offload_policy: str = "auto"
    # host tier capacity; overflow demotes LRU payloads to the remote
    # tier (or drops them without one).  None = unbounded
    kv_host_tier_bytes: Optional[int] = None
    # remote tier transport: a ConnectorFactory name ("inproc" | "shm"
    # | "tcp", distributed/connectors.py) + its constructor kwargs;
    # the edge runs under the PR 3 retry policy + circuit breaker
    kv_offload_connector: Optional[str] = None
    kv_offload_connector_args: Optional[dict] = None
    # pin the single-token decode family (sync, async dispatch,
    # multi-step window) to the TOP batch bucket.  XLA fuses the
    # [B]-leading decode matmuls differently per bucket shape, so the
    # same request decoded next to 3 neighbours vs 7 can differ in the
    # last bf16 bit — enough to flip a greedy argmax when logits run
    # close.  With this on, a request's greedy stream is bit-stable
    # under co-batch churn (arrivals, preemptions, offload restores),
    # which is what lets the kv_reuse bench compare an offloading
    # engine against a never-preempted oracle token for token.  Costs
    # padded rows when the batch runs small; spec-decode verify and
    # the unified token-packed path keep their dynamic shapes.
    deterministic_decode: bool = False
    # precompile bucketed executables before serving: True warms every
    # decode batch bucket; a list of (batch, seq_len) pairs additionally
    # warms those prefill shapes.  A shape-cache miss mid-traffic stalls
    # all in-flight requests for a full XLA compile (20-40 s per shape
    # on a remote-attached chip) — see ARModelRunner.precompile.
    warmup: Any = False  # bool | list[(batch, seq_len)]
    dtype: Any = jnp.bfloat16
    kv_transfer: Optional[KVTransferConfig] = None
    collect_hidden: bool = False
    # serving SLO targets (docs/load_testing.md): per-request TTFT and
    # TPOT upper bounds the engine accounts every finished request
    # against — slo_attainment_ratio / goodput_tokens_total on
    # /metrics, split per tenant.  None = that leg always passes
    # (goodput degenerates to throughput), so unconfigured serving
    # keeps its old behavior
    slo_ttft_ms: Optional[float] = None
    slo_tpot_ms: Optional[float] = None
    # admission control (load shedding): waiting-queue cap — arrivals
    # past it are refused with error_kind "shed" (HTTP 429,
    # shed_requests_total{reason="queue_depth"}) before any engine
    # admission work.  None = unbounded
    max_queue_depth: Optional[int] = None
    # shed arrivals whose remaining deadline is below this floor
    # (reason="deadline_headroom"); 0.0 disables
    admission_deadline_headroom_s: float = 0.0
    # weighted-fair overload scheduling (docs/control_plane.md): order
    # waiting-queue admission by per-tenant deficit round robin keyed
    # on Request.priority (the sanitized x-omni-priority metadata) and
    # make the max_queue_depth shed priority-ordered — under overload
    # low-priority work defers or sheds instead of everyone starving
    # equally.  Off keeps strict arrival order
    wfq_scheduling: bool = False
    # DRR quantum per unit of priority weight, in prompt tokens (the
    # tenant-interleave granularity; see core/scheduler.py)
    wfq_quantum_tokens: int = 256
    seed: Optional[int] = None  # pins sampling entropy for reproducibility
    # tensor parallelism over the first N devices (reference:
    # tensor_parallel_size, stage_configs/qwen3_omni_moe.yaml:27)
    tensor_parallel_size: int = 1


@dataclass
class _InflightStep:
    """One slot of the two-slot pipeline: a dispatched-but-unretired
    decode step.  The engine retires it (token readback + stop checks +
    metrics) while the NEXT step's forward runs on the device."""

    sched_out: SchedulerOutput
    handle: Any                    # worker InflightDecode (device tokens)


class LLMEngine:
    def __init__(self, params, model_cfg: tfm.TransformerConfig,
                 config: Optional[EngineConfig] = None,
                 eos_token_id: Optional[int] = None,
                 draft_fn=None):
        config = config if config is not None else EngineConfig()
        if config.engine_role not in ("prefill", "decode", "colocated"):
            raise ValueError(
                f"engine_role must be prefill|decode|colocated, got "
                f"{config.engine_role!r}")
        if (config.engine_role == "prefill"
                and config.kv_transfer is None
                and config.worker_type == "ar"):
            # a prefill-role engine EXISTS to ship KV: arm the transfer
            # trigger so every request that finishes prompt processing
            # pins + extracts its pages for the decode tier.  Private
            # copy — the caller may build other roles from the same
            # config object.
            config = dataclasses.replace(
                config, kv_transfer=KVTransferConfig(
                    trigger="prefill_finished"))
        if (config.async_scheduling or config.unified_batching) \
                and config.worker_type != "ar":
            logger.warning(
                "async_scheduling/unified_batching only apply to AR "
                "engines; disabled for worker_type=%s", config.worker_type)
            # private copy — writing through would silently disable
            # async for other engines built from the same config object.
            # unified too: a generation-stage scheduler never emits
            # unified batches, so the runner must not warm a token-bucket
            # line of executables that can never dispatch
            config = dataclasses.replace(config, async_scheduling=False,
                                         unified_batching=False)
        self.config = config
        self.eos_token_id = eos_token_id
        # tiered KV offload: the cold-side store + break-even policy
        # (docs/kv_cache.md).  AR engines only — the one-shot
        # generation scheduler never preempts or prefix-caches.
        self.kv_tiers = None
        kv_policy = None
        if config.kv_offload and config.worker_type == "ar" \
                and isinstance(model_cfg, tfm.TransformerConfig):
            remote = None
            if config.kv_offload_connector:
                from vllm_omni_tpu.distributed.connectors import (
                    ConnectorFactory,
                )

                remote = ConnectorFactory.create(
                    config.kv_offload_connector,
                    **(config.kv_offload_connector_args or {}))
            from vllm_omni_tpu.kvcache import OffloadPolicy, TieredKVStore

            self.kv_tiers = TieredKVStore(
                quant=config.kv_offload_quant,
                host_capacity_bytes=config.kv_host_tier_bytes,
                remote=remote)
            kv_policy = OffloadPolicy.for_model(
                model_cfg.num_layers, model_cfg.num_kv_heads,
                model_cfg.head_dim,
                jnp.dtype(config.dtype).itemsize,
                mode=config.kv_offload_policy,
                quant_mode=config.kv_offload_quant)
        # HBM-resident KV layout: resolve the page-pool size from the
        # HBM budget under the chosen dtype.  int8 pages cost roughly
        # half a bf16 page (data + per-(head, page) scales), so the
        # SAME budget yields ~2x pages — capacity, not just bytes, is
        # the point of the quantized pool (docs/performance.md)
        if config.kv_cache_dtype not in ("auto", "bf16", "int8"):
            raise ValueError(
                f"kv_cache_dtype must be auto|bf16|int8, got "
                f"{config.kv_cache_dtype!r}")
        self._kv_quant = config.kv_cache_dtype == "int8"
        num_pages = config.num_pages
        self._kv_bytes_per_token: Optional[float] = None
        if isinstance(model_cfg, tfm.TransformerConfig) \
                and config.worker_type == "ar":
            from vllm_omni_tpu.kvcache.quant import (
                bytes_per_token,
                page_bytes,
                pages_for_budget,
            )

            itemsize = jnp.dtype(config.dtype).itemsize
            if self._kv_quant or config.kv_hbm_budget_bytes is not None:
                budget = config.kv_hbm_budget_bytes
                if budget is None:
                    budget = config.num_pages * model_cfg.num_layers * \
                        page_bytes(model_cfg.num_kv_heads,
                                   config.page_size, model_cfg.head_dim,
                                   quantized=False, itemsize=itemsize)
                num_pages = pages_for_budget(
                    budget, model_cfg.num_layers, model_cfg.num_kv_heads,
                    config.page_size, model_cfg.head_dim,
                    quantized=self._kv_quant, itemsize=itemsize)
                logger.info(
                    "kv_cache_dtype=%s: %d pages in a %.1f MiB HBM "
                    "budget (config asked %d at the dense layout)",
                    config.kv_cache_dtype, num_pages, budget / 2**20,
                    config.num_pages)
            self._kv_bytes_per_token = bytes_per_token(
                model_cfg.num_layers, model_cfg.num_kv_heads,
                config.page_size, model_cfg.head_dim,
                quantized=self._kv_quant, itemsize=itemsize)
        # prefix caching skips the forward for cached positions, so it
        # cannot coexist with collect_hidden (downstream stages need the
        # hidden row of EVERY prompt position) — thinker-style stages
        # run uncached, plain LM serving gets APC
        kv = KVCacheManager(num_pages, config.page_size,
                            enable_prefix_caching=(
                                config.enable_prefix_caching
                                and config.worker_type == "ar"
                                and not config.collect_hidden),
                            tiers=self.kv_tiers, policy=kv_policy,
                            cache_dtype=(
                                "int8" if self._kv_quant
                                else str(jnp.dtype(config.dtype))),
                            bytes_per_token=self._kv_bytes_per_token)
        sched_cfg = SchedulerConfig(
            max_num_seqs=config.max_num_seqs,
            max_num_batched_tokens=config.max_num_batched_tokens,
            max_model_len=config.max_model_len,
            enable_chunked_prefill=config.enable_chunked_prefill,
            num_speculative_tokens=config.num_speculative_tokens,
            kv_transfer=config.kv_transfer,
            unified_batching=config.unified_batching,
            kv_offload=self.kv_tiers is not None,
            max_queue_depth=config.max_queue_depth,
            admission_deadline_headroom_s=(
                config.admission_deadline_headroom_s),
            wfq_scheduling=config.wfq_scheduling,
            wfq_quantum_tokens=config.wfq_quantum_tokens,
        )
        if config.multi_step_decode > 1:
            logger.warning(
                "multi_step_decode=%d is retired (PR 11): the async "
                "pipelined step is the round-trip amortization; the "
                "knob is ignored", config.multi_step_decode)
        sched_cls = (GenerationScheduler if config.worker_type == "generation"
                     else ARScheduler)
        self.scheduler = sched_cls(sched_cfg, kv)
        if not isinstance(model_cfg, tfm.TransformerConfig):
            # custom generation model object (code2wav vocoder etc.) —
            # only valid under the one-shot generation scheduler
            if config.worker_type != "generation":
                raise TypeError(
                    "model_cfg must be a TransformerConfig for AR stages; "
                    f"got {type(model_cfg).__name__}"
                )
            from vllm_omni_tpu.worker.generation_runner import (
                GenerationModelRunner,
            )

            self.runner = GenerationModelRunner(
                params, model_cfg,
                max_num_seqs=config.max_num_seqs,
                max_model_len=config.max_model_len,
            )
        else:
            mesh = None
            if config.tensor_parallel_size > 1:
                import numpy as _np
                from jax.sharding import Mesh

                from vllm_omni_tpu.parallel.mesh import AXIS_TP

                devs = jax.devices()
                tp = config.tensor_parallel_size
                if len(devs) < tp:
                    raise ValueError(
                        f"tensor_parallel_size={tp} but only "
                        f"{len(devs)} devices visible")
                mesh = Mesh(_np.array(devs[:tp]), (AXIS_TP,))
            self.runner = ARModelRunner(
                params, model_cfg,
                num_pages=num_pages, page_size=config.page_size,
                kv_cache_dtype=config.kv_cache_dtype,
                max_model_len=config.max_model_len, dtype=config.dtype,
                collect_hidden=config.collect_hidden, seed=config.seed,
                max_num_seqs=config.max_num_seqs, mesh=mesh,
                async_scheduling=config.async_scheduling,
                max_num_batched_tokens=config.max_num_batched_tokens,
                deterministic_decode=config.deterministic_decode,
            )
        if (draft_fn is not None and config.num_speculative_tokens > 0
                and hasattr(self.runner, "set_draft_fn")):
            self.runner.set_draft_fn(
                draft_fn, config.num_speculative_tokens
            )
        # connector hook: called with (request, kv_payload) when a
        # cross-stage KV extraction completes (OmniKVTransferManager put)
        self.kv_transfer_sink: Optional[Callable] = None
        self._req_counter = 0
        self._starved_ticks = 0
        # async pipelined step: the dispatched-but-unretired slot
        self._inflight: Optional[_InflightStep] = None
        # observability: step-level gauges/histograms (TTFT/TPOT/ITL) +
        # per-request span recording.  stage_id is stamped by OmniStage
        # so spans and /metrics series carry the pipeline position.
        self.stage_id = 0
        # fleet span identity (tracing/journey.py): empty for pipeline
        # stages; EngineReplica stamps {"replica_id", "role"} so this
        # engine's spans render on its own Perfetto replica track
        self.span_tags: dict = {}
        self.step_metrics = EngineStepMetrics()
        # SLO accounting targets: every finished request is judged
        # against them per tenant (slo_attainment_ratio, goodput)
        self.step_metrics.slo_ttft_ms = config.slo_ttft_ms
        self.step_metrics.slo_tpot_ms = config.slo_tpot_ms
        # per-tenant heavy-hitter attribution (metrics/attribution.py):
        # bounded-memory space-saving sketches metering prefill/decode
        # tokens, KV page·seconds per tier, handoff bytes, queue wait,
        # and sheds — the answer to "which tenant is eating this
        # engine" that survives millions of distinct tenants.  The
        # scheduler's shed path and the KV manager's occupancy clock
        # feed it; top-k renders on /metrics and /debug/tenants
        from vllm_omni_tpu.metrics.attribution import TenantAttribution

        self.attribution = TenantAttribution()
        self.scheduler.attribution_sink = self.attribution.add
        # async pipeline drain granularity: how many steps fell back to
        # the synchronous path, PER REASON ("prefill", "spec",
        # "logprobs", "kv_transfer", ...) — under unified batching the
        # prefill row stops growing, which makes the unified win
        # directly visible on /metrics (async_fallback_total)
        self.async_fallback: dict[str, int] = {}
        # request_id -> [first_token_ts, last_token_ts, tokens_seen]
        self._req_lat: dict[str, list] = {}
        self._trace_started: set[str] = set()
        # introspection (docs/debugging.md): the per-step flight
        # recorder (bounded ring, appended with zero device syncs),
        # the per-component device-memory ledger, and registration in
        # the process registry so crash dumps / the stall watchdog /
        # the /debug/z endpoints can find this engine
        from vllm_omni_tpu import envs as _envs2

        self.flight = FlightRecorder(
            capacity=max(int(_envs2.OMNI_TPU_FLIGHT_CAPACITY), 1),
            name=f"{config.worker_type}-engine")
        # live roofline attribution (metrics/roofline.py): per-step
        # achieved FLOPs / HBM bytes from static geometry × the step's
        # token mix, against the platform peaks — engine_step_mfu /
        # engine_step_mbu{phase} on /metrics, per-record fields in the
        # flight recorder, the rolling window on /debug/engine.  Host
        # math only (zero device syncs); AR transformers only — the
        # one-shot generation runner has no token-mix geometry.
        self.roofline = None
        if isinstance(model_cfg, tfm.TransformerConfig):
            from vllm_omni_tpu.metrics.roofline import (
                ModelGeometry,
                RooflineTracker,
            )
            from vllm_omni_tpu.platforms import current_platform

            p = current_platform()
            self.roofline = RooflineTracker(
                ModelGeometry.from_transformer_config(
                    model_cfg, jnp.dtype(config.dtype).itemsize),
                peak_tflops=p.peak_tflops_bf16(),
                peak_gbps=p.peak_hbm_gbps())
        self.memory = DeviceMemoryLedger(self._memory_components)
        # kv tier moves drained this step — recorded per step so the
        # flight tail shows offload/restore churn around a bad minute
        self._last_kv_moves = (0, 0)
        # watchdog progress signal: step() COMPLETIONS.  Distinct from
        # flight.total_steps on purpose — zero-scheduled ticks (e.g. a
        # streaming request idling for its next chunk, pages pinned by
        # an in-flight transfer) append no record but ARE the step loop
        # turning; a watchdog keyed on records would false-trip on
        # those documented-normal busy-idle states, while a step
        # wedged mid-flight freezes this counter exactly as intended
        self._steps_completed = 0
        register_engine(self)
        if config.warmup:
            shapes = (config.warmup if isinstance(
                config.warmup, (list, tuple)) else ())
            n = self.warmup(prefill_shapes=shapes)
            logger.info(
                "engine warmup: %d executables precompiled before "
                "serving", n)

    # ------------------------------------------------------------- warmup
    def warmup(self, prefill_shapes=(), progress_fn=None) -> int:
        """Precompile the runner's bucketed executables before serving
        (every decode batch bucket, plus the given (batch, seq_len)
        prefill shapes).  A shape-cache miss mid-traffic stalls all
        in-flight requests for a full XLA compile — 20-40 s per shape
        on a remote-attached chip.  Returns executables requested.
        Reference analogue: worker warmup / graph capture before the
        engine goes live."""
        fn = getattr(self.runner, "precompile", None)
        if fn is None:
            return 0
        built = fn(prefill_shapes=prefill_shapes, progress_fn=progress_fn)
        stats = getattr(self.runner, "compile_stats", None)
        if stats is not None:
            # the shape-cache telemetry baseline: compiles past this
            # line are mid-traffic stalls (jit_compiles_total on
            # /metrics keeps counting them)
            logger.info(
                "warmup compiled %d executables in %.1fs "
                "(%d cache hits)", stats["compiles"],
                stats["compile_s"], stats["cache_hits"])
        return built

    # ------------------------------------------------------------- intake
    def add_request(
        self,
        prompt_token_ids: list[int],
        sampling_params: Optional[SamplingParams] = None,
        request_id: Optional[str] = None,
        injected_kv: Optional[list] = None,
        injected_first_token: Optional[int] = None,
        **kwargs,
    ) -> str:
        """``injected_kv``: per-layer [(k, v)] dense KV of a prompt prefix
        computed by an upstream engine (disaggregated prefill / cross-stage
        KV reuse).  The prefix lands in this engine's paged cache and only
        the remainder of the prompt is (re)computed — at least the last
        prompt token always recomputes so there are logits to sample from
        (the receive half of OmniKVTransferManager, reference:
        kv_transfer_manager.py:100+).

        ``injected_first_token``: the first sampled token, when the
        upstream PREFILL engine already produced it (disaggregated
        prefill, docs/disaggregation.md).  With it, the injected KV may
        cover the WHOLE prompt and the request resumes through the
        DECODE executable (the scheduler's resume-as-decode branch) —
        the same executable an uninterrupted colocated stream runs, so
        greedy continuations stay bit-identical to the colocated
        oracle.  Without it the prefix is capped at prompt-1 tokens and
        the last prompt position recomputes for its logits."""
        if request_id is None:
            request_id = f"req-{self._req_counter}"
            self._req_counter += 1
        req = Request(
            request_id=request_id,
            prompt_token_ids=list(prompt_token_ids),
            sampling_params=sampling_params or SamplingParams(),
            eos_token_id=self.eos_token_id,
            arrival_time=time.time(),
            arrival_mono=time.monotonic(),
            **kwargs,
        )
        if injected_first_token is not None:
            # appended BEFORE admission: num_tokens includes it, so the
            # remainder-to-compute is exactly one sampling position
            req.append_output_token(int(injected_first_token))
        injected_len = 0
        if injected_kv is not None:
            injected_len = min(payload_seq_len(injected_kv),
                               max(req.num_tokens - 1, 0))
        self.scheduler.add_request(req, injected_len=injected_len)
        if injected_kv is not None and req.status is RequestStatus.WAITING:
            self._inject_prefix_kv(req, injected_kv)
        return request_id

    def _inject_prefix_kv(self, req: Request, payload: list) -> None:
        # with a pre-appended first token (disaggregated prefill) the
        # whole PROMPT may inject — the one remaining position is the
        # sampling one and re-enters as a decode; otherwise the last
        # prompt token recomputes for its logits
        seq_len = payload_seq_len(payload)
        use = min(seq_len, req.num_tokens - 1)
        if use <= 0:
            if req.output_token_ids:
                req.output_token_ids.pop()  # unbackable first token
            return
        # provenance split (PR 19): a payload the router pulled from
        # the cluster KV fabric admits through adopt_prefix and counts
        # as prefix_pull_tokens; a disaggregated-prefill handoff stays
        # on the adopt_streamed/streamed_tokens path.  Same allocator,
        # same guards — only the accounting differs.
        pulled = bool(req.additional_information.get("prefix_pull"))
        kv = self.scheduler.kv
        table = (kv.adopt_prefix(req, use) if pulled
                 else kv.adopt_streamed(req, use))
        if table is not None:
            try:
                t0, w0 = time.perf_counter(), time.time()
                # format-agnostic trim: dense slices the token axis;
                # quantized wire payloads also trim the per-page scale
                # axis (kvcache/quant.py)
                trimmed = trim_payload(payload, use,
                                       self.config.page_size)
                self.runner.inject_kv(table, trimmed)
                req.num_computed_tokens = use
                (kv.note_pulled if pulled else kv.note_streamed)(use)
                get_recorder().record(
                    req.additional_information.get("trace"), "kv_inject",
                    w0, time.perf_counter() - t0, stage_id=self.stage_id,
                    cat="kv",
                    args={"tokens": use,
                          "src": "fabric" if pulled else "peer"},
                    **self.span_tags,
                )
                return
            except (ValueError, IndexError) as e:
                # malformed payload (e.g. upstream layer-count mismatch):
                # fall back to full recompute — the prefix is re-derivable
                # from the prompt tokens, and the already-allocated pages
                # cover the same positions the recompute will write
                logger.warning(
                    "request %s: injected KV rejected (%s); recomputing "
                    "the full prompt", req.request_id, e,
                )
        # fallback taken (pool pressure or bad payload): the request
        # recomputes from scratch.  A pre-appended first token whose
        # backing KV never landed is STRIPPED first — keeping it would
        # compute its successor position through a (prompt+1)-token
        # prefill chunk, while the colocated oracle samples that
        # position through full-prefill + decode; recompute must
        # re-derive t1 through the oracle's own executables
        # (bit-exactness rule, docs/disaggregation.md)
        if req.output_token_ids and req.num_computed_tokens == 0:
            req.output_token_ids.pop()
        # recheck it can actually be scheduled as a full recompute
        if (not self.scheduler.config.chunking_enabled
                and req.num_tokens
                > self.scheduler.config.max_num_batched_tokens):
            self.scheduler.waiting.remove(req)
            self.scheduler.kv.free(req)
            self.scheduler.reject(
                req,
                "prompt exceeds max_num_batched_tokens and its injected "
                "KV prefix could not be applied (chunked prefill off)",
            )

    def append_prompt_chunk(
        self,
        request_id: str,
        token_ids: list[int] = (),
        prompt_embeds=None,
        final: bool = False,
    ) -> None:
        """Extend a streaming request's prompt (async_chunk intake,
        reference: OmniChunkTransferAdapter feeding WAITING_FOR_CHUNK
        requests, transfer_adapter/chunk_transfer_adapter.py:19).

        The request must have been added with ``awaiting_chunks=True``;
        arrived tokens prefill as chunks while later ones are still being
        produced upstream, and sampling starts only after ``final=True``.
        Embeds-based requests append matching ``prompt_embeds`` rows.
        """
        queue, req = self.scheduler.find_request(request_id)
        if req is None:
            raise KeyError(f"no in-flight request {request_id!r}")
        if not req.awaiting_chunks:
            raise ValueError(
                f"request {request_id!r} is not a streaming request "
                "(awaiting_chunks=False)"
            )
        token_ids = list(token_ids)
        # embeds/token mode is fixed by the first content chunk; mixing
        # silently corrupts positions, so it is an error OUTPUT (the
        # caller is usually a remote stage that cannot handle a raise)
        embeds_based = req.prompt_embeds is not None
        if token_ids and req.num_prompt_tokens > 0:
            if embeds_based and prompt_embeds is None:
                self.scheduler.fail_request(
                    request_id,
                    "embeds-based streaming request: every chunk must "
                    "carry prompt_embeds rows matching its token_ids",
                )
                return
            if not embeds_based and prompt_embeds is not None:
                self.scheduler.fail_request(
                    request_id,
                    "token-based streaming request received an embeds "
                    "chunk (mode is fixed by the first chunk)",
                )
                return
        new_len = req.num_prompt_tokens + len(token_ids)
        over = (new_len > self.config.max_model_len
                or self.scheduler.kv.pages_needed(new_len)
                > self.scheduler.kv.num_pages)
        # a request still in WAITING is admitted whole: without chunked
        # prefill its remainder must fit one step's budget (add_request
        # enforces the same at intake; a grown waiting request would pin
        # the queue head forever). RUNNING streams are exempt — the
        # continuation branch chunks them under the budget regardless.
        if (not over and queue is self.scheduler.waiting
                and not self.scheduler.config.chunking_enabled
                and new_len - req.num_computed_tokens
                > self.config.max_num_batched_tokens):
            over = True
        if over:
            self.scheduler.fail_request(
                request_id,
                f"streamed prompt grew to {new_len} tokens, exceeding "
                "the engine limits",
            )
            return
        if token_ids:
            req.prompt_token_ids.extend(int(t) for t in token_ids)
            if prompt_embeds is not None:
                import numpy as np

                pe = np.asarray(prompt_embeds)
                if pe.shape[0] != len(token_ids):
                    self.scheduler.fail_request(
                        request_id,
                        f"chunk embeds rows {pe.shape[0]} != chunk "
                        f"tokens {len(token_ids)}",
                    )
                    return
                req.prompt_embeds = (
                    pe if req.prompt_embeds is None
                    else np.concatenate([req.prompt_embeds, pe], axis=0)
                )
        if final:
            req.awaiting_chunks = False
            if req.num_tokens == 0:
                # a stream that never produced content can neither sample
                # nor finish; error-finish instead of wedging the engine
                self.scheduler.fail_request(
                    request_id, "streaming request finalized empty")
                return
            if req.num_computed_tokens >= req.num_tokens:
                # every arrived token was already prefilled with sampling
                # suppressed — the final position's logits were discarded,
                # so recompute it (same slot, one-token chunk) to sample
                req.num_computed_tokens = req.num_tokens - 1

    def add_errored_request(
        self, request_id: str, reason: str, kind: str = "invalid_request"
    ) -> str:
        """Register a request already known to be invalid (e.g. multimodal
        preprocessing failed) so it surfaces as an error output through the
        normal step() drain instead of raising into the submitter."""
        req = Request(
            request_id=request_id, prompt_token_ids=[],
            sampling_params=SamplingParams(), arrival_time=time.time(),
        )
        self.scheduler.reject(req, reason, kind)
        return request_id

    def abort_request(self, request_id: str) -> None:
        self.scheduler.abort_request(request_id)
        self._req_lat.pop(request_id, None)
        self._trace_started.discard(request_id)

    @property
    def has_unfinished_requests(self) -> bool:
        # Pending errored (intake-rejected) requests count as unfinished so
        # the stage polling loop keeps stepping until step() drains them —
        # otherwise a lone invalid request is silently dropped and its
        # client hangs forever (ADVICE r1 medium).
        return (self.scheduler.has_unfinished
                or self.scheduler.has_pending_errored)

    # ----------------------------------------------------------- re-roling
    def set_engine_role(self, role: str) -> None:
        """Flip a QUIESCED engine's disaggregated-serving role
        (docs/control_plane.md live re-roling): prefill arms the
        prefill_finished KV-transfer trigger, decode/colocated disarm
        it.  The same compiled executables serve every role — a role is
        scheduler policy plus transfer arming, never a recompile — so a
        flip is O(host state).  Refused while requests are in flight:
        an armed/disarmed trigger changing under a live request would
        split its stream across transfer regimes (the caller drains
        first — the router's drain -> quiesce -> flip sequence)."""
        if role not in ("prefill", "decode", "colocated"):
            raise ValueError(
                f"engine_role must be prefill|decode|colocated, got "
                f"{role!r}")
        if self.has_unfinished_requests:
            raise RuntimeError(
                "cannot re-role an engine with unfinished requests; "
                "drain it first (router.drain -> quiesced)")
        if role == self.config.engine_role:
            return
        if (self.config.engine_role == "colocated"
                and self.config.kv_transfer is not None):
            # a colocated engine whose transfer trigger serves the
            # CROSS-STAGE pipeline (thinker -> talker) is not a disagg
            # tier: flipping it would silently unhook the next stage
            raise RuntimeError(
                "refusing to re-role an engine with a cross-stage "
                "kv_transfer config")
        kv_cfg = (KVTransferConfig(trigger="prefill_finished")
                  if role == "prefill" else None)
        self.config = dataclasses.replace(
            self.config, engine_role=role, kv_transfer=kv_cfg)
        self.scheduler.config.kv_transfer = kv_cfg

    # ---------------------------------------------------------------- step
    @property
    def prefix_cache_stats(self) -> dict:
        """APC effectiveness counters (vLLM-core cache hit metrics)."""
        kv = self.scheduler.kv
        return {"hits": getattr(kv, "prefix_hits", 0),
                "hit_tokens": getattr(kv, "prefix_hit_tokens", 0),
                "enabled": getattr(kv, "enable_prefix_caching", False)}

    def reset_prefix_cache(self) -> int:
        """Release every unreferenced APC page (reference:
        reset_prefix_cache — cached KV is stale after a weight swap);
        returns pages released."""
        kv = self.scheduler.kv
        fn = getattr(kv, "reset_prefix_cache", None)
        return fn() if fn is not None else 0

    # --------------------------------------------------------- introspection
    def _memory_components(self) -> dict:
        """Attributable device-memory components for the ledger (the
        runner's static buffer sizes; empty for runners that don't
        account themselves)."""
        fn = getattr(self.runner, "memory_components", None)
        return fn() if fn is not None else {}

    def introspect_progress(self) -> dict:
        """Stall-watchdog probe: busy-ness, a monotone step counter,
        and the compile telemetry that separates an XLA-compile stall
        from a true hang (docs/debugging.md).  Host-side reads only."""
        compile_stats = getattr(self.runner, "compile_stats", {}) or {}
        return {
            "busy": self.has_unfinished_requests,
            "progress": self._steps_completed,
            "compiles": int(compile_stats.get("compiles", 0)),
            "compile_in_flight": bool(compile_stats.get("in_flight", 0)),
            "detail": {
                "stage_id": self.stage_id,
                "waiting": len(self.scheduler.waiting),
                "running": len(self.scheduler.running),
            },
        }

    def _record_step(self, path: str, sched_out: SchedulerOutput,
                     scheduled, new_tokens: int, host_ms: float,
                     device_ms: float,
                     fallback: Optional[str] = None) -> None:
        """Append one flight-recorder record.  Every field is a host
        int/str the step already computed — NO device syncs here (the
        recorder path is omnilint OL2 HOT_PATHS scoped)."""
        compile_stats = getattr(self.runner, "compile_stats", {}) or {}
        inflight = self._inflight
        rows = (getattr(inflight.handle, "rows", None)
                if inflight is not None else None)
        # consume the drain counts: pipelined steps never run
        # _drain_kv_moves, so without the reset every pipelined record
        # would replay the LAST sync step's tier churn
        offloads, restores = self._last_kv_moves
        self._last_kv_moves = (0, 0)
        # spec decode honesty (record schema v2, docs/debugging.md): a
        # verify-heavy step is distinguishable from plain decode —
        # spec_rows counts k+1-token verify rows, verify_tokens their
        # total candidate positions.  ``unified`` reflects the EXECUTED
        # path (spec steps ride the unified dispatch since PR 11), not
        # just the scheduler's packing-policy flag.
        spec_rows = [s for s in sched_out.decodes if s.num_new_tokens > 1]
        unified = bool(getattr(sched_out, "unified", False)
                       or sched_out.prefills or spec_rows)
        # record schema v3 additions (docs/debugging.md): live roofline
        # attribution + the journey-trace cross-link.  All host ints —
        # start_pos/num_new_tokens are scheduler state, the wall time
        # is the host_ms/device_ms sum already computed; NO device syncs
        roofline = None
        tracker = getattr(self, "roofline", None)  # duck-typed fakes
        if tracker is not None:
            from vllm_omni_tpu.metrics.roofline import ctx_positions

            roofline = tracker.on_step(
                prefill_tokens=sum(s.num_new_tokens
                                   for s in sched_out.prefills),
                prefill_ctx=sum(ctx_positions(s.start_pos,
                                              s.num_new_tokens)
                                for s in sched_out.prefills),
                decode_tokens=sum(s.num_new_tokens
                                  for s in sched_out.decodes),
                decode_ctx=sum(ctx_positions(s.start_pos,
                                             s.num_new_tokens)
                               for s in sched_out.decodes),
                sampled_rows=len(sched_out.prefills)
                + len(sched_out.decodes),
                wall_s=(host_ms + device_ms) / 1e3,
            )
        # capped trace-id cross-link: a watchdog-trip dump pivots from
        # the bad step straight to the journey timeline (the ids to
        # grep in the .trace.jsonl / Perfetto search box)
        trace_ids = []
        for s in scheduled[:32]:
            t = (getattr(s.request, "additional_information", None)
                 or {}).get("trace")
            if t and t.get("trace_id") and len(trace_ids) < 8:
                if t["trace_id"] not in trace_ids:
                    trace_ids.append(t["trace_id"])
        self.flight.append({
            "path": path,
            "unified": unified,
            "fallback": fallback,
            "prefills": len(sched_out.prefills),
            "decodes": len(sched_out.decodes),
            "spec_rows": len(spec_rows),
            "verify_tokens": sum(s.num_new_tokens for s in spec_rows),
            "new_tokens": new_tokens,
            "prefill_tokens": sum(s.num_new_tokens
                                  for s in sched_out.prefills),
            "waiting": len(self.scheduler.waiting),
            "running": len(self.scheduler.running),
            "host_ms": round(host_ms, 3),
            "device_ms": round(device_ms, 3),
            "kv_offloads": offloads,
            "kv_restores": restores,
            "slot": {"occupied": inflight is not None,
                     "rows": (len(rows) if isinstance(rows, dict)
                              else None)},
            "compiles": int(compile_stats.get("compiles", 0)),
            # which requests rode this step (capped: the record must
            # stay small at any batch size)
            "requests": [s.request.request_id for s in scheduled[:32]],
            # v3: roofline attribution + journey cross-link (capped)
            "mfu": roofline["mfu"] if roofline else None,
            "mbu": roofline["mbu"] if roofline else None,
            "roofline_phase": roofline["phase"] if roofline else None,
            "trace_ids": trace_ids,
        })

    def _padding_totals(self) -> tuple[int, int]:
        """Runner-side lifetime (useful, padded) token counters — the
        per-step deltas feed the padding-efficiency metrics."""
        return (getattr(self.runner, "useful_tokens", 0),
                getattr(self.runner, "padded_tokens", 0))

    def _observe_padding(self, useful_before: int, padded_before: int
                         ) -> None:
        useful, padded = self._padding_totals()
        self.step_metrics.on_padding(useful - useful_before,
                                     padded - padded_before)

    def _note_first_scheduled(self, scheduled) -> None:
        """First-time-scheduled bookkeeping shared by the synchronous
        and pipelined paths: the queue_wait trace span and the
        queue_wait_ms histogram (arrival -> first scheduled, monotonic
        duration — the queueing term the serving curve bends on)."""
        rec = get_recorder()
        now_w = time.time()
        now_m = time.monotonic()
        for s in scheduled:
            req = s.request
            if req.request_id in self._trace_started:
                continue
            self._trace_started.add(req.request_id)
            wait_s = (max(now_m - req.arrival_mono, 0.0)
                      if req.arrival_mono else 0.0)
            self.step_metrics.queue_wait_ms.observe(wait_s * 1e3)
            self.attribution.add(req.tenant, "queue_wait_ms",
                                 wait_s * 1e3)
            ctx = req.additional_information.get("trace")
            if ctx and req.arrival_time:
                # span START stays wall-clock (trace timelines align on
                # wall timestamps); the DURATION is monotonic.  The
                # tenant rides the args so WFQ queue-wait reads
                # per-tenant straight off the timeline
                rec.record(ctx, "queue_wait", req.arrival_time,
                           wait_s if req.arrival_mono
                           else now_w - req.arrival_time,
                           stage_id=self.stage_id, cat="queue",
                           args={"tenant": getattr(req, "tenant",
                                                   "default")},
                           **self.span_tags)

    def _observe_saturation(self, sched_out: SchedulerOutput) -> None:
        """Per-phase saturation gauges from this schedule: prefill and
        decode token-budget fractions + running-seat fraction — the
        axis that pins first is where the serving curve knees."""
        budget = max(self.config.max_num_batched_tokens, 1)
        prefill_toks = sum(s.num_new_tokens for s in sched_out.prefills)
        decode_toks = sum(s.num_new_tokens for s in sched_out.decodes)
        self.step_metrics.on_saturation(
            prefill=prefill_toks / budget,
            decode=decode_toks / budget,
            seats=(len(self.scheduler.running)
                   / max(self.config.max_num_seqs, 1)),
        )

    def metrics_snapshot(self) -> dict:
        """Step-level engine metrics for /metrics (Prometheus + JSON):
        latency histograms, scheduler depth + preemption/rejection
        counters, KV page utilization, prefix-cache effectiveness."""
        kv = self.scheduler.kv
        used = kv.num_pages - kv.num_free_pages
        snap = self.step_metrics.snapshot()
        snap["scheduler"] = {
            "waiting": len(self.scheduler.waiting),
            "running": len(self.scheduler.running),
            "preemptions": getattr(self.scheduler, "num_preemptions", 0),
            "rejections": getattr(self.scheduler, "num_rejections", 0),
        }
        # serving-curve observability: per-tenant queue depth + the
        # admission-control shed ledger (docs/load_testing.md)
        snap["queue"] = {
            "depth_by_tenant": self.scheduler.queue_depth_by_tenant(),
        }
        snap["shed"] = {
            f"{reason}/{tenant}": n
            for (reason, tenant), n in sorted(
                self.scheduler.shed_counts.items())
        }
        # weighted-fair queueing deferral ledger (only the AR
        # scheduler keeps one; wfq_deferred_requests_total on /metrics)
        wfq = getattr(self.scheduler, "wfq_deferred", None)
        if wfq:
            snap["wfq"] = {"deferred_by_tenant": dict(wfq)}
        snap["kv"] = {
            "pages_total": kv.num_pages,
            "pages_used": used,
            "utilization": round(used / kv.num_pages, 4),
            # resident layout label + amortized HBM cost per cached
            # token (all layers) — the capacity story the int8 pool
            # exists for (docs/performance.md)
            "cache_dtype": getattr(
                self.runner, "kv_cache_dtype",
                str(jnp.dtype(self.config.dtype))),
            "bytes_per_token": self._kv_bytes_per_token,
        }
        snap["prefix_cache"] = self.prefix_cache_stats
        if self.kv_tiers is not None:
            st = self.kv_tiers.stats()
            snap["kv_tiers"] = {
                # pages holding live KV on the device (tables + hot
                # cache nodes) vs. payload entries parked per cold tier
                "hbm_pages": kv.num_pages - len(kv._free),
                "host_pages": st["host_entries"],
                "remote_pages": st["remote_entries"],
                "host_bytes": st["host_bytes"],
                "bytes_moved": st["bytes_moved"],
                "prefix_hit_tokens": kv.prefix_hit_tokens,
                "restored_tokens": kv.restored_tokens,
                "parked_tokens": kv.parked_tokens,
                "offload_evictions": kv.offload_evictions,
            }
        compile_stats = getattr(self.runner, "compile_stats", None)
        if compile_stats is not None:
            snap["compile"] = dict(compile_stats)
        if self.roofline is not None:
            # rolling-window MFU/MBU (engine_step_mfu /
            # engine_step_mbu{phase}); the per-step series rides the
            # flight recorder and /debug/engine
            rf = self.roofline.snapshot(recent=0)
            snap["roofline"] = {"mfu": rf["mfu"], "mbu": rf["mbu"],
                                "window_steps": rf["window_steps"]}
        if self.config.async_scheduling:
            snap["async_fallback"] = dict(self.async_fallback)
        # per-tenant heavy-hitter boards (metrics/attribution.py):
        # top-k per meter, inside the tenant-cardinality budget
        snap["attribution"] = self.attribution.snapshot()
        # device-memory ledger: per-component live/peak bytes
        # (device_memory_bytes{component} on /metrics; refresh is a
        # cold-path metadata walk + optional allocator probe)
        snap["device_memory"] = self.memory.refresh()
        return snap

    def step(self) -> list[OmniRequestOutput]:
        # deterministic stall injection for the watchdog/debugz tests
        # (resilience/faults.py site "step": delay_ms stalls every step,
        # fail_step raises into the caller) — one dict lookup when no
        # fault plan is installed
        fault_point("step")
        t_step0 = time.perf_counter()
        # deadline sweep BEFORE scheduling: expired requests become
        # deadline_exceeded outputs this very step instead of consuming
        # another forward (resilience/deadline.py)
        self.scheduler.expire_deadlines()
        # surface intake-rejected requests as errored outputs instead of
        # silently dropping them
        errored_reqs = self.scheduler.drain_errored()
        for r in errored_reqs:
            self._req_lat.pop(r.request_id, None)
            self._trace_started.discard(r.request_id)
            if (r.additional_information.get("error_kind")
                    == "deadline_exceeded"):
                from vllm_omni_tpu.resilience.metrics import (
                    resilience_metrics,
                )

                resilience_metrics.inc("deadline_exceeded_total",
                                       stage=self.stage_id)
        errored = [OmniRequestOutput.from_pipeline(r)
                   for r in errored_reqs]
        if self.config.async_scheduling:
            outs = errored + self._step_async(t_step0)
        else:
            sched_out = self.scheduler.schedule()
            outs = errored + self._run_scheduled(sched_out, t_step0)
        # counted at COMPLETION: a step wedged mid-flight never
        # advances the watchdog's progress signal
        self._steps_completed += 1
        return outs

    # ------------------------------------------------ async pipelined step
    def _note_fallback(self, reason: str) -> None:
        self.async_fallback[reason] = self.async_fallback.get(
            reason, 0) + 1

    def _step_async(self, t_step0: float) -> list[OmniRequestOutput]:
        """Two-slot pipelined step: dispatch step N BEFORE retiring step
        N-1 — the device starts computing N while the host does N-1's
        token readback, stop checks, and bookkeeping, plus (on the next
        call) N+1's scheduling.  Since PR 11 EVERY batch shape rides the
        pipeline (mixed prefill+decode, spec verify, logprobs,
        collect_hidden, embeds — the unified executable serves them
        all); only host-synchronous KV movement (cross-stage transfer,
        tier offload drains) and streaming chunk intake drain to the
        synchronous path, counted per reason in ``async_fallback``."""
        ready, reason = self._pipeline_ready()
        if ready:
            sched_out = self.scheduler.schedule()
            self.step_metrics.on_schedule(
                waiting=len(self.scheduler.waiting),
                running=len(self.scheduler.running),
            )
            if sched_out.num_scheduled == 0 and self._inflight is not None:
                # pipeline bubble: everything schedulable is waiting on
                # the in-flight retire (e.g. a spec verify's accept
                # count pins the request's next KV position) — retire
                # now; the freed knowledge schedules next step
                outs, _ = self._drain_pipeline()
                return outs
            if self._pipeline_eligible(sched_out):
                return self._step_pipelined(sched_out, t_step0)
            # scheduled but not dispatchable (e.g. page pressure
            # preempted the whole batch, or the reshaped batch fell off
            # the unified fast path): drain the pipeline, drop requests
            # the retire just finished from the stale schedule, and run
            # the remainder synchronously
            self._note_fallback("reshaped")
            outs, drain_wait = self._drain_pipeline()
            drop = lambda ss: [  # noqa: E731
                s for s in ss
                if not s.request.is_finished
                and s.request.status is RequestStatus.RUNNING
            ]
            sched_out.decodes = drop(sched_out.decodes)
            sched_out.prefills = drop(sched_out.prefills)
            return outs + self._run_scheduled(
                sched_out, t_step0, skip_on_schedule=True,
                drained_wait_s=drain_wait, fallback="reshaped")
        # fallback step (prefills / spec / logprobs / streaming / ...):
        # retire FIRST so scheduling sees post-retire state and decode
        # inputs are host-visible for the synchronous runner
        if reason is not None:
            self._note_fallback(reason)
        outs, drain_wait = self._drain_pipeline()
        sched_out = self.scheduler.schedule()
        return outs + self._run_scheduled(sched_out, t_step0,
                                          drained_wait_s=drain_wait,
                                          fallback=reason)

    def _pipeline_ready(self) -> "tuple[bool, Optional[str]]":
        """Cheap pre-schedule check: can the NEXT step be dispatched
        ahead of token knowledge?  Since PR 11 the list of drain
        reasons is exactly the host-synchronous ones — KV movement and
        streaming chunk intake; spec/logprobs/collect_hidden/embeds
        batches pipeline through the unified dispatch and CANNOT
        produce a fallback (docs/async_engine.md).  Returns (ready,
        fallback_reason) — reason is None when there is simply nothing
        to dispatch."""
        s = self.scheduler
        if not s.running and not s.waiting:
            return False, None  # idle: nothing to pipeline
        if self.config.kv_transfer is not None or s._pending_kv_transfers:
            return False, "kv_transfer"
        if self.kv_tiers is not None and (
                s.kv.has_pending_moves()
                or any(r.additional_information.get("_parked_len")
                       for r in s.waiting)):
            # tier moves are host-synchronous (batched extract/inject
            # between schedule and execute): run those steps sync
            return False, "kv_offload"
        for r in list(s.running) + list(s.waiting):
            if r.awaiting_chunks:
                # chunk intake mutates the prompt between steps — the
                # one remaining host-state hazard
                return False, "streaming"
        return True, None

    def _pipeline_eligible(self, sched_out: SchedulerOutput) -> bool:
        """Post-schedule check on the actual output (preemption may have
        reshaped it): every decode input token either host-visible or
        device-resident in the in-flight handle, no KV movement queued
        by this very schedule, and the batch packs into ONE unified
        group (multi-group steps exist only under the one-shot
        generation scheduler, which is never async)."""
        if not sched_out.decodes and not sched_out.prefills:
            return False
        if sched_out.kv_transfer_requests:
            return False
        if self.kv_tiers is not None \
                and self.scheduler.kv.has_pending_moves():
            # this very schedule() queued tier moves (eviction offload
            # or a cold-prefix restore): they must drain before the
            # forward runs, so the step goes synchronous
            return False
        prev = self._inflight
        for s in sched_out.decodes:
            if s.start_pos >= s.request.num_tokens and (
                    prev is None
                    or s.request.request_id not in prev.handle.rows):
                return False
        if not self.runner._plain_decode_only(sched_out) \
                and not self.runner.fits_unified(sched_out):
            return False
        return True

    def _step_pipelined(self, sched_out: SchedulerOutput,
                        t_step0: float) -> list[OmniRequestOutput]:
        rec = get_recorder()
        prev = self._inflight
        scheduled = sched_out.prefills + sched_out.decodes
        self._note_first_scheduled(scheduled)
        self._observe_saturation(sched_out)
        t_d0, w_d0 = time.perf_counter(), time.time()
        u0, p0 = self._padding_totals()
        if self.runner._plain_decode_only(sched_out):
            handle = self.runner.dispatch_decode(
                sched_out.decodes,
                prev.handle if prev is not None else None,
            )
        else:
            # unified dispatch: prefill chunks, spec verify rows,
            # logprobs, and embeds batches pipeline too
            handle = self.runner.dispatch_unified(
                sched_out, prev.handle if prev is not None else None)
        # schedule-ahead accounting: the dispatched rows' tokens are
        # now in flight; the next schedule() counts them without seeing
        # their values
        self.scheduler.note_async_dispatch(sched_out)
        self._observe_padding(u0, p0)
        dur_disp = time.perf_counter() - t_d0
        for s in scheduled:
            rec.record(s.request.additional_information.get("trace"),
                       "dispatch", w_d0, dur_disp,
                       stage_id=self.stage_id,
                       args={"batch": len(scheduled)},
                       **self.span_tags)
        self._inflight = _InflightStep(sched_out=sched_out, handle=handle)
        outs: list[OmniRequestOutput] = []
        new_total = 0
        wait_s = 0.0
        if prev is not None:
            # step N-1's host work, overlapped with step N's compute
            outs, new_total, wait_s = self._retire_step(prev)
            if not self.scheduler.has_unfinished:
                # the step just dispatched is pure overshoot (every
                # request finished at this retire): drain it now instead
                # of dangling device buffers + finished requests until
                # the next traffic burst
                extra, drain_wait = self._drain_pipeline()
                outs += extra
                wait_s += drain_wait
        total_s = time.perf_counter() - t_step0
        host_ms = max(total_s - wait_s, 0.0) * 1e3
        # with a predecessor in flight, schedule+dispatch overlapped ITS
        # compute and the retire's post-wait work overlaps the step just
        # dispatched — the only unoverlapped host time is the wait
        self.step_metrics.on_step(
            step_ms=total_s * 1e3, new_tokens=new_total,
            prefill_tokens=sum(s.num_new_tokens
                               for s in sched_out.prefills),
            host_ms=host_ms, device_ms=wait_s * 1e3,
            overlapped_host_ms=host_ms if prev is not None else 0.0,
        )
        self._record_step("pipelined", sched_out, scheduled, new_total,
                          host_ms=host_ms, device_ms=wait_s * 1e3)
        return outs

    def _consolidate_hidden(self, finished) -> None:
        """Fold per-step hidden chunks into the next-stage payload
        (reference pooler_output routing, engine/output_processor.py:246)
        — shared by the sync step and the async lagged retire, which
        both finish requests."""
        if not self.config.collect_hidden:
            return
        import numpy as np

        for r in finished:
            chunks = r.additional_information.pop("_hidden_chunks", None)
            if chunks:
                r.multimodal_output["hidden_states"] = np.concatenate(
                    chunks, axis=0
                )

    def _retire_step(self, inflight: _InflightStep):
        """Retire a dispatched step: the single lagged device_get, then
        token append / stop checks / latency bookkeeping.  Returns
        (outputs, new_tokens, seconds spent blocked on the device)."""
        rec = get_recorder()
        t_g0, w_g0 = time.perf_counter(), time.time()
        sampled = self.runner.retire_step(inflight.handle)
        wait_s = time.perf_counter() - t_g0
        finished = self.scheduler.update_from_async_retire(
            inflight.sched_out, sampled)
        self._consolidate_hidden(finished)
        scheds = (inflight.sched_out.prefills
                  + inflight.sched_out.decodes)
        # only requests that could have appended a token this retire:
        # an overshoot row for a request that finished at the PREVIOUS
        # retire (or was aborted/expired mid-flight) already had its
        # latency entry popped — setdefault would resurrect it with a
        # zero token count, re-counting the whole stream into
        # tokens_generated/TTFT and leaking the entry forever
        just_finished = {r.request_id for r in finished}
        live = [s for s in scheds
                if not s.request.is_finished
                or s.request.request_id in just_finished]
        new_total = self._observe_token_latencies(live, finished)
        dur = time.perf_counter() - t_g0
        for s in scheds:
            rec.record(s.request.additional_information.get("trace"),
                       "retire", w_g0, dur, stage_id=self.stage_id,
                       args={"batch": len(scheds)}, **self.span_tags)
        outs = [OmniRequestOutput.from_pipeline(r) for r in finished]
        return outs, new_total, wait_s

    def _drain_pipeline(self) -> tuple[list[OmniRequestOutput], float]:
        """Retire the in-flight step (if any) so the host state is fully
        caught up before a synchronous step runs.  Returns (outputs,
        seconds blocked on the device) — the caller folds the wait into
        its step's device time so the host/device breakdown stays
        honest across pipeline-to-sync transitions."""
        if self._inflight is None:
            return [], 0.0
        inflight, self._inflight = self._inflight, None
        outs, new_total, wait_s = self._retire_step(inflight)
        # the drained step has no on_step of its own (the sync step that
        # follows records this call's single on_step, and its per-request
        # deltas were already consumed here): credit the tokens directly
        # so throughput counters stay exact
        self.step_metrics.tokens_generated += new_total
        return outs, wait_s

    # ------------------------------------------------------ kv tier moves
    def _drain_kv_moves(self) -> set[str]:
        """Drain the KV manager's queued tier moves between schedule()
        and execute(): batched extraction of evicted/parked pages (ONE
        pytree transfer for every payload this step), then injection of
        queued restores (per-request contiguous runs, one transfer
        each).  Extractions run FIRST — a page reclaimed by eviction
        may be the very page a restore was just given.  Returns the
        request_ids whose restore came up short (payload vanished
        between match and fetch); the caller must drop their scheds
        from this step before executing."""
        kv = self.scheduler.kv
        self._last_kv_moves = (0, 0)
        if self.kv_tiers is None or not kv.has_pending_moves():
            return set()
        offloads, restores = kv.take_pending_moves()
        self._last_kv_moves = (len(offloads), len(restores))
        failed: set[str] = set()
        if offloads:
            payloads = self.runner.extract_kv_batch(
                [(o.pages, o.n_tokens) for o in offloads])
            for o, payload in zip(offloads, payloads):
                self.kv_tiers.put(o.key, payload)
                kv.note_park_extracted(o.key)
        by_req: dict[str, list] = {}
        for r in restores:
            by_req.setdefault(r.request_id, []).append(r)
        for rid, entries in by_req.items():
            t0 = time.perf_counter()
            pages: list[int] = []
            parts: list[list] = []
            keep_tokens = 0
            fail_at: Optional[int] = None
            for i, e in enumerate(entries):
                payload = self.kv_tiers.fetch(e.key)
                if payload is None:
                    fail_at = i
                    # the contiguous valid prefix ends where the failed
                    # payload would have STARTED (cold entries can
                    # interleave with already-hot pages, so a sum of
                    # injected lengths would overshoot)
                    keep_tokens = e.start_tokens
                    break
                pages.extend(e.pages)
                parts.append(payload)
                keep_tokens = e.start_tokens + e.n_tokens
                if e.drop_after:
                    self.kv_tiers.drop(e.key)
            if parts:
                if len(parts) == 1:
                    payload = parts[0]
                else:
                    # format-agnostic stitch (kvcache/quant.py): dense
                    # parts concat on the token axis; quantized parts
                    # concat data + per-page scales (radix node runs
                    # are page-aligned, so scales never split a page)
                    payload = concat_payloads(
                        parts, self.config.page_size)
                self.runner.inject_kv(pages, payload)
                self.kv_tiers.restored_tokens += sum(
                    e.n_tokens for e in entries[:len(parts)])
                self.step_metrics.kv_restore_s.observe(
                    time.perf_counter() - t0)
            if fail_at is not None:
                unwound = entries[fail_at:]
                kv.restored_tokens -= sum(e.n_tokens for e in unwound)
                # restore_failed also truncates any request that
                # co-adopted a failed node hot in the same pass — its
                # scheds are misaligned too and must drop with ours
                failed |= self.scheduler.restore_failed(
                    rid, unwound, keep_tokens)
                failed.add(rid)
        return failed

    # --------------------------------------------------- synchronous step
    def _run_scheduled(self, sched_out: SchedulerOutput, t_step0: float,
                       skip_on_schedule: bool = False,
                       drained_wait_s: float = 0.0,
                       fallback: Optional[str] = None
                       ) -> list[OmniRequestOutput]:
        failed_restores = self._drain_kv_moves()
        if failed_restores:
            # a restore came up short: this step's chunks for those
            # requests are positionally misaligned (start_pos past the
            # rewound num_computed_tokens) — drop them; the scheduler
            # re-chunks the remainder next step
            sched_out.prefills = [
                s for s in sched_out.prefills
                if s.request.request_id not in failed_restores]
            sched_out.decodes = [
                s for s in sched_out.decodes
                if s.request.request_id not in failed_restores]
            if sched_out.num_scheduled == 0:
                # everything scheduled this step was a casualty: the
                # rewound requests are RUNNING and re-chunk next step —
                # don't fall through to the starvation/deadlock checks
                return []
        if not skip_on_schedule:
            self.step_metrics.on_schedule(
                waiting=len(self.scheduler.waiting),
                running=len(self.scheduler.running),
            )
        if sched_out.num_scheduled == 0:
            if self.scheduler.waiting:
                if any(r.awaiting_chunks for r in self.scheduler.running):
                    # an idle streaming request makes zero-scheduled ticks
                    # a NORMAL long-lived state (upstream may be slow) —
                    # the tick counter would error-finish healthy waiting
                    # requests within milliseconds
                    self._starved_ticks = 0
                    return []
                # Transient zero-scheduled ticks happen while pages are
                # pinned by an in-flight KV-transfer awaiting its ACK —
                # only declare starvation after a few consecutive ticks.
                self._starved_ticks += 1
                if self._starved_ticks < 3:
                    return []
                self._starved_ticks = 0
                # Starved: the head waiting request can never fit (e.g. its
                # recompute footprint outgrew the pool). Error-finish it so
                # one bad request can't wedge the whole engine.
                victim = self.scheduler.waiting.pop(0)
                self._req_lat.pop(victim.request_id, None)
                self._trace_started.discard(victim.request_id)
                # error-finished outside scheduler.reject(): count it so
                # rejections_total covers starvation too
                self.scheduler.num_rejections += 1
                victim.status = RequestStatus.FINISHED_ERROR
                victim.additional_information.setdefault(
                    "error",
                    "request starved: does not fit in the KV cache "
                    f"({self.scheduler.kv.num_free_pages} pages free)",
                )
                # an injected-KV request may already own prefix pages
                # while WAITING — evicting without freeing would leak
                # them; a parked payload of the dead request likewise
                self.scheduler.kv.free(victim)
                self.scheduler.kv.drop_park(victim)
                return [OmniRequestOutput.from_pipeline(victim)]
            stalled = [
                r for r in self.scheduler.running
                if not (r.awaiting_chunks
                        and r.num_computed_tokens >= r.num_tokens)
            ]
            if stalled or self.scheduler.waiting:
                raise RuntimeError(
                    "scheduler deadlock: running requests but nothing "
                    "schedulable"
                )
            # only streaming requests idling for their next chunk remain
            return []
        self._starved_ticks = 0
        rec = get_recorder()
        scheduled = sched_out.prefills + sched_out.decodes
        self._note_first_scheduled(scheduled)
        self._observe_saturation(sched_out)
        t_ex0, w_ex0 = time.perf_counter(), time.time()
        u0, p0 = self._padding_totals()
        run_out = self.runner.execute(
            sched_out, extract_kv=self.kv_transfer_sink is not None
        )
        self._observe_padding(u0, p0)
        dur_ex = time.perf_counter() - t_ex0
        for s in sched_out.prefills:
            rec.record(s.request.additional_information.get("trace"),
                       "prefill", w_ex0, dur_ex, stage_id=self.stage_id,
                       args={"tokens": s.num_new_tokens,
                             "start_pos": s.start_pos},
                       **self.span_tags)
        for s in sched_out.decodes:
            rec.record(s.request.additional_information.get("trace"),
                       "decode", w_ex0, dur_ex, stage_id=self.stage_id,
                       args={"tokens": s.num_new_tokens},
                       **self.span_tags)
        if self.kv_transfer_sink is not None:
            for req, _, _ in sched_out.kv_transfer_requests:
                payload = run_out.extracted_kv.get(req.request_id)
                if payload is not None:
                    self.kv_transfer_sink(req, payload)
        t_up0, w_up0 = time.perf_counter(), time.time()
        finished = self.scheduler.update_from_output(
            sched_out, run_out.sampled, run_out.kv_extracted_req_ids
        )
        dur_up = time.perf_counter() - t_up0
        for s in scheduled:
            rec.record(s.request.additional_information.get("trace"),
                       "sampling", w_up0, dur_up, stage_id=self.stage_id,
                       args={"batch": len(scheduled)}, **self.span_tags)
        new_total = self._observe_token_latencies(scheduled, finished)
        total_s = time.perf_counter() - t_step0
        self.step_metrics.on_step(
            step_ms=total_s * 1e3,
            new_tokens=new_total,
            prefill_tokens=sum(s.num_new_tokens
                               for s in sched_out.prefills),
            # execute() syncs internally, so its span (plus any
            # pipeline-drain wait that preceded it) is the device-bound
            # portion; no host work overlaps it
            host_ms=max(total_s - dur_ex - drained_wait_s, 0.0) * 1e3,
            device_ms=(dur_ex + drained_wait_s) * 1e3,
            overlapped_host_ms=0.0,
        )
        self._record_step(
            "sync", sched_out, scheduled, new_total,
            host_ms=max(total_s - dur_ex - drained_wait_s, 0.0) * 1e3,
            device_ms=(dur_ex + drained_wait_s) * 1e3,
            fallback=fallback)
        self._consolidate_hidden(finished)
        if not self.scheduler.has_unfinished:
            # no further step will run: drain transfers triggered just now
            # so finished requests still ship their KV
            for req, block_ids, seq_len in \
                    self.scheduler.drain_pending_kv_transfers():
                if self.kv_transfer_sink is not None:
                    payload = self.runner.extract_kv(block_ids, seq_len)
                    self.kv_transfer_sink(req, payload)
                self.scheduler.update_from_output(
                    SchedulerOutput(), {}, {req.request_id})
        return [OmniRequestOutput.from_pipeline(r) for r in finished]

    def _observe_token_latencies(self, scheduled, finished) -> int:
        """TTFT / ITL / TPOT bookkeeping from the host-visible token
        deltas (shared by the sync step and the async lagged retire);
        returns the number of new tokens observed.  All durations are
        monotonic-to-monotonic (``Request.arrival_mono``) — a wall
        clock stepped by NTP mid-request must never corrupt the
        latency histograms or the SLO verdicts built on them."""
        now = time.monotonic()
        sm = self.step_metrics
        new_total = 0
        for s in scheduled:
            req = s.request
            n_out = len(req.output_token_ids)
            # [first_token_mono, last_token_mono, tokens_seen, ttft_ms]
            st = self._req_lat.setdefault(req.request_id,
                                          [0.0, 0.0, 0, None])
            if n_out <= st[2]:
                continue
            new = n_out - st[2]
            new_total += new
            if st[2] == 0:
                if req.arrival_mono:
                    st[3] = (now - req.arrival_mono) * 1e3
                    sm.ttft_ms.observe(st[3])
                st[0] = now
                new -= 1  # the first token is TTFT, not an ITL
            if new > 0 and st[1]:
                # a multi-step window emits its tokens in one host round
                # trip: amortize the gap over them
                sm.itl_ms.observe((now - st[1]) * 1e3 / new, n=new)
            st[1] = now
            st[2] = n_out
        for req in finished:
            st = self._req_lat.pop(req.request_id, None)
            self._trace_started.discard(req.request_id)
            n_out = len(req.output_token_ids)
            tpot = None
            if st and st[0] and n_out > 1:
                tpot = (now - st[0]) * 1e3 / (n_out - 1)
                sm.tpot_ms.observe(tpot)
            # SLO verdict per finished request (per-tenant attainment +
            # goodput): TTFT unknown (e.g. a request that finished on
            # its first observed token batch before a TTFT stamp
            # existed) judges as inf against a configured target
            if st is not None:
                ttft = st[3] if st[3] is not None else (
                    float("inf") if sm.slo_ttft_ms is not None else 0.0)
                sm.on_request_slo(req.tenant, ttft, tpot, n_out)
            # heavy-hitter token attribution, metered at finish (one
            # sketch update per request, not per token)
            self.attribution.add(req.tenant, "prefill_tokens",
                                 req.num_prompt_tokens)
            self.attribution.add(req.tenant, "decode_tokens", n_out)
        # KV occupancy attribution: fold the manager's host-int
        # interval clock into the sketch (engine thread — the KV
        # manager is single-threaded by contract)
        drained = self.scheduler.kv.drain_page_seconds()
        for tier, by_tenant in drained.items():
            for tenant, secs in by_tenant.items():
                self.attribution.add(tenant, f"kv_page_seconds_{tier}",
                                     secs)
        return new_total

    # ---------------------------------------------------------- generate()
    def generate(
        self,
        prompts_token_ids: list[list[int]],
        sampling_params: Optional[SamplingParams | list[SamplingParams]] = None,
    ) -> list[OmniRequestOutput]:
        """Blocking batch generate — the reference's OmniLLM._run_engine
        step loop (reference: entrypoints/omni_llm.py:199-241)."""
        if isinstance(sampling_params, list):
            if len(sampling_params) != len(prompts_token_ids):
                raise ValueError(
                    f"sampling_params length {len(sampling_params)} != "
                    f"prompts length {len(prompts_token_ids)}"
                )
            params_list = sampling_params
        else:
            params_list = [sampling_params] * len(prompts_token_ids)
        order = {}
        for toks, sp in zip(prompts_token_ids, params_list):
            rid = self.add_request(toks, sp)
            order[rid] = len(order)
        results: dict[str, OmniRequestOutput] = {}
        while self.has_unfinished_requests:
            for out in self.step():
                results[out.request_id] = out
        # requests rejected at intake when no step ran afterwards
        for req in self.scheduler.drain_errored():
            out = OmniRequestOutput.from_pipeline(req)
            results[out.request_id] = out
        return [results[rid] for rid in
                sorted(results, key=lambda r: order.get(r, 1 << 30))]
