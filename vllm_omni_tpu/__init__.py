"""vllm_omni_tpu — a TPU-native omni-modality inference & serving framework.

Brand-new JAX/XLA/Pallas implementation with the capabilities of the
vLLM-Omni reference (see SURVEY.md): autoregressive engines with continuous
batching over a paged KV cache, Diffusion-Transformer engines, multi-stage
heterogeneous pipelines with disaggregated stage transfer, and an
OpenAI-compatible serving layer — all with no GPU/CUDA in the loop.
"""

from vllm_omni_tpu.version import __version__

__all__ = [
    "__version__",
    "Omni",
    "OmniModelConfig",
    "OmniDiffusionConfig",
]


def __getattr__(name):
    # Lazy top-level exports (reference: vllm_omni/__init__.py:24-43) so
    # `import vllm_omni_tpu` stays light for kernel-only users.
    if name == "Omni":
        from vllm_omni_tpu.entrypoints.omni import Omni

        return Omni
    if name == "OmniModelConfig":
        from vllm_omni_tpu.config.model import OmniModelConfig

        return OmniModelConfig
    if name == "OmniDiffusionConfig":
        from vllm_omni_tpu.config.diffusion import OmniDiffusionConfig

        return OmniDiffusionConfig
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
