"""Backend dispatch for ops: pallas-native (TPU), pallas-interpret (CPU
tests), or pure-XLA fallback.  The analogue of the reference's attention
backend selector (vllm_omni/diffusion/attention/selector.py:54-85) and
CustomOp dispatch base (diffusion/layers/custom_op.py:9)."""

from __future__ import annotations

import functools


@functools.cache
def pallas_mode() -> str:
    """"native" | "interpret" | "off"."""
    from vllm_omni_tpu import envs
    from vllm_omni_tpu.platforms import current_platform

    if envs.OMNI_TPU_PALLAS_INTERPRET:
        return "interpret"
    if current_platform().supports_pallas:
        return "native"
    return "interpret"


def interpret_flag() -> bool:
    return pallas_mode() == "interpret"
