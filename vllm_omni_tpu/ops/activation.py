"""Gated-MLP activations (reference: vLLM's fused SiLU-mul CUDA op,
SURVEY.md §2.10).  On TPU, XLA fuses these elementwise ops into the
surrounding matmuls, so the idiomatic implementation is plain jnp — kept
here as named ops so model code reads like the reference's layer inventory.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def silu_mul(gate_up: jax.Array) -> jax.Array:
    """Input [..., 2*d] = concat(gate, up); returns silu(gate) * up."""
    gate, up = jnp.split(gate_up, 2, axis=-1)
    return jax.nn.silu(gate) * up


def gelu_tanh_mul(gate_up: jax.Array) -> jax.Array:
    gate, up = jnp.split(gate_up, 2, axis=-1)
    return jax.nn.gelu(gate, approximate=True) * up
