"""Routed MoE dispatch — grouped matmul (ragged_dot) + expert-parallel
shard_map.

Replaces the round-1 dense-dispatch MoE (every expert computed every
token — a k/E FLOP waste; VERDICT r1 weak#4) with real top-k routing.
Reference semantics: vLLM's fused MoE consumed by the Qwen3-Omni
thinker/talker (reference: models/qwen3_omni/qwen3_moe.py; EP via
all-to-all token dispatch, SURVEY.md §2.11).

TPU-first mechanics:
- **Local (single shard)**: sort token-expert pairs by expert id, run the
  expert MLPs as ONE grouped matmul per projection (``jax.lax.ragged_dot``
  — rides the MXU with static [T*k, ...] shapes), scatter-add back with
  the renormalized router weights.  FLOPs scale with top-k, not E.
- **Expert parallel**: ``shard_map`` over the ``ep`` mesh axis with the
  stacked expert weights sharded on their leading E axis.  Activations are
  replicated across ep; each shard computes only the pairs routed to its
  local experts (masked to zero-weight elsewhere — pair count stays the
  static T*k, so no capacity drops and numerics match the dense oracle
  exactly), and the partial outputs combine with one ``psum``.  This is
  the GSPMD-friendly analogue of the reference's all-to-all dispatch; the
  token-sharded all-to-all variant is the dp x ep follow-up.

The dense path stays in models/common/transformer.py as the test oracle.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.ops.activation import silu_mul

# Engine-configured mesh for EP dispatch (set once before tracing; the
# transformer's pure functions read it at trace time).
_EP_MESH = None


def set_ep_mesh(mesh) -> None:
    """Register (or clear, with None) the mesh whose ``ep`` axis routed
    MoE should shard experts over."""
    global _EP_MESH
    _EP_MESH = mesh


def ep_mesh():
    if _EP_MESH is not None:
        ax = dict(zip(_EP_MESH.axis_names, _EP_MESH.devices.shape))
        if ax.get("ep", 1) > 1:
            return _EP_MESH
    return None


def router_topk(x, router_w, num_experts_per_tok: int,
                renormalize: bool = True):
    """Softmax router -> top-k (idx [T,k], weights [T,k]).

    ``renormalize`` divides the kept weights by their sum (Qwen3-MoE
    norm_topk_prob=True); the Qwen3-Omni talker keeps the raw softmax
    mass (norm_topk_prob=False)."""
    logits = x @ router_w  # [T, E]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    topk_w, topk_idx = jax.lax.top_k(probs, num_experts_per_tok)
    if renormalize:
        topk_w = topk_w / jnp.sum(topk_w, axis=-1, keepdims=True)
    return topk_idx, topk_w


def _grouped_mlp(xs, gate_up, down, group_sizes):
    """One grouped-matmul MLP over expert-sorted rows."""
    h = jax.lax.ragged_dot(xs, gate_up, group_sizes)
    h = silu_mul(h)
    return jax.lax.ragged_dot(h, down, group_sizes)


def routed_moe(
    x: jax.Array,          # [T, hidden]
    router_w: jax.Array,   # [hidden, E]
    gate_up: jax.Array,    # [E, hidden, 2*inter]
    down: jax.Array,       # [E, inter, hidden]
    num_experts_per_tok: int,
    renormalize: bool = True,
) -> jax.Array:
    """Top-k routed MoE on one shard: sort pairs by expert, grouped
    matmul, weighted scatter-add."""
    t, hidden = x.shape
    e = gate_up.shape[0]
    k = num_experts_per_tok
    topk_idx, topk_w = router_topk(x, router_w, k, renormalize)

    flat_e = topk_idx.reshape(-1)                    # [T*k]
    flat_w = topk_w.reshape(-1)                      # [T*k]
    order = jnp.argsort(flat_e)                      # stable
    token_of = order // k                            # source token per pair
    xs = x[token_of]                                 # [T*k, hidden]
    group_sizes = jnp.bincount(flat_e, length=e)
    y = _grouped_mlp(xs, gate_up, down, group_sizes)  # [T*k, hidden]
    y = y * flat_w[order][:, None].astype(y.dtype)
    out = jnp.zeros((t, hidden), y.dtype).at[token_of].add(y)
    return out.astype(x.dtype)


def _routed_moe_ep_shard(x, router_w, gate_up, down, k: int,
                         renormalize: bool = True):
    """Per-ep-shard body: full token set, local expert slab.  Pairs routed
    to remote experts keep their slot (static shapes) but are masked to
    weight zero and land in a local expert group; the psum over ep sums
    exactly one live contribution per pair."""
    e_local = gate_up.shape[0]
    shard = jax.lax.axis_index("ep")
    lo = shard * e_local

    topk_idx, topk_w = router_topk(x, router_w, k, renormalize)
    flat_e = topk_idx.reshape(-1)
    flat_w = topk_w.reshape(-1)
    mine = (flat_e >= lo) & (flat_e < lo + e_local)
    local_e = jnp.where(mine, flat_e - lo, 0)
    flat_w = jnp.where(mine, flat_w, 0.0)

    order = jnp.argsort(local_e)
    token_of = order // k
    xs = x[token_of]
    group_sizes = jnp.bincount(local_e, length=e_local)
    y = _grouped_mlp(xs, gate_up, down, group_sizes)
    y = y * flat_w[order][:, None].astype(y.dtype)
    out = jnp.zeros((x.shape[0], x.shape[1]), y.dtype).at[token_of].add(y)
    return jax.lax.psum(out, "ep").astype(x.dtype)


def _moe_a2a_shard(x, router_w, gate_up, down, k: int, capacity: int,
                   renormalize: bool = True):
    """Per-shard body of all-to-all EP dispatch (inside shard_map over
    ``ep``): tokens are SHARDED over ep (x is the local [Tl, H] slice).

    GShard/Switch-style capacity dispatch: each shard scatters its
    routed pairs into per-destination buckets [ep, C, H], one
    ``lax.all_to_all`` ships them to the experts' shards, the local slab
    runs ONE grouped matmul over the received [ep*C] rows, and the
    reverse all_to_all brings results home for the weighted combine.
    Per-shard grouped-matmul rows = ep*C ≈ T*k*factor/ep — compute
    scales DOWN with ep (the property the masked-psum variant lacks;
    VERDICT r2 weak #9).  Pairs beyond a bucket's capacity are dropped
    (combine weight 0); capacity_factor sizes the headroom.
    """
    ep = jax.lax.axis_size("ep")
    e_local = gate_up.shape[0]
    tl, hidden = x.shape
    p = tl * k

    topk_idx, topk_w = router_topk(x, router_w, k, renormalize)
    flat_e = topk_idx.reshape(-1)                  # [P] global expert ids
    flat_w = topk_w.reshape(-1)
    dest = flat_e // e_local                       # destination shard
    local_e = flat_e % e_local

    # slot within the destination bucket (stable order by dest)
    order = jnp.argsort(dest)
    sdest = dest[order]
    counts = jnp.bincount(dest, length=ep)
    starts = jnp.concatenate(
        [jnp.zeros((1,), counts.dtype), jnp.cumsum(counts)[:-1]])
    pos = jnp.arange(p) - starts[sdest]
    keep = pos < capacity
    slot = jnp.minimum(pos, capacity - 1)

    def scatter(vals, width, dtype):
        """[P] (or [P, H]) values -> [ep, C, ...] buckets; dropped pairs
        contribute zero via masked add (no slot collisions among kept)."""
        buf = jnp.zeros((ep, capacity) + (() if width == 0 else (width,)),
                        dtype)
        mask = keep if width == 0 else keep[:, None]
        return buf.at[sdest, slot].add(
            jnp.where(mask, vals, jnp.zeros_like(vals)))

    tok_of = order // k
    buf_x = scatter(x[tok_of], hidden, x.dtype)
    buf_le = scatter(local_e[order].astype(jnp.int32), 0, jnp.int32)

    rx_x = jax.lax.all_to_all(buf_x, "ep", 0, 0)     # [ep_src, C, H]
    rx_le = jax.lax.all_to_all(buf_le, "ep", 0, 0)

    rows = rx_x.reshape(ep * capacity, hidden)
    les = rx_le.reshape(ep * capacity)
    ro = jnp.argsort(les)
    group_sizes = jnp.bincount(les, length=e_local)
    y = _grouped_mlp(rows[ro], gate_up, down, group_sizes)
    y = jnp.zeros_like(y).at[ro].set(y)              # unsort
    ret = jax.lax.all_to_all(
        y.reshape(ep, capacity, hidden), "ep", 0, 0)  # back at sources

    got = ret[sdest, slot]                           # [P, H] per pair
    w = jnp.where(keep, flat_w[order], 0.0)
    out = jnp.zeros((tl, hidden), got.dtype).at[tok_of].add(
        got * w[:, None].astype(got.dtype))
    return out.astype(x.dtype)


def routed_moe_ep_a2a(x, router_w, gate_up, down,
                      num_experts_per_tok: int, mesh,
                      capacity_factor: float = 2.0,
                      renormalize: bool = True) -> jax.Array:
    """Token-sharded dp x ep all-to-all EP dispatch (reference: fused MoE
    all-to-all, worker/gpu_ar_model_runner.py:522-523; SURVEY §2.11 EP).
    Tokens shard over (dp, ep); experts over ep.  Requires divisibility —
    callers fall back to ``routed_moe_ep`` otherwise."""
    import math

    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    ax = dict(zip(mesh.axis_names, mesh.devices.shape))
    ep = ax.get("ep", 1)
    dp = ax.get("dp", 1)
    t = x.shape[0]
    e = gate_up.shape[0]
    if ep == 1 or t % (dp * ep) or e % ep:
        return routed_moe_ep(x, router_w, gate_up, down,
                             num_experts_per_tok, mesh, renormalize)
    tl = t // (dp * ep)
    capacity = max(1, math.ceil(
        num_experts_per_tok * tl / ep * capacity_factor))
    fn = shard_map(
        lambda xx, rw, gu, dn: _moe_a2a_shard(
            xx, rw, gu, dn, num_experts_per_tok, capacity, renormalize),
        mesh=mesh,
        in_specs=(P(("dp", "ep")), P(), P("ep"), P("ep")),
        out_specs=P(("dp", "ep")),
        check_vma=False,
    )
    return fn(x, router_w, gate_up, down)


def routed_moe_ep(x, router_w, gate_up, down, num_experts_per_tok: int,
                  mesh, renormalize: bool = True) -> jax.Array:
    """Expert-parallel routed MoE: experts sharded over the ``ep`` mesh
    axis; tokens stay sharded over ``dp`` (replicated only over ep —
    each dp rank computes its own token slice, each ep shard its local
    experts, one psum over ep combines)."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    ax = dict(zip(mesh.axis_names, mesh.devices.shape))
    dp = ax.get("dp", 1)
    tok_spec = P("dp") if x.shape[0] % max(dp, 1) == 0 else P()
    fn = shard_map(
        lambda xx, rw, gu, dn: _routed_moe_ep_shard(
            xx, rw, gu, dn, num_experts_per_tok, renormalize),
        mesh=mesh,
        in_specs=(tok_spec, P(), P("ep"), P("ep")),
        out_specs=tok_spec,
    )
    return fn(x, router_w, gate_up, down)


# ------------------------------------------------------------------ EPLB
def eplb_assignments(counts, n_shards: int):
    """Expert-parallel load balancing: a permutation placing experts on
    shards so per-shard routed-token load evens out (reference:
    eplb_step, worker/gpu_ar_model_runner.py:522-523).

    ``counts`` [E] — routed tokens per expert (current weight order).
    Returns ``perm`` [E] int array: new_position -> old_index, built
    greedy-LPT (heaviest expert onto the least-loaded shard); slot
    order inside a shard is load-descending.  Identity-stable: balanced
    inputs return a permutation with the same per-shard load.
    """
    counts = np.asarray(counts)
    e = counts.shape[0]
    if e % n_shards:
        raise ValueError(f"{e} experts do not shard over {n_shards}")
    cap = e // n_shards
    order = np.argsort(-counts, kind="stable")
    shard_load = np.zeros(n_shards, counts.dtype)
    shard_slots = [[] for _ in range(n_shards)]
    for idx in order:
        open_shards = [s for s in range(n_shards)
                       if len(shard_slots[s]) < cap]
        s = min(open_shards, key=lambda s: shard_load[s])
        shard_slots[s].append(idx)
        shard_load[s] += counts[idx]
    return np.concatenate([np.asarray(s, np.int64)
                           for s in shard_slots])


def eplb_apply(layer_params: dict, perm) -> dict:
    """Permute one MoE layer's expert placement: expert stacks reorder
    along the leading E axis and the router's output columns follow, so
    logits[t, new_pos] score the expert now stored at new_pos — the
    routed computation is numerically IDENTICAL, only which ep shard
    owns each expert changes."""
    perm = jnp.asarray(perm)
    out = dict(layer_params)
    out["experts"] = {
        "gate_up": layer_params["experts"]["gate_up"][perm],
        "down": layer_params["experts"]["down"][perm],
    }
    out["router"] = dict(layer_params["router"])
    out["router"]["w"] = layer_params["router"]["w"][:, perm]
    return out


def eplb_step(params: dict, counts_per_layer, n_shards: int) -> dict:
    """Rebalance every MoE layer of a transformer param tree.

    ``counts_per_layer``: routed-token counts [n_moe_layers, E], one
    row per MoE layer IN ORDER (dense layers consume no row — the
    serving layer's sampled router statistics only exist for routed
    layers).  Returns a new param tree with permuted expert placement;
    non-MoE layers pass through."""
    layers = []
    li = 0
    for layer in params["layers"]:
        if "experts" in layer:
            perm = eplb_assignments(counts_per_layer[li], n_shards)
            layers.append(eplb_apply(layer, perm))
            li += 1
        else:
            layers.append(layer)
    return {**params, "layers": layers}
