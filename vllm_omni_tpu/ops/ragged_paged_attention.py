"""Ragged paged attention — one token-packed kernel for prefill + decode.

The unified-batching counterpart of ``ops/paged_attention.py`` (single
token per sequence) and the chunked path of ``ops/attention.py`` (padded
[B, S] chunks): variable-length prefill chunks and 1-token decode rows
ride the SAME token-packed launch, so a mixed engine step is ONE device
dispatch instead of the fresh/chunk/decode triple ("Ragged Paged
Attention: A High-Performance and Flexible LLM Inference Kernel for
TPU", PAPERS.md).

Token-packed layout (the metadata contract, see docs/ragged_batching.md):

- ``q [T, H, D]`` — queries of every scheduled sequence, concatenated.
  Sequence ``i``'s ``q_lens[i]`` query tokens occupy rows
  ``cu_q_lens[i] .. cu_q_lens[i] + q_lens[i])``.  Segment starts are
  aligned to ``token_block`` rows (the per-sequence q-block size), so a
  (sequence, q-block) grid cell owns an EXCLUSIVE output region — the
  alignment gap is at most ``token_block - 1`` rows per sequence,
  replacing the ``batch_bucket x seq_bucket`` padding of the split path.
- ``cu_q_lens [S+1]`` — aligned segment starts (row offsets into ``q``);
  ``cu_q_lens[num_seqs]`` is the packed end.  NOT simply the cumsum of
  ``q_lens`` — alignment rounds each segment up.
- ``q_lens [S]`` — real (unaligned) query-token count per sequence;
  0 for padding rows of the metadata arrays.
- ``seq_lens [S]`` — context length per sequence INCLUDING this chunk
  (the ``context_lens`` convention of ``forward_prefill_chunked``).
- ``page_tables [S, max_pages]`` — KV page ids covering each context.
- ``num_seqs`` — sequences actually present (rows past it are padding).

Causality is per-token global positions: query ``j`` of sequence ``i``
sits at ``seq_lens[i] - q_lens[i] + j`` and attends keys at positions
``<= `` that (the ``q_offsets`` semantics of ``ops/attention.py``).  A
decode row is the degenerate ``q_lens[i] == 1`` case — last position,
full context — so decodes and prefill chunks need no special-casing.

The kernel follows ``_paged_decode_kernel``'s structure: each grid cell
owns one (sequence, q-block) pair — the grid is (kv-head, global
q-block) and the owning sequence comes from a host-computed SMEM lookup
(alignment makes the mapping unique) — and online-softmaxes over
double-buffered HBM→VMEM page DMAs; ``ragged_paged_attention_ref`` is
the XLA fallback / test oracle.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from vllm_omni_tpu.ops._dispatch import interpret_flag
from vllm_omni_tpu.ops.autotune import auto_ragged_blocks
from vllm_omni_tpu.ops.paged_attention import (
    cache_data,
    cache_is_quantized,
    cache_shape,
    gather_pages,
)

_NEG_INF = -1e30

# Per-sequence q-block size in TOKENS (also the segment alignment the
# packer must honor).  8 keeps the f32 sublane tile exact at group=1 and
# bounds per-sequence alignment waste at 7 rows — a decode row costs 8
# packed rows, vs the full (batch, seq) bucket pad of the split path.
# ``auto_ragged_blocks`` (ops/autotune.py) picks the per-shape
# (token_block, dma_slots) pair; this stays the packing contract's
# default.
DEFAULT_TOKEN_BLOCK = 8


def align_to_block(n: int, token_block: int = DEFAULT_TOKEN_BLOCK) -> int:
    """Rows a ``n``-token segment occupies in the packed layout."""
    return -(-n // token_block) * token_block


def ragged_paged_attention_ref(
    q: jax.Array,            # [T, H, D] token-packed queries
    k_cache: jax.Array,      # [Hkv, P, page, D]
    v_cache: jax.Array,
    page_tables: jax.Array,  # [S, max_pages] int32
    cu_q_lens: jax.Array,    # [S+1] int32 aligned segment starts
    q_lens: jax.Array,       # [S] int32
    seq_lens: jax.Array,     # [S] int32 (context incl. this chunk)
    num_seqs,                # int | [] | [1]
    scale: Optional[float] = None,
):
    """Pure-XLA reference with identical semantics (fp32 softmax).

    Gathers each TOKEN's full context — O(T * max_ctx) memory — so it is
    the oracle and the CPU/interpret fallback for test-scale shapes, not
    a production path (production shapes satisfy the kernel's tiling
    requirements: D % 128 == 0, page_size % 8 == 0)."""
    t, h, d = q.shape
    hkv, _, page, _ = cache_shape(k_cache)
    s_max = q_lens.shape[0]
    group = h // hkv
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    nseq = jnp.asarray(num_seqs, jnp.int32).reshape(())
    rows = jnp.arange(t)
    starts = cu_q_lens[:s_max]
    live = jnp.arange(s_max) < nseq
    in_seq = (
        (rows[None, :] >= starts[:, None])
        & (rows[None, :] < (starts + q_lens)[:, None])
        & live[:, None]
    )
    seq_of = jnp.argmax(in_seq, axis=0)          # [T] (0 when padding)
    valid = jnp.any(in_seq, axis=0)              # [T] real-token rows
    tok = rows - starts[seq_of]                  # index within the chunk
    ctx = seq_lens[seq_of]
    q_pos = ctx - q_lens[seq_of] + tok           # global query position

    max_ctx = page_tables.shape[1] * page
    # [Hkv, S, P, page, D] -> [S, max_ctx, Hkv, D] -> per-token [T, ...]
    # (gather_pages dequantizes int8 pages with their per-page scales)
    kg = jnp.transpose(
        gather_pages(k_cache, page_tables), (1, 2, 3, 0, 4)
    ).reshape(s_max, max_ctx, hkv, d)[seq_of]
    vg = jnp.transpose(
        gather_pages(v_cache, page_tables), (1, 2, 3, 0, 4)
    ).reshape(s_max, max_ctx, hkv, d)[seq_of]
    qg = q.reshape(t, hkv, group, d).astype(jnp.float32)
    s = jnp.einsum("thgd,tlhd->thgl", qg, kg.astype(jnp.float32)) * scale
    k_pos = jnp.arange(max_ctx)
    mask = (
        (k_pos[None, :] < ctx[:, None])
        & (k_pos[None, :] <= q_pos[:, None])
        & valid[:, None]
    )
    s = jnp.where(mask[:, None, None, :], s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.where(mask[:, None, None, :], jnp.exp(s - m), 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o = jnp.einsum("thgl,tlhd->thgd", p / l_safe, vg.astype(jnp.float32))
    return o.reshape(t, h, d).astype(q.dtype)


def _ragged_kernel(
    # scalar prefetch (SMEM)
    block_seq_ref,  # [NB] owning sequence per q block (-1 = padding)
    cu_ref,       # [S+1] aligned segment starts
    qlen_ref,     # [S]
    slen_ref,     # [S] context lengths
    tables_ref,   # [S, max_pages]
    # inputs
    q_ref,        # [1, 1, token_block * group, D] VMEM
    k_hbm,        # [Hkv, P, page, D] ANY/HBM (int8 when quantized)
    v_hbm,
    # quantized only: k_sc_ref/v_sc_ref [1, P] VMEM per-page scales,
    # then outputs o_ref [1, 1, token_block * group, D] and scratch
    # k_buf/v_buf [dma_slots, page, D], sems [dma_slots, 2],
    # acc_scr [token_block * group, D]
    *refs,
    page_size: int,
    token_block: int,
    group: int,
    scale: float,
    dma_slots: int,
    quantized: bool,
):
    if quantized:
        k_sc_ref, v_sc_ref, o_ref, k_buf, v_buf, sems, acc_scr = refs
    else:
        o_ref, k_buf, v_buf, sems, acc_scr = refs
        k_sc_ref = v_sc_ref = None
    kvh = pl.program_id(0)
    j = pl.program_id(1)   # GLOBAL q block: segment alignment means it
    #                        belongs to exactly one sequence — the grid
    #                        is (Hkv, NB), no per-sequence dimension and
    #                        no inactive cells beyond the packed tail
    i = block_seq_ref[j]
    # clamp for SMEM reads; every use below is masked by ``active``
    i_safe = jnp.maximum(i, 0)
    q_len = qlen_ref[i_safe]
    ctx_len = slen_ref[i_safe]
    active = i >= 0
    num_pages = jax.lax.div(ctx_len + page_size - 1, page_size)

    def page_dma(slot, p_idx):
        page_id = tables_ref[i_safe, p_idx]
        return (
            pltpu.make_async_copy(
                k_hbm.at[kvh, page_id], k_buf.at[slot], sems.at[slot, 0]
            ),
            pltpu.make_async_copy(
                v_hbm.at[kvh, page_id], v_buf.at[slot], sems.at[slot, 1]
            ),
        )

    rows = token_block * group

    @pl.when(jnp.logical_and(active, num_pages > 0))
    def _run():
        # prime the page pipeline: up to ``dma_slots - 1`` pages in
        # flight before the loop body consumes page 0 (dma_slots == 2
        # is classic double buffering; deeper pipelines hide more HBM
        # latency — ops/autotune.py picks the depth per shape)
        for dma in page_dma(0, 0):
            dma.start()
        for ahead in range(1, dma_slots - 1):
            @pl.when(ahead < num_pages)
            def _prime(ahead=ahead):
                for dma in page_dma(ahead, ahead):
                    dma.start()

        # token index within the chunk / global position per q row
        # (rows pack ``group`` query heads per token, token-major);
        # this block's first packed row is j*tb, so its first chunk
        # token is j*tb - cu[i]
        row_tok = j * token_block - cu_ref[i_safe] + jax.lax.div(
            jax.lax.broadcasted_iota(jnp.int32, (rows, page_size), 0),
            group)
        q_pos = ctx_len - q_len + row_tok
        row_valid = row_tok < q_len

        def body(p_idx, carry):
            m_prev, l_prev, _ = carry  # acc lives in scratch
            slot = jax.lax.rem(p_idx, dma_slots)
            # keep the pipeline ``dma_slots - 1`` pages deep: the slot
            # being refilled is the one consumed longest ago
            pre = p_idx + dma_slots - 1
            nxt = jax.lax.rem(pre, dma_slots)

            @pl.when(pre < num_pages)
            def _prefetch():
                for dma in page_dma(nxt, pre):
                    dma.start()

            for dma in page_dma(slot, p_idx):
                dma.wait()

            q = q_ref[0, 0].astype(jnp.float32)
            k = k_buf[slot].astype(jnp.float32)
            v = v_buf[slot].astype(jnp.float32)
            if quantized:
                # dequantize in-register: the page's int8 bytes were
                # DMAed; its (head, page) f32 scale rides a VMEM row
                page_id = tables_ref[i_safe, p_idx]
                k = k * k_sc_ref[0, page_id]
                v = v * v_sc_ref[0, page_id]
            s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
            k_pos = p_idx * page_size + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1
            )
            mask = (k_pos < ctx_len) & (k_pos <= q_pos) & row_valid
            s = jnp.where(mask, s, _NEG_INF)

            m_cur = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            # explicit zero for fully-masked rows (segment-tail padding):
            # there s == m_new == _NEG_INF and exp(0) would count them
            p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
            l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc_scr[:] = acc_scr[:] * alpha + jnp.dot(
                p, v, preferred_element_type=jnp.float32,
            )
            return m_new, l_new, 0

        acc_scr[:] = jnp.zeros_like(acc_scr)
        m0 = jnp.full((rows, 1), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((rows, 1), jnp.float32)
        _, l_fin, _ = jax.lax.fori_loop(0, num_pages, body, (m0, l0, 0))
        l_safe = jnp.where(l_fin == 0.0, 1.0, l_fin)
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)

    @pl.when(jnp.logical_not(jnp.logical_and(active, num_pages > 0)))
    def _padding():
        # trailing padding blocks (and the defensive empty-context
        # case) own their output block too — zero it so padded rows of
        # the packed hidden state stay exactly zero
        o_ref[0, 0] = jnp.zeros_like(o_ref[0, 0])


@functools.partial(
    jax.jit,
    static_argnames=("scale", "token_block", "use_pallas", "dma_slots"))
def _ragged_attention(
    q, k_cache, v_cache, page_tables, cu_q_lens, q_lens, seq_lens,
    num_seqs, scale, token_block, use_pallas, dma_slots,
):
    t, h, d = q.shape
    quantized = isinstance(k_cache, tuple)
    k_data, k_scale = k_cache if quantized else (k_cache, None)
    v_data, v_scale = v_cache if quantized else (v_cache, None)
    hkv, num_pages_total, page_size, _ = k_data.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if not use_pallas:
        return ragged_paged_attention_ref(
            q, k_cache, v_cache, page_tables, cu_q_lens, q_lens,
            seq_lens, num_seqs, scale,
        )
    if t % token_block:
        raise ValueError(
            f"packed length {t} not a multiple of token_block "
            f"{token_block}")
    group = h // hkv
    s_max = q_lens.shape[0]
    nb = t // token_block
    rows = token_block * group
    # [T, H, D] -> [Hkv, NB, token_block * group, D]: token-major rows
    # so q row r of block b is (token b*tb + r // group, head r % group)
    qx = jnp.transpose(
        q.reshape(t, hkv, group, d), (1, 0, 2, 3)
    ).reshape(hkv, nb, rows, d)

    # Owning sequence per GLOBAL q block (-1 = packed-tail padding):
    # segment starts are token_block-aligned, so every block belongs to
    # at most one sequence — the grid is (Hkv, NB) with no dead
    # per-sequence dimension, and the block specs need no
    # prefetch-dependent index math.
    nseq = jnp.asarray(num_seqs, jnp.int32).reshape(())
    starts = cu_q_lens[:s_max].astype(jnp.int32)
    bs = jnp.arange(nb, dtype=jnp.int32) * token_block
    in_seq = (
        (bs[None, :] >= starts[:, None])
        & (bs[None, :] < (starts + q_lens.astype(jnp.int32))[:, None])
        & (jnp.arange(s_max)[:, None] < nseq)
    )
    block_seq = jnp.where(jnp.any(in_seq, axis=0),
                          jnp.argmax(in_seq, axis=0), -1).astype(jnp.int32)

    in_specs = [
        pl.BlockSpec((1, 1, rows, d),
                     lambda kvh, j, *_: (kvh, j, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    ]
    operands = [
        block_seq,
        cu_q_lens.astype(jnp.int32),
        q_lens.astype(jnp.int32),
        seq_lens.astype(jnp.int32),
        page_tables.astype(jnp.int32),
        qx,
        k_data,
        v_data,
    ]
    if quantized:
        # per-page scales ride in VMEM, one (1, P) row per kv head
        sc_spec = pl.BlockSpec((1, num_pages_total),
                               lambda kvh, j, *_: (kvh, 0),
                               memory_space=pltpu.VMEM)
        in_specs += [sc_spec, sc_spec]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(hkv, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, rows, d),
                               lambda kvh, j, *_: (kvh, j, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((dma_slots, page_size, d), k_data.dtype),
            pltpu.VMEM((dma_slots, page_size, d), v_data.dtype),
            pltpu.SemaphoreType.DMA((dma_slots, 2)),
            pltpu.VMEM((rows, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _ragged_kernel,
            page_size=page_size,
            token_block=token_block,
            group=group,
            scale=scale,
            dma_slots=dma_slots,
            quantized=quantized,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((hkv, nb, rows, d), q.dtype),
        interpret=interpret_flag(),
    )(*operands)
    # [Hkv, NB, tb*group, D] -> [T, H, D]
    out = out.reshape(hkv, t, group, d)
    return jnp.transpose(out, (1, 0, 2, 3)).reshape(t, h, d)


def ragged_paged_attention(
    q: jax.Array,            # [T, H, D] token-packed queries
    k_cache,                 # [Hkv, P, page, D] or quantized tuple
    v_cache,
    page_tables: jax.Array,  # [S, max_pages]
    cu_q_lens: jax.Array,    # [S+1] aligned segment starts
    q_lens: jax.Array,       # [S]
    seq_lens: jax.Array,     # [S]
    num_seqs,                # int | [] | [1]
    scale: Optional[float] = None,
    token_block: int = DEFAULT_TOKEN_BLOCK,
    use_pallas: Optional[bool] = None,
    dma_slots: Optional[int] = None,
):
    """Mixed prefill+decode paged attention over a token-packed batch.

    See the module docstring for the layout/metadata contract.  Auto
    dispatch mirrors ``paged_attention``: the Pallas kernel needs
    lane-dim ``D % 128 == 0``, sublane ``page_size % 8 == 0``, and a
    ``token_block``-aligned packed length; anything else (CPU tests,
    tiny shapes) takes the XLA reference.  An explicit
    ``use_pallas=True`` is honored as-is and fails loudly if
    unsupported.  ``dma_slots`` (page-DMA pipeline depth) defaults to
    the per-shape ``auto_ragged_blocks`` choice."""
    quantized = cache_is_quantized(k_cache)
    k_data = cache_data(k_cache)
    if use_pallas is None:
        from vllm_omni_tpu.ops._dispatch import pallas_mode

        use_pallas = pallas_mode() == "native"
        # int8 page tiles need sublane % 32 (vs % 8 for bf16/f32)
        sublane = 32 if quantized else 8
        if (q.shape[-1] % 128 != 0 or k_data.shape[2] % sublane != 0
                or q.shape[0] % token_block != 0):
            use_pallas = False
    if dma_slots is None:
        _, dma_slots = auto_ragged_blocks(
            head_dim=q.shape[-1], page_size=k_data.shape[2],
            group=q.shape[1] // k_data.shape[0],
            kv_itemsize=k_data.dtype.itemsize,
            q_itemsize=q.dtype.itemsize,
            quantized=quantized,
            num_pages=k_data.shape[1])
    num_seqs = jnp.asarray(num_seqs, jnp.int32)
    return _ragged_attention(
        q, k_cache, v_cache, page_tables, cu_q_lens, q_lens, seq_lens,
        num_seqs, scale, token_block, use_pallas, dma_slots,
    )
