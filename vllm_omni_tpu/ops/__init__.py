"""TPU-native op library.

Pallas replacements for the native CUDA ops the reference consumes through
its vllm / flash-attn dependencies (SURVEY.md §2.10): RMSNorm, fused
RoPE/MRoPE, dense flash attention (DiT blocks), paged attention + KV-cache
scatter (AR decode), plus jit-safe sampling ops.  Every op has a pure-JAX
reference implementation (`*_ref`) used for numerics tests and as the XLA
fallback on CPU.
"""

from vllm_omni_tpu.ops.rmsnorm import rms_norm, rms_norm_ref
from vllm_omni_tpu.ops.rope import (
    apply_rope,
    apply_rope_ref,
    compute_rope_freqs,
    compute_mrope_freqs,
)
from vllm_omni_tpu.ops.attention import flash_attention, attention_ref
from vllm_omni_tpu.ops.paged_attention import (
    cache_data,
    cache_is_quantized,
    cache_shape,
    gather_pages,
    paged_attention,
    paged_attention_ref,
    write_kv_cache,
)
from vllm_omni_tpu.ops.ragged_paged_attention import (
    ragged_paged_attention,
    ragged_paged_attention_ref,
)
from vllm_omni_tpu.ops.activation import silu_mul, gelu_tanh_mul

__all__ = [
    "rms_norm",
    "rms_norm_ref",
    "apply_rope",
    "apply_rope_ref",
    "compute_rope_freqs",
    "compute_mrope_freqs",
    "flash_attention",
    "attention_ref",
    "cache_data",
    "cache_is_quantized",
    "cache_shape",
    "gather_pages",
    "paged_attention",
    "paged_attention_ref",
    "ragged_paged_attention",
    "ragged_paged_attention_ref",
    "write_kv_cache",
    "silu_mul",
    "gelu_tanh_mul",
]
