"""Dense flash attention — Pallas TPU kernel.

Replaces the reference's FlashAttention/SDPA/SageAttention backend stack
(vllm_omni/diffusion/attention/backends/{flash_attn,sdpa,sage_attn}.py and
the vLLM prefill attention kernels; SURVEY.md §2.10).  One kernel serves:

- DiT block attention (non-causal, joint text+image sequences — the joint
  QKV layout of backends/abstract.py:13,55 is handled by concatenating text
  and image tokens before the call, with the per-sequence text padding mask
  passed as ``kv_mask``, the analogue of the reference's
  encoder_hidden_states_mask),
- AR prefill attention (causal, GQA),
- the per-chunk inner step of ring attention (returns the logsumexp so
  chunk results merge with the numerically-stable LSE rule that
  ring/ring_utils.py `update_out_and_lse` implements in the reference).

Layout: q [B, Sq, H, D]; k/v [B, Skv, Hkv, D] with Hkv | H (GQA);
kv_mask [B, Skv] (1 = attend, 0 = masked).  Online-softmax accumulation
over KV blocks, fp32 accumulators in VMEM scratch, MXU matmuls via
jnp.dot with preferred_element_type=f32.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from vllm_omni_tpu.ops._dispatch import interpret_flag

_NEG_INF = -1e30


def attention_ref(
    q: jax.Array,  # [B, Sq, H, D]
    k: jax.Array,  # [B, Skv, Hkv, D]
    v: jax.Array,  # [B, Skv, Hkv, D]
    causal: bool = False,
    scale: Optional[float] = None,
    return_lse: bool = False,
    kv_mask: Optional[jax.Array] = None,  # [B, Skv]
    q_offsets: Optional[jax.Array] = None,  # [B] per-seq q position offset
):
    """Pure-JAX reference with identical semantics (fp32 softmax)."""
    b, sq, h, d = q.shape
    hkv = k.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    group = h // hkv
    kx = jnp.repeat(k, group, axis=2)
    vx = jnp.repeat(v, group, axis=2)
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), kx.astype(jnp.float32)
    ) * scale
    if causal:
        qi = jnp.arange(sq)[:, None]
        ki = jnp.arange(k.shape[1])[None, :]
        if q_offsets is not None:
            # chunked prefill: query i of sequence b sits at global
            # position q_offsets[b] + i, keys at 0..Skv
            cm = qi[None] + q_offsets[:, None, None] >= ki[None]
            s = jnp.where(cm[:, None], s, _NEG_INF)
        else:
            offset = k.shape[1] - sq  # q positions align to the KV suffix
            s = jnp.where(qi + offset >= ki, s, _NEG_INF)
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :] > 0, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bkhd->bqhd", p / l, vx.astype(jnp.float32))
    o = o.astype(q.dtype)
    if return_lse:
        lse = (m + jnp.log(l))[..., 0]  # [B, H, Sq]
        return o, lse
    return o


def attention_xla(
    q: jax.Array,  # [B, Sq, H, D]
    k: jax.Array,  # [B, Skv, Hkv, D]
    v: jax.Array,  # [B, Skv, Hkv, D]
    causal: bool = False,
    scale: Optional[float] = None,
    return_lse: bool = False,
    kv_mask: Optional[jax.Array] = None,  # [B, Skv]
    block_k: int = 512,
    q_offsets: Optional[jax.Array] = None,  # [B]
):
    """Blockwise XLA attention: lax.scan over KV blocks with online
    softmax.  Peak memory is O(B*H*Sq*block_k) — never the full [Sq, Skv]
    score matrix that ``attention_ref`` materializes — so it stays usable
    at video sequence lengths (the 131k-token Wan warmup that OOM'd the
    O(S²) path).  Dots run in the STORED dtype with fp32 accumulation
    (same recipe as ``_flash_core``): f32 inputs match ``attention_ref``
    exactly; bf16 inputs trade ~0.4% relative error on the softmax
    weights for the MXU's full bf16 rate.
    """
    b, sq, h, d = q.shape
    skv, hkv = k.shape[1], k.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    group = h // hkv
    block_k = min(block_k, skv)
    nk = (skv + block_k - 1) // block_k
    pad = nk * block_k - skv

    kx = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
    vx = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    # [nk, B, block_k, Hkv, D]
    kx = kx.reshape(b, nk, block_k, hkv, d).transpose(1, 0, 2, 3, 4)
    vx = vx.reshape(b, nk, block_k, hkv, d).transpose(1, 0, 2, 3, 4)
    if kv_mask is not None:
        mx = jnp.pad(kv_mask.astype(jnp.int32), ((0, 0), (0, pad)))
        mx = mx.reshape(b, nk, block_k).transpose(1, 0, 2)
    else:
        mx = jnp.zeros((nk, 0, 0), jnp.int32)

    qb = q.reshape(b, sq, hkv, group, d)  # stored dtype (MXU dot)
    q_idx = jnp.arange(sq)
    causal_offset = skv - sq  # q positions align to the KV suffix

    def body(carry, blk):
        m_prev, l_prev, acc = carry
        k_blk, v_blk, m_blk, ki = blk
        # s: [B, Hkv, group, Sq, block_k] — the dot runs in the stored
        # dtype (bf16 hits the MXU's full rate; f32 tests unchanged)
        # with f32 accumulation
        s = jnp.einsum(
            "bqhgd,bkhd->bhgqk", qb, k_blk,
            preferred_element_type=jnp.float32,
        ) * scale
        k_pos = ki * block_k + jnp.arange(block_k)
        mask = (k_pos < skv)[None, None, None, None, :]
        if kv_mask is not None:
            mask = mask & (m_blk[:, None, None, None, :] > 0)
        if causal:
            if q_offsets is not None:
                cm = (q_idx[None, :, None] + q_offsets[:, None, None]
                      >= k_pos[None, None, :])  # [B, Sq, block_k]
                mask = mask & cm[:, None, None]
            else:
                mask = mask & (
                    (q_idx[:, None] + causal_offset >= k_pos[None, :])[
                        None, None, None, :, :
                    ]
                )
        s = jnp.where(mask, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.where(mask, jnp.exp(s - m_new[..., None]), 0.0)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bhgqk,bkhd->bhgqd", p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32,
        )
        return (m_new, l_new, acc), None

    # Derive the init carry from the inputs (zeroed) rather than fresh
    # constants: under shard_map the inputs carry varying-manual-axis
    # types, and a plain jnp.zeros init would make scan's carry-in/
    # carry-out types disagree.  The zero scalar folds in k's and the
    # mask's vma too (the mask may depend on axis_index when built inside
    # shard_map, e.g. the joint-SP text path).
    z = k.astype(jnp.float32).reshape(-1)[0] * 0.0
    if kv_mask is not None:
        z = z + kv_mask.astype(jnp.float32).reshape(-1)[0] * 0.0
    if q_offsets is not None:
        z = z + q_offsets.astype(jnp.float32).reshape(-1)[0] * 0.0
    acc0 = (jnp.zeros_like(qb, jnp.float32).transpose(0, 2, 3, 1, 4)
            + z)  # [B,Hkv,g,Sq,D]
    init = (acc0[..., 0] + _NEG_INF, acc0[..., 0], acc0)
    (m, l, acc), _ = jax.lax.scan(
        body, init, (kx, vx, mx, jnp.arange(nk))
    )
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o = (acc / l_safe[..., None]).astype(q.dtype)
    # [B, Hkv, group, Sq, D] -> [B, Sq, H, D]
    o = o.transpose(0, 3, 1, 2, 4).reshape(b, sq, h, d)
    if return_lse:
        lse = jnp.where(l == 0.0, _NEG_INF, m + jnp.log(l_safe))
        return o, lse.reshape(b, h, sq)
    return o


def _flash_core(
    q_ref,
    k_ref,
    v_ref,
    mask_ref,  # full [B, Skv] (tiny; whole array in VMEM) or None
    qoff_ref,  # [B, 1] int32 in VMEM (per-seq q position offset) or None
    m_scr,
    l_scr,
    acc_scr,
    *,
    scale: float,
    causal: bool,
    kv_len: int,
    causal_offset: int,
    block_q: int,
    block_k: int,
    num_q_heads: int = 1,
):
    """Shared online-softmax update for one (q_block, kv_block) pair."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_start = qi * block_q
    k_start = ki * block_k
    # batch row for per-sequence refs; bound OUTSIDE pl.when bodies —
    # program_id inside a traced-predicate pl.when fails to lower in
    # interpret mode
    b_idx = pl.program_id(0) // num_q_heads

    # Per-sequence offset (chunked prefill: queries of sequence b start at
    # global position qoff[b]) or the static suffix alignment.
    if qoff_ref is not None:
        offset = qoff_ref[b_idx, 0]
    else:
        offset = causal_offset

    # Skip KV blocks fully above the causal diagonal.
    run = True
    if causal:
        run = k_start <= q_start + block_q - 1 + offset

    @pl.when(run)
    def _compute():
        # dots stay in the STORED dtype (bf16 on chip): the MXU runs
        # bf16 x bf16 -> f32 at full rate while an fp32 matmul runs at
        # ~1/8th of it (measured 14.6% vs 70% MFU at the DiT shapes);
        # preferred_element_type keeps the f32 accumulation
        q = q_ref[0]
        k = k_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale

        # Mask: KV padding + per-sequence mask + (optionally) causal.
        k_idx = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = k_idx < kv_len
        if mask_ref is not None:
            # mask_ref is blocked over k by the BlockSpec (static, aligned
            # offsets); only the batch row is picked dynamically — sublane
            # indexing, which Mosaic supports at any offset. A dynamic
            # pl.ds(k_start, ...) lane slice would require 128-aligned
            # starts and fails to compile for tail block sizes.
            mrow = mask_ref[b_idx, :]
            # Out-of-range reads in a partial tail block are undefined but
            # already excluded by the kv_len term of `mask`.
            mask = mask & (mrow[None, :] > 0)
        if causal:
            q_idx = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            mask = mask & (q_idx + offset >= k_idx)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        # Explicitly zero masked probabilities: in a fully-masked block
        # s - m_new == 0, and exp(0) would silently count masked slots.
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        l_new = l_scr[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # Zero padded V rows: out-of-bounds block reads are undefined
        # (NaN in interpret mode) and 0 * NaN = NaN in the matmul.
        v_valid = (
            k_start
            + jax.lax.broadcasted_iota(jnp.int32, v_ref.shape[1:], 0)
        ) < kv_len
        v = jnp.where(v_valid, v_ref[0], 0)
        # p rounds to v's dtype for the MXU (standard TPU flash-attn
        # recipe — probabilities in [0,1] lose <0.4% relative in bf16;
        # f32 inputs keep f32 dots, so CPU parity tests are unchanged)
        acc_scr[:] = acc_scr[:] * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)


def _finalize(o_ref, lse_ref, m_scr, l_scr, acc_scr):
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == nk - 1)
    def _():
        l = l_scr[:, :1]
        # Fully-masked rows (e.g. ring-attention chunks before this rank's
        # KV, or padded q rows) have l == 0: emit zeros / -inf lse.
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        if lse_ref is not None:
            lse = jnp.where(
                l == 0.0, _NEG_INF, m_scr[:, :1] + jnp.log(l_safe)
            )
            lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


# (block_q, block_k) selection lives in ops/autotune.py (shared with
# the ragged paged kernel's block picker); these aliases keep the
# historical private names importable.
from vllm_omni_tpu.ops.autotune import SCORE_CAP as _SCORE_CAP  # noqa: E402,F401
from vllm_omni_tpu.ops.autotune import auto_blocks as _auto_blocks  # noqa: E402


def _mk_kernel(with_lse: bool, with_mask: bool, with_qoff: bool = False, **cfg):
    def kernel(*refs):
        i = 3 + (1 if with_mask else 0) + (1 if with_qoff else 0)
        q_ref, k_ref, v_ref = refs[0], refs[1], refs[2]
        j = 3
        mask_ref = qoff_ref = None
        if with_mask:
            mask_ref = refs[j]
            j += 1
        if with_qoff:
            qoff_ref = refs[j]
        outs = refs[i : i + 1 + (1 if with_lse else 0)]
        o_ref = outs[0]
        lse_ref = outs[1] if with_lse else None
        m_scr, l_scr, acc_scr = refs[-3], refs[-2], refs[-1]
        _flash_core(
            q_ref, k_ref, v_ref, mask_ref, qoff_ref, m_scr, l_scr, acc_scr,
            **cfg
        )
        _finalize(o_ref, lse_ref, m_scr, l_scr, acc_scr)

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=(
        "causal",
        "scale",
        "return_lse",
        "block_q",
        "block_k",
        "use_pallas",
    ),
)
def _flash_attention(
    q, k, v, kv_mask, causal, scale, return_lse, block_q, block_k,
    use_pallas, q_offsets=None,
):
    b, sq, h, d = q.shape
    skv, hkv = k.shape[1], k.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if not use_pallas:
        # Blockwise fallback: identical numerics to attention_ref without
        # ever materializing the [Sq, Skv] score matrix (VERDICT weak#2 —
        # the O(S²) ref path OOM'd at video sequence lengths).
        return attention_xla(
            q, k, v, causal, scale, return_lse, kv_mask, block_k=block_k,
            q_offsets=q_offsets,
        )

    group = h // hkv
    block_q = min(block_q, max(8, sq))
    block_k = min(block_k, max(8, skv))
    # q positions align to the KV suffix (AR prefill with cached prefix).
    causal_offset = skv - sq

    qx = jnp.moveaxis(q, 2, 1).reshape(b * h, sq, d)
    kx = jnp.moveaxis(k, 2, 1).reshape(b * hkv, skv, d)
    vx = jnp.moveaxis(v, 2, 1).reshape(b * hkv, skv, d)

    nq = pl.cdiv(sq, block_q)
    nk = pl.cdiv(skv, block_k)
    grid = (b * h, nq, nk)

    q_spec = pl.BlockSpec(
        (1, block_q, d), lambda bh, qi, ki: (bh, qi, 0), memory_space=pltpu.VMEM
    )
    kv_spec = pl.BlockSpec(
        (1, block_k, d),
        lambda bh, qi, ki, group=group: (bh // group, ki, 0),
        memory_space=pltpu.VMEM,
    )
    in_specs = [q_spec, kv_spec, kv_spec]
    inputs = [qx, kx, vx]
    if kv_mask is not None:
        # Full batch in the sublane dim, blocked over k in the lane dim so
        # block starts stay static multiples of block_k (Mosaic rejects
        # dynamic lane offsets that aren't 128-aligned).
        in_specs.append(
            pl.BlockSpec(
                (b, block_k),
                lambda bh, qi, ki: (0, ki),
                memory_space=pltpu.VMEM,
            )
        )
        inputs.append(kv_mask.astype(jnp.int32))
    if q_offsets is not None:
        # whole [B, 1] array in VMEM (tiny); batch row picked dynamically
        # via sublane indexing, same pattern as the kv_mask spec above
        in_specs.append(
            pl.BlockSpec(
                (b, 1), lambda bh, qi, ki: (0, 0), memory_space=pltpu.VMEM
            )
        )
        inputs.append(q_offsets.astype(jnp.int32).reshape(b, 1))

    out_specs = [q_spec]
    out_shapes = [jax.ShapeDtypeStruct((b * h, nq * block_q, d), q.dtype)]
    if return_lse:
        out_specs.append(
            pl.BlockSpec(
                (1, block_q, 128),
                lambda bh, qi, ki: (bh, qi, 0),
                memory_space=pltpu.VMEM,
            )
        )
        out_shapes.append(
            jax.ShapeDtypeStruct((b * h, nq * block_q, 128), jnp.float32)
        )

    kernel = _mk_kernel(
        return_lse,
        kv_mask is not None,
        q_offsets is not None,
        scale=scale,
        causal=causal,
        kv_len=skv,
        causal_offset=causal_offset,
        block_q=block_q,
        block_k=block_k,
        num_q_heads=h,
    )
    res = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=tuple(out_specs) if return_lse else out_specs[0],
        out_shape=tuple(out_shapes) if return_lse else out_shapes[0],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret_flag(),
    )(*inputs)

    out = res[0] if return_lse else res
    out = out[:, :sq].reshape(b, h, sq, d)
    out = jnp.moveaxis(out, 1, 2)
    if return_lse:
        return out, res[1][:, :sq, 0].reshape(b, h, sq)
    return out


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: Optional[float] = None,
    return_lse: bool = False,
    kv_mask: Optional[jax.Array] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    use_pallas: Optional[bool] = None,
    q_offsets: Optional[jax.Array] = None,
):
    """Flash attention over [B, S, H, D] tensors (GQA via Hkv | H).

    ``q_offsets`` [B] gives each sequence's global position of query row 0
    (chunked prefill: the chunk attends cached-prefix keys at 0..offset-1
    plus itself causally); overrides the static suffix alignment.

    ``block_q``/``block_k`` default to a shape-aware choice
    (``_auto_blocks``); pass explicit sizes to pin the tiling.
    """
    if use_pallas is None:
        from vllm_omni_tpu.ops._dispatch import pallas_mode

        use_pallas = pallas_mode() == "native"
        # Mosaic tiling: a KV shorter than one sublane tile makes the
        # mask/kv block shapes unsatisfiable ((1, 8) block over a (1, 5)
        # array). Sub-tile shapes gain nothing from the kernel — route
        # them to the blockwise XLA path. Explicit use_pallas=True is
        # honored as-is (kernel tests), failing loudly if unsupported.
        if k.shape[1] < 8:
            use_pallas = False
    if block_q is None or block_k is None:
        if use_pallas:
            abq, abk = _auto_blocks(q.shape[1], k.shape[1], q.shape[3],
                                    q.dtype.itemsize)
        else:
            # the XLA fallback has its own memory model (peak is
            # O(B*H*Sq*block_k) f32 — Pallas-VMEM-tuned sizes would
            # multiply it 4x at video sequence lengths); block_q is
            # ignored there entirely.  256 preserves the pre-auto-tune
            # default this path always ran with.
            abq, abk = 256, 256
        block_q = abq if block_q is None else block_q
        block_k = abk if block_k is None else block_k
    return _flash_attention(
        q, k, v, kv_mask, causal, scale, return_lse, block_q, block_k,
        use_pallas, q_offsets,
    )
