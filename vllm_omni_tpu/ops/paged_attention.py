"""Paged KV-cache attention — Pallas replacement for vLLM's PagedAttention
CUDA kernels (SURVEY.md §2.10; used by the reference through
GPUModelRunner's attention metadata, worker/gpu_ar_model_runner.py:243-255).

Cache layout (TPU-first): ``[Hkv, num_pages, page_size, D]`` — fixing the
head and page indices yields a *contiguous* (page_size, D) tile, so the
decode kernel's HBM→VMEM page DMAs are dense (the CUDA layout
[pages, page_size, Hkv, D] would stride every row on TPU).

Three ops:
- ``write_kv_cache``  — slot-mapping scatter of new K/V into the paged cache
- ``paged_attention_ref`` — gather-based XLA fallback (also the test oracle)
- ``paged_attention`` — Pallas decode kernel: per (seq, kv-head) grid cell,
  double-buffered page DMAs + online softmax over pages.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from vllm_omni_tpu.ops._dispatch import interpret_flag

_NEG_INF = -1e30


def init_kv_cache(
    num_layers: int,
    num_pages: int,
    page_size: int,
    num_kv_heads: int,
    head_dim: int,
    dtype=jnp.bfloat16,
):
    """Allocate per-layer (k, v) caches."""
    shape = (num_kv_heads, num_pages, page_size, head_dim)
    return [
        (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
        for _ in range(num_layers)
    ]


@jax.jit
def write_kv_cache(
    k_cache: jax.Array,  # [Hkv, P, page, D]
    v_cache: jax.Array,
    k_new: jax.Array,  # [T, Hkv, D]
    v_new: jax.Array,
    slot_mapping: jax.Array,  # [T] int32, flat slot = page*page_size + offset
):
    """Scatter new KV into the paged cache at the given flat slots.

    Padded tokens use slot -1: they scatter out of bounds, which XLA drops
    (mode=drop), matching the CUDA kernel's ignore-negative-slot contract.
    """
    hkv, p, ps, d = k_cache.shape
    kc = k_cache.reshape(hkv, p * ps, d)
    vc = v_cache.reshape(hkv, p * ps, d)
    kn = jnp.moveaxis(k_new, 1, 0).astype(k_cache.dtype)  # [Hkv, T, D]
    vn = jnp.moveaxis(v_new, 1, 0).astype(v_cache.dtype)
    # Negative slots would wrap Python-style; push them out of bounds so
    # mode="drop" discards them.
    slots = jnp.where(slot_mapping < 0, p * ps, slot_mapping)
    kc = kc.at[:, slots].set(kn, mode="drop")
    vc = vc.at[:, slots].set(vn, mode="drop")
    return kc.reshape(k_cache.shape), vc.reshape(v_cache.shape)


def paged_attention_ref(
    q: jax.Array,  # [B, H, D] (one decode token per sequence)
    k_cache: jax.Array,  # [Hkv, P, page, D]
    v_cache: jax.Array,
    block_tables: jax.Array,  # [B, max_pages] int32 page ids
    context_lens: jax.Array,  # [B] int32
    scale: Optional[float] = None,
):
    b, h, d = q.shape
    hkv, _, page, _ = k_cache.shape
    group = h // hkv
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    max_pages = block_tables.shape[1]
    # Gather pages: [B, Hkv, max_pages, page, D] -> [B, Hkv, L, D]
    kg = jnp.moveaxis(k_cache[:, block_tables], 0, 1).reshape(
        b, hkv, max_pages * page, d
    )
    vg = jnp.moveaxis(v_cache[:, block_tables], 0, 1).reshape(
        b, hkv, max_pages * page, d
    )
    qg = q.reshape(b, hkv, group, d).astype(jnp.float32)
    s = jnp.einsum("bngd,bnld->bngl", qg, kg.astype(jnp.float32)) * scale
    pos = jnp.arange(max_pages * page)[None, None, None, :]
    mask = pos < context_lens[:, None, None, None]
    s = jnp.where(mask, s, _NEG_INF)
    p_ = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bngl,bnld->bngd", p_, vg.astype(jnp.float32))
    return o.reshape(b, h, d).astype(q.dtype)


def _paged_decode_kernel(
    # scalar prefetch
    block_tables_ref,  # [B, max_pages] (SMEM)
    context_lens_ref,  # [B] (SMEM)
    # inputs
    q_ref,  # [1, 1, group_p, D] VMEM
    k_hbm,  # [Hkv, P, page, D] ANY/HBM
    v_hbm,
    # outputs
    o_ref,  # [1, 1, group_p, D] VMEM
    # scratch
    k_buf,  # [2, page, D]
    v_buf,
    sems,  # DMA sems [2, 2]
    acc_scr,  # [group_p, D]
    *,
    page_size: int,
    scale: float,
):
    b = pl.program_id(0)
    kvh = pl.program_id(1)
    ctx_len = context_lens_ref[b]
    num_pages = jax.lax.div(ctx_len + page_size - 1, page_size)

    def page_dma(slot, p_idx):
        page_id = block_tables_ref[b, p_idx]
        return (
            pltpu.make_async_copy(
                k_hbm.at[kvh, page_id], k_buf.at[slot], sems.at[slot, 0]
            ),
            pltpu.make_async_copy(
                v_hbm.at[kvh, page_id], v_buf.at[slot], sems.at[slot, 1]
            ),
        )

    @pl.when(num_pages > 0)
    def _run():
        for dma in page_dma(0, 0):
            dma.start()

        def body(p_idx, carry):
            m_prev, l_prev, _ = carry  # acc lives in scratch
            slot = jax.lax.rem(p_idx, 2)
            nxt = jax.lax.rem(p_idx + 1, 2)

            @pl.when(p_idx + 1 < num_pages)
            def _prefetch():
                for dma in page_dma(nxt, p_idx + 1):
                    dma.start()

            for dma in page_dma(slot, p_idx):
                dma.wait()

            q = q_ref[0, 0].astype(jnp.float32)
            k = k_buf[slot].astype(jnp.float32)
            s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
            pos = p_idx * page_size + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1
            )
            s = jnp.where(pos < ctx_len, s, _NEG_INF)

            m_cur = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)
            l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc_scr[:] = acc_scr[:] * alpha + jnp.dot(
                p, v_buf[slot].astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
            return m_new, l_new, 0

        group_p = q_ref.shape[2]
        acc_scr[:] = jnp.zeros_like(acc_scr)
        m0 = jnp.full((group_p, 1), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((group_p, 1), jnp.float32)
        m_fin, l_fin, _ = jax.lax.fori_loop(
            0, num_pages, body, (m0, l0, 0)
        )
        l_safe = jnp.where(l_fin == 0.0, 1.0, l_fin)
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)

    @pl.when(num_pages == 0)
    def _empty():
        o_ref[0, 0] = jnp.zeros_like(o_ref[0, 0])


@functools.partial(jax.jit, static_argnames=("scale", "use_pallas"))
def _paged_attention(
    q, k_cache, v_cache, block_tables, context_lens, scale, use_pallas
):
    b, h, d = q.shape
    hkv, num_pages_total, page_size, _ = k_cache.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if not use_pallas:
        return paged_attention_ref(
            q, k_cache, v_cache, block_tables, context_lens, scale
        )
    group = h // hkv
    group_p = max(8, group)  # sublane-align the per-kv-head q group
    qx = q.reshape(b, hkv, group, d)
    if group_p != group:
        qx = jnp.pad(qx, ((0, 0), (0, 0), (0, group_p - group), (0, 0)))
    max_pages = block_tables.shape[1]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hkv),
        in_specs=[
            pl.BlockSpec(
                (1, 1, group_p, d),
                lambda b_, h_, *_: (b_, h_, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, group_p, d),
            lambda b_, h_, *_: (b_, h_, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((2, page_size, d), k_cache.dtype),
            pltpu.VMEM((2, page_size, d), v_cache.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.VMEM((group_p, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _paged_decode_kernel,
            page_size=page_size,
            scale=scale,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, group_p, d), q.dtype),
        interpret=interpret_flag(),
    )(
        block_tables.astype(jnp.int32),
        context_lens.astype(jnp.int32),
        qx,
        k_cache,
        v_cache,
    )
    return out[:, :, :group].reshape(b, h, d)


def paged_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    block_tables: jax.Array,
    context_lens: jax.Array,
    scale: Optional[float] = None,
    use_pallas: Optional[bool] = None,
):
    """Single-token-per-sequence paged decode attention."""
    if use_pallas is None:
        from vllm_omni_tpu.ops._dispatch import pallas_mode

        use_pallas = pallas_mode() == "native"
        # Mosaic tiling constraints: page tiles are (page_size, head_dim)
        # VMEM buffers → need lane dim % 128 and sublane dim % 8 (f32).
        # Auto-dispatch routes tiny/test shapes to the XLA ref path;
        # production shapes (D=128, page_size>=16) take the kernel.  An
        # explicit use_pallas=True is honored as-is (kernel tests rely on
        # it; unsupported shapes then fail loudly at compile).
        if q.shape[-1] % 128 != 0 or k_cache.shape[2] % 8 != 0:
            use_pallas = False
    return _paged_attention(
        q, k_cache, v_cache, block_tables, context_lens, scale, use_pallas
    )
