"""Paged KV-cache attention — Pallas replacement for vLLM's PagedAttention
CUDA kernels (SURVEY.md §2.10; used by the reference through
GPUModelRunner's attention metadata, worker/gpu_ar_model_runner.py:243-255).

Cache layout (TPU-first): ``[Hkv, num_pages, page_size, D]`` — fixing the
head and page indices yields a *contiguous* (page_size, D) tile, so the
decode kernel's HBM→VMEM page DMAs are dense (the CUDA layout
[pages, page_size, Hkv, D] would stride every row on TPU).

Quantized layout (``--kv-cache-dtype int8``): each cache half becomes a
2-tuple ``(data int8 [Hkv, P, page_size, D], scale f32 [Hkv, P])`` — one
absmax scale per (kv-head, page), rounding shared with
``kvcache/quant.py``.  The pytree structure carries the layout through
jit, so every op here branches statically on ``isinstance(half, tuple)``
and the kernels dequantize in-register after the page DMA (the math
stays f32; only HBM bytes shrink ~2x).

Three ops:
- ``write_kv_cache``  — slot-mapping scatter of new K/V into the paged cache
  (the quantized branch grows per-page scales monotonically within a
  page's tenancy and rescales the page's prior rows in-place)
- ``paged_attention_ref`` — gather-based XLA fallback (also the test oracle)
- ``paged_attention`` — Pallas decode kernel: per (seq, kv-head) grid cell,
  double-buffered page DMAs + online softmax over pages.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from vllm_omni_tpu.kvcache.quant import QMAX, SCALE_EPS
from vllm_omni_tpu.ops._dispatch import interpret_flag

_NEG_INF = -1e30


def init_kv_cache(
    num_layers: int,
    num_pages: int,
    page_size: int,
    num_kv_heads: int,
    head_dim: int,
    dtype=jnp.bfloat16,
    quantized: bool = False,
):
    """Allocate per-layer (k, v) caches.

    ``quantized`` allocates the int8 layout: each half is
    ``(data int8, scale f32 [Hkv, P])``; zero scales mean "never
    written" and dequantize to the same zeros the bf16 pool starts
    with."""
    shape = (num_kv_heads, num_pages, page_size, head_dim)
    if quantized:
        return [
            ((jnp.zeros(shape, jnp.int8),
              jnp.zeros((num_kv_heads, num_pages), jnp.float32)),
             (jnp.zeros(shape, jnp.int8),
              jnp.zeros((num_kv_heads, num_pages), jnp.float32)))
            for _ in range(num_layers)
        ]
    return [
        (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
        for _ in range(num_layers)
    ]


def cache_is_quantized(cache_half) -> bool:
    """True for the (data, scale) int8 layout of one cache half."""
    return isinstance(cache_half, tuple)


def cache_data(cache_half) -> jax.Array:
    """The [Hkv, P, page_size, D] data array of either layout."""
    return cache_half[0] if isinstance(cache_half, tuple) else cache_half


def cache_shape(cache_half) -> tuple:
    return cache_data(cache_half).shape


def gather_pages(cache_half, page_ids: jax.Array) -> jax.Array:
    """Dequantizing page gather: ``cache[:, page_ids]`` for either
    layout.  Returns ``[Hkv, *page_ids.shape, page_size, D]`` — float32
    when quantized, the cache dtype otherwise."""
    if isinstance(cache_half, tuple):
        data, scale = cache_half
        return (data[:, page_ids].astype(jnp.float32)
                * scale[:, page_ids][..., None, None])
    return cache_half[:, page_ids]


def _write_kv_quant(cache_half, x_new, slot_mapping):
    """Quantized slot scatter for one cache half.

    Per touched page: (1) a page whose FIRST row is being written is a
    fresh tenancy — its old scale (a previous sequence's) is treated as
    zero so stale scales never leak across the page pool's reuse; (2)
    the scale grows monotonically, ``new = max(old, absmax(new)/127)``,
    and the page's prior int8 rows are rescaled onto the grown scale
    in-place (cost O(T * page_size), never O(cache)); (3) the new rows
    quantize with the settled scale and scatter through the flat-slot
    view exactly like the dense path (slot -1 drops)."""
    data, scale = cache_half  # int8 [Hkv,P,ps,D], f32 [Hkv,P]
    hkv, p, ps, d = data.shape
    xn = jnp.moveaxis(x_new, 1, 0).astype(jnp.float32)  # [Hkv, T, D]
    slots = jnp.where(slot_mapping < 0, p * ps, slot_mapping)
    pages = slots // ps  # p (out of range -> dropped) for padding rows
    offs = slots % ps
    ones = jnp.ones_like(pages, jnp.int32)
    fresh = jnp.zeros((p,), jnp.int32).at[pages].max(
        jnp.where(offs == 0, ones, 0), mode="drop")
    touched = jnp.zeros((p,), jnp.int32).at[pages].max(ones, mode="drop")
    old = jnp.where(fresh[None, :] > 0, 0.0, scale)
    cand = jnp.zeros((hkv, p), jnp.float32).at[:, pages].max(
        jnp.max(jnp.abs(xn), axis=-1), mode="drop") / QMAX
    new_scale = jnp.where(
        touched[None, :] > 0,
        jnp.maximum(jnp.maximum(old, cand), SCALE_EPS), scale)
    # rescale what the touched pages already hold onto the grown scale
    # (fresh pages get ratio 0: the previous tenant's rows zero out)
    ratio = (old / jnp.maximum(new_scale, SCALE_EPS))[:, pages]
    pg = data[:, pages].astype(jnp.float32) * ratio[..., None, None]
    pg = jnp.clip(jnp.round(pg), -QMAX, QMAX).astype(jnp.int8)
    data = data.at[:, pages].set(pg, mode="drop")
    # quantize + scatter the step's rows
    s_tok = jnp.maximum(new_scale[:, pages], SCALE_EPS)  # [Hkv, T]
    qn = jnp.clip(jnp.round(xn / s_tok[..., None]),
                  -QMAX, QMAX).astype(jnp.int8)
    flat = data.reshape(hkv, p * ps, d).at[:, slots].set(qn, mode="drop")
    return flat.reshape(data.shape), new_scale


@jax.jit
def write_kv_cache(
    k_cache,  # [Hkv, P, page, D] or quantized (data, scale) tuple
    v_cache,
    k_new: jax.Array,  # [T, Hkv, D]
    v_new: jax.Array,
    slot_mapping: jax.Array,  # [T] int32, flat slot = page*page_size + offset
):
    """Scatter new KV into the paged cache at the given flat slots.

    Padded tokens use slot -1: they scatter out of bounds, which XLA drops
    (mode=drop), matching the CUDA kernel's ignore-negative-slot contract.
    The quantized (data, scale) layout dispatches on pytree structure —
    static under jit, so both layouts share one entry point.
    """
    if isinstance(k_cache, tuple):  # omnilint: disable=OL1 - pytree STRUCTURE branch (tuple vs array), static at trace time: jit specializes per layout by design
        return (_write_kv_quant(k_cache, k_new, slot_mapping),
                _write_kv_quant(v_cache, v_new, slot_mapping))
    hkv, p, ps, d = k_cache.shape
    kc = k_cache.reshape(hkv, p * ps, d)
    vc = v_cache.reshape(hkv, p * ps, d)
    kn = jnp.moveaxis(k_new, 1, 0).astype(k_cache.dtype)  # [Hkv, T, D]
    vn = jnp.moveaxis(v_new, 1, 0).astype(v_cache.dtype)
    # Negative slots would wrap Python-style; push them out of bounds so
    # mode="drop" discards them.
    slots = jnp.where(slot_mapping < 0, p * ps, slot_mapping)
    kc = kc.at[:, slots].set(kn, mode="drop")
    vc = vc.at[:, slots].set(vn, mode="drop")
    return kc.reshape(k_cache.shape), vc.reshape(v_cache.shape)


def paged_attention_ref(
    q: jax.Array,  # [B, H, D] (one decode token per sequence)
    k_cache,  # [Hkv, P, page, D] or quantized (data, scale) tuple
    v_cache,
    block_tables: jax.Array,  # [B, max_pages] int32 page ids
    context_lens: jax.Array,  # [B] int32
    scale: Optional[float] = None,
):
    b, h, d = q.shape
    hkv, _, page, _ = cache_shape(k_cache)
    group = h // hkv
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    max_pages = block_tables.shape[1]
    # Gather pages: [B, Hkv, max_pages, page, D] -> [B, Hkv, L, D]
    kg = jnp.moveaxis(gather_pages(k_cache, block_tables), 0, 1).reshape(
        b, hkv, max_pages * page, d
    )
    vg = jnp.moveaxis(gather_pages(v_cache, block_tables), 0, 1).reshape(
        b, hkv, max_pages * page, d
    )
    qg = q.reshape(b, hkv, group, d).astype(jnp.float32)
    s = jnp.einsum("bngd,bnld->bngl", qg, kg.astype(jnp.float32)) * scale
    pos = jnp.arange(max_pages * page)[None, None, None, :]
    mask = pos < context_lens[:, None, None, None]
    s = jnp.where(mask, s, _NEG_INF)
    p_ = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bngl,bnld->bngd", p_, vg.astype(jnp.float32))
    return o.reshape(b, h, d).astype(q.dtype)


def _paged_decode_kernel(
    # scalar prefetch
    block_tables_ref,  # [B, max_pages] (SMEM)
    context_lens_ref,  # [B] (SMEM)
    # inputs
    q_ref,  # [1, 1, group_p, D] VMEM
    k_hbm,  # [Hkv, P, page, D] ANY/HBM (int8 when quantized)
    v_hbm,
    # quantized only: k_sc_ref/v_sc_ref [1, P] VMEM per-page scales,
    # then outputs o_ref [1, 1, group_p, D] and scratch
    # k_buf/v_buf [2, page, D], sems [2, 2], acc_scr [group_p, D]
    *refs,
    page_size: int,
    scale: float,
    quantized: bool,
):
    if quantized:
        k_sc_ref, v_sc_ref, o_ref, k_buf, v_buf, sems, acc_scr = refs
    else:
        o_ref, k_buf, v_buf, sems, acc_scr = refs
        k_sc_ref = v_sc_ref = None
    b = pl.program_id(0)
    kvh = pl.program_id(1)
    ctx_len = context_lens_ref[b]
    num_pages = jax.lax.div(ctx_len + page_size - 1, page_size)

    def page_dma(slot, p_idx):
        page_id = block_tables_ref[b, p_idx]
        return (
            pltpu.make_async_copy(
                k_hbm.at[kvh, page_id], k_buf.at[slot], sems.at[slot, 0]
            ),
            pltpu.make_async_copy(
                v_hbm.at[kvh, page_id], v_buf.at[slot], sems.at[slot, 1]
            ),
        )

    @pl.when(num_pages > 0)
    def _run():
        for dma in page_dma(0, 0):
            dma.start()

        def body(p_idx, carry):
            m_prev, l_prev, _ = carry  # acc lives in scratch
            slot = jax.lax.rem(p_idx, 2)
            nxt = jax.lax.rem(p_idx + 1, 2)

            @pl.when(p_idx + 1 < num_pages)
            def _prefetch():
                for dma in page_dma(nxt, p_idx + 1):
                    dma.start()

            for dma in page_dma(slot, p_idx):
                dma.wait()

            q = q_ref[0, 0].astype(jnp.float32)
            k = k_buf[slot].astype(jnp.float32)
            v = v_buf[slot].astype(jnp.float32)
            if quantized:
                # dequantize in-register: one f32 scale per (head, page),
                # fetched alongside the int8 page bytes
                page_id = block_tables_ref[b, p_idx]
                k = k * k_sc_ref[0, page_id]
                v = v * v_sc_ref[0, page_id]
            s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
            pos = p_idx * page_size + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1
            )
            s = jnp.where(pos < ctx_len, s, _NEG_INF)

            m_cur = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)
            l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc_scr[:] = acc_scr[:] * alpha + jnp.dot(
                p, v, preferred_element_type=jnp.float32,
            )
            return m_new, l_new, 0

        group_p = q_ref.shape[2]
        acc_scr[:] = jnp.zeros_like(acc_scr)
        m0 = jnp.full((group_p, 1), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((group_p, 1), jnp.float32)
        m_fin, l_fin, _ = jax.lax.fori_loop(
            0, num_pages, body, (m0, l0, 0)
        )
        l_safe = jnp.where(l_fin == 0.0, 1.0, l_fin)
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)

    @pl.when(num_pages == 0)
    def _empty():
        o_ref[0, 0] = jnp.zeros_like(o_ref[0, 0])


@functools.partial(jax.jit, static_argnames=("scale", "use_pallas"))
def _paged_attention(
    q, k_cache, v_cache, block_tables, context_lens, scale, use_pallas
):
    b, h, d = q.shape
    quantized = isinstance(k_cache, tuple)
    k_data, k_scale = k_cache if quantized else (k_cache, None)
    v_data, v_scale = v_cache if quantized else (v_cache, None)
    hkv, num_pages_total, page_size, _ = k_data.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if not use_pallas:
        return paged_attention_ref(
            q, k_cache, v_cache, block_tables, context_lens, scale
        )
    group = h // hkv
    group_p = max(8, group)  # sublane-align the per-kv-head q group
    qx = q.reshape(b, hkv, group, d)
    if group_p != group:
        qx = jnp.pad(qx, ((0, 0), (0, 0), (0, group_p - group), (0, 0)))
    max_pages = block_tables.shape[1]

    in_specs = [
        pl.BlockSpec(
            (1, 1, group_p, d),
            lambda b_, h_, *_: (b_, h_, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    ]
    operands = [
        block_tables.astype(jnp.int32),
        context_lens.astype(jnp.int32),
        qx,
        k_data,
        v_data,
    ]
    if quantized:
        # per-page scales ride in VMEM, one (1, P) row per kv head
        sc_spec = pl.BlockSpec(
            (1, num_pages_total),
            lambda b_, h_, *_: (h_, 0),
            memory_space=pltpu.VMEM,
        )
        in_specs += [sc_spec, sc_spec]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hkv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, group_p, d),
            lambda b_, h_, *_: (b_, h_, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((2, page_size, d), k_data.dtype),
            pltpu.VMEM((2, page_size, d), v_data.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.VMEM((group_p, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _paged_decode_kernel,
            page_size=page_size,
            scale=scale,
            quantized=quantized,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, group_p, d), q.dtype),
        interpret=interpret_flag(),
    )(*operands)
    return out[:, :, :group].reshape(b, h, d)


def paged_attention(
    q: jax.Array,
    k_cache,
    v_cache,
    block_tables: jax.Array,
    context_lens: jax.Array,
    scale: Optional[float] = None,
    use_pallas: Optional[bool] = None,
):
    """Single-token-per-sequence paged decode attention."""
    if use_pallas is None:
        from vllm_omni_tpu.ops._dispatch import pallas_mode

        use_pallas = pallas_mode() == "native"
        # Mosaic tiling constraints: page tiles are (page_size, head_dim)
        # VMEM buffers → need lane dim % 128 and sublane dim % 8 (f32),
        # % 32 for int8 page tiles (docs/performance.md capacity notes).
        # Auto-dispatch routes tiny/test shapes to the XLA ref path;
        # production shapes (D=128, page_size>=16) take the kernel.  An
        # explicit use_pallas=True is honored as-is (kernel tests rely on
        # it; unsupported shapes then fail loudly at compile).
        page_size = cache_shape(k_cache)[2]
        sublane = 32 if cache_is_quantized(k_cache) else 8
        if q.shape[-1] % 128 != 0 or page_size % sublane != 0:
            use_pallas = False
    return _paged_attention(
        q, k_cache, v_cache, block_tables, context_lens, scale, use_pallas
    )
