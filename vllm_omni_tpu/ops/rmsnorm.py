"""RMSNorm — Pallas replacement for vLLM's fused RMSNorm CUDA op
(SURVEY.md §2.10; used by every transformer block in the reference's models).

Supports the fused residual-add form (``x = x + residual`` then normalize,
returning both), matching the CUDA op's ``fused_add_rms_norm`` contract.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from vllm_omni_tpu.ops._dispatch import interpret_flag


def rms_norm_ref(
    x: jax.Array,
    weight: jax.Array,
    eps: float = 1e-6,
    residual: Optional[jax.Array] = None,
):
    """Pure-JAX reference. x: [..., hidden]; weight: [hidden].

    Fused form accumulates the residual add in fp32 and normalizes the
    fp32 sum (the CUDA fused_add_rms_norm contract); the returned residual
    is the sum rounded to the activation dtype.
    """
    xf = x.astype(jnp.float32)
    residual_out = None
    if residual is not None:
        xf = xf + residual.astype(jnp.float32)
        residual_out = xf.astype(x.dtype)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    y = (y * weight.astype(jnp.float32)).astype(x.dtype)
    if residual is not None:
        return y, residual_out
    return y


def _rmsnorm_kernel(x_ref, w_ref, o_ref, *, eps: float):
    xf = x_ref[:].astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    o_ref[:] = (y * w_ref[0, :].astype(jnp.float32)).astype(o_ref.dtype)


def _rmsnorm_fused_kernel(x_ref, r_ref, w_ref, o_ref, ro_ref, *, eps: float):
    xf = x_ref[:].astype(jnp.float32) + r_ref[:].astype(jnp.float32)
    ro_ref[:] = xf.astype(ro_ref.dtype)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    o_ref[:] = (y * w_ref[0, :].astype(jnp.float32)).astype(o_ref.dtype)


def _block_rows(n_rows: int, hidden: int, dtype) -> int:
    # Keep the block within a conservative VMEM budget; hidden stays whole
    # (the reduction axis must be in one block).
    bytes_per = jnp.dtype(dtype).itemsize
    budget = 4 * 1024 * 1024
    rows = max(8, min(n_rows, budget // max(1, hidden * bytes_per * 3)))
    # round down to a multiple of 8 (f32 sublane)
    return max(8, (rows // 8) * 8)


@functools.partial(jax.jit, static_argnames=("eps", "use_pallas"))
def _rms_norm_2d(x, weight, residual, eps, use_pallas):
    n, h = x.shape
    if not use_pallas:
        return rms_norm_ref(x, weight, eps, residual)
    br = _block_rows(n, h, x.dtype)
    grid = (pl.cdiv(n, br),)
    x_spec = pl.BlockSpec((br, h), lambda i: (i, 0), memory_space=pltpu.VMEM)
    w_spec = pl.BlockSpec((1, h), lambda i: (0, 0), memory_space=pltpu.VMEM)
    weight = weight.reshape(1, h)
    if residual is None:
        return pl.pallas_call(
            functools.partial(_rmsnorm_kernel, eps=eps),
            grid=grid,
            in_specs=[x_spec, w_spec],
            out_specs=x_spec,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            interpret=interpret_flag(),
        )(x, weight)
    return pl.pallas_call(
        functools.partial(_rmsnorm_fused_kernel, eps=eps),
        grid=grid,
        in_specs=[x_spec, x_spec, w_spec],
        out_specs=(x_spec, x_spec),
        out_shape=(
            jax.ShapeDtypeStruct(x.shape, x.dtype),
            jax.ShapeDtypeStruct(x.shape, x.dtype),
        ),
        interpret=interpret_flag(),
    )(x, residual, weight)


def rms_norm(
    x: jax.Array,
    weight: jax.Array,
    eps: float = 1e-6,
    residual: Optional[jax.Array] = None,
    use_pallas: Optional[bool] = None,
):
    """RMSNorm over the last axis. Any leading shape; optionally fused
    residual add (returns (normed, new_residual))."""
    if use_pallas is None:
        from vllm_omni_tpu.ops._dispatch import pallas_mode

        use_pallas = pallas_mode() == "native"
    lead = x.shape[:-1]
    h = x.shape[-1]
    x2 = x.reshape(-1, h)
    r2 = residual.reshape(-1, h) if residual is not None else None
    out = _rms_norm_2d(x2, weight, r2, eps, use_pallas)
    if residual is None:
        return out.reshape(*lead, h)
    y, r = out
    return y.reshape(*lead, h), r.reshape(*lead, h)
