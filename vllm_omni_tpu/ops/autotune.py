"""Per-shape kernel block selection, shared across the attention family.

``auto_blocks`` is the dense flash kernel's (block_q, block_k) picker —
lifted out of ``ops/attention.py`` so the ragged paged kernel can reuse
the same methodology ("The Anatomy of a Triton Attention Kernel",
PAPERS.md: per-shape tile choice is where the MFU lives; a fixed grid
ran 13% MFU on the DiT joint sequence, the tuned one 68%).

``auto_ragged_blocks`` applies it to the ragged paged-attention kernel's
two knobs — the per-sequence q block (``token_block``) and the page-DMA
pipeline depth (``dma_slots``) — under the VMEM budget a grid cell
actually has.  Both pickers preserve the guaranteed-fit fallback: a cap
below every candidate shrinks the choice instead of crashing.
"""

from __future__ import annotations

import functools

#: f32 score-block element budget for the dense kernel (~8 MB)
SCORE_CAP = 2_097_152

#: VMEM byte budget for one ragged grid cell's working set (q block +
#: f32 accumulator + output + KV DMA buffers + score block).  VMEM is
#: ~16 MiB/core; the budget leaves headroom for compiler temporaries
#: and the second grid cell XLA may keep in flight.
RAGGED_VMEM_CAP = 4 * 1024 * 1024


def auto_blocks(sq: int, skv: int, d: int,
                itemsize: int = 2) -> tuple[int, int]:
    """Pick (block_q, block_k) for the dense kernel by minimizing padded
    MXU work under the score-block VMEM cap.

    Measured on the chip (v5 lite, DiT joint seq 4608, d=128): the old
    fixed (256, 256) grid ran 15552 tiny kernel invocations at 13% MFU —
    per-step overhead dominated; (2048, 1024) hit 56%, and (2304, 768) —
    both dividing the sequence exactly — 68%.  Large q blocks also cut
    HBM traffic (KV is re-read once per q block), so ties prefer the
    bigger bq.  Callers passing explicit block sizes bypass this.

    The cap scales down with head dim and input width: q/k/v blocks and
    the accumulator share VMEM with the score block, and f32 inputs
    double their footprint (measured: (2304, 768) fits at bf16 d=128,
    OOMs by 2.2 MB at f32)."""
    cap = SCORE_CAP * 128 // max(d, 128) * 2 // max(itemsize, 2)

    def padded(s, b):
        return -(-s // b) * b

    best = None
    for bq in (2304, 2048, 1792, 1536, 1280, 1024, 768, 512, 256):
        bq_c = min(bq, max(8, sq))
        for bk in (1024, 896, 768, 640, 512, 384, 256):
            bk_c = min(bk, max(8, skv))
            if bq_c * bk_c > cap:
                continue
            cand = (padded(sq, bq_c) * padded(skv, bk_c), -bq_c, -bk_c)
            if best is None or cand < best[0]:
                best = (cand, bq_c, bk_c)
    if best is None:
        # cap below even the smallest candidate product (huge head dim /
        # wide inputs shrink it past 256*256): fall back instead of
        # crashing on best[1].  Start from the smallest candidate pair
        # and keep halving the larger side until the score block honors
        # the cap too (floor 8 — the minimum tile).
        bq = min(256, max(8, sq))
        bk = min(256, max(8, skv))
        while bq * bk > cap and (bq > 8 or bk > 8):
            if bq >= bk and bq > 8:
                bq = max(8, bq // 2)
            else:
                bk = max(8, bk // 2)
        return bq, bk
    return best[1], best[2]


@functools.lru_cache(maxsize=64)
def auto_ragged_blocks(
    head_dim: int,
    page_size: int,
    group: int = 1,
    kv_itemsize: int = 2,
    q_itemsize: int = 4,
    decode_heavy: bool = True,
    vmem_cap_bytes: int = RAGGED_VMEM_CAP,
    quantized: bool = False,
    num_pages: int = 0,
) -> tuple[int, int]:
    """(token_block, dma_slots) for the ragged paged-attention kernel.

    The search runs PER LAYOUT (the lru key includes ``quantized`` and
    the scale-array width ``num_pages``): the int8 layout halves the
    page DMA buffers (kv_itemsize is the int8 data's) but adds the
    resident per-(head, page) f32 scale rows pinned in VMEM plus an f32
    dequant staging copy of the in-flight page — a deeper pipeline may
    fit quantized where bf16 took 2 slots, and the warmup log shows
    which choice each layout got.

    ``token_block`` is the per-sequence q block in TOKENS and doubles as
    the host packer's segment alignment — every (packed) decode row
    costs ``token_block`` rows, so a decode-heavy serving mix
    (``decode_heavy=True``, the engine default) pins it at 8 (the f32
    sublane tile at group=1); a prefill-dominated deployment may take 16
    to halve the number of q blocks — each block re-reads its
    sequence's whole paged context, so fewer blocks = half the HBM
    traffic — at 16 rows/decode-row padding cost.

    ``dma_slots`` is the HBM→VMEM page pipeline depth: ``slots - 1``
    pages are in flight while one is being consumed, so deeper pipelines
    hide more HBM latency (the decode inner loop is DMA-bound — the
    whole context streams through VMEM once per q block).  Deeper costs
    ``2 * page_size * head_dim * kv_itemsize`` bytes per extra slot;
    the picker takes the deepest of (4, 3, 2) that fits the cell
    budget, and the guaranteed-fit fallback degrades to classic double
    buffering (2) rather than failing."""
    for tb in ((8, 16) if decode_heavy else (16, 8)):
        rows = tb * max(group, 1)
        # q block (input itemsize) + f32 accumulator + output block
        fixed = rows * head_dim * (q_itemsize + 4 + kv_itemsize)
        # f32 score block per page
        fixed += rows * page_size * 4
        if quantized:
            # k/v per-(head, page) f32 scale rows pinned in VMEM for
            # the whole launch + the f32 dequant staging copy of the
            # page being consumed (k and v)
            fixed += 2 * max(num_pages, 0) * 4
            fixed += 2 * page_size * head_dim * 4
        for slots in (4, 3, 2):
            kv = 2 * slots * page_size * head_dim * kv_itemsize
            if fixed + kv <= vmem_cap_bytes:
                return tb, slots
    # guaranteed fit: the smallest working set the kernel supports
    return 8, 2
