"""Rotary position embedding — Pallas replacement for vLLM's fused
RoPE/MRoPE CUDA op (SURVEY.md §2.10).

Frequencies (cos/sin tables) are computed host/XLA-side — including the
*sectioned multimodal* MRoPE layout where the rotary feature dims are split
among temporal/height/width position streams (the position math the
reference implements in model_executor/layers/rotary_embedding/mrope.py:25);
the Pallas kernel then applies the rotation to Q and K in one fused pass.

Convention: GPT-NeoX half-split rotation (x1 = x[..., :d/2], x2 = x[..., d/2:]),
the layout used by the Qwen model families.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from vllm_omni_tpu.ops._dispatch import interpret_flag


def compute_rope_freqs(
    positions: jax.Array,  # [T] int
    head_dim: int,
    theta: float = 10000.0,
    scaling: float = 1.0,
) -> tuple[jax.Array, jax.Array]:
    """Return (cos, sin), each [T, head_dim//2], float32."""
    half = head_dim // 2
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, half, dtype=jnp.float32) / half)
    )
    angles = positions.astype(jnp.float32)[:, None] * inv_freq[None, :]
    angles = angles * scaling
    return jnp.cos(angles), jnp.sin(angles)


def compute_mrope_freqs(
    positions: jax.Array,  # [3, T] int — (temporal, height, width) streams
    head_dim: int,
    mrope_section: Sequence[int],  # splits of head_dim//2 among the streams
    theta: float = 10000.0,
) -> tuple[jax.Array, jax.Array]:
    """Sectioned multimodal RoPE frequencies.

    Each of the 3 position streams owns a contiguous slice of the rotary
    feature dims (sum(mrope_section) == head_dim//2), matching the
    interleaved 3D-RoPE the reference computes for image/video/audio
    positions (mrope.py:25).
    """
    assert positions.ndim == 2 and positions.shape[0] == len(mrope_section)
    half = head_dim // 2
    assert sum(mrope_section) == half, (mrope_section, half)
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, half, dtype=jnp.float32) / half)
    )
    # angles per stream: [3, T, half]
    angles = positions.astype(jnp.float32)[:, :, None] * inv_freq[None, None, :]
    # select the owning stream per feature dim
    section_id = jnp.repeat(
        jnp.arange(len(mrope_section)),
        jnp.asarray(mrope_section),
        total_repeat_length=half,
    )  # [half]
    angles = jnp.take_along_axis(
        angles, section_id[None, None, :].repeat(positions.shape[1], 1), axis=0
    )[0]  # [T, half]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope_ref(
    x: jax.Array,  # [T, H, D]
    cos: jax.Array,  # [T, D//2]
    sin: jax.Array,  # [T, D//2]
) -> jax.Array:
    d = x.shape[-1]
    x1 = x[..., : d // 2].astype(jnp.float32)
    x2 = x[..., d // 2 :].astype(jnp.float32)
    c = cos[:, None, :]
    s = sin[:, None, :]
    o1 = x1 * c - x2 * s
    o2 = x2 * c + x1 * s
    return jnp.concatenate([o1, o2], axis=-1).astype(x.dtype)


def _rope_kernel(x_ref, cos_ref, sin_ref, o_ref, *, dim: int):
    # x_ref: [bt, H, D] 3D block — no in-kernel reshape (Mosaic layout
    # inference rejects 2D->3D shape casts for small head dims).
    half = dim // 2
    v = x_ref[:].astype(jnp.float32)
    x1 = v[..., :half]
    x2 = v[..., half:]
    c = cos_ref[:].astype(jnp.float32)[:, None, :]
    s = sin_ref[:].astype(jnp.float32)[:, None, :]
    o1 = x1 * c - x2 * s
    o2 = x2 * c + x1 * s
    o_ref[:] = jnp.concatenate([o1, o2], axis=-1).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("use_pallas",))
def _apply_rope(x, cos, sin, use_pallas):
    t, h, d = x.shape
    # Pallas pays off only for MXU-aligned head dims; XLA fuses the rest.
    if not use_pallas or d % 64 != 0:
        return apply_rope_ref(x, cos, sin)
    # Size the token block from a VMEM budget: the kernel holds several
    # fp32 intermediates of the block shape, so keep one copy ~<=1MB.
    budget_rows = (1 << 20) // (h * d * 4)
    bt = max(8, min(t, budget_rows, 512))
    bt = max(8, (bt // 8) * 8)
    grid = (pl.cdiv(t, bt),)
    return pl.pallas_call(
        functools.partial(_rope_kernel, dim=d),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bt, h, d), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bt, d // 2), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bt, d // 2), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (bt, h, d), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((t, h, d), x.dtype),
        interpret=interpret_flag(),
    )(x, cos, sin)


def apply_rope(
    x: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    use_pallas: Optional[bool] = None,
) -> jax.Array:
    """Apply rotary embedding to [T, H, D] activations."""
    if use_pallas is None:
        from vllm_omni_tpu.ops._dispatch import pallas_mode

        use_pallas = pallas_mode() == "native"
    return _apply_rope(x, cos, sin, use_pallas)
