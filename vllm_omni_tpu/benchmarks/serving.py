"""Online serving benchmark: hits the OpenAI server, reports percentiles.

The TPU counterpart of the reference's serving benchmarks (reference:
benchmarks/diffusion/diffusion_benchmark_serving.py — request throughput,
latency percentiles, per-request SLO attainment; in-tree
``vllm bench serve --omni``, vllm_omni/benchmarks/serve.py:8).

Drives ``/v1/chat/completions`` (streaming SSE for TTFT or non-streaming)
or ``/v1/images/generations`` with a bounded concurrency worker pool, and
prints one JSON report: throughput, TTFT (streaming) and E2E latency
p50/p90/p99, and error counts.  Pure stdlib (http.client + threads) so it
runs anywhere the server does.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class BenchResult:
    num_requests: int = 0
    num_errors: int = 0
    duration_s: float = 0.0
    e2e_ms: list = field(default_factory=list)
    ttft_ms: list = field(default_factory=list)

    @staticmethod
    def _pct(xs: list, p: float) -> float:
        from vllm_omni_tpu.metrics.stats import nearest_rank_pct

        return nearest_rank_pct(xs, p)

    def report(self) -> dict:
        ok = self.num_requests - self.num_errors
        out = {
            "num_requests": self.num_requests,
            "num_errors": self.num_errors,
            "duration_s": round(self.duration_s, 3),
            "requests_per_s": round(ok / self.duration_s, 4)
            if self.duration_s else 0.0,
            "e2e_ms": {
                "p50": round(self._pct(self.e2e_ms, 0.50), 2),
                "p90": round(self._pct(self.e2e_ms, 0.90), 2),
                "p99": round(self._pct(self.e2e_ms, 0.99), 2),
            },
        }
        if self.ttft_ms:
            out["ttft_ms"] = {
                "p50": round(self._pct(self.ttft_ms, 0.50), 2),
                "p90": round(self._pct(self.ttft_ms, 0.90), 2),
                "p99": round(self._pct(self.ttft_ms, 0.99), 2),
            }
        return out


def _one_chat(base_url: str, prompt: str, max_tokens: int,
              stream: bool, result: BenchResult, lock: threading.Lock):
    body = json.dumps({
        "model": "bench",
        "messages": [{"role": "user", "content": prompt}],
        "max_tokens": max_tokens,
        "stream": stream,
    }).encode()
    req = urllib.request.Request(
        f"{base_url}/v1/chat/completions", data=body,
        headers={"Content-Type": "application/json"},
    )
    t0 = time.perf_counter()
    ttft = None
    failed = False
    try:
        with urllib.request.urlopen(req, timeout=300) as resp:
            if stream:
                for line in resp:
                    if not line.startswith(b"data:"):
                        continue
                    payload = line[5:].strip()
                    if payload == b"[DONE]":
                        break
                    # the server surfaces in-stream failures as HTTP 200
                    # with an error event — count them as errors, not as
                    # healthy latencies
                    if b'"error"' in payload:
                        try:
                            if "error" in json.loads(payload):
                                failed = True
                                break
                        except json.JSONDecodeError:
                            pass
                    if ttft is None:
                        ttft = (time.perf_counter() - t0) * 1e3
            else:
                body_out = json.loads(resp.read() or b"{}")
                failed = "error" in body_out
        e2e = (time.perf_counter() - t0) * 1e3
        with lock:
            if failed:
                result.num_errors += 1
            else:
                result.e2e_ms.append(e2e)
                if ttft is not None:
                    result.ttft_ms.append(ttft)
    except Exception:
        with lock:
            result.num_errors += 1


def _one_image(base_url: str, prompt: str, size: str,
               result: BenchResult, lock: threading.Lock):
    body = json.dumps({"prompt": prompt, "size": size, "n": 1}).encode()
    req = urllib.request.Request(
        f"{base_url}/v1/images/generations", data=body,
        headers={"Content-Type": "application/json"},
    )
    t0 = time.perf_counter()
    try:
        with urllib.request.urlopen(req, timeout=600) as resp:
            resp.read()
        with lock:
            result.e2e_ms.append((time.perf_counter() - t0) * 1e3)
    except Exception:
        with lock:
            result.num_errors += 1


def run_bench(
    base_url: str,
    endpoint: str = "chat",  # "chat" | "images"
    num_requests: int = 16,
    concurrency: int = 4,
    max_tokens: int = 32,
    stream: bool = True,
    size: str = "64x64",
    prompt: str = "benchmark prompt",
) -> dict:
    """Run the bench; returns the report dict (also what the CLI prints)."""
    if endpoint not in ("chat", "images"):
        raise ValueError(f"unknown endpoint {endpoint!r}")
    result = BenchResult(num_requests=num_requests)
    lock = threading.Lock()
    # fixed pool of `concurrency` workers pulling indices from a queue —
    # one thread per request would spawn num_requests stacks that mostly
    # block, perturbing the latencies being measured
    import queue as queue_mod

    work: queue_mod.Queue = queue_mod.Queue()
    for i in range(num_requests):
        work.put(i)

    def worker():
        while True:
            try:
                i = work.get_nowait()
            except queue_mod.Empty:
                return
            p = f"{prompt} #{i}"
            if endpoint == "chat":
                _one_chat(base_url, p, max_tokens, stream, result, lock)
            else:
                _one_image(base_url, p, size, result, lock)

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker)
               for _ in range(max(1, min(concurrency, num_requests)))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    result.duration_s = time.perf_counter() - t0
    return result.report()


def add_cli_args(ap) -> None:
    """Shared option set (used by both this module's main() and the
    vllm-omni-tpu bench-serve subcommand — one definition)."""
    ap.add_argument("--base-url", default="http://127.0.0.1:8000")
    ap.add_argument("--endpoint", choices=("chat", "images"),
                    default="chat")
    ap.add_argument("--num-requests", type=int, default=16)
    ap.add_argument("--concurrency", type=int, default=4)
    ap.add_argument("--max-tokens", type=int, default=32)
    ap.add_argument("--no-stream", action="store_true")
    ap.add_argument("--size", default="64x64")
    ap.add_argument("--prompt", default="benchmark prompt")


def run_from_args(args) -> int:
    report = run_bench(
        args.base_url, endpoint=args.endpoint,
        num_requests=args.num_requests, concurrency=args.concurrency,
        max_tokens=args.max_tokens, stream=not args.no_stream,
        size=args.size, prompt=args.prompt,
    )
    print(json.dumps(report))
    return 0


def main(argv: Optional[list] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    add_cli_args(ap)
    return run_from_args(ap.parse_args(argv))


if __name__ == "__main__":
    raise SystemExit(main())
