"""Online serving benchmark: hits the OpenAI server, reports percentiles.

The TPU counterpart of the reference's serving benchmarks (reference:
benchmarks/diffusion/diffusion_benchmark_serving.py — request throughput,
latency percentiles, per-request SLO attainment; in-tree
``vllm bench serve --omni``, vllm_omni/benchmarks/serve.py:8).

Drives ``/v1/chat/completions`` (streaming SSE for TTFT or
non-streaming), ``/v1/images/generations``, ``/v1/audio/speech``, or
``/v1/videos`` with a bounded concurrency worker pool, and prints one
JSON report: throughput, TTFT (streaming) and E2E latency p50/p90/p99,
error counts, and per-request SLO attainment — an explicit ``--slo-ms``
E2E target, or one inferred from warmup requests scaled by
``--slo-scale`` (reference ``_populate_slo_ms_from_warmups``,
diffusion_benchmark_serving.py:629-661).  Pure stdlib (http.client +
threads) so it runs anywhere the server does.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class BenchResult:
    num_requests: int = 0
    num_errors: int = 0
    duration_s: float = 0.0
    e2e_ms: list = field(default_factory=list)
    ttft_ms: list = field(default_factory=list)
    # per-request E2E SLO target; None disables attainment reporting
    slo_ms: Optional[float] = None

    @staticmethod
    def _pct(xs: list, p: float) -> float:
        from vllm_omni_tpu.metrics.stats import nearest_rank_pct

        return nearest_rank_pct(xs, p)

    def report(self) -> dict:
        ok = self.num_requests - self.num_errors
        out = {
            "num_requests": self.num_requests,
            "num_errors": self.num_errors,
            "duration_s": round(self.duration_s, 3),
            "requests_per_s": round(ok / self.duration_s, 4)
            if self.duration_s else 0.0,
            "e2e_ms": {
                "p50": round(self._pct(self.e2e_ms, 0.50), 2),
                "p90": round(self._pct(self.e2e_ms, 0.90), 2),
                "p99": round(self._pct(self.e2e_ms, 0.99), 2),
            },
        }
        if self.ttft_ms:
            out["ttft_ms"] = {
                "p50": round(self._pct(self.ttft_ms, 0.50), 2),
                "p90": round(self._pct(self.ttft_ms, 0.90), 2),
                "p99": round(self._pct(self.ttft_ms, 0.99), 2),
            }
        if self.slo_ms is not None:
            # errored requests count as missed (reference slo_achieved
            # is only set on success, diffusion_benchmark_serving.py:765)
            achieved = sum(1 for ms in self.e2e_ms if ms <= self.slo_ms)
            out["slo"] = {
                "slo_ms": round(self.slo_ms, 2),
                "achieved": achieved,
                "missed": self.num_requests - achieved,
                "attainment": round(achieved / self.num_requests, 4)
                if self.num_requests else 0.0,
            }
        return out


def chat_http_request(base_url: str, body: dict,
                      headers: Optional[dict] = None,
                      timeout_s: float = 300.0) -> dict:
    """The ONE chat-completions HTTP/SSE driver (this bench and the
    loadgen harness both call it — the stream framing and in-stream
    error detection must never fork).  Streams when ``body["stream"]``
    is set, stamping the first SSE data event.  Never raises; returns:

    - ``ok``: completed successfully (a stream that produced NO data
      event counts as failed — the server dropped it without an error)
    - ``http_status``: status code when the server refused the request
      outright (429 shed / 504 deadline / 400 ...), else None
    - ``error``: the OpenAI error payload, from the error body or the
      in-stream SSE error event (which carries ``type`` + would-be
      ``code`` for the 429/503/504 taxonomy), else None
    - ``first_event_mono`` / ``end_mono``: time.monotonic stamps
    - ``usage_completion_tokens``: from the non-streaming usage block
    """
    req = urllib.request.Request(
        f"{base_url}/v1/chat/completions",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    out = {"ok": False, "http_status": None, "error": None,
           "first_event_mono": None, "end_mono": 0.0,
           "usage_completion_tokens": None}
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            if body.get("stream"):
                for line in resp:
                    if not line.startswith(b"data:"):
                        continue
                    payload = line[5:].strip()
                    if payload == b"[DONE]":
                        break
                    # in-stream failures arrive as HTTP 200 + an error
                    # event — surface them, never count them healthy
                    if b'"error"' in payload:
                        try:
                            obj = json.loads(payload)
                        except json.JSONDecodeError:
                            obj = None
                        if obj and "error" in obj:
                            out["error"] = obj["error"]
                            break
                    if out["first_event_mono"] is None:
                        out["first_event_mono"] = time.monotonic()
                out["ok"] = (out["error"] is None
                             and out["first_event_mono"] is not None)
            else:
                obj = json.loads(resp.read() or b"{}")
                if "error" in obj:
                    out["error"] = obj["error"]
                else:
                    out["ok"] = True
                    out["usage_completion_tokens"] = (
                        obj.get("usage") or {}).get("completion_tokens")
    except urllib.error.HTTPError as e:
        out["http_status"] = e.code
        try:
            out["error"] = json.loads(e.read() or b"{}").get("error")
        except Exception:
            pass
    except Exception:
        pass
    out["end_mono"] = time.monotonic()
    return out


def _one_chat(base_url: str, prompt: str, max_tokens: int,
              stream: bool, result: BenchResult, lock: threading.Lock):
    t0 = time.monotonic()
    res = chat_http_request(base_url, {
        "model": "bench",
        "messages": [{"role": "user", "content": prompt}],
        "max_tokens": max_tokens,
        "stream": stream,
    })
    with lock:
        if not res["ok"]:
            result.num_errors += 1
        else:
            result.e2e_ms.append((res["end_mono"] - t0) * 1e3)
            if res["first_event_mono"] is not None:
                result.ttft_ms.append(
                    (res["first_event_mono"] - t0) * 1e3)


def _one_blocking(base_url: str, path: str, payload: dict,
                  result: BenchResult, lock: threading.Lock,
                  timeout: float = 600):
    """Non-streaming POST leg: images / speech / videos share the same
    request-to-bytes measurement."""
    req = urllib.request.Request(
        f"{base_url}{path}", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    t0 = time.perf_counter()
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            resp.read()
        with lock:
            result.e2e_ms.append((time.perf_counter() - t0) * 1e3)
    except Exception:
        with lock:
            result.num_errors += 1


def _endpoint_request(endpoint: str, prompt: str, size: str) -> tuple:
    """(path, payload) per non-chat endpoint."""
    if endpoint == "images":
        return ("/v1/images/generations",
                {"prompt": prompt, "size": size, "n": 1})
    if endpoint == "speech":
        # reference speech leg (vllm_omni/benchmarks/serve.py:8 drives
        # the audio endpoints)
        return ("/v1/audio/speech", {"input": prompt, "model": "bench"})
    if endpoint == "videos":
        return ("/v1/videos", {"prompt": prompt, "size": size})
    raise ValueError(f"unknown endpoint {endpoint!r}")


def _infer_slo_ms(base_url: str, endpoint: str, prompt: str,
                  max_tokens: int, size: str, warmup: int,
                  slo_scale: float) -> Optional[float]:
    """Derive the per-request E2E SLO from sequential warmup requests:
    median unloaded latency x slo_scale (reference
    _infer_slo_base_time_ms_from_warmups + slo_scale default 3.0,
    diffusion_benchmark_serving.py:590-661)."""
    from vllm_omni_tpu.metrics.stats import nearest_rank_pct

    probe = BenchResult(num_requests=warmup)
    lock = threading.Lock()
    for i in range(warmup):
        p = f"{prompt} warmup-{i}"
        if endpoint == "chat":
            _one_chat(base_url, p, max_tokens, False, probe, lock)
        else:
            path, payload = _endpoint_request(endpoint, p, size)
            _one_blocking(base_url, path, payload, probe, lock)
    if not probe.e2e_ms:
        # the operator asked for SLO attainment; a report silently
        # missing the "slo" key would read as success
        raise RuntimeError(
            f"SLO inference failed: all {warmup} warmup requests "
            "errored — server unhealthy or endpoint mismatch")
    # same p50 definition the report uses (nearest-rank)
    return nearest_rank_pct(probe.e2e_ms, 0.50) * slo_scale


def run_bench(
    base_url: str,
    endpoint: str = "chat",  # "chat" | "images" | "speech" | "videos"
    num_requests: int = 16,
    concurrency: int = 4,
    max_tokens: int = 32,
    stream: bool = True,
    size: str = "64x64",
    prompt: str = "benchmark prompt",
    slo_ms: Optional[float] = None,
    slo_scale: Optional[float] = None,
    warmup: int = 2,
) -> dict:
    """Run the bench; returns the report dict (also what the CLI
    prints).  SLO attainment reports when ``slo_ms`` is given, or when
    ``slo_scale`` is given (target = median warmup latency x scale)."""
    if endpoint not in ("chat", "images", "speech", "videos"):
        raise ValueError(f"unknown endpoint {endpoint!r}")
    if slo_ms is None and slo_scale is not None:
        slo_ms = _infer_slo_ms(base_url, endpoint, prompt, max_tokens,
                               size, max(1, warmup), slo_scale)
    result = BenchResult(num_requests=num_requests, slo_ms=slo_ms)
    lock = threading.Lock()
    # fixed pool of `concurrency` workers pulling indices from a queue —
    # one thread per request would spawn num_requests stacks that mostly
    # block, perturbing the latencies being measured
    import queue as queue_mod

    work: queue_mod.Queue = queue_mod.Queue()
    for i in range(num_requests):
        work.put(i)

    def worker():
        while True:
            try:
                i = work.get_nowait()
            except queue_mod.Empty:
                return
            p = f"{prompt} #{i}"
            if endpoint == "chat":
                _one_chat(base_url, p, max_tokens, stream, result, lock)
            else:
                path, payload = _endpoint_request(endpoint, p, size)
                _one_blocking(base_url, path, payload, result, lock)

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker)
               for _ in range(max(1, min(concurrency, num_requests)))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    result.duration_s = time.perf_counter() - t0
    return result.report()


def add_cli_args(ap) -> None:
    """Shared option set (used by both this module's main() and the
    vllm-omni-tpu bench-serve subcommand — one definition)."""
    ap.add_argument("--base-url", default="http://127.0.0.1:8000")
    ap.add_argument("--endpoint",
                    choices=("chat", "images", "speech", "videos"),
                    default="chat")
    ap.add_argument("--num-requests", type=int, default=16)
    ap.add_argument("--concurrency", type=int, default=4)
    ap.add_argument("--max-tokens", type=int, default=32)
    ap.add_argument("--no-stream", action="store_true")
    ap.add_argument("--size", default="64x64")
    ap.add_argument("--prompt", default="benchmark prompt")
    ap.add_argument("--slo-ms", type=float, default=None,
                    help="per-request E2E SLO target (ms)")
    ap.add_argument("--slo-scale", type=float, default=None,
                    help="infer the SLO as median warmup latency x "
                         "this scale (reference default 3.0)")
    ap.add_argument("--warmup", type=int, default=2,
                    help="sequential warmup requests for SLO inference")


def run_from_args(args) -> int:
    report = run_bench(
        args.base_url, endpoint=args.endpoint,
        num_requests=args.num_requests, concurrency=args.concurrency,
        max_tokens=args.max_tokens, stream=not args.no_stream,
        size=args.size, prompt=args.prompt, slo_ms=args.slo_ms,
        slo_scale=args.slo_scale, warmup=args.warmup,
    )
    print(json.dumps(report))
    return 0


def main(argv: Optional[list] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    add_cli_args(ap)
    return run_from_args(ap.parse_args(argv))


if __name__ == "__main__":
    raise SystemExit(main())
