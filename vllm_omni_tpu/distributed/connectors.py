"""OmniConnectors: typed put/get transport between pipeline stages.

Behavioral port of the reference connector stack (reference:
vllm_omni/distributed/omni_connectors/connectors/base.py:12 ``put/get/
cleanup/health``; shm_connector.py:17 posix-SHM default transport;
factory.py:24 name→constructor registry).  The Mooncake/Yuanrong RDMA
connectors map to a TCP connector on TPU-VM NICs (future: DCN collectives
for same-pod slices).

Keys follow the reference convention ``{request_id}/{from_stage}_{to_stage}``.
"""

from __future__ import annotations

import os
import pickle
import tempfile
import threading
import time
from abc import ABC, abstractmethod
from typing import Any, Optional

from vllm_omni_tpu.analysis.runtime import traced
from vllm_omni_tpu.distributed.serialization import OmniSerializer
from vllm_omni_tpu.logger import init_logger

logger = init_logger(__name__)


def make_key(request_id: str, from_stage: int, to_stage: int) -> str:
    return f"{request_id}/{from_stage}_{to_stage}"


class OmniConnectorBase(ABC):
    """put/get with centralized serialization (base.py:12).

    ``timeout`` contract (all connectors): ``None`` = non-blocking
    probe, a float = bounded wait, ``float("inf")`` = block until the
    key appears.  ``fault_point("conn")`` is the resilience fault-plan
    injection site for both directions (resilience/faults.py)."""

    def put(self, key: str, obj: Any) -> int:
        from vllm_omni_tpu.resilience.faults import fault_point

        fault_point("conn")
        data = OmniSerializer.dumps(obj)
        self._put_bytes(key, data)
        return len(data)

    def get(self, key: str, timeout: Optional[float] = None) -> Any:
        from vllm_omni_tpu.resilience.faults import fault_point

        fault_point("conn")
        data = self._get_bytes(key, timeout)
        return None if data is None else OmniSerializer.loads(data)

    @abstractmethod
    def _put_bytes(self, key: str, data: bytes) -> None: ...

    @abstractmethod
    def _get_bytes(self, key: str, timeout: Optional[float]) -> Optional[bytes]: ...

    def cleanup(self, key: str) -> None:
        pass

    def health(self) -> bool:
        return True

    def close(self) -> None:
        pass


class InProcConnector(OmniConnectorBase):
    """Same-process dict store — the unit-test fake of distributed transfer
    (the reference uses SHM connectors in-proc for the same purpose,
    SURVEY.md §4 fixtures inventory).

    ``zero_copy``: same-address-space edges can hand objects over
    directly; orchestrators skip the serialize->store->deserialize round
    trip (it measured serialization, not transport — VERDICT r2 weak #5)
    unless OMNI_TPU_FORCE_CONNECTOR_SERIALIZATION=1 pins the full path
    (serialization regression tests)."""

    zero_copy = True

    # namespace -> (store, condition), shared by EVERY instance of that
    # namespace.  The condition must be per-STORE, not per-instance:
    # two instances of one namespace share the dict, so they must share
    # the wakeup channel too — with a private per-instance cv (the old
    # shape), a put through instance A never notified a get blocked on
    # instance B, which then only progressed on its 1 s re-check slice.
    _stores: dict[str, tuple[dict, Any]] = {}
    # deliberately class-level (process-global): it guards the
    # class-level namespace registry above — a per-instance lock could
    # not serialize two instances creating the same namespace.  Taken
    # only at construction, never on the data path (the per-namespace
    # cv owns that), so cross-instance contention is nil.
    _registry_lock = threading.Lock()

    def __init__(self, namespace: str = "default", **_):
        with InProcConnector._registry_lock:
            # omnilint: disable=OL9 - local registry dict probe, not a
            # remote store round trip; non-blocking under the lock
            entry = InProcConnector._stores.get(namespace)
            if entry is None:
                entry = InProcConnector._stores[namespace] = (
                    {}, traced(threading.Condition(),
                               "InProcConnector._cv"))
        self._store, self._cv = entry

    def _put_bytes(self, key: str, data: bytes) -> None:
        with self._cv:
            self._store[key] = data
            self._cv.notify_all()

    def _get_bytes(self, key: str, timeout: Optional[float]) -> Optional[bytes]:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while key not in self._store:
                if deadline is None:
                    # omnilint: disable=OL9 - local dict probe, not a
                    # remote store round trip; non-blocking under the cv
                    return self._store.get(key)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                # sliced wait: Condition.wait overflows on float("inf")
                self._cv.wait(min(remaining, 1.0))
            return self._store.pop(key)

    def cleanup(self, key: str) -> None:
        with self._cv:
            self._store.pop(key, None)


class SharedMemoryConnector(OmniConnectorBase):
    """Single-node cross-process transport over the filesystem (tmpfs).

    The reference's shm connector uses posix SHM + flock
    (shm_connector.py:17,53-57); files on /dev/shm give the same kernel
    page-cache path with simpler lifetime management, using atomic rename
    for the ready signal instead of a lock.
    """

    def __init__(self, namespace: str = "omni", base_dir: Optional[str] = None, **_):
        root = base_dir or os.environ.get("OMNI_TPU_SHM_DIR") or (
            "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()
        )
        self._dir = os.path.join(root, f"omni_tpu_{namespace}")
        os.makedirs(self._dir, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self._dir, key.replace("/", "__"))

    def _put_bytes(self, key: str, data: bytes) -> None:
        path = self._path(key)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.rename(tmp, path)  # atomic publish

    def _get_bytes(self, key: str, timeout: Optional[float]) -> Optional[bytes]:
        path = self._path(key)
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                with open(path, "rb") as f:
                    data = f.read()
                os.unlink(path)
                return data
            except FileNotFoundError:
                if deadline is None or time.monotonic() >= deadline:
                    return None
                time.sleep(0.002)

    def cleanup(self, key: str) -> None:
        try:
            os.unlink(self._path(key))
        except FileNotFoundError:
            pass

    def health(self) -> bool:
        return os.path.isdir(self._dir)


class ConnectorFactory:
    """name → constructor registry (factory.py:24,96-100)."""

    _registry: dict[str, type[OmniConnectorBase]] = {}

    @classmethod
    def register(cls, name: str, ctor: type[OmniConnectorBase]) -> None:
        cls._registry[name] = ctor

    @classmethod
    def create(cls, name: str, **kwargs) -> OmniConnectorBase:
        if name not in cls._registry:
            raise KeyError(
                f"unknown connector {name!r}; known: {sorted(cls._registry)}"
            )
        return cls._registry[name](**kwargs)


ConnectorFactory.register("inproc", InProcConnector)
ConnectorFactory.register("shm", SharedMemoryConnector)
