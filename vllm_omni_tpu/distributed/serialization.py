"""Tensor-aware serialization for cross-stage transfer.

Behavioral analogue of the reference's ``OmniSerializer``
(reference: vllm_omni/distributed/omni_connectors/utils/serialization.py:
msgpack/pickle hybrid with tensor extraction).  Here the container format is
a simple length-prefixed frame: a pickled skeleton where every ndarray /
jax.Array leaf is swapped for a placeholder, followed by raw array buffers.
Arrays transfer zero-copy out of the buffer on load (np.frombuffer view).

Pickle is used only for the *skeleton* (dicts/lists/dataclasses of plain
data) — payloads come from our own stage workers, the same trust domain the
reference operates in.
"""

from __future__ import annotations

import io
import pickle
import struct
from typing import Any

import numpy as np

_MAGIC = b"OTSZ"


class _ArrayRef:
    __slots__ = ("index",)

    def __init__(self, index: int):
        self.index = index

    def __reduce__(self):
        return (_ArrayRef, (self.index,))


def _extract(obj: Any, arrays: list[np.ndarray]):
    """Recursively swap array leaves for _ArrayRef placeholders."""
    # jax.Array → numpy without importing jax here (duck-typed)
    if hasattr(obj, "__array__") and not isinstance(obj, (str, bytes)):
        if isinstance(obj, np.ndarray) or type(obj).__module__.startswith(
            ("jax", "jaxlib")
        ):
            arr = np.ascontiguousarray(np.asarray(obj))
            arrays.append(arr)
            return _ArrayRef(len(arrays) - 1)
    if isinstance(obj, dict):
        return {k: _extract(v, arrays) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        mapped = [_extract(v, arrays) for v in obj]
        return type(obj)(mapped) if not isinstance(obj, tuple) else tuple(mapped)
    return obj


def _restore(obj: Any, arrays: list[np.ndarray]):
    if isinstance(obj, _ArrayRef):
        return arrays[obj.index]
    if isinstance(obj, dict):
        return {k: _restore(v, arrays) for k, v in obj.items()}
    if isinstance(obj, tuple):
        return tuple(_restore(v, arrays) for v in obj)
    if isinstance(obj, list):
        return [_restore(v, arrays) for v in obj]
    return obj


class OmniSerializer:
    @staticmethod
    def dumps(obj: Any) -> bytes:
        arrays: list[np.ndarray] = []
        skeleton = _extract(obj, arrays)
        buf = io.BytesIO()
        buf.write(_MAGIC)
        payload = pickle.dumps(skeleton, protocol=pickle.HIGHEST_PROTOCOL)
        buf.write(struct.pack("<I", len(payload)))
        buf.write(payload)
        buf.write(struct.pack("<I", len(arrays)))
        for arr in arrays:
            # pickle the dtype object (not .str): extension dtypes like
            # ml_dtypes.bfloat16 have no losslessly-parseable str form
            header = pickle.dumps((arr.dtype, arr.shape))
            buf.write(struct.pack("<I", len(header)))
            buf.write(header)
            raw = arr.tobytes()
            buf.write(struct.pack("<Q", len(raw)))
            buf.write(raw)
        return buf.getvalue()

    @staticmethod
    def loads(data: bytes) -> Any:
        view = memoryview(data)
        if view[:4] != _MAGIC:
            raise ValueError("bad frame magic")
        off = 4
        (skel_len,) = struct.unpack_from("<I", view, off)
        off += 4
        skeleton = pickle.loads(view[off: off + skel_len])
        off += skel_len
        (n_arrays,) = struct.unpack_from("<I", view, off)
        off += 4
        arrays: list[np.ndarray] = []
        for _ in range(n_arrays):
            (h_len,) = struct.unpack_from("<I", view, off)
            off += 4
            dtype, shape = pickle.loads(view[off: off + h_len])
            off += h_len
            (raw_len,) = struct.unpack_from("<Q", view, off)
            off += 8
            arr = np.frombuffer(
                view[off: off + raw_len], dtype=dtype
            ).reshape(shape)
            off += raw_len
            arrays.append(arr)
        return _restore(skeleton, arrays)
