"""Cross-stage KV shipping over connectors, layer-streamed.

The transport half of the KV-transfer story (reference:
omni_connectors/kv_transfer_manager.py:47 send / :100+ receive;
transfer_adapter/chunk_transfer_adapter.py:19 — the async_chunk mode
streams payloads in chunks so the receiver starts before the sender
finishes).  Here the natural chunk is a *layer*: the sender puts one
``(k, v)`` pair per layer under ``{key}/L{i}`` plus a ``{key}/meta``
header, and the receiver consumes layers in order — with a paged-cache
receiver (ARModelRunner.inject_kv) each layer can land as it arrives.
"""

from __future__ import annotations

import time
from typing import Any, Iterator, Optional

import numpy as np

from vllm_omni_tpu.distributed.connectors import OmniConnectorBase
from vllm_omni_tpu.resilience.faults import fault_point
from vllm_omni_tpu.resilience.retry import RetryPolicy, call_with_retry

# Transfer-level retry default: deliberately shallower than the generic
# policy because the TCP connector ALREADY retries each RPC internally —
# this layer exists for connectors without internal retries (inproc/shm)
# and for transfer-scoped fault injection (site "kv"); attempts multiply
# across the two layers, so keep this one at 2.
_KV_RETRY = RetryPolicy(max_attempts=2)


def ship_kv(conn: OmniConnectorBase, key: str, payload: list,
            retry: Optional[RetryPolicy] = None) -> int:
    """Put a per-layer KV payload ([(k, v)] dense arrays) under ``key``.
    Returns total bytes shipped.  Each per-layer put retries
    independently under ``retry`` (puts are idempotent: re-putting a
    layer overwrites the identical bytes)."""
    retry = retry or _KV_RETRY

    def put(subkey, obj):
        def attempt():
            fault_point("kv")
            return conn.put(subkey, obj)

        return call_with_retry(attempt, site=f"kv:{subkey}",
                               policy=retry)

    total = put(f"{key}/meta", {
        "num_layers": len(payload),
        "seq_len": int(payload[0][0].shape[1]),
    })
    for i, (k, v) in enumerate(payload):
        total += put(f"{key}/L{i}", (np.asarray(k), np.asarray(v)))
    return total


def iter_kv(conn: OmniConnectorBase, key: str, timeout: float = 30.0,
            retry: Optional[RetryPolicy] = None,
            deadline_ts: Optional[float] = None) -> Iterator[tuple]:
    """Yield (k, v) per layer as they arrive (streaming receive).

    Transient connector failures retry per fetch under ``retry``;
    ``deadline_ts`` (monotonic) bounds the WHOLE transfer — per-layer
    waits shrink to the remaining budget so a stalled sender surfaces
    as a TimeoutError at the deadline, not layers*timeout later."""
    retry = retry or _KV_RETRY

    def fetch(subkey: str, what: str):
        t = timeout
        if deadline_ts is not None:
            t = min(t, max(deadline_ts - time.monotonic(), 0.0))

        def attempt():
            fault_point("kv")
            return conn.get(subkey, timeout=t)

        data = call_with_retry(
            attempt, site=f"kv:{subkey}", policy=retry,
            deadline_ts=deadline_ts)
        if data is None:
            raise TimeoutError(
                f"KV transfer {key}: {what} missing within {t:.1f}s")
        return data

    meta = fetch(f"{key}/meta", "metadata")
    for i in range(meta["num_layers"]):
        yield fetch(f"{key}/L{i}", f"layer {i}")


def recv_kv(conn: OmniConnectorBase, key: str, timeout: float = 30.0,
            retry: Optional[RetryPolicy] = None,
            deadline_ts: Optional[float] = None) -> list:
    """Assemble the full per-layer payload (blocking)."""
    return list(iter_kv(conn, key, timeout, retry=retry,
                        deadline_ts=deadline_ts))


def make_output_kv_sink(attach_to: str = "kv_payload"):
    """Engine ``kv_transfer_sink`` that rides the extracted KV on the
    request's multimodal_output — the D2H2D v1 path (SURVEY §7 hard-part
    4): the payload crosses stage boundaries like any other stage output
    (in-proc, SHM, or TCP serialized), and the downstream stage injects it
    via ``add_request(injected_kv=...)``."""

    def sink(request, payload: list) -> None:
        request.multimodal_output[attach_to] = payload

    return sink
