"""Cross-stage KV shipping over connectors, layer-streamed.

The transport half of the KV-transfer story (reference:
omni_connectors/kv_transfer_manager.py:47 send / :100+ receive;
transfer_adapter/chunk_transfer_adapter.py:19 — the async_chunk mode
streams payloads in chunks so the receiver starts before the sender
finishes).  Here the natural chunk is a *layer*: the sender puts one
``(k, v)`` pair per layer under ``{key}/L{i}`` plus a ``{key}/meta``
header, and the receiver consumes layers in order — with a paged-cache
receiver (ARModelRunner.inject_kv) each layer can land as it arrives.

Two hard edges of the disaggregated prefill/decode topology
(docs/disaggregation.md) live here:

- **Integrity**: the meta header carries per-layer shape/dtype/crc32 so
  a torn, truncated, or bit-flipped stream raises ``KVIntegrityError``
  at the receiver instead of injecting garbage pages into the decode
  tier's cache.  The consumer degrades to local recompute — wrong KV is
  the one failure mode with no recovery once attended.
- **Deadlines**: per-layer waits clamp to the request's remaining
  end-to-end budget (``deadline_ts``), and a wait that dies because the
  DEADLINE expired (not the flat transport timeout) raises the distinct
  ``KVDeadlineExceeded`` so callers surface 504, not a generic
  connector timeout — a doomed handoff fails fast with the right
  taxonomy.
"""

from __future__ import annotations

import time
import zlib
from typing import Any, Iterator, Optional

import numpy as np

from vllm_omni_tpu.distributed.connectors import OmniConnectorBase
from vllm_omni_tpu.resilience.deadline import (
    DEADLINE_EXCEEDED,
    clamp_timeout,
    expired,
)
from vllm_omni_tpu.resilience.faults import fault_point
from vllm_omni_tpu.resilience.retry import RetryPolicy, call_with_retry

# Transfer-level retry default: deliberately shallower than the generic
# policy because the TCP connector ALREADY retries each RPC internally —
# this layer exists for connectors without internal retries (inproc/shm)
# and for transfer-scoped fault injection (site "kv"); attempts multiply
# across the two layers, so keep this one at 2.
_KV_RETRY = RetryPolicy(max_attempts=2)


class KVIntegrityError(ValueError):
    """A received KV layer failed its shape/dtype/checksum guard.

    Deliberately NOT a ConnectionError: retrying fetches the same
    bytes, so the retry layer must not treat this as transient — the
    caller degrades to recompute instead."""


class KVDeadlineExceeded(TimeoutError):
    """A KV wait died because the request's END-TO-END deadline passed
    (as opposed to the flat per-fetch transport timeout).  Carries the
    deadline taxonomy so serving layers map it to 504, never 500."""

    error_kind = DEADLINE_EXCEEDED


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes())


def _half_crc(half) -> int:
    """CRC of one cache half.  A quantized half is a (data, scale)
    pair; ONE checksum covers BOTH arrays (scale corruption dequantizes
    every token of the page wrongly — exactly as fatal as flipped data
    bytes) by chaining the scale bytes onto the data crc."""
    if isinstance(half, (tuple, list)):
        data, scale = half
        c = _crc(np.asarray(data))
        return zlib.crc32(
            np.ascontiguousarray(np.asarray(scale)).tobytes(), c)
    return _crc(half)


def _half_np(half):
    if isinstance(half, (tuple, list)):
        return (np.asarray(half[0]), np.asarray(half[1]))
    return np.asarray(half)


def _half_shape_dtype(half):
    if isinstance(half, (tuple, list)):
        return ([list(half[0].shape), list(half[1].shape)],
                f"{half[0].dtype}+{half[1].dtype}")
    return list(half.shape), str(half.dtype)


def _layer_spec(k, v) -> dict:
    """Integrity header for one layer.  Dense halves keep the original
    fields; quantized (data, scale) halves record both shapes and a
    joint dtype/crc — the disagg handoff ships int8 pages + scales and
    the CRC covers both arrays."""
    ks, kd = _half_shape_dtype(k)
    vs, _ = _half_shape_dtype(v)
    spec = {
        "k_shape": ks, "v_shape": vs,
        "dtype": kd,
        "k_crc": _half_crc(k), "v_crc": _half_crc(v),
    }
    if isinstance(k, (tuple, list)):
        spec["quant"] = True
    return spec


def _verify_layer(key: str, i: int, k, v, spec: dict) -> None:
    """Raise KVIntegrityError unless layer ``i`` matches its header."""
    if bool(spec.get("quant")) != isinstance(k, (tuple, list)):
        raise KVIntegrityError(
            f"KV transfer {key}: layer {i} layout "
            f"({'quant' if isinstance(k, (tuple, list)) else 'dense'}) "
            f"!= header ({'quant' if spec.get('quant') else 'dense'})")
    k_shape, k_dtype = _half_shape_dtype(k)
    v_shape, v_dtype = _half_shape_dtype(v)
    if k_shape != spec["k_shape"] or v_shape != spec["v_shape"]:
        raise KVIntegrityError(
            f"KV transfer {key}: layer {i} shape "
            f"{k_shape}/{v_shape} != header "
            f"{spec['k_shape']}/{spec['v_shape']}")
    if k_dtype != spec["dtype"] or v_dtype != spec["dtype"]:
        raise KVIntegrityError(
            f"KV transfer {key}: layer {i} dtype {k_dtype}/{v_dtype} "
            f"!= header {spec['dtype']}")
    if _half_crc(k) != spec["k_crc"] or _half_crc(v) != spec["v_crc"]:
        raise KVIntegrityError(
            f"KV transfer {key}: layer {i} checksum mismatch (torn or "
            "corrupted stream)")


def ship_kv(conn: OmniConnectorBase, key: str, payload: list,
            retry: Optional[RetryPolicy] = None) -> int:
    """Put a per-layer KV payload under ``key`` — dense ``[(k, v)]``
    arrays or the quantized wire layout ``[((kq, ks), (vq, vs))]``
    (kvcache/quant.py); int8 handoffs ship roughly half the bytes.
    Returns total bytes shipped.  Each per-layer put retries
    independently under ``retry`` (puts are idempotent: re-putting a
    layer overwrites the identical bytes).  The meta header carries the
    per-layer integrity specs the receiver verifies against."""
    retry = retry or _KV_RETRY
    arrays = [(_half_np(k), _half_np(v)) for k, v in payload]

    def put(subkey, obj):
        def attempt():
            fault_point("kv")
            return conn.put(subkey, obj)

        return call_with_retry(attempt, site=f"kv:{subkey}",
                               policy=retry)

    first = arrays[0][0]
    seq_len = int(first[0].shape[1] if isinstance(first, tuple)
                  else first.shape[1])
    total = put(f"{key}/meta", {
        "num_layers": len(arrays),
        "seq_len": seq_len,
        "layers": [_layer_spec(k, v) for k, v in arrays],
    })
    for i, (k, v) in enumerate(arrays):
        total += put(f"{key}/L{i}", (k, v))
    return total


def iter_kv(conn: OmniConnectorBase, key: str, timeout: float = 30.0,
            retry: Optional[RetryPolicy] = None,
            deadline_ts: Optional[float] = None) -> Iterator[tuple]:
    """Yield (k, v) per layer as they arrive (streaming receive).

    Transient connector failures retry per fetch under ``retry``;
    ``deadline_ts`` (monotonic) bounds the WHOLE transfer — per-layer
    waits shrink to the remaining budget, and a wait that dies because
    the deadline (not the flat ``timeout``) ran out raises
    ``KVDeadlineExceeded`` (504), not a generic TimeoutError.  Layers
    carrying an integrity header are verified; a mismatch raises
    ``KVIntegrityError`` so a torn stream can never inject garbage."""
    retry = retry or _KV_RETRY

    def fetch(subkey: str, what: str):
        if expired(deadline_ts):
            # fail fast: a doomed handoff must not spend a full
            # transport timeout discovering the budget is gone
            raise KVDeadlineExceeded(
                f"KV transfer {key}: deadline exceeded before "
                f"{what} arrived")
        t = clamp_timeout(timeout, deadline_ts)

        def attempt():
            fault_point("kv")
            return conn.get(subkey, timeout=t)

        data = call_with_retry(
            attempt, site=f"kv:{subkey}", policy=retry,
            deadline_ts=deadline_ts)
        if data is None:
            if deadline_ts is not None \
                    and time.monotonic() >= deadline_ts:
                raise KVDeadlineExceeded(
                    f"KV transfer {key}: deadline exceeded waiting "
                    f"for {what}")
            raise TimeoutError(
                f"KV transfer {key}: {what} missing within {t:.1f}s")
        return data

    meta = fetch(f"{key}/meta", "metadata")
    specs = meta.get("layers")
    for i in range(meta["num_layers"]):
        k, v = fetch(f"{key}/L{i}", f"layer {i}")
        k, v = _half_np(k), _half_np(v)
        if specs is not None:
            # pre-header senders (no "layers") skip verification —
            # the guard is opt-out by omission, never by flag
            _verify_layer(key, i, k, v, specs[i])
        yield k, v


def recv_kv(conn: OmniConnectorBase, key: str, timeout: float = 30.0,
            retry: Optional[RetryPolicy] = None,
            deadline_ts: Optional[float] = None) -> list:
    """Assemble the full per-layer payload (blocking)."""
    return list(iter_kv(conn, key, timeout, retry=retry,
                        deadline_ts=deadline_ts))


def make_output_kv_sink(attach_to: str = "kv_payload"):
    """Engine ``kv_transfer_sink`` that rides the extracted KV on the
    request's multimodal_output — the D2H2D v1 path (SURVEY §7 hard-part
    4): the payload crosses stage boundaries like any other stage output
    (in-proc, SHM, or TCP serialized), and the downstream stage injects it
    via ``add_request(injected_kv=...)``."""

    def sink(request, payload: list) -> None:
        request.multimodal_output[attach_to] = payload

    return sink
