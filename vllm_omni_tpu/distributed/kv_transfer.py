"""Cross-stage KV shipping over connectors, layer-streamed.

The transport half of the KV-transfer story (reference:
omni_connectors/kv_transfer_manager.py:47 send / :100+ receive;
transfer_adapter/chunk_transfer_adapter.py:19 — the async_chunk mode
streams payloads in chunks so the receiver starts before the sender
finishes).  Here the natural chunk is a *layer*: the sender puts one
``(k, v)`` pair per layer under ``{key}/L{i}`` plus a ``{key}/meta``
header, and the receiver consumes layers in order — with a paged-cache
receiver (ARModelRunner.inject_kv) each layer can land as it arrives.

Two hard edges of the disaggregated prefill/decode topology
(docs/disaggregation.md) live here:

- **Integrity**: the meta header carries per-layer shape/dtype/crc32 so
  a torn, truncated, or bit-flipped stream raises ``KVIntegrityError``
  at the receiver instead of injecting garbage pages into the decode
  tier's cache.  The consumer degrades to local recompute — wrong KV is
  the one failure mode with no recovery once attended.
- **Deadlines**: per-layer waits clamp to the request's remaining
  end-to-end budget (``deadline_ts``), and a wait that dies because the
  DEADLINE expired (not the flat transport timeout) raises the distinct
  ``KVDeadlineExceeded`` so callers surface 504, not a generic
  connector timeout — a doomed handoff fails fast with the right
  taxonomy.
"""

from __future__ import annotations

import time
import zlib
from typing import Any, Iterator, Optional

import numpy as np

from vllm_omni_tpu.distributed.connectors import OmniConnectorBase
from vllm_omni_tpu.resilience.deadline import (
    DEADLINE_EXCEEDED,
    clamp_timeout,
    expired,
)
from vllm_omni_tpu.resilience.faults import fault_point
from vllm_omni_tpu.resilience.retry import RetryPolicy, call_with_retry

# Transfer-level retry default: deliberately shallower than the generic
# policy because the TCP connector ALREADY retries each RPC internally —
# this layer exists for connectors without internal retries (inproc/shm)
# and for transfer-scoped fault injection (site "kv"); attempts multiply
# across the two layers, so keep this one at 2.
_KV_RETRY = RetryPolicy(max_attempts=2)


class KVIntegrityError(ValueError):
    """A received KV layer failed its shape/dtype/checksum guard.

    Deliberately NOT a ConnectionError: retrying fetches the same
    bytes, so the retry layer must not treat this as transient — the
    caller degrades to recompute instead."""


class KVDeadlineExceeded(TimeoutError):
    """A KV wait died because the request's END-TO-END deadline passed
    (as opposed to the flat per-fetch transport timeout).  Carries the
    deadline taxonomy so serving layers map it to 504, never 500."""

    error_kind = DEADLINE_EXCEEDED


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes())


def _layer_spec(k: np.ndarray, v: np.ndarray) -> dict:
    return {
        "k_shape": list(k.shape), "v_shape": list(v.shape),
        "dtype": str(k.dtype),
        "k_crc": _crc(k), "v_crc": _crc(v),
    }


def _verify_layer(key: str, i: int, k: np.ndarray, v: np.ndarray,
                  spec: dict) -> None:
    """Raise KVIntegrityError unless layer ``i`` matches its header."""
    if (list(k.shape) != spec["k_shape"]
            or list(v.shape) != spec["v_shape"]):
        raise KVIntegrityError(
            f"KV transfer {key}: layer {i} shape "
            f"{list(k.shape)}/{list(v.shape)} != header "
            f"{spec['k_shape']}/{spec['v_shape']}")
    if str(k.dtype) != spec["dtype"] or str(v.dtype) != spec["dtype"]:
        raise KVIntegrityError(
            f"KV transfer {key}: layer {i} dtype {k.dtype}/{v.dtype} "
            f"!= header {spec['dtype']}")
    if _crc(k) != spec["k_crc"] or _crc(v) != spec["v_crc"]:
        raise KVIntegrityError(
            f"KV transfer {key}: layer {i} checksum mismatch (torn or "
            "corrupted stream)")


def ship_kv(conn: OmniConnectorBase, key: str, payload: list,
            retry: Optional[RetryPolicy] = None) -> int:
    """Put a per-layer KV payload ([(k, v)] dense arrays) under ``key``.
    Returns total bytes shipped.  Each per-layer put retries
    independently under ``retry`` (puts are idempotent: re-putting a
    layer overwrites the identical bytes).  The meta header carries the
    per-layer integrity specs the receiver verifies against."""
    retry = retry or _KV_RETRY
    arrays = [(np.asarray(k), np.asarray(v)) for k, v in payload]

    def put(subkey, obj):
        def attempt():
            fault_point("kv")
            return conn.put(subkey, obj)

        return call_with_retry(attempt, site=f"kv:{subkey}",
                               policy=retry)

    total = put(f"{key}/meta", {
        "num_layers": len(arrays),
        "seq_len": int(arrays[0][0].shape[1]),
        "layers": [_layer_spec(k, v) for k, v in arrays],
    })
    for i, (k, v) in enumerate(arrays):
        total += put(f"{key}/L{i}", (k, v))
    return total


def iter_kv(conn: OmniConnectorBase, key: str, timeout: float = 30.0,
            retry: Optional[RetryPolicy] = None,
            deadline_ts: Optional[float] = None) -> Iterator[tuple]:
    """Yield (k, v) per layer as they arrive (streaming receive).

    Transient connector failures retry per fetch under ``retry``;
    ``deadline_ts`` (monotonic) bounds the WHOLE transfer — per-layer
    waits shrink to the remaining budget, and a wait that dies because
    the deadline (not the flat ``timeout``) ran out raises
    ``KVDeadlineExceeded`` (504), not a generic TimeoutError.  Layers
    carrying an integrity header are verified; a mismatch raises
    ``KVIntegrityError`` so a torn stream can never inject garbage."""
    retry = retry or _KV_RETRY

    def fetch(subkey: str, what: str):
        if expired(deadline_ts):
            # fail fast: a doomed handoff must not spend a full
            # transport timeout discovering the budget is gone
            raise KVDeadlineExceeded(
                f"KV transfer {key}: deadline exceeded before "
                f"{what} arrived")
        t = clamp_timeout(timeout, deadline_ts)

        def attempt():
            fault_point("kv")
            return conn.get(subkey, timeout=t)

        data = call_with_retry(
            attempt, site=f"kv:{subkey}", policy=retry,
            deadline_ts=deadline_ts)
        if data is None:
            if deadline_ts is not None \
                    and time.monotonic() >= deadline_ts:
                raise KVDeadlineExceeded(
                    f"KV transfer {key}: deadline exceeded waiting "
                    f"for {what}")
            raise TimeoutError(
                f"KV transfer {key}: {what} missing within {t:.1f}s")
        return data

    meta = fetch(f"{key}/meta", "metadata")
    specs = meta.get("layers")
    for i in range(meta["num_layers"]):
        k, v = fetch(f"{key}/L{i}", f"layer {i}")
        k, v = np.asarray(k), np.asarray(v)
        if specs is not None:
            # pre-header senders (no "layers") skip verification —
            # the guard is opt-out by omission, never by flag
            _verify_layer(key, i, k, v, specs[i])
        yield k, v


def recv_kv(conn: OmniConnectorBase, key: str, timeout: float = 30.0,
            retry: Optional[RetryPolicy] = None,
            deadline_ts: Optional[float] = None) -> list:
    """Assemble the full per-layer payload (blocking)."""
    return list(iter_kv(conn, key, timeout, retry=retry,
                        deadline_ts=deadline_ts))


def make_output_kv_sink(attach_to: str = "kv_payload"):
    """Engine ``kv_transfer_sink`` that rides the extracted KV on the
    request's multimodal_output — the D2H2D v1 path (SURVEY §7 hard-part
    4): the payload crosses stage boundaries like any other stage output
    (in-proc, SHM, or TCP serialized), and the downstream stage injects it
    via ``add_request(injected_kv=...)``."""

    def sink(request, payload: list) -> None:
        request.multimodal_output[attach_to] = payload

    return sink
