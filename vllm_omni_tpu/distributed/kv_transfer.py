"""Cross-stage KV shipping over connectors, layer-streamed.

The transport half of the KV-transfer story (reference:
omni_connectors/kv_transfer_manager.py:47 send / :100+ receive;
transfer_adapter/chunk_transfer_adapter.py:19 — the async_chunk mode
streams payloads in chunks so the receiver starts before the sender
finishes).  Here the natural chunk is a *layer*: the sender puts one
``(k, v)`` pair per layer under ``{key}/L{i}`` plus a ``{key}/meta``
header, and the receiver consumes layers in order — with a paged-cache
receiver (ARModelRunner.inject_kv) each layer can land as it arrives.
"""

from __future__ import annotations

from typing import Any, Iterator, Optional

import numpy as np

from vllm_omni_tpu.distributed.connectors import OmniConnectorBase


def ship_kv(conn: OmniConnectorBase, key: str, payload: list) -> int:
    """Put a per-layer KV payload ([(k, v)] dense arrays) under ``key``.
    Returns total bytes shipped."""
    total = conn.put(f"{key}/meta", {
        "num_layers": len(payload),
        "seq_len": int(payload[0][0].shape[1]),
    })
    for i, (k, v) in enumerate(payload):
        total += conn.put(f"{key}/L{i}", (np.asarray(k), np.asarray(v)))
    return total


def iter_kv(conn: OmniConnectorBase, key: str,
            timeout: float = 30.0) -> Iterator[tuple]:
    """Yield (k, v) per layer as they arrive (streaming receive)."""
    meta = conn.get(f"{key}/meta", timeout=timeout)
    if meta is None:
        raise TimeoutError(f"KV transfer {key}: no metadata within "
                           f"{timeout}s")
    for i in range(meta["num_layers"]):
        layer = conn.get(f"{key}/L{i}", timeout=timeout)
        if layer is None:
            raise TimeoutError(f"KV transfer {key}: layer {i} missing")
        yield layer


def recv_kv(conn: OmniConnectorBase, key: str,
            timeout: float = 30.0) -> list:
    """Assemble the full per-layer payload (blocking)."""
    return list(iter_kv(conn, key, timeout))


def make_output_kv_sink(attach_to: str = "kv_payload"):
    """Engine ``kv_transfer_sink`` that rides the extracted KV on the
    request's multimodal_output — the D2H2D v1 path (SURVEY §7 hard-part
    4): the payload crosses stage boundaries like any other stage output
    (in-proc, SHM, or TCP serialized), and the downstream stage injects it
    via ``add_request(injected_kv=...)``."""

    def sink(request, payload: list) -> None:
        request.multimodal_output[attach_to] = payload

    return sink
