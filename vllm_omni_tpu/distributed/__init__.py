from vllm_omni_tpu.distributed.serialization import OmniSerializer
from vllm_omni_tpu.distributed.connectors import (
    ConnectorFactory,
    InProcConnector,
    OmniConnectorBase,
    SharedMemoryConnector,
)

__all__ = [
    "ConnectorFactory",
    "InProcConnector",
    "OmniConnectorBase",
    "OmniSerializer",
    "SharedMemoryConnector",
]
