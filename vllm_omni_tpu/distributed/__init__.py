from vllm_omni_tpu.distributed.serialization import OmniSerializer
from vllm_omni_tpu.distributed.connectors import (
    ConnectorFactory,
    InProcConnector,
    OmniConnectorBase,
    SharedMemoryConnector,
)
from vllm_omni_tpu.distributed.tcp import KVStoreServer, TCPConnector

__all__ = [
    "ConnectorFactory",
    "InProcConnector",
    "KVStoreServer",
    "OmniConnectorBase",
    "OmniSerializer",
    "SharedMemoryConnector",
    "TCPConnector",
]
