"""TCP key-value transport: the multi-node connector.

The TPU-VM NIC counterpart of the reference's Mooncake/Yuanrong multi-node
connectors (reference: distributed/omni_connectors/connectors/
mooncake_connector.py:22 — RDMA/TCP object store keyed
``rid/from_to``; yuanrong_connector.py — etcd-backed store).  One
orchestrator-side ``KVStoreServer`` holds the object table; any process
(stage workers on other hosts included) connects a ``TCPConnector``.

Wire protocol (both directions length-prefixed):
  request : u32 len | u8 op | u16 klen | key utf-8 | payload
  response: u32 len | u8 status | payload
Ops: PUT (payload = value bytes), GET (payload = f64 timeout seconds;
blocking on the server against a condition variable — no client polling),
DEL, PING.  Values are serialized by the caller (OmniConnectorBase /
OmniSerializer), so tensors ride the tensor-aware path.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Optional

from vllm_omni_tpu.distributed.connectors import (
    ConnectorFactory,
    OmniConnectorBase,
)
from vllm_omni_tpu.logger import init_logger

logger = init_logger(__name__)

OP_PUT, OP_GET, OP_DEL, OP_PING = 1, 2, 3, 4
ST_OK, ST_MISSING, ST_ERR = 0, 1, 2

_MAX_FRAME = 1 << 31


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack("<I", len(payload)) + payload)


def _recv_frame(sock: socket.socket) -> Optional[bytes]:
    hdr = _recv_exact(sock, 4)
    if hdr is None:
        return None
    (n,) = struct.unpack("<I", hdr)
    if n > _MAX_FRAME:
        raise ValueError(f"frame too large: {n}")
    return _recv_exact(sock, n)


class KVStoreServer:
    """Threaded TCP object store with blocking GET."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._store: dict[str, bytes] = {}
        self._cv = threading.Condition()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()
        self._closing = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True
        )
        self._accept_thread.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            while True:
                frame = _recv_frame(conn)
                if frame is None:
                    return
                op = frame[0]
                (klen,) = struct.unpack_from("<H", frame, 1)
                key = frame[3:3 + klen].decode()
                payload = frame[3 + klen:]
                if op == OP_PUT:
                    with self._cv:
                        self._store[key] = payload
                        self._cv.notify_all()
                    _send_frame(conn, bytes([ST_OK]))
                elif op == OP_GET:
                    (timeout,) = struct.unpack("<d", payload)
                    data = self._blocking_pop(key, timeout)
                    if data is None:
                        _send_frame(conn, bytes([ST_MISSING]))
                    else:
                        _send_frame(conn, bytes([ST_OK]) + data)
                elif op == OP_DEL:
                    with self._cv:
                        self._store.pop(key, None)
                    _send_frame(conn, bytes([ST_OK]))
                elif op == OP_PING:
                    _send_frame(conn, bytes([ST_OK]))
                else:
                    _send_frame(conn, bytes([ST_ERR]))
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def _blocking_pop(self, key: str, timeout: float) -> Optional[bytes]:
        deadline = time.monotonic() + max(timeout, 0.0)
        with self._cv:
            while key not in self._store:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._cv.wait(min(remaining, 1.0))
            return self._store.pop(key)

    def close(self) -> None:
        self._closing = True
        try:
            self._sock.close()
        except OSError:
            pass


class TCPConnector(OmniConnectorBase):
    """Client of a KVStoreServer; thread-safe over one persistent socket.

    ``address`` is "host:port" of the store (orchestrator side starts it);
    pass ``serve=True`` to own an embedded server (then ``address`` is the
    bind spec and the effective address is ``self.address``).
    """

    def __init__(self, address: str = "127.0.0.1:0", serve: bool = False, **_):
        self._server: Optional[KVStoreServer] = None
        if serve:
            host, _, port = address.partition(":")
            self._server = KVStoreServer(host or "127.0.0.1", int(port or 0))
            address = self._server.address
        self.address = address
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None

    def _connect(self) -> socket.socket:
        if self._sock is None:
            host, _, port = self.address.partition(":")
            s = socket.create_connection((host, int(port)), timeout=30.0)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
        return self._sock

    def _request(self, op: int, key: str, payload: bytes,
                 timeout: Optional[float] = None) -> tuple[int, bytes]:
        kb = key.encode()
        frame = bytes([op]) + struct.pack("<H", len(kb)) + kb + payload
        # server-side block (GET) + generous network slack; the timeout is
        # re-applied on the reconnect path too, and ANY failure closes the
        # socket — a late response left in the stream would otherwise be
        # read as the next request's reply (desync)
        deadline = (timeout + 30.0) if timeout is not None else 300.0
        with self._lock:
            for attempt in (0, 1):
                try:
                    sock = self._connect()
                    sock.settimeout(deadline)
                    _send_frame(sock, frame)
                    resp = _recv_frame(sock)
                    if resp is None:
                        raise ConnectionError(
                            f"kv store at {self.address} hung up"
                        )
                    return resp[0], resp[1:]
                except (ConnectionError, OSError):
                    self._drop_sock()
                    if attempt:
                        raise
        raise AssertionError("unreachable")

    def _drop_sock(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _put_bytes(self, key: str, data: bytes) -> None:
        status, _ = self._request(OP_PUT, key, data)
        if status != ST_OK:
            raise RuntimeError(f"PUT {key} failed (status {status})")

    def _get_bytes(self, key: str, timeout: Optional[float]) -> Optional[bytes]:
        t = 0.0 if timeout is None else float(timeout)
        status, payload = self._request(
            OP_GET, key, struct.pack("<d", t), timeout=t
        )
        return payload if status == ST_OK else None

    def cleanup(self, key: str) -> None:
        self._request(OP_DEL, key, b"")

    def health(self) -> bool:
        try:
            return self._request(OP_PING, "", b"")[0] == ST_OK
        except (ConnectionError, OSError):
            return False

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None
        if self._server is not None:
            self._server.close()


ConnectorFactory.register("tcp", TCPConnector)
