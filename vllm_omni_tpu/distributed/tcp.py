"""TCP key-value transport: the multi-node connector.

The TPU-VM NIC counterpart of the reference's Mooncake/Yuanrong multi-node
connectors (reference: distributed/omni_connectors/connectors/
mooncake_connector.py:22 — RDMA/TCP object store keyed
``rid/from_to``; yuanrong_connector.py — etcd-backed store).  One
orchestrator-side ``KVStoreServer`` holds the object table; any process
(stage workers on other hosts included) connects a ``TCPConnector``.

Wire protocol (both directions length-prefixed):
  request : u32 len | u8 op | u16 klen | key utf-8 | payload
  response: u32 len | u8 status | payload
Ops: PUT (payload = value bytes), GET (payload = f64 timeout seconds;
blocking on the server against a condition variable — no client polling;
a NEGATIVE timeout means block until the key appears), DEL, PING.
Values are serialized by the caller (OmniConnectorBase / OmniSerializer),
so tensors ride the tensor-aware path.

Timeout contract (the resilience PR made this explicit): a GET's wait
has two independent parts — the SERVER-side block (how long the store
waits for the key) and the NETWORK slack (socket timeout headroom on
top of it, ``net_slack_s``).  ``get(key, timeout=None)`` is a
non-blocking probe (the contract every connector shares);
``timeout=float("inf")`` blocks indefinitely on the server with NO
client socket timeout.  Transient connection failures retry under a
``RetryPolicy`` behind a per-connector ``CircuitBreaker`` — the retry
deadline covers only the network slack, never re-counting server block
time already spent.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Optional

from vllm_omni_tpu.analysis.runtime import traced
from vllm_omni_tpu.distributed.connectors import (
    ConnectorFactory,
    OmniConnectorBase,
)
from vllm_omni_tpu.logger import init_logger

logger = init_logger(__name__)

OP_PUT, OP_GET, OP_DEL, OP_PING = 1, 2, 3, 4
ST_OK, ST_MISSING, ST_ERR = 0, 1, 2

_MAX_FRAME = 1 << 31


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack("<I", len(payload)) + payload)


def _recv_frame(sock: socket.socket) -> Optional[bytes]:
    hdr = _recv_exact(sock, 4)
    if hdr is None:
        return None
    (n,) = struct.unpack("<I", hdr)
    if n > _MAX_FRAME:
        raise ValueError(f"frame too large: {n}")
    return _recv_exact(sock, n)


class KVStoreServer:
    """Threaded TCP object store with blocking GET."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._store: dict[str, bytes] = {}
        self._cv = traced(threading.Condition(), "KVStoreServer._cv")
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.host, self.port = self._sock.getsockname()
        self._closing = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True
        )
        self._accept_thread.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            while True:
                frame = _recv_frame(conn)
                if frame is None:
                    return
                op = frame[0]
                (klen,) = struct.unpack_from("<H", frame, 1)
                key = frame[3:3 + klen].decode()
                payload = frame[3 + klen:]
                if op == OP_PUT:
                    with self._cv:
                        self._store[key] = payload
                        self._cv.notify_all()
                    _send_frame(conn, bytes([ST_OK]))
                elif op == OP_GET:
                    (timeout,) = struct.unpack("<d", payload)
                    data = self._blocking_pop(key, timeout)
                    if data is None:
                        _send_frame(conn, bytes([ST_MISSING]))
                    else:
                        _send_frame(conn, bytes([ST_OK]) + data)
                elif op == OP_DEL:
                    with self._cv:
                        self._store.pop(key, None)
                    _send_frame(conn, bytes([ST_OK]))
                elif op == OP_PING:
                    _send_frame(conn, bytes([ST_OK]))
                else:
                    _send_frame(conn, bytes([ST_ERR]))
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def _blocking_pop(self, key: str, timeout: float) -> Optional[bytes]:
        # negative timeout = wait forever (the wire encoding of the
        # client's explicit infinite-wait contract, timeout=inf)
        deadline = (None if timeout < 0
                    else time.monotonic() + timeout)
        with self._cv:
            while key not in self._store:
                if deadline is None:
                    self._cv.wait(1.0)
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._cv.wait(min(remaining, 1.0))
            return self._store.pop(key)

    def close(self) -> None:
        self._closing = True
        try:
            self._sock.close()
        except OSError:
            pass


class TCPConnector(OmniConnectorBase):
    """Client of a KVStoreServer; thread-safe over one persistent socket.

    ``address`` is "host:port" of the store (orchestrator side starts it);
    pass ``serve=True`` to own an embedded server (then ``address`` is the
    bind spec and the effective address is ``self.address``).

    ``net_slack_s`` is the socket-timeout headroom ON TOP of any
    server-side block time (it also bounds server-non-blocking ops:
    PUT/DEL/PING) — the old behavior of silently capping an unspecified
    timeout at 300 s is gone.  ``retry``/``breaker`` dicts override the
    RetryPolicy / CircuitBreaker knobs per edge; ``retry=None`` (the
    dict value ``{"max_attempts": 1}``-equivalent) is spelled
    ``retry={"max_attempts": 1}``.
    """

    def __init__(self, address: str = "127.0.0.1:0", serve: bool = False,
                 net_slack_s: float = 30.0,
                 retry: Optional[dict] = None,
                 breaker: Optional[dict] = None, **_):
        from vllm_omni_tpu.resilience.retry import (
            CircuitBreaker,
            RetryPolicy,
        )

        self._server: Optional[KVStoreServer] = None
        if serve:
            host, _, port = address.partition(":")
            self._server = KVStoreServer(host or "127.0.0.1", int(port or 0))
            address = self._server.address
        self.address = address
        self.net_slack_s = float(net_slack_s)
        self._retry_policy = RetryPolicy(**(retry or {}))
        self._breaker = CircuitBreaker(
            site=f"tcp:{address}", **(breaker or {}))
        self._lock = traced(threading.Lock(), "TCPConnector._lock")
        self._sock: Optional[socket.socket] = None

    def _connect(self) -> socket.socket:
        if self._sock is None:
            host, _, port = self.address.partition(":")
            s = socket.create_connection((host, int(port)),
                                         timeout=self.net_slack_s)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
        return self._sock

    def _request(self, op: int, key: str, payload: bytes,
                 server_block_s: float = 0.0) -> tuple[int, bytes]:
        """One RPC under the retry policy + breaker.

        ``server_block_s`` is how long the SERVER may block before
        answering (only GET blocks; negative = forever).  The client
        socket timeout is that block plus ``net_slack_s`` — or None
        (infinite) for a block-forever GET, making the infinite-wait
        contract explicit instead of a silent cap.  Retries never
        double-count server block time: server-non-blocking ops bound
        their whole retry sequence by the network slack, while blocking
        GETs retry purely per attempt (bounded by max_attempts — an
        attempt legitimately spends its time waiting on the server, so
        a wall-clock retry deadline would eat the retries).
        """
        from vllm_omni_tpu.resilience.retry import call_with_retry

        kb = key.encode()
        frame = bytes([op]) + struct.pack("<H", len(kb)) + kb + payload
        sock_timeout = (None if server_block_s < 0
                        else server_block_s + self.net_slack_s)

        def rpc() -> tuple[int, bytes]:
            # ANY failure closes the socket — a late response left in
            # the stream would otherwise be read as the next request's
            # reply (desync)
            # the lock IS the socket serializer: one persistent socket,
            # many caller threads — connect, send, and the matching recv
            # must pair atomically per RPC or replies desync.  Holding
            # it across the (blocking) network round trip is therefore
            # the lock's contract, not an accident (OL9 below).
            with self._lock:
                try:
                    # omnilint: disable=OL9 - see above: the hold is
                    # the request/response pairing invariant
                    sock = self._connect()
                    sock.settimeout(sock_timeout)
                    # omnilint: disable=OL9 - see above
                    _send_frame(sock, frame)
                    # omnilint: disable=OL9 - see above
                    resp = _recv_frame(sock)
                except BaseException:
                    self._drop_sock()
                    raise
                if resp is None:
                    self._drop_sock()
                    raise ConnectionError(
                        f"kv store at {self.address} hung up"
                    )
                return resp[0], resp[1:]

        retry_deadline = (time.monotonic() + self.net_slack_s
                          if server_block_s == 0 else None)
        return call_with_retry(
            rpc, site=f"tcp:{self.address}", policy=self._retry_policy,
            breaker=self._breaker, deadline_ts=retry_deadline,
        )

    def _drop_sock(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _put_bytes(self, key: str, data: bytes) -> None:
        status, _ = self._request(OP_PUT, key, data)
        if status != ST_OK:
            raise RuntimeError(f"PUT {key} failed (status {status})")

    def _get_bytes(self, key: str, timeout: Optional[float]) -> Optional[bytes]:
        # None = non-blocking probe (the cross-connector contract);
        # float("inf") = block forever (explicit, end-to-end: negative
        # sentinel on the wire, no client socket timeout)
        if timeout is None:
            t = 0.0
        elif timeout == float("inf"):
            t = -1.0
        else:
            t = max(float(timeout), 0.0)
        status, payload = self._request(
            OP_GET, key, struct.pack("<d", t), server_block_s=t
        )
        return payload if status == ST_OK else None

    def cleanup(self, key: str) -> None:
        self._request(OP_DEL, key, b"")

    def health(self) -> bool:
        try:
            return self._request(OP_PING, "", b"")[0] == ST_OK
        except (ConnectionError, OSError):
            return False

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None
        if self._server is not None:
            self._server.close()


ConnectorFactory.register("tcp", TCPConnector)
