"""Multi-host bring-up: jax.distributed initialization + global meshes.

Role of the reference's Ray-based multi-node runtime (reference:
vllm_omni/distributed/ray_utils/utils.py:1 — placement groups + per-node
worker scheduling; NCCL groups spanning hosts).  The TPU-native shape has
no Ray and no NCCL: ``jax.distributed.initialize`` joins every host
process into ONE JAX runtime whose ``jax.devices()`` spans all hosts, a
``Mesh`` over those devices gives multi-host SPMD (XLA routes collectives
over ICI within a slice and DCN across slices), and cross-host *stage*
placement rides remote stage workers over the TCP transport
(entrypoints/stage_proc.py remote mode + KV-store address discovery).

Env bring-up (each host process):
    OMNI_TPU_COORDINATOR=host:port   # process 0's address
    OMNI_TPU_NUM_PROCESSES=N
    OMNI_TPU_PROCESS_ID=i
then ``initialize()`` (or let the engine call ``ensure_initialized()``).
"""

from __future__ import annotations

import os
from typing import Optional

from vllm_omni_tpu.logger import init_logger

logger = init_logger(__name__)

_INITIALIZED = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids=None,
) -> None:
    """Join this process into the multi-host JAX runtime.  Arguments
    default from the OMNI_TPU_* env registry; no-op when already
    initialized."""
    global _INITIALIZED
    if _INITIALIZED:
        return
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "OMNI_TPU_COORDINATOR")
    if num_processes is None:
        env = os.environ.get("OMNI_TPU_NUM_PROCESSES")
        num_processes = int(env) if env else None
    if process_id is None:
        env = os.environ.get("OMNI_TPU_PROCESS_ID")
        process_id = int(env) if env else None
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    _INITIALIZED = True
    logger.info(
        "multi-host runtime up: process %d/%d, %d global devices "
        "(%d local)", jax.process_index(), jax.process_count(),
        len(jax.devices()), len(jax.local_devices()))


def ensure_initialized() -> bool:
    """Initialize iff the env requests multi-host; returns whether the
    process is part of a multi-host runtime."""
    if _INITIALIZED:
        return True
    if os.environ.get("OMNI_TPU_COORDINATOR"):
        initialize()
        return True
    return False


def is_initialized() -> bool:
    return _INITIALIZED


def process_index() -> int:
    import jax

    return jax.process_index() if _INITIALIZED else 0


def global_mesh(mesh_config):
    """Mesh over ALL hosts' devices (jax.devices() is global after
    initialize); shardings over it make XLA insert cross-host
    collectives."""
    import jax

    from vllm_omni_tpu.parallel.mesh import build_mesh

    return build_mesh(mesh_config, jax.devices())


# ------------------------------------------------- stage address discovery
def publish_stage_address(store_address: str, stage_id: int,
                          address: str) -> None:
    """Orchestrator side: announce where a remote stage worker should
    connect (KV-store discovery — the analogue of the reference's
    connector address exchange, mooncake_connector.py:22).  Retries
    transient store failures — bring-up races (store just starting)
    must not kill the whole pipeline."""
    from vllm_omni_tpu.distributed.tcp import TCPConnector
    from vllm_omni_tpu.resilience.retry import RetryPolicy, call_with_retry

    conn = TCPConnector(address=store_address)
    call_with_retry(
        lambda: conn.put(f"stage-addr/{stage_id}", {"address": address}),
        site=f"discovery:{store_address}",
        policy=RetryPolicy(max_attempts=5, base_delay_s=0.2,
                           max_delay_s=5.0))


def discover_stage_address(store_address: str, stage_id: int,
                           timeout: float = 120.0) -> str:
    """Remote worker side: look up the orchestrator's listener for this
    stage.  The whole lookup (connect retries included) is bounded by
    ``timeout``."""
    import time

    from vllm_omni_tpu.distributed.tcp import TCPConnector
    from vllm_omni_tpu.resilience.retry import RetryPolicy, call_with_retry

    conn = TCPConnector(address=store_address)
    deadline = time.monotonic() + timeout

    def lookup():
        remaining = max(deadline - time.monotonic(), 0.0)
        return conn.get(f"stage-addr/{stage_id}", timeout=remaining)

    payload = call_with_retry(
        lookup, site=f"discovery:{store_address}",
        policy=RetryPolicy(max_attempts=8, base_delay_s=0.5,
                           max_delay_s=10.0),
        deadline_ts=deadline)
    if not payload:
        raise TimeoutError(
            f"no address published for stage {stage_id} at "
            f"{store_address}")
    return payload["address"]
