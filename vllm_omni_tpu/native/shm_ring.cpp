// Shared-memory SPSC ring buffer — the native IPC transport.
//
// Role of the reference's C-backed shm MessageQueue (reference: vLLM's
// ring-buffer MessageQueue consumed at diffusion/executor/
// multiproc_executor.py:57,334 and diffusion_worker.py:334; SURVEY §2.10
// row "shm MessageQueue"): a lock-free single-producer single-consumer
// byte-frame ring over POSIX shared memory, used for same-host
// orchestrator <-> stage-worker messaging where the TCP socket's
// copy + syscall overhead matters.
//
// Layout (all offsets in one shm segment):
//   [Header | data bytes ...]
// Header: capacity, head (write cursor), tail (read cursor) — head/tail
// are monotonically increasing uint64s (mod capacity for position), with
// C++11 atomics for cross-process visibility (shm is coherent memory).
// Frames: u32 length | payload, contiguous; a frame never wraps — if it
// would, the producer writes a SKIP marker (length 0xFFFFFFFF) and starts
// at offset 0.
//
// Exposed as a tiny C ABI for ctypes (no pybind11 in the image).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kSkip = 0xFFFFFFFFu;
constexpr uint64_t kMagic = 0x4f4d4e49524e4731ull;  // "OMNIRNG1"

struct Header {
  std::atomic<uint64_t> magic;
  uint64_t capacity;  // data bytes
  std::atomic<uint64_t> head;  // producer cursor (monotonic)
  std::atomic<uint64_t> tail;  // consumer cursor (monotonic)
};

struct Ring {
  Header* hdr;
  uint8_t* data;
  size_t map_len;
  int fd;
  bool owner;
  char name[256];
};

uint64_t now_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return uint64_t(ts.tv_sec) * 1000000000ull + uint64_t(ts.tv_nsec);
}

void backoff(int attempt) {
  // escalate: stay responsive for bursts, stop burning CPU when idle
  long ns = attempt < 20 ? 50000 : (attempt < 200 ? 500000 : 2000000);
  timespec ts{0, ns};
  nanosleep(&ts, nullptr);
}

}  // namespace

extern "C" {

// Create (owner=1) or attach (owner=0) a ring named `name` with `capacity`
// data bytes. Returns an opaque handle or null.
void* shm_ring_open(const char* name, uint64_t capacity, int owner) {
  int flags = owner ? (O_CREAT | O_EXCL | O_RDWR) : O_RDWR;
  int fd = shm_open(name, flags, 0600);
  if (fd < 0) return nullptr;
  size_t len = sizeof(Header) + capacity;
  if (owner && ftruncate(fd, (off_t)len) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  if (!owner) {
    // attach: capacity comes from the segment itself
    struct stat st;
    if (fstat(fd, &st) != 0 || (size_t)st.st_size < sizeof(Header)) {
      close(fd);
      return nullptr;
    }
    len = (size_t)st.st_size;
  }
  void* mem = mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    if (owner) shm_unlink(name);
    return nullptr;
  }
  Ring* r = new Ring;
  r->hdr = reinterpret_cast<Header*>(mem);
  r->data = reinterpret_cast<uint8_t*>(mem) + sizeof(Header);
  r->map_len = len;
  r->fd = fd;
  r->owner = owner != 0;
  strncpy(r->name, name, sizeof(r->name) - 1);
  r->name[sizeof(r->name) - 1] = '\0';
  if (owner) {
    r->hdr->capacity = capacity;
    r->hdr->head.store(0, std::memory_order_relaxed);
    r->hdr->tail.store(0, std::memory_order_relaxed);
    r->hdr->magic.store(kMagic, std::memory_order_release);
  } else {
    // wait (bounded) for the owner's initialization
    uint64_t deadline = now_ns() + 5000000000ull;
    int attempt = 0;
    while (r->hdr->magic.load(std::memory_order_acquire) != kMagic) {
      if (now_ns() > deadline) {
        munmap(mem, len);
        close(fd);
        delete r;
        return nullptr;
      }
      backoff(attempt++);
    }
  }
  return r;
}

uint64_t shm_ring_capacity(void* h) {
  return reinterpret_cast<Ring*>(h)->hdr->capacity;
}

// Push one frame; blocks up to timeout_ms for space. Returns 0 on success,
// -1 timeout, -2 frame too large.
int shm_ring_push(void* h, const uint8_t* buf, uint64_t n,
                  int64_t timeout_ms) {
  Ring* r = reinterpret_cast<Ring*>(h);
  const uint64_t cap = r->hdr->capacity;
  const uint64_t need = 4 + n;
  // worst case a skip marker wastes up to need-1 bytes before the frame,
  // so only frames with 2*need - 1 <= cap are pushable from EVERY cursor
  // position — admit exactly those (a larger frame could wedge forever
  // depending on where head happens to sit)
  if (2 * need - 1 > cap) return -2;
  uint64_t deadline = now_ns() + uint64_t(timeout_ms) * 1000000ull;
  uint64_t head = r->hdr->head.load(std::memory_order_relaxed);
  int attempt = 0;
  for (;;) {
    uint64_t tail = r->hdr->tail.load(std::memory_order_acquire);
    uint64_t pos = head % cap;
    uint64_t contiguous = cap - pos;
    // a frame never wraps: account for the skip marker if needed
    uint64_t total = (contiguous >= need) ? need : contiguous + need;
    if (head + total - tail <= cap) {
      if (contiguous < need) {
        if (contiguous >= 4) {
          uint32_t skip = kSkip;
          memcpy(r->data + pos, &skip, 4);
        }
        head += contiguous;
        pos = 0;
      }
      uint32_t len32 = (uint32_t)n;
      memcpy(r->data + pos, &len32, 4);
      memcpy(r->data + pos + 4, buf, n);
      r->hdr->head.store(head + need, std::memory_order_release);
      return 0;
    }
    if (timeout_ms >= 0 && now_ns() > deadline) return -1;
    backoff(attempt++);
  }
}

// Peek next frame length without consuming; -1 if empty after timeout.
int64_t shm_ring_next_len(void* h, int64_t timeout_ms) {
  Ring* r = reinterpret_cast<Ring*>(h);
  const uint64_t cap = r->hdr->capacity;
  uint64_t deadline = now_ns() + uint64_t(timeout_ms) * 1000000ull;
  int attempt = 0;
  for (;;) {
    uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
    uint64_t head = r->hdr->head.load(std::memory_order_acquire);
    if (head != tail) {
      uint64_t pos = tail % cap;
      uint64_t contiguous = cap - pos;
      if (contiguous < 4) {
        // implicit skip (not even room for a marker)
        r->hdr->tail.store(tail + contiguous, std::memory_order_release);
        continue;
      }
      uint32_t len32;
      memcpy(&len32, r->data + pos, 4);
      if (len32 == kSkip) {
        r->hdr->tail.store(tail + contiguous, std::memory_order_release);
        continue;
      }
      return (int64_t)len32;
    }
    if (timeout_ms >= 0 && now_ns() > deadline) return -1;
    backoff(attempt++);
  }
}

// Pop next frame into buf (size bufcap). Returns payload length, -1 empty
// after timeout, -3 buffer too small (frame left in place).
int64_t shm_ring_pop(void* h, uint8_t* buf, uint64_t bufcap,
                     int64_t timeout_ms) {
  Ring* r = reinterpret_cast<Ring*>(h);
  int64_t n = shm_ring_next_len(h, timeout_ms);
  if (n < 0) return n;
  if ((uint64_t)n > bufcap) return -3;
  const uint64_t cap = r->hdr->capacity;
  uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  uint64_t pos = tail % cap;
  memcpy(buf, r->data + pos + 4, (size_t)n);
  r->hdr->tail.store(tail + 4 + (uint64_t)n, std::memory_order_release);
  return n;
}

void shm_ring_close(void* h) {
  Ring* r = reinterpret_cast<Ring*>(h);
  munmap(r->hdr, r->map_len);
  close(r->fd);
  if (r->owner) shm_unlink(r->name);
  delete r;
}

}  // extern "C"
