"""Native (C++) runtime components.

The reference's runtime leans on native pieces via its dependencies (the
C-backed shm MessageQueue ring buffer, NCCL, CUDA allocators — SURVEY
§2.10); the TPU build keeps the compute path in XLA/Pallas and implements
the *runtime* native pieces here.  Today: ``shm_ring`` — a POSIX
shared-memory SPSC ring buffer (shm_ring.cpp) bound through ctypes (no
pybind11 in the image), compiled on first use with g++ and cached next to
the source.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

from vllm_omni_tpu.analysis.runtime import traced
from vllm_omni_tpu.logger import init_logger

logger = init_logger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "shm_ring.cpp")
_SO = os.path.join(_HERE, "_shm_ring.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


def _build() -> str:
    """Compile the ring buffer if the cached .so is missing or stale."""
    if (os.path.exists(_SO)
            and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)):
        return _SO
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp]
    logger.info("building native shm_ring: %s", " ".join(cmd))
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, _SO)  # atomic: concurrent builders race safely
    return _SO


def load_shm_ring() -> ctypes.CDLL:
    """Load (building if needed) the native library; raises on toolchain
    failure — callers fall back to the pure-Python transport."""
    global _lib
    with _lock:
        if _lib is None:
            # omnilint: disable=OL9 - one-time toolchain build; the
            # lock exists precisely to serialize concurrent builders
            lib = ctypes.CDLL(_build(), use_errno=True)
            lib.shm_ring_open.restype = ctypes.c_void_p
            lib.shm_ring_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                          ctypes.c_int]
            lib.shm_ring_capacity.restype = ctypes.c_uint64
            lib.shm_ring_capacity.argtypes = [ctypes.c_void_p]
            lib.shm_ring_push.restype = ctypes.c_int
            lib.shm_ring_push.argtypes = [ctypes.c_void_p,
                                          ctypes.c_char_p,
                                          ctypes.c_uint64, ctypes.c_int64]
            lib.shm_ring_next_len.restype = ctypes.c_int64
            lib.shm_ring_next_len.argtypes = [ctypes.c_void_p,
                                              ctypes.c_int64]
            lib.shm_ring_pop.restype = ctypes.c_int64
            lib.shm_ring_pop.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.c_uint64, ctypes.c_int64]
            lib.shm_ring_close.restype = None
            lib.shm_ring_close.argtypes = [ctypes.c_void_p]
            _lib = lib
    return _lib


class ShmRing:
    """One direction of a shared-memory frame channel (SPSC).

    Thread-safety contract: native waits run in short slices under an
    operation lock so ``close()`` (munmap + unlink) can never pull the
    mapping out from under a blocked push/pop in another thread — the
    exact use-after-unmap a socket's close/recv race doesn't have.
    """

    _SLICE_MS = 100

    def __init__(self, name: str, capacity: int = 1 << 22,
                 owner: bool = True):
        import threading

        self._lib = load_shm_ring()
        self._h = self._lib.shm_ring_open(
            name.encode(), capacity, 1 if owner else 0)
        if not self._h:
            raise OSError(
                f"shm_ring_open({name!r}, owner={owner}) failed "
                f"(errno hint: {ctypes.get_errno()})"
            )
        self.name = name
        self.owner = owner
        self._op_lock = traced(threading.Lock(), "ShmRing._op_lock")

    @property
    def capacity(self) -> int:
        return int(self._lib.shm_ring_capacity(self._h))

    def _deadline_slices(self, timeout: float):
        import time

        deadline = time.monotonic() + max(timeout, 0.0)
        while True:
            rem_ms = int((deadline - time.monotonic()) * 1000)
            if rem_ms <= 0:
                return
            yield min(rem_ms, self._SLICE_MS)

    def push(self, data: bytes, timeout: float = 30.0) -> None:
        for slice_ms in self._deadline_slices(max(timeout, 1e-3)):
            with self._op_lock:
                if self._h is None:
                    raise OSError(f"shm ring {self.name} is closed")
                rc = self._lib.shm_ring_push(
                    self._h, data, len(data), slice_ms)
            if rc == 0:
                return
            if rc == -2:
                raise ValueError(
                    f"frame of {len(data)} bytes exceeds ring capacity "
                    f"{self.capacity}"
                )
        raise TimeoutError(f"shm ring {self.name}: push timed out")

    def pop(self, timeout: float = 30.0) -> Optional[bytes]:
        """Next frame, or None on timeout/closed."""
        for slice_ms in self._deadline_slices(max(timeout, 1e-3)):
            with self._op_lock:
                if self._h is None:
                    return None
                n = self._lib.shm_ring_next_len(self._h, slice_ms)
                if n >= 0:
                    buf = ctypes.create_string_buffer(int(n))
                    got = self._lib.shm_ring_pop(self._h, buf, int(n), 0)
                    if got < 0:
                        return None
                    return buf.raw[: int(got)]
        return None

    def close(self) -> None:
        with self._op_lock:
            if self._h:
                self._lib.shm_ring_close(self._h)
                self._h = None

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass


def native_available() -> bool:
    try:
        load_shm_ring()
        return True
    except (subprocess.CalledProcessError, OSError) as e:
        logger.warning("native shm_ring unavailable: %s", e)
        return False
