from vllm_omni_tpu.core.kv_cache_manager import KVCacheManager
from vllm_omni_tpu.core.scheduler import (
    ARScheduler,
    GenerationScheduler,
    SchedulerConfig,
    SchedulerOutput,
)

__all__ = [
    "ARScheduler",
    "GenerationScheduler",
    "KVCacheManager",
    "SchedulerConfig",
    "SchedulerOutput",
]
