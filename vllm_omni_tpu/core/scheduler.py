"""Continuous-batching schedulers.

Behavioral port of the reference's two scheduler subclasses onto a
device-agnostic, host-side core:

- ``ARScheduler``   ≈ OmniARScheduler (reference:
  core/sched/omni_ar_scheduler.py:40) — waiting/running queues, chunked
  prefill under a token budget, preemption by recompute, plus the
  cross-stage KV-transfer lifecycle: trigger criteria (prefill_finished /
  special_token, :84-136), block snapshot (:553-594), delayed free until
  extraction ACK (:444-546).
- ``GenerationScheduler`` ≈ OmniGenerationScheduler (reference:
  core/sched/omni_generation_scheduler.py:25) — one-shot generators
  (code2wav / DiT-as-stage): the whole prompt is scheduled at once and the
  request finishes in a single step.

The scheduler never touches jax; its output is plain ints/lists which the
model runner buckets and pads into device arrays.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional

from vllm_omni_tpu.core.kv_cache_manager import KVCacheManager
from vllm_omni_tpu.request import KVTransferState, Request, RequestStatus
from vllm_omni_tpu.resilience.deadline import DEADLINE_EXCEEDED

#: error_kind of a load-shed rejection (HTTP 429 at the serving layer).
#: Distinct from the PR 3 taxonomy on purpose: 503 ("retryable") means
#: infrastructure broke mid-request, 504 ("deadline_exceeded") means the
#: time budget was spent — 429 means the server is HEALTHY but at
#: capacity, and backing off (not just resubmitting) is the right
#: client response.  The open-loop load harness maps the knee of the
#: serving curve off this status instead of timing out.
SHED = "shed"


@dataclass
class KVTransferConfig:
    """When to trigger cross-stage KV extraction for a request
    (reference: omni_ar_scheduler.py:84-136)."""

    trigger: str = "prefill_finished"  # or "special_token"
    special_token_id: Optional[int] = None


@dataclass
class SchedulerConfig:
    max_num_seqs: int = 8
    max_num_batched_tokens: int = 2048
    max_model_len: int = 4096
    # Chunked prefill: prompts longer than the token budget are split into
    # chunks; later chunks attend the cached KV of earlier ones
    # (forward_prefill_chunked / the flash kernel's q_offsets path).
    enable_chunked_prefill: bool = False
    # speculative decoding: max draft tokens verified per decode step
    # (drafts come from the runner's MTP head via req.spec_draft_tokens)
    num_speculative_tokens: int = 0
    kv_transfer: Optional[KVTransferConfig] = None
    # RETIRED (PR 11): the multi-step lax.scan window is gone — the
    # async pipelined step is the round-trip amortization, and it works
    # for mixed/sampled/spec batches where the scan could not.  The
    # field is accepted so existing configs keep constructing; the
    # scheduler always emits window 1.
    multi_step_decode: int = 1
    # unified ragged batching: emit ONE token-budgeted mixed batch per
    # step — decodes claim the budget first, prefill chunks fill the
    # remainder (chunked prefill becomes the mechanism, not an opt-in
    # special case) — which the runner packs into a single ragged
    # device dispatch (docs/ragged_batching.md)
    unified_batching: bool = False
    # tiered KV offload (docs/kv_cache.md): preemption PARKS the
    # victim's computed KV in the host/remote tiers instead of
    # discarding it, and re-admission restores the run — recompute
    # becomes a transfer whenever the bytes beat the flops
    # (kvcache/policy.py decides per run)
    kv_offload: bool = False
    # admission control (load shedding, docs/load_testing.md): cap on
    # the waiting queue — an arrival that would push past it is SHED
    # (error_kind "shed", HTTP 429) instead of queued into a wait it
    # can only lose.  None = unbounded (classic behavior); 0 sheds
    # every new request (drain mode)
    max_queue_depth: Optional[int] = None
    # shed arrivals whose remaining deadline budget is below this floor
    # — a request that cannot plausibly finish in time is refused at
    # the door (429) rather than admitted to expire mid-queue (504).
    # 0.0 disables the check
    admission_deadline_headroom_s: float = 0.0
    # weighted-fair overload scheduling (docs/control_plane.md): order
    # the waiting queue by per-tenant deficit round robin — each
    # tenant's admission share is proportional to its requests'
    # priority weight (Request.priority, the sanitized x-omni-priority
    # metadata) — and make the max_queue_depth shed priority-ordered:
    # a full queue sheds its lowest-priority fresh entry to admit a
    # higher-priority arrival, instead of FCFS-shedding the arrival.
    # Off (default) keeps strict arrival order; with it on but no
    # client sending priorities, every tenant carries the neutral
    # weight and DRR degenerates to per-tenant round robin
    wfq_scheduling: bool = False
    # DRR quantum added per unit of priority weight each round, in
    # prompt tokens — the granularity of interleaving between tenants
    # (bigger = longer per-tenant runs, smaller = finer interleave)
    wfq_quantum_tokens: int = 256

    @property
    def chunking_enabled(self) -> bool:
        """Chunked prefill is ON whenever unified batching is: splitting
        prompts under the token budget is how the unified batch packs."""
        return self.enable_chunked_prefill or self.unified_batching


@dataclass
class ScheduledRequest:
    request: Request
    num_new_tokens: int
    slot_mapping: list[int]
    block_table: list[int]
    # position of the first new token (== num_computed_tokens at schedule)
    start_pos: int
    # RETIRED (PR 11): the multi-step decode window is always 1 — kept
    # only so stored/constructed ScheduledRequests keep their shape
    window: int = 1

    @property
    def is_prefill(self) -> bool:
        return self.start_pos < self.request.num_prompt_tokens

    @property
    def samples_final(self) -> bool:
        """This chunk reaches the sequence's last token, so the step
        SAMPLES from its final row.  The ONE definition of the
        final-chunk predicate — the scheduler's async accounting
        (note_async_dispatch) and the runner's sampling-row selection
        (_unified_sampling) must agree exactly, or a lagged retire
        consumes a token the runner never sampled.
        Evaluate BEFORE the step's token is appended (num_tokens moves)."""
        req = self.request
        return (self.start_pos + self.num_new_tokens >= req.num_tokens
                and not req.awaiting_chunks)


@dataclass
class SchedulerOutput:
    prefills: list[ScheduledRequest] = field(default_factory=list)
    decodes: list[ScheduledRequest] = field(default_factory=list)
    preempted: list[Request] = field(default_factory=list)
    # requests whose KV must be extracted+shipped this step
    # (reference: OmniSchedulerOutput.finished_requests_needing_kv_transfer)
    kv_transfer_requests: list[tuple[Request, list[int], int]] = field(
        default_factory=list
    )
    # unified ragged batching: the runner may pack prefills + decodes
    # into ONE token-packed dispatch (it still applies its own fallback
    # matrix — spec decode, logprobs, collect_hidden, embeds)
    unified: bool = False
    # async pipelining: request_id -> Request.async_generation at
    # dispatch, for every row the in-flight step SAMPLES (decodes and
    # sequence-final prefill chunks; mid-prefill chunks are absent).
    # The lagged retire consumes a token only when the generation still
    # matches — a preempt-and-readmit while the step was in flight
    # bumps the generation, discarding the stale token.
    async_sampled: dict[str, int] = field(default_factory=dict)

    @property
    def num_scheduled(self) -> int:
        return len(self.prefills) + len(self.decodes)


class ARScheduler:
    def __init__(self, config: SchedulerConfig, kv_manager: KVCacheManager):
        self.config = config
        self.kv = kv_manager
        self.waiting: list[Request] = []
        self.running: list[Request] = []
        self._finished_ids: set[str] = set()
        # transfers triggered in update_from_output, delivered to the runner
        # via the *next* schedule() output (the reference's runner handles
        # them at the start of execute_model, gpu_ar_model_runner.py:100-106)
        self._pending_kv_transfers: list[tuple[Request, list[int], int]] = []
        # requests rejected at intake; drained by the engine into outputs
        self._errored: list[Request] = []
        # transfers awaiting extraction ACK, keyed by request_id
        self._active_transfer_reqs: dict[str, Request] = {}
        # lifetime counters for step-level metrics (/metrics gauges)
        self.num_preemptions = 0
        self.num_rejections = 0
        # load-shed counters, keyed (reason, tenant) — rendered as
        # shed_requests_total{reason, tenant} on /metrics
        self.shed_counts: dict[tuple[str, str], int] = {}
        # optional heavy-hitter attribution sink installed by the
        # engine (metrics/attribution.py ``TenantAttribution.add``):
        # unlike the capped shed ledger above, the sketch sees past
        # the cardinality cap — which tenant is driving the 429s
        self.attribution_sink = None
        # WFQ deferral ledger: rounds a tenant's head-of-line fresh
        # request was held back by its deficit while the DRR pass
        # placed other work — rendered as
        # wfq_deferred_requests_total{tenant} on /metrics
        self.wfq_deferred: dict[str, int] = {}
        # DRR rotation pointer: the tenant the next ordering pass
        # visits first (rotates every pass so quantum ties don't
        # permanently favor the first-arrived tenant)
        self._wfq_rotation = 0
        # set once any admitted request carries a deadline, so the
        # per-step expiry sweep stays free for deadline-less serving
        self._deadlines_possible = False

    # ------------------------------------------------------------- intake
    def add_request(self, request: Request, injected_len: int = 0) -> None:
        """``injected_len``: prompt-prefix tokens whose KV the engine will
        inject from an upstream stage — only the remainder must fit the
        per-step token budget."""
        n = request.num_prompt_tokens
        # reject anything that could never be scheduled — otherwise the
        # request would pin the waiting queue and starve the engine
        reason = None
        if n > self.config.max_model_len:
            reason = "prompt exceeds max_model_len"
        elif (not self.config.chunking_enabled
              and n - injected_len > self.config.max_num_batched_tokens):
            reason = "prompt exceeds max_num_batched_tokens (chunked prefill off)"
        elif self.kv.pages_needed(n) > self.kv.num_pages:
            reason = "prompt needs more KV pages than the whole pool"
        if reason is not None:
            self.reject(request, reason)
            return
        if (request.deadline_ts is not None
                and time.monotonic() >= request.deadline_ts):
            # deadline enforcement at admission: the budget was spent
            # upstream (earlier stages / queues / transfers) — reject
            # with the distinct terminal status instead of burning
            # compute on an answer nobody is waiting for
            self.reject(request, "deadline exceeded before admission",
                        kind=DEADLINE_EXCEEDED)
            return
        # admission control AFTER the validity + expiry checks: a
        # malformed request is a 400 and a spent budget a 504 even when
        # the server is also overloaded — shed (429) only claims
        # requests that WOULD have been served on an idle server.  The
        # shed path returns before the request ever enters the waiting
        # queue: no pages, no scheduling work, no engine admission.
        if (self.config.max_queue_depth is not None
                and len(self.waiting) >= self.config.max_queue_depth):
            # priority-ordered shed (WFQ): a full queue prefers to
            # displace its lowest-priority FRESH entry over refusing a
            # strictly higher-priority arrival — under overload the
            # low-priority work is what defers, not whoever arrived
            # last.  Equal priority keeps the FCFS shed (no churn).
            if not (self.config.wfq_scheduling
                    and self._shed_lower_priority(request)):
                self.shed(request, "queue_depth",
                          f"waiting queue at capacity "
                          f"({self.config.max_queue_depth}); retry with "
                          "backoff")
                return
        if (self.config.admission_deadline_headroom_s > 0.0
                and request.deadline_ts is not None
                and request.deadline_ts - time.monotonic()
                < self.config.admission_deadline_headroom_s):
            self.shed(request, "deadline_headroom",
                      "remaining deadline below the admission floor "
                      f"({self.config.admission_deadline_headroom_s}s); "
                      "request would expire mid-queue")
            return
        if request.deadline_ts is not None:
            self._deadlines_possible = True
        request.status = RequestStatus.WAITING
        # per-request opt-out: a disagg router placing a request
        # COLOCATED on a prefill-role engine (degraded mode) suppresses
        # the transfer — the whole-prompt extraction would produce a
        # payload nobody consumes, exactly in the capacity-constrained
        # state the degradation ladder exists for
        if (self.config.kv_transfer is not None
                and not request.additional_information.get(
                    "disable_kv_transfer")):
            request.kv_transfer = KVTransferState.PENDING
        self.waiting.append(request)

    def shed(self, request: Request, reason: str, message: str) -> None:
        """Load-shed an arrival (admission control): count it per
        (reason, tenant) and error-finish it with the distinct ``shed``
        kind (HTTP 429) — the request never enters the waiting queue.
        Tenant values past the cardinality cap collapse into "other"
        (a client inventing tenants must not grow the ledger forever)."""
        from vllm_omni_tpu.metrics.stats import cap_tenant

        tenant = cap_tenant(request.tenant,
                            {t for _, t in self.shed_counts})
        key = (reason, tenant)
        self.shed_counts[key] = self.shed_counts.get(key, 0) + 1
        if self.attribution_sink is not None:
            # UNcapped tenant on purpose: the sketch bounds its own
            # memory, and attribution past the cap is its whole point
            self.attribution_sink(request.tenant, "sheds", 1.0)
        self.reject(request, message, kind=SHED)

    def _shed_lower_priority(self, arrival: Request) -> bool:
        """Displace the lowest-priority fresh waiting request (newest
        among ties) when the ``arrival`` strictly outranks it; returns
        True when room was made.  Only FRESH entries are candidates —
        anything with computed progress (preemption victims, prefix-
        cache adoptions, parked restores) or streaming chunk intake
        holds state worth strictly more than an empty slot."""
        victim = None
        for req in self.waiting:
            if (req.num_computed_tokens > 0 or req.awaiting_chunks
                    or req.status is RequestStatus.PREEMPTED
                    or req.output_token_ids
                    or req.additional_information.get("_parked_len")):
                # progress, streamed output, or a preemption victim
                # (whose num_computed_tokens was RESET to 0): all hold
                # state a client already saw — never displaceable
                continue
            if victim is None or req.priority <= victim.priority:
                victim = req  # <=: newest of the lowest class loses
        if victim is None or victim.priority >= arrival.priority:
            return False
        self.waiting.remove(victim)
        self.shed(victim, "queue_depth",
                  "displaced by a higher-priority arrival with the "
                  f"waiting queue at capacity "
                  f"({self.config.max_queue_depth}); retry with backoff")
        return True

    def _wfq_order(self) -> None:
        """Deficit-round-robin ordering of the waiting queue
        (docs/control_plane.md).  Entries with computed progress —
        preemption victims, parked restores — keep the queue head in
        their existing order (their pages/progress must not rot behind
        fresh arrivals); fresh arrivals are grouped per tenant (FIFO
        within a tenant) and interleaved by DRR: each round a tenant's
        deficit grows by ``wfq_quantum_tokens x priority`` and its
        head requests are placed while the deficit covers their token
        cost.  Every tenant's deficit grows every round, so every
        admitted tenant makes progress — starvation-free by
        construction.  A round that holds a tenant's head back while
        placing other work counts one deferral for that tenant."""
        from vllm_omni_tpu.metrics.stats import cap_tenant

        resuming: list[Request] = []
        groups: dict[str, list[Request]] = {}
        for req in self.waiting:
            if (req.num_computed_tokens > 0
                    or req.status is RequestStatus.PREEMPTED
                    or req.additional_information.get("_parked_len")):
                resuming.append(req)
            else:
                groups.setdefault(req.tenant, []).append(req)
        if len(groups) <= 1:
            return  # zero or one tenant: FIFO is already fair
        tenants = list(groups)
        start = self._wfq_rotation % len(tenants)
        tenants = tenants[start:] + tenants[:start]
        self._wfq_rotation += 1
        quantum = max(self.config.wfq_quantum_tokens, 1)
        deficit = {t: 0.0 for t in tenants}
        idx = {t: 0 for t in tenants}
        order: list[Request] = []
        remaining = sum(len(q) for q in groups.values())
        while remaining > 0:
            placed_this_round = 0
            held: list[str] = []
            for t in tenants:
                q = groups[t]
                i = idx[t]
                if i >= len(q):
                    continue
                deficit[t] += quantum * q[i].priority
                while i < len(q) and deficit[t] >= max(
                        q[i].num_tokens, 1):
                    deficit[t] -= max(q[i].num_tokens, 1)
                    order.append(q[i])
                    i += 1
                    remaining -= 1
                    placed_this_round += 1
                idx[t] = i
                if i < len(q):
                    held.append(t)
                else:
                    # classic DRR: an emptied queue forfeits its
                    # leftover deficit (no banking credit while idle)
                    deficit[t] = 0.0
            if placed_this_round:
                for t in held:
                    key = cap_tenant(t, self.wfq_deferred)
                    self.wfq_deferred[key] = \
                        self.wfq_deferred.get(key, 0) + 1
            # a round that placed nothing still grew every deficit, so
            # the loop always terminates (costs are finite)
        self.waiting = resuming + order

    def queue_depth_by_tenant(self) -> dict[str, int]:
        """Waiting-queue depth split per tenant (request_queue_depth
        gauge).  Always contains "default" so the series exists from
        the first scrape, idle or not; tenants past the cardinality
        cap report under "other"."""
        from vllm_omni_tpu.metrics.stats import cap_tenant

        depths: dict[str, int] = {"default": 0}
        for req in self.waiting:
            t = cap_tenant(req.tenant, depths)
            depths[t] = depths.get(t, 0) + 1
        return depths

    def reject(self, request: Request, reason: str,
               kind: str = "invalid_request") -> None:
        """Error-finish a request at intake: it surfaces as a FINISHED_ERROR
        output on the next step() instead of raising into the caller
        (one bad request must not break its batch-mates)."""
        request.status = RequestStatus.FINISHED_ERROR
        request.additional_information.setdefault("error", reason)
        # invalid_request -> HTTP 400; internal -> 500
        request.additional_information.setdefault("error_kind", kind)
        self._finished_ids.add(request.request_id)
        self._errored.append(request)
        self.num_rejections += 1
        # a parked payload for a dead request is unreachable garbage
        self.kv.drop_park(request)

    def find_request(self, request_id: str):
        """(queue, request) for an in-flight id, else (None, None)."""
        for q in (self.waiting, self.running):
            for req in q:
                if req.request_id == request_id:
                    return q, req
        return None, None

    def fail_request(self, request_id: str, reason: str,
                     kind: str = "invalid_request") -> bool:
        """Error-finish an IN-FLIGHT request (e.g. a streamed prompt chunk
        overflowed the limits): frees its pages and surfaces a
        FINISHED_ERROR output on the next step."""
        q, req = self.find_request(request_id)
        if req is None:
            return False
        q.remove(req)
        self.kv.free(req)
        self.reject(req, reason, kind)
        return True

    def expire_deadlines(self) -> list[Request]:
        """Error-finish every waiting/running request whose deadline
        passed (engine calls this each step; the requests surface as
        ``deadline_exceeded`` outputs through the normal errored
        drain).  Returns the expired requests so the engine can count
        them per stage."""
        if not self._deadlines_possible:
            return []
        now = time.monotonic()
        out: list[Request] = []
        for q in (self.waiting, self.running):
            for req in [r for r in q
                        if r.deadline_ts is not None
                        and now >= r.deadline_ts]:
                q.remove(req)
                self.kv.free(req)
                self.reject(req, "deadline exceeded",
                            kind=DEADLINE_EXCEEDED)
                out.append(req)
        return out

    def abort_request(self, request_id: str) -> None:
        q, req = self.find_request(request_id)
        if req is None:
            return
        req.status = RequestStatus.FINISHED_ABORTED
        q.remove(req)
        self._free_request(req)

    @property
    def has_unfinished(self) -> bool:
        return bool(self.waiting or self.running)

    @property
    def has_pending_errored(self) -> bool:
        """Intake-rejected requests waiting to be drained into outputs.
        Engines must keep stepping while these exist — a lone rejected
        request would otherwise never surface (ADVICE r1 medium)."""
        return bool(self._errored)

    # ----------------------------------------------------------- schedule
    def schedule(self) -> SchedulerOutput:
        out = SchedulerOutput()
        out.unified = self.config.unified_batching
        out.kv_transfer_requests = self.drain_pending_kv_transfers()
        budget = self.config.max_num_batched_tokens
        if self.config.wfq_scheduling and len(self.waiting) > 1:
            # weighted-fair admission order: the loop below still pops
            # waiting[0]; DRR just decides who stands there
            self._wfq_order()

        # 1. running requests decode first (one token each) — prioritize
        #    latency of in-flight sequences, preempting the newest on OOM
        #    (recompute policy, matching vLLM's default the reference extends).
        still_running: list[Request] = []
        snapshot = list(self.running)
        if self.config.unified_batching:
            # unified admission order: DECODES claim the token budget
            # first, prefill chunks fill the remainder (stable sort —
            # relative arrival order preserved within each class).  This
            # also points the preemption policy (_preempt_for walks the
            # snapshot tail) at chunking requests before decoding ones.
            def _needs_chunk(r: Request) -> bool:
                remaining = (r.num_tokens + r.num_inflight_tokens
                             - r.num_computed_tokens)
                return (remaining > 1 or r.awaiting_chunks
                        or (r.prompt_embeds is not None
                            and r.num_computed_tokens
                            < r.num_prompt_tokens))

            snapshot.sort(key=_needs_chunk)
        for i, req in enumerate(snapshot):
            if req.status is not RequestStatus.RUNNING:
                continue  # preempted earlier in this very loop
            if budget <= 0:
                still_running.append(req)
                continue
            if req.num_inflight_tokens > 1:
                # a spec VERIFY dispatch is in flight: how many of its
                # k+1 candidates were accepted — and therefore where
                # this request's next KV position is — is unknown until
                # the lagged retire.  Hold the request one step; plain
                # decode rows (exactly one in-flight token) keep
                # pipelining ahead.
                still_running.append(req)
                continue
            # async pipelining schedules AHEAD of token knowledge: a
            # dispatched-but-unretired decode will append exactly one
            # token, so its in-flight count stands in for the token the
            # host hasn't seen yet (num_computed_tokens was already
            # advanced at dispatch; sync mode always has inflight 0)
            remaining = (req.num_tokens + req.num_inflight_tokens
                         - req.num_computed_tokens)
            if remaining <= 0:
                # streaming request fully caught up with the chunks that
                # have arrived: idle until the next append
                still_running.append(req)
                continue
            # awaiting_chunks: the would-be sampling position may still
            # be mid-prompt (more chunks coming) — compute arrived tokens
            # as prefill chunks, never as a sampling decode.
            # mid-prompt embeds: the decode path embeds from the token
            # table, so any still-in-prompt position of an embeds-based
            # request MUST run as a prefill chunk (its input is the
            # upstream hidden row, not token id 0) — this also covers a
            # chunked-prefill resume whose last chunk is a single token
            mid_prompt_embeds = (
                req.prompt_embeds is not None
                and req.num_computed_tokens < req.num_prompt_tokens
            )
            if remaining > 1 or req.awaiting_chunks or mid_prompt_embeds:
                # mid-prefill, or a preempted request recomputing prompt +
                # generated tokens (num_tokens, not num_prompt_tokens — a
                # resumed request chunks through its generated suffix too
                # instead of crawling it one decode step at a time):
                # schedule the next chunk rather than a decode token
                chunk = min(remaining, budget)
                if not self.kv.can_allocate(req, chunk):
                    out.preempted.extend(
                        self._preempt_for(req, snapshot[i + 1:], chunk)
                    )
                if not self.kv.can_allocate(req, chunk):
                    self._preempt(req)
                    out.preempted.append(req)
                    continue
                table = self.kv.allocate(req, chunk)
                if table is None:
                    self._preempt(req)
                    out.preempted.append(req)
                    continue
                slots = self.kv.slot_mapping(req, chunk)
                out.prefills.append(ScheduledRequest(
                    request=req, num_new_tokens=chunk, slot_mapping=slots,
                    block_table=table, start_pos=req.num_computed_tokens,
                ))
                budget -= chunk
                still_running.append(req)
                continue
            if not self.kv.can_allocate(req, 1):
                # victims come only from *unscheduled* requests (later in
                # the priority order) — preempting one already in
                # out.decodes would free pages its scheduled KV write
                # still targets
                out.preempted.extend(self._preempt_for(req, snapshot[i + 1:]))
                if not self.kv.can_allocate(req, 1):
                    # still not enough: preempt this request itself
                    self._preempt(req)
                    out.preempted.append(req)
                    continue
            # speculative decode: verify up to k drafted tokens in this
            # step's forward (1 regular + n_spec draft positions); degrade
            # to a plain decode under budget/page pressure
            n_new = 1
            k = self.config.num_speculative_tokens
            if k and req.spec_draft_tokens and budget > 1:
                # drafts beyond the request's remaining max_tokens are
                # guaranteed-discarded work — don't schedule them
                remaining_out = (req.sampling_params.max_tokens
                                 - len(req.output_token_ids))
                # max_model_len leg counts the IN-FLIGHT token too: an
                # async-scheduled verify whose input token is still in
                # flight has num_tokens lagging by one, and without the
                # correction the last candidate position would land one
                # slot past the cap (allocating a page the block-table
                # truncation then cannot address)
                n_spec = min(
                    len(req.spec_draft_tokens), k, budget - 1,
                    self.config.max_model_len
                    - (req.num_tokens + req.num_inflight_tokens),
                    max(remaining_out - 1, 0),
                )
                if n_spec > 0 and self.kv.can_allocate(req, 1 + n_spec):
                    n_new = 1 + n_spec
            table = self.kv.allocate(req, n_new)
            if table is None:
                self._preempt(req)
                out.preempted.append(req)
                continue
            slots = self.kv.slot_mapping(req, n_new)
            out.decodes.append(ScheduledRequest(
                request=req, num_new_tokens=n_new, slot_mapping=slots,
                block_table=table, start_pos=req.num_computed_tokens,
            ))
            budget -= n_new
            still_running.append(req)
        self.running = still_running

        # 2. admit waiting requests (chunked prefill under the budget).
        # num_tokens (not num_prompt_tokens): a preempted request resumes by
        # recomputing KV for its prompt *and* its already-generated tokens.
        while self.waiting and budget > 0 and len(self.running) < self.config.max_num_seqs:
            req = self.waiting[0]
            if (self.config.kv_offload
                    and req.num_computed_tokens == 0
                    and not req.awaiting_chunks
                    and req.additional_information.get("_parked_len")):
                # parked preemption victim: restore its KV run from the
                # tier store instead of recomputing it.  Extraction
                # still in flight (queued this very step) -> wait one
                # step; payload gone -> fall through to full recompute
                if self.kv.park_in_flight(req):
                    break
                if self.kv.parked_available(req):
                    if not self.kv.restore_parked(req):
                        break  # page pressure: retry next step
                else:
                    # payload lost (host tier shed it with no remote
                    # edge): full recompute — which, with chunking off,
                    # may no longer fit one step.  _preempt skipped its
                    # starvation reject trusting the park; re-check
                    # here or the head request wedges the queue forever
                    # while other traffic keeps the engine busy.
                    # drop_park (not a bare _parked_len pop) also
                    # closes the host-tier page·second interval —
                    # residency attribution must stop at the shed, not
                    # run on through the whole recompute
                    self.kv.drop_park(req)
                    if (not self.config.chunking_enabled
                            and req.num_tokens
                            > self.config.max_num_batched_tokens):
                        self.waiting.pop(0)
                        # reject() alone doesn't free: release any pages
                        # a prior restore attempt left behind
                        self.kv.free(req)
                        self.reject(
                            req,
                            "parked KV payload lost and the recompute "
                            f"footprint ({req.num_tokens} tokens) "
                            "exceeds the step budget "
                            f"({self.config.max_num_batched_tokens}) "
                            "with chunked prefill off",
                            kind="internal",
                        )
                        continue
            if req.num_computed_tokens == 0 and not req.awaiting_chunks:
                # automatic prefix caching: adopt cached pages covering
                # the longest full-page prompt prefix; the request then
                # prefills from mid-prompt through the runner's
                # chunked-continuation path (vLLM-core APC semantics)
                self.kv.match_prefix(req)
            remaining = req.num_tokens - req.num_computed_tokens
            if remaining <= 0 and req.awaiting_chunks:
                # streaming request admitted before its first chunk has
                # content to compute: park it in running (idle) so it
                # doesn't pin the waiting queue
                self.waiting.pop(0)
                req.status = RequestStatus.RUNNING
                self.running.append(req)
                continue
            if (remaining == 1 and req.output_token_ids
                    and not req.awaiting_chunks):
                # resume-as-decode: a restored preemption victim whose
                # only outstanding position is the sampling one re-enters
                # through the decode executable — the one the
                # uninterrupted stream would have run — not a 1-token
                # prefill chunk.  The two executables agree only to the
                # last ULP, and on near-flat logits that flips greedy
                # argmaxes, breaking the offload bit-equality contract
                if not self.kv.can_allocate(req, 1):
                    break
                table = self.kv.allocate(req, 1)
                if table is None:
                    break
                slots = self.kv.slot_mapping(req, 1)
                out.decodes.append(ScheduledRequest(
                    request=req, num_new_tokens=1, slot_mapping=slots,
                    block_table=table, start_pos=req.num_computed_tokens,
                    window=1,
                ))
                budget -= 1
                self.waiting.pop(0)
                req.status = RequestStatus.RUNNING
                self.running.append(req)
                continue
            if self.config.chunking_enabled:
                chunk = min(remaining, budget)
            elif remaining > budget:
                break  # whole prompt must fit this step's budget
            else:
                chunk = remaining
            if chunk <= 0 or not self.kv.can_allocate(req, chunk):
                break
            table = self.kv.allocate(req, chunk)
            if table is None:
                break
            slots = self.kv.slot_mapping(req, chunk)
            out.prefills.append(ScheduledRequest(
                request=req, num_new_tokens=chunk, slot_mapping=slots,
                block_table=table, start_pos=req.num_computed_tokens,
            ))
            budget -= chunk
            self.waiting.pop(0)
            req.status = RequestStatus.RUNNING
            self.running.append(req)
        return out

    def _preempt(self, req: Request) -> None:
        """Preemption: free pages, reset progress, back to waiting.
        With kv_offload on, the victim's computed KV run is PARKED in
        the tier store first (extraction drains before this step's
        forward can overwrite the freed pages) — re-admission restores
        the run instead of recomputing it.  Recompute remains the
        fallback: in-flight async tokens, a policy veto, or a lost
        payload all degrade to the classic path bit-identically."""
        self.num_preemptions += 1
        if self.config.kv_offload:
            # the manager parks only the COMMITTED prefix (in-flight
            # async slots excluded — their tokens are discarded below
            # and may re-sample differently on recompute)
            self.kv.park_request(req)
        self.kv.free(req)
        req.num_computed_tokens = 0
        # an in-flight async token is discarded with the progress — the
        # recompute re-derives it (bit-identical for greedy; the retire
        # skips requests whose in-flight count was reset)
        req.num_inflight_tokens = 0
        # collected hidden states are recomputed from scratch on resume —
        # stale chunks would duplicate the prefix
        req.additional_information.pop("_hidden_chunks", None)
        req.status = RequestStatus.PREEMPTED
        # invalidate any in-flight async token for this request (see
        # Request.async_generation)
        req.async_generation += 1
        if req in self.running:
            self.running.remove(req)
        parked = req.additional_information.get("_parked_len", 0)
        if (not self.config.chunking_enabled
                and req.num_tokens - parked
                > self.config.max_num_batched_tokens):
            # the recompute footprint (prompt + generated, or a formerly
            # injected prefix) no longer fits one step and chunking is off:
            # requeueing would pin the waiting head forever while other
            # requests keep the engine busy (the starvation guard never
            # fires when something else schedules).  A parked run
            # shrinks the footprint to its un-parked remainder — but if
            # the payload is later lost the starvation guard still
            # error-finishes the request rather than wedging the queue
            self.kv.drop_park(req)
            self.reject(
                req,
                "preempted request cannot resume: recompute footprint "
                f"({req.num_tokens} tokens) exceeds the step budget "
                f"({self.config.max_num_batched_tokens}) with chunked "
                "prefill off",
                kind="internal",
            )
            return
        self.waiting.insert(0, req)

    def _preempt_for(
        self, req: Request, candidates: list[Request], num_tokens: int = 1
    ) -> list[Request]:
        """Preempt newest-first from ``candidates`` until ``req`` fits;
        returns the victims (possibly insufficient — caller rechecks)."""
        preempted = []
        for victim in reversed(candidates):
            if victim is req or victim.status is not RequestStatus.RUNNING:
                continue
            self._preempt(victim)
            preempted.append(victim)
            if self.kv.can_allocate(req, num_tokens):
                break
        return preempted

    # ------------------------------------------------------ update (post-run)
    def update_from_output(
        self,
        scheduler_output: SchedulerOutput,
        sampled: dict[str, "int | list[int]"],
        kv_extracted_req_ids: Optional[set[str]] = None,
    ) -> list[Request]:
        """Advance request state after the runner executed a step.

        ``sampled`` maps request_id -> new token (int), or — for a
        speculative-decode verify step — the list of accepted tokens (the
        regular sample plus every draft that matched; its length is the
        number of positions whose KV is now verified-valid).
        ``kv_extracted_req_ids`` ACKs completed KV extractions so pinned
        pages can be freed (reference: omni_ar_scheduler.py:444-471).
        Returns the list of requests that finished this step.
        """
        finished: list[Request] = []
        for sched in scheduler_output.prefills + scheduler_output.decodes:
            req = sched.request
            token = sampled.get(req.request_id)
            if token is None:
                req.num_computed_tokens += sched.num_new_tokens
                continue  # mid-prefill chunk: nothing sampled yet
            if isinstance(token, list):
                # spec decode: only accepted positions advance — rejected
                # draft slots are re-written when real tokens reach those
                # positions (slots are position-keyed, stale KV beyond
                # the context is never attended).  Advance is per-token
                # inside the loop so a special_token KV-transfer trigger
                # sees exactly the coverage plain decoding would
                # (KV through the token BEFORE the one just appended).
                tokens = token
                per_token_advance = True
            else:
                req.num_computed_tokens += sched.num_new_tokens
                tokens = [token]
                per_token_advance = False
            stopped = False
            for t in tokens:
                if per_token_advance:
                    req.num_computed_tokens += 1
                stopped = self._append_and_check_stop(req, t)
                if stopped:
                    break
            if stopped:
                finished.append(req)
                self._finish_running(req)
        if kv_extracted_req_ids:
            for rid in kv_extracted_req_ids:
                self._ack_kv_transfer(rid)
        return finished

    # ------------------------------------------------- async pipelined step
    def note_async_dispatch(self, scheduler_output: SchedulerOutput) -> None:
        """Account a pipelined dispatch BEFORE its tokens are host-
        visible: every scheduled chunk advances num_computed_tokens (its
        KV slots are being written by the in-flight step), and each row
        the step SAMPLES — single-token decodes and sequence-final
        prefill chunks — marks one in-flight token, so the next
        schedule() can emit the following decode without waiting for
        the readback.  Mid-prefill chunks sample nothing; the next
        chunk pipelines right behind them."""
        for sched in scheduler_output.decodes:
            req = sched.request
            req.num_computed_tokens += sched.num_new_tokens
            req.num_inflight_tokens += sched.num_new_tokens
            scheduler_output.async_sampled[req.request_id] = \
                req.async_generation
        for sched in scheduler_output.prefills:
            req = sched.request
            final = sched.samples_final
            req.num_computed_tokens += sched.num_new_tokens
            if final:
                req.num_inflight_tokens += 1
                scheduler_output.async_sampled[req.request_id] = \
                    req.async_generation

    def update_from_async_retire(
        self,
        scheduler_output: SchedulerOutput,
        sampled: dict[str, "int | list[int]"],
    ) -> list[Request]:
        """The one-step-lagged counterpart of ``update_from_output`` for
        a pipelined dispatch: num_computed_tokens already advanced at
        dispatch, so only the token append + stop checks happen here.
        Requests that finished, aborted, expired, or were preempted
        while their step was in flight have their token DISCARDED (the
        overshoot contract — greedy recompute re-derives a preempted
        request's token bit-identically); a preempt-and-readmit is
        caught by the async_generation stamp, not just the in-flight
        counter.

        A spec VERIFY row retires a LIST of accepted tokens: its
        dispatch advanced ``num_computed_tokens`` by the full candidate
        width (1 + drafts), so the rewind here keeps exactly the
        accepted prefix — rejected candidate slots are position-keyed
        garbage re-written when real tokens reach those positions, the
        same contract as the synchronous update."""
        finished: list[Request] = []
        # in-flight contribution per row (mirrors note_async_dispatch):
        # a final prefill chunk marked ONE in-flight token however wide
        # the chunk; a decode/verify row marked its full candidate width
        rows = ([(s, 1) for s in scheduler_output.prefills]
                + [(s, s.num_new_tokens) for s in scheduler_output.decodes])
        for sched, contrib in rows:
            req = sched.request
            gen = scheduler_output.async_sampled.get(req.request_id)
            consumed = (gen is not None
                        and gen == req.async_generation
                        and req.num_inflight_tokens > 0)
            if consumed:
                req.num_inflight_tokens = max(
                    req.num_inflight_tokens - contrib, 0)
            if req.is_finished:
                # overshoot: the request stopped one step earlier
                # (EOS/stop/abort/deadline) while this dispatch was in
                # flight — discard the token and rewind the speculative
                # advance so KV accounting matches what sync mode would
                # have recorded (the overshoot slot's write is garbage
                # in the request's own freed pages, never attended)
                if consumed:
                    req.num_computed_tokens -= sched.num_new_tokens
                continue
            if not consumed:
                # mid-prefill chunk (nothing sampled), or preempted /
                # re-admitted while in flight (token discarded with the
                # progress reset)
                continue
            token = sampled.get(req.request_id)
            if token is None:
                continue
            if isinstance(token, list):
                # verify row: keep the accepted prefix, rewind the rest
                # (per-token advance mirrors the sync spec update so a
                # stop inside the run leaves computed == appended)
                req.num_computed_tokens -= sched.num_new_tokens
                stopped = False
                for t in token:
                    req.num_computed_tokens += 1
                    stopped = self._append_and_check_stop(req, t)
                    if stopped:
                        break
                if stopped:
                    finished.append(req)
                    self._finish_running(req)
                continue
            if self._append_and_check_stop(req, token):
                finished.append(req)
                self._finish_running(req)
        return finished

    def _append_and_check_stop(self, req: Request, token: int) -> bool:
        """The ONE append/stop sequence shared by the sync update and
        the async lagged retire — a finish criterion or transfer
        trigger added here applies to both, preserving the sync/async
        bit-identity contract."""
        req.append_output_token(int(token))
        self._maybe_trigger_kv_transfer(req)
        stopped = req.check_stop()
        if not stopped and req.num_tokens >= self.config.max_model_len:
            req.status = RequestStatus.FINISHED_LENGTH
            stopped = True
        return stopped

    def _finish_running(self, req: Request) -> None:
        if req in self.running:
            self.running.remove(req)
        self._free_request(req)

    # ----------------------------------------------------- kv transfer hooks
    def drain_errored(self) -> list[Request]:
        errored, self._errored = self._errored, []
        return errored

    def drain_pending_kv_transfers(self) -> list[tuple[Request, list[int], int]]:
        pending, self._pending_kv_transfers = self._pending_kv_transfers, []
        return pending

    def _maybe_trigger_kv_transfer(self, req: Request) -> None:
        cfg = self.config.kv_transfer
        if cfg is None or req.kv_transfer is not KVTransferState.PENDING:
            return
        trigger = False
        if cfg.trigger == "prefill_finished":
            trigger = req.num_computed_tokens >= req.num_prompt_tokens
        elif cfg.trigger == "special_token":
            trigger = (cfg.special_token_id is not None
                       and req.output_token_ids
                       and req.output_token_ids[-1] == cfg.special_token_id)
        if not trigger:
            return
        # Only tokens whose KV is actually in the cache: the token sampled
        # this step is written at the *next* step's decode.
        seq_len = req.num_computed_tokens
        block_ids = self.kv.pin_for_transfer(req, seq_len)
        req.kv_transfer = KVTransferState.ACTIVE
        req.kv_transfer_block_ids = block_ids
        req.kv_transfer_seq_len = seq_len
        self._pending_kv_transfers.append((req, block_ids, seq_len))
        self._active_transfer_reqs[req.request_id] = req

    def _ack_kv_transfer(self, request_id: str) -> None:
        self.kv.ack_transfer(request_id)
        # direct map, not a queue scan: the request may already have
        # finished and left running/waiting by the time the ACK lands
        req = self._active_transfer_reqs.pop(request_id, None)
        if req is not None:
            req.kv_transfer = KVTransferState.DONE

    def _free_request(self, req: Request) -> None:
        """Free pages unless a transfer is still ACTIVE (delayed free,
        reference: omni_ar_scheduler.py:473-546 — pinned pages survive)."""
        self._finished_ids.add(req.request_id)
        self.kv.free(req)
        self.kv.drop_park(req)

    def restore_failed(self, request_id: str, failed_entries: list,
                       keep_tokens: int) -> set[str]:
        """A queued tier restore came up short at engine drain time
        (payload vanished between match and fetch): unwind the
        never-injected entries (their nodes sit on garbage pages),
        keep the contiguous ``keep_tokens`` that are valid, and rewind
        the rest — the scheduler recomputes it as ordinary chunks next
        step.  The engine drops this step's now-misaligned
        ScheduledRequest before executing.  Returns the ids of OTHER
        requests that co-adopted a failed node in the same pass and
        were truncated along with it — their scheds must drop too."""
        _, req = self.find_request(request_id)
        if req is None:
            return set()
        co = self.kv.restore_failed_entries(req, failed_entries,
                                            keep_tokens)
        unwound: set[str] = set()
        for rid, keep in co.items():
            _, co_req = self.find_request(rid)
            if co_req is None:
                continue
            self.kv.restore_truncated(co_req, keep)
            unwound.add(rid)
        return unwound


class GenerationScheduler(ARScheduler):
    """One-shot generation fast path (reference:
    omni_generation_scheduler.py:33-261): the entire prompt is allocated and
    scheduled in one step; there is no decode phase — the model's forward
    produces the final (multimodal) output and the request finishes
    (:362-377)."""

    def schedule(self) -> SchedulerOutput:
        out = SchedulerOutput()
        while self.waiting and len(self.running) < self.config.max_num_seqs:
            req = self.waiting[0]
            n = req.num_prompt_tokens
            if not self.kv.can_allocate(req, n):
                break
            table = self.kv.allocate(req, n)
            if table is None:
                break
            slots = self.kv.slot_mapping(req, n)
            out.prefills.append(ScheduledRequest(
                request=req, num_new_tokens=n, slot_mapping=slots,
                block_table=table, start_pos=0,
            ))
            self.waiting.pop(0)
            req.status = RequestStatus.RUNNING
            self.running.append(req)
        return out

    def update_from_output(
        self,
        scheduler_output: SchedulerOutput,
        sampled: dict[str, int],
        kv_extracted_req_ids: Optional[set[str]] = None,
    ) -> list[Request]:
        finished: list[Request] = []
        for sched in scheduler_output.prefills:
            req = sched.request
            req.num_computed_tokens += sched.num_new_tokens
            req.status = RequestStatus.FINISHED_STOPPED
            finished.append(req)
            self.running.remove(req)
            self._free_request(req)
        return finished
