"""Host-side paged KV-cache block pool.

The behavioral port of vLLM's KVCacheManager slice that the reference's
``OmniARScheduler`` leans on (reference: core/sched/omni_ar_scheduler.py —
block allocation during schedule(), block-id snapshots for KV transfer at
:553-594, delayed free until extraction ACK at :444-546).

Device arrays never appear here: this class hands out integer page ids; the
model runner turns them into ``block_tables`` / ``slot_mapping`` arrays for
the Pallas paged-attention kernel (ops/paged_attention.py).  One pool is
shared by all layers — every layer uses the same page ids, so the per-layer
caches stay aligned (same layout the TPU kernel wants).
"""

from __future__ import annotations

from typing import Optional

from vllm_omni_tpu.request import Request


class KVCacheManager:
    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 1 or page_size < 1:
            raise ValueError("num_pages and page_size must be positive")
        self.num_pages = num_pages
        self.page_size = page_size
        self._free: list[int] = list(range(num_pages))
        # request_id -> allocated page ids, in sequence order
        self._tables: dict[str, list[int]] = {}
        # pages pinned by an in-flight KV transfer even after request free
        # (reference: delayed _free_request while transfer ACTIVE)
        self._pinned: dict[str, list[int]] = {}

    # ------------------------------------------------------------- queries
    @property
    def num_free_pages(self) -> int:
        return len(self._free)

    def pages_needed(self, num_tokens: int) -> int:
        return -(-num_tokens // self.page_size)

    def block_table(self, request_id: str) -> list[int]:
        return list(self._tables.get(request_id, ()))

    def can_allocate(self, request: Request, num_new_tokens: int) -> bool:
        have = len(self._tables.get(request.request_id, ()))
        need = self.pages_needed(request.num_computed_tokens + num_new_tokens)
        return need - have <= len(self._free)

    # ---------------------------------------------------------- allocation
    def allocate(self, request: Request, num_new_tokens: int) -> Optional[list[int]]:
        """Grow the request's table to cover ``num_computed_tokens +
        num_new_tokens``; returns the full table, or None if out of pages."""
        table = self._tables.setdefault(request.request_id, [])
        need = self.pages_needed(request.num_computed_tokens + num_new_tokens)
        grow = need - len(table)
        if grow > len(self._free):
            return None
        for _ in range(max(grow, 0)):
            table.append(self._free.pop())
        return list(table)

    def slot_mapping(self, request: Request, num_new_tokens: int) -> list[int]:
        """Flat slots (page*page_size + offset) for the next
        ``num_new_tokens`` tokens starting at num_computed_tokens."""
        table = self._tables[request.request_id]
        start = request.num_computed_tokens
        slots = []
        for i in range(num_new_tokens):
            pos = start + i
            slots.append(table[pos // self.page_size] * self.page_size
                         + pos % self.page_size)
        return slots

    # ---------------------------------------------------------------- free
    def free(self, request: Request) -> None:
        """Release the request's pages — unless a KV transfer pinned them
        (then they are released by ack_transfer)."""
        table = self._tables.pop(request.request_id, None)
        if table is None:
            return
        pinned = set(self._pinned.get(request.request_id, ()))
        for page in table:
            if page not in pinned:
                self._free.append(page)

    def pin_for_transfer(self, request: Request, seq_len: int) -> list[int]:
        """Snapshot + pin the pages holding the first ``seq_len`` tokens
        (reference: block-id snapshot truncated to seq_len,
        omni_ar_scheduler.py:553-594)."""
        table = self._tables.get(request.request_id, [])
        keep = self.pages_needed(seq_len)
        snapshot = table[:keep]
        self._pinned[request.request_id] = list(snapshot)
        return list(snapshot)

    def ack_transfer(self, request_id: str) -> None:
        """Extraction ACK: release pinned pages not still in a live table
        (reference: free on kv_extracted_req_ids, omni_ar_scheduler.py:444)."""
        pinned = self._pinned.pop(request_id, [])
        live = set(self._tables.get(request_id, ()))
        for page in pinned:
            if page not in live:
                self._free.append(page)
