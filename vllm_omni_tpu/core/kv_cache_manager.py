"""Host-side paged KV-cache block pool with automatic prefix caching.

The behavioral port of vLLM's KVCacheManager slice that the reference's
``OmniARScheduler`` leans on (reference: core/sched/omni_ar_scheduler.py —
block allocation during schedule(), block-id snapshots for KV transfer at
:553-594, delayed free until extraction ACK at :444-546), plus the
content-addressed prefix cache the reference inherits from vLLM core:
full prompt pages register under a chained content hash when their
producing request frees; a new request whose prompt shares the prefix
adopts those pages (refcounted, shared across concurrent tables) and
starts computing mid-prompt — the runner's chunked-continuation path
attends the cached context exactly like a resumed chunked prefill.
Cached pages with no live references stay allocatable (LRU-evicted on
demand), so prefix caching never reduces effective capacity.

Device arrays never appear here: this class hands out integer page ids; the
model runner turns them into ``block_tables`` / ``slot_mapping`` arrays for
the Pallas paged-attention kernel (ops/paged_attention.py).  One pool is
shared by all layers — every layer uses the same page ids, so the per-layer
caches stay aligned (same layout the TPU kernel wants).
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Optional

from vllm_omni_tpu.request import Request


class KVCacheManager:
    def __init__(self, num_pages: int, page_size: int,
                 enable_prefix_caching: bool = True):
        if num_pages < 1 or page_size < 1:
            raise ValueError("num_pages and page_size must be positive")
        self.num_pages = num_pages
        self.page_size = page_size
        self.enable_prefix_caching = enable_prefix_caching
        self._free: list[int] = list(range(num_pages))
        # request_id -> allocated page ids, in sequence order
        self._tables: dict[str, list[int]] = {}
        # pages pinned by an in-flight KV transfer even after request free
        # (reference: delayed _free_request while transfer ACTIVE)
        self._pinned: dict[str, list[int]] = {}
        # ---- prefix cache state ----
        # chain-hash -> page holding that full prompt page's KV
        self._cached: dict[str, int] = {}
        self._hash_of: dict[int, str] = {}        # page -> its hash
        self._ref: dict[int, int] = {}            # live refs per cached page
        self._evictable: "OrderedDict[int, None]" = OrderedDict()  # LRU
        # cache effectiveness counters (surfaced by engine stats)
        self.prefix_hits = 0
        self.prefix_hit_tokens = 0

    # ------------------------------------------------------------- queries
    @property
    def num_free_pages(self) -> int:
        # evictable cached pages are allocatable on demand
        return len(self._free) + len(self._evictable)

    def pages_needed(self, num_tokens: int) -> int:
        return -(-num_tokens // self.page_size)

    def block_table(self, request_id: str) -> list[int]:
        return list(self._tables.get(request_id, ()))

    def can_allocate(self, request: Request, num_new_tokens: int) -> bool:
        have = len(self._tables.get(request.request_id, ()))
        need = self.pages_needed(request.num_computed_tokens + num_new_tokens)
        return need - have <= self.num_free_pages

    # ------------------------------------------------------- prefix cache
    def _page_hashes(self, token_ids, max_pages: Optional[int] = None):
        """Chained content hashes of the FULL pages of ``token_ids``."""
        hashes = []
        prev = b""
        n_full = len(token_ids) // self.page_size
        if max_pages is not None:
            n_full = min(n_full, max_pages)
        for p in range(n_full):
            chunk = token_ids[p * self.page_size: (p + 1) * self.page_size]
            h = hashlib.blake2b(
                prev + b"," + repr(list(chunk)).encode(), digest_size=16
            ).hexdigest()
            hashes.append(h)
            prev = h.encode()
        return hashes

    def match_prefix(self, request: Request) -> int:
        """Adopt cached pages covering the longest full-page prefix of
        the request's prompt; returns the number of tokens whose KV the
        request now starts with (``num_computed_tokens`` is updated and
        the pages seed its block table).  At least one prompt token is
        always left to compute — its forward produces the first logits.
        Embeds-based prompts never match (their placeholder ids carry no
        content)."""
        if (not self.enable_prefix_caching
                or request.prompt_embeds is not None
                or request.num_computed_tokens
                or request.request_id in self._tables):
            return 0
        # leave >= 1 token to compute; hashes memoize on the request —
        # a head-of-queue request blocked on pages re-matches every
        # scheduler step and must not re-hash its whole prompt each time
        usable = len(request.prompt_token_ids) - 1
        hashes = getattr(request, "_apc_hashes", None)
        if hashes is None:
            hashes = self._page_hashes(request.prompt_token_ids,
                                       max_pages=usable // self.page_size)
            request._apc_hashes = hashes
        pages = []
        for h in hashes:
            page = self._cached.get(h)
            if page is None:
                break
            pages.append(page)
        if not pages:
            return 0
        for page in pages:
            self._ref[page] = self._ref.get(page, 0) + 1
            self._evictable.pop(page, None)
        self._tables[request.request_id] = list(pages)
        matched = len(pages) * self.page_size
        request.num_computed_tokens = matched
        self.prefix_hits += 1
        self.prefix_hit_tokens += matched
        return matched

    def _register_pages(self, request: Request, table: list[int],
                        candidates: set) -> set:
        """Content-register the request's full PROMPT pages at free time
        (pages become shareable once their producer completes).  Only
        pages in ``candidates`` are considered; returns the set of pages
        the cache consumed (now evictable, NOT to be freed)."""
        consumed: set = set()
        if (not self.enable_prefix_caching
                or request.prompt_embeds is not None):
            return consumed
        hashes = self._page_hashes(request.prompt_token_ids)
        # only pages whose KV was actually computed/valid
        valid = min(len(hashes),
                    request.num_computed_tokens // self.page_size,
                    len(table))
        for h, page in zip(hashes[:valid], table[:valid]):
            if page not in candidates:
                continue
            old = self._cached.get(h)
            if old is not None and old != page:
                # prefix already cached by another producer: keep the
                # old page; this one frees normally
                continue
            self._cached[h] = page
            self._hash_of[page] = h
            self._evictable[page] = None
            self._evictable.move_to_end(page)
            consumed.add(page)
        return consumed

    def reset_prefix_cache(self) -> int:
        """Drop EVERY unreferenced cached page back to the free pool
        (reference: reset_prefix_cache during pause_generation,
        async_omni.py:771 — weight updates invalidate cached KV).
        Pages still referenced by live requests stay cached; returns the
        number of pages released."""
        n = 0
        while self._evictable:
            page = self._evict_one()
            if page is None:
                break
            self._free.append(page)
            n += 1
        return n

    def _evict_one(self) -> Optional[int]:
        """Drop the least-recently-used unreferenced cached page back to
        the free pool."""
        if not self._evictable:
            return None
        page, _ = self._evictable.popitem(last=False)
        h = self._hash_of.pop(page, None)
        if h is not None:
            self._cached.pop(h, None)
        self._ref.pop(page, None)
        return page

    def _take_free_page(self) -> Optional[int]:
        if self._free:
            return self._free.pop()
        return self._evict_one()

    # ---------------------------------------------------------- allocation
    def allocate(self, request: Request, num_new_tokens: int) -> Optional[list[int]]:
        """Grow the request's table to cover ``num_computed_tokens +
        num_new_tokens``; returns the full table, or None if out of pages."""
        table = self._tables.setdefault(request.request_id, [])
        need = self.pages_needed(request.num_computed_tokens + num_new_tokens)
        grow = need - len(table)
        if grow > self.num_free_pages:
            return None
        for _ in range(max(grow, 0)):
            page = self._take_free_page()
            if page is None:
                return None
            table.append(page)
        return list(table)

    def slot_mapping(self, request: Request, num_new_tokens: int) -> list[int]:
        """Flat slots (page*page_size + offset) for the next
        ``num_new_tokens`` tokens starting at num_computed_tokens."""
        table = self._tables[request.request_id]
        start = request.num_computed_tokens
        slots = []
        for i in range(num_new_tokens):
            pos = start + i
            slots.append(table[pos // self.page_size] * self.page_size
                         + pos % self.page_size)
        return slots

    # ---------------------------------------------------------------- free
    def free(self, request: Request) -> None:
        """Release the request's pages — unless a KV transfer pinned them
        (then they are released by ack_transfer).  Full prompt pages
        register in the prefix cache instead of returning to the free
        pool (they remain allocatable via LRU eviction)."""
        table = self._tables.pop(request.request_id, None)
        if table is None:
            return
        pinned = set(self._pinned.get(request.request_id, ()))
        private = []
        for page in table:
            if page in self._ref:
                # shared cache page: drop this request's reference;
                # unreferenced registered pages become LRU-evictable —
                # UNLESS pinned by an in-flight transfer (eviction would
                # hand the page to a new request mid-read; ack_transfer
                # releases it)
                self._ref[page] -= 1
                if self._ref[page] <= 0:
                    self._ref.pop(page, None)
                    if page in pinned:
                        pass  # released by ack_transfer
                    elif page in self._hash_of:
                        self._evictable[page] = None
                        self._evictable.move_to_end(page)
                    else:
                        self._free.append(page)
                continue
            private.append(page)
        consumed = self._register_pages(
            request, table, candidates=set(private) - pinned)
        for page in private:
            if page in pinned or page in consumed:
                continue
            self._free.append(page)

    def pin_for_transfer(self, request: Request, seq_len: int) -> list[int]:
        """Snapshot + pin the pages holding the first ``seq_len`` tokens
        (reference: block-id snapshot truncated to seq_len,
        omni_ar_scheduler.py:553-594)."""
        table = self._tables.get(request.request_id, [])
        keep = self.pages_needed(seq_len)
        snapshot = table[:keep]
        self._pinned[request.request_id] = list(snapshot)
        return list(snapshot)

    def ack_transfer(self, request_id: str) -> None:
        """Extraction ACK: release pinned pages not still in a live table
        (reference: free on kv_extracted_req_ids, omni_ar_scheduler.py:444).
        Registered pages whose producer already freed become evictable
        here; re-shared pages (ref > 0) stay live."""
        pinned = self._pinned.pop(request_id, [])
        live = set(self._tables.get(request_id, ()))
        for page in pinned:
            if page in live or page in self._ref:
                continue
            if page in self._hash_of:
                if page not in self._evictable:
                    self._evictable[page] = None
                self._evictable.move_to_end(page)
            else:
                self._free.append(page)
