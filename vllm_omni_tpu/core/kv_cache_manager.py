"""Host-side paged KV-cache block pool over a radix prefix index.

The behavioral port of vLLM's KVCacheManager slice that the reference's
``OmniARScheduler`` leans on (reference: core/sched/omni_ar_scheduler.py —
block allocation during schedule(), block-id snapshots for KV transfer at
:553-594, delayed free until extraction ACK at :444-546), grown from the
flat chained-hash prefix cache into fleet-scale KV economics
(docs/kv_cache.md):

- **Radix prefix index** (kvcache/radix.py): full prompt pages register
  as reference-counted trie nodes when their producing request frees; a
  new request adopts the longest matching root-path — shared across
  concurrent requests and tenants — and starts computing mid-prompt.
  Eviction is deepest-first LRU, so a prefix outlives its extensions
  and the index never holds unmatchable orphan entries (the failure
  mode of the flat map under pressure).
- **Tiered offload** (kvcache/tiers.py + kvcache/policy.py): when the
  pool is under pressure, evicted pages whose round trip beats
  recompute PARK their KV in the host/remote tiers instead of dropping
  it, and preempted requests park their whole computed run.  Cold
  nodes stay matchable; adoption allocates fresh pages and queues a
  restore.  This class only QUEUES device moves (pending_offloads /
  pending_parks / pending_restores) — the engine drains the queues
  between schedule() and execute() with batched pytree transfers
  (``LLMEngine._drain_kv_moves``).

Device arrays never appear here: this class hands out integer page ids;
the model runner turns them into ``block_tables`` / ``slot_mapping``
arrays for the Pallas paged-attention kernel (ops/paged_attention.py).
One pool is shared by all layers — every layer uses the same page ids,
so the per-layer caches stay aligned (same layout the TPU kernel wants).
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Optional

from vllm_omni_tpu.kvcache.policy import OffloadPolicy
from vllm_omni_tpu.kvcache.radix import RadixNode, RadixPrefixIndex
from vllm_omni_tpu.kvcache.tiers import TIER_HOST, TieredKVStore
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.request import Request

logger = init_logger(__name__)

# unclaimed per-request prefix-hit entries age out past this many ids
# (an engine whose router never joins them — decode tier, aborts —
# must not accumulate them forever)
_REQUEST_HIT_CAP = 1024


def park_key(request_id: str) -> str:
    """Tier-store key of a preempted request's parked KV run."""
    return f"park/{request_id}"


@dataclass
class PendingOffload:
    """Extract ``n_tokens`` of KV from ``pages`` and park under ``key``
    (drained by the engine BEFORE this step's forward reuses the
    pages)."""

    key: str
    pages: list[int]
    n_tokens: int


@dataclass
class PendingRestore:
    """Inject the tier payload at ``key`` into freshly allocated
    ``pages`` for ``request_id`` (drained before the forward attends
    them).  ``start_tokens`` is the payload's position offset within
    the request — on a fetch failure the contiguous valid prefix ends
    exactly there (cold nodes can interleave with hot ones, so a sum
    of injected lengths would be wrong).  ``nodes`` are the adopted
    radix nodes the payloads back (empty for a park restore);
    ``drop_after`` deletes the one-shot park payload once injected."""

    request_id: str
    key: str
    pages: list[int]
    n_tokens: int
    start_tokens: int = 0
    nodes: list[RadixNode] = field(default_factory=list)
    drop_after: bool = False


class KVCacheManager:
    def __init__(self, num_pages: int, page_size: int,
                 enable_prefix_caching: bool = True,
                 tiers: Optional[TieredKVStore] = None,
                 policy: Optional[OffloadPolicy] = None,
                 cache_dtype: Optional[str] = None,
                 bytes_per_token: Optional[float] = None):
        if num_pages < 1 or page_size < 1:
            raise ValueError("num_pages and page_size must be positive")
        self.num_pages = num_pages
        self.page_size = page_size
        # resident KV layout metadata for /debug/kv (informational —
        # the allocator is layout-agnostic): the pool dtype label
        # ("int8" / "bfloat16" / None when the engine didn't say) and
        # the amortized all-layer HBM bytes per cached token
        self.cache_dtype = cache_dtype
        self.bytes_per_token = bytes_per_token
        self.enable_prefix_caching = enable_prefix_caching
        self._free: list[int] = list(range(num_pages))
        # request_id -> allocated page ids, in sequence order
        self._tables: dict[str, list[int]] = {}
        # pages pinned by an in-flight KV transfer even after request free
        # (reference: delayed _free_request while transfer ACTIVE)
        self._pinned: dict[str, list[int]] = {}
        # GLOBAL pin refcounts: a page can be pinned through one
        # request's snapshot while another request (or the prefix
        # cache) still owns it — eviction and free() consult THIS, not
        # the per-request snapshot, so a pinned page can never sit in
        # the evictable pool (the evict-under-pressure-vs-pin race)
        self._pin_count: dict[int, int] = {}
        # ---- prefix cache state: the radix index over full pages ----
        self.index = RadixPrefixIndex(page_size)
        # request_id -> adopted radix nodes (released on free)
        self._adopted: dict[str, list[RadixNode]] = {}
        # ---- tiered offload ----
        self.tiers = tiers
        self.policy = policy or OffloadPolicy(mode="never")
        self.pending_offloads: list[PendingOffload] = []
        self.pending_restores: list[PendingRestore] = []
        # keys queued for extraction but not yet drained (park runs
        # AND offload-evicted nodes): their payload is not fetchable
        # yet — park admission waits a step, and match_prefix must not
        # mistake an in-flight cold node for a dead one
        self._extract_in_flight: set[str] = set()
        # cache effectiveness counters (surfaced by engine stats)
        self.prefix_hits = 0
        self.prefix_hit_tokens = 0
        # per-request hit sizes for the fleet cache-economics board
        # (metrics/cache_economics.py): the router joins the ACTUAL
        # prefix hit onto its dispatch-time expectation.  MUST survive
        # free() — a prefill-tier engine frees the request inside the
        # same step() that emits its output, before the router's join
        # runs — so entries live until take_request_hit pops them,
        # bounded by an LRU cap instead (never-claimed ids age out)
        self._request_hit_tokens: "OrderedDict[str, int]" = OrderedDict()
        # recompute avoided by tier restores (cold prefix adoptions +
        # park restores), in tokens
        self.restored_tokens = 0
        self.parked_tokens = 0
        self.offload_evictions = 0
        self.drop_evictions = 0
        # prompt tokens whose KV arrived as a STREAMED payload from a
        # peer engine (disaggregated prefill adoption, docs/
        # disaggregation.md) rather than being computed here
        self.streamed_tokens = 0
        # prompt tokens whose KV was PULLED from the cluster KV fabric
        # (a shared-prefix page another replica published to the
        # connector store) instead of being re-prefilled here
        self.prefix_pull_tokens = 0
        # ---- per-tenant attribution hooks (metrics/attribution.py):
        # host-int timestamp accounting of page occupancy — every
        # table-size change closes the previous (pages x elapsed)
        # interval into the per-tenant accumulator.  Pure monotonic
        # host arithmetic, zero device syncs; the engine drains the
        # accumulators into its heavy-hitter sketch each step.
        # request_id -> (pages, since_mono, tenant) for live HBM
        # tables; parked host-tier payloads tracked separately
        self._page_time: dict[str, tuple[int, float, str]] = {}
        self._park_time: dict[str, tuple[int, float, str]] = {}
        # tenant -> page·seconds accumulated since the last drain
        self._page_seconds: dict[str, float] = {}
        self._park_seconds: dict[str, float] = {}

    # ------------------------------------------------------------- queries
    def _pinned_pages(self) -> set[int]:
        return {p for p, c in self._pin_count.items() if c > 0}

    @property
    def num_free_pages(self) -> int:
        # evictable cached pages are allocatable on demand; pinned
        # pages are NOT (an in-flight transfer is still reading them)
        return len(self._free) + self.index.evictable(self._pinned_pages())

    def pages_needed(self, num_tokens: int) -> int:
        return -(-num_tokens // self.page_size)

    def block_table(self, request_id: str) -> list[int]:
        return list(self._tables.get(request_id, ()))

    def can_allocate(self, request: Request, num_new_tokens: int) -> bool:
        have = len(self._tables.get(request.request_id, ()))
        need = self.pages_needed(request.num_computed_tokens + num_new_tokens)
        return need - have <= self.num_free_pages

    @property
    def offload_enabled(self) -> bool:
        return self.tiers is not None and self.policy.mode != "never"

    def has_pending_moves(self) -> bool:
        return bool(self.pending_offloads or self.pending_restores)

    def take_request_hit(self, request_id: str) -> int:
        """Pop the request's recorded prefix-hit token count (0 when
        the prompt missed the cache entirely).  One-shot by design:
        the router's cache-economics join reads it exactly once.
        Deliberately NOT swept by free() — a prefill-tier engine frees
        the request before the router sees its output, so the entry
        must outlive the table; the LRU cap bounds unclaimed ids."""
        return self._request_hit_tokens.pop(request_id, 0)

    def _record_request_hit(self, request_id: str, matched: int) -> None:
        self._request_hit_tokens[request_id] = matched
        self._request_hit_tokens.move_to_end(request_id)
        while len(self._request_hit_tokens) > _REQUEST_HIT_CAP:
            self._request_hit_tokens.popitem(last=False)

    def debug_snapshot(self) -> dict:
        """JSON-ready occupancy view for /debug/kv (docs/debugging.md):
        page pool state, per-request table sizes, pin refcounts, radix
        node/tier counts, and the pending tier-move queues.  Read-only
        host bookkeeping — safe from the HTTP thread mid-step."""
        # C-level dict copies first: the engine thread mutates these
        # dicts mid-step while the /debug HTTP thread snapshots, and a
        # Python-level iteration over the live dicts could raise
        # "dictionary changed size during iteration"
        tables = dict(self._tables)
        pin_count = dict(self._pin_count)
        per_req_pins = dict(self._pinned)
        pinned = {p: c for p, c in pin_count.items() if c > 0}
        return {
            "pages_total": self.num_pages,
            "pages_free_list": len(self._free),
            "pages_allocatable": self.num_free_pages,
            "page_size": self.page_size,
            "cache_dtype": self.cache_dtype,
            "bytes_per_token": self.bytes_per_token,
            "tables": {rid: len(pages)
                       for rid, pages in sorted(tables.items())},
            "pins": {
                "pages_pinned": len(pinned),
                "refcounts": {str(p): c
                              for p, c in sorted(pinned.items())},
                "by_request": {rid: len(pages) for rid, pages
                               in sorted(per_req_pins.items())},
            },
            "prefix_index": (self.index.debug_stats()
                             if self.enable_prefix_caching
                             else {"enabled": False}),
            "pending_moves": {
                "offloads": len(self.pending_offloads),
                "restores": len(self.pending_restores),
                "extract_in_flight": len(self._extract_in_flight),
            },
            "counters": {
                "prefix_hits": self.prefix_hits,
                "prefix_hit_tokens": self.prefix_hit_tokens,
                "restored_tokens": self.restored_tokens,
                "parked_tokens": self.parked_tokens,
                "offload_evictions": self.offload_evictions,
                "drop_evictions": self.drop_evictions,
                "streamed_tokens": self.streamed_tokens,
                "prefix_pull_tokens": self.prefix_pull_tokens,
            },
        }

    # ------------------------------------------------ tenant attribution
    def _stamp_pages(self, request: Request) -> None:
        """Close the request's open (pages x elapsed) HBM interval into
        the per-tenant accumulator and re-open it at the CURRENT table
        size (0 pages closes for good).  Called at every table-size
        change; the interval's tenant is captured at open so a free()
        after the request object is otherwise forgotten still lands on
        the right tenant."""
        rid = request.request_id
        now = time.monotonic()
        prev = self._page_time.pop(rid, None)
        if prev is not None:
            pages, since, tenant = prev
            self._page_seconds[tenant] = (
                self._page_seconds.get(tenant, 0.0)
                + pages * (now - since))
        else:
            tenant = getattr(request, "tenant", "default")
        n = len(self._tables.get(rid, ()))
        if n:
            self._page_time[rid] = (n, now, tenant)

    def _close_park(self, request: Request) -> None:
        """Close the request's parked host-tier interval (restore or
        drop)."""
        prev = self._park_time.pop(request.request_id, None)
        if prev is not None:
            pages, since, tenant = prev
            self._park_seconds[tenant] = (
                self._park_seconds.get(tenant, 0.0)
                + pages * (time.monotonic() - since))

    def drain_page_seconds(self) -> dict[str, dict[str, float]]:
        """Per-tenant KV page·seconds accumulated since the last drain,
        per tier: ``{"hbm": {tenant: s}, "host": {tenant: s}}``.  Live
        intervals are folded up to now and re-stamped, so repeated
        drains partition time exactly (no interval is counted twice or
        dropped).  The engine calls this on its own thread each step
        and meters the result through its attribution sketch."""
        now = time.monotonic()
        for table, acc in ((self._page_time, self._page_seconds),
                           (self._park_time, self._park_seconds)):
            for rid, (pages, since, tenant) in table.items():
                acc[tenant] = (acc.get(tenant, 0.0)
                               + pages * (now - since))
                table[rid] = (pages, now, tenant)
        hbm, self._page_seconds = self._page_seconds, {}
        host, self._park_seconds = self._park_seconds, {}
        return {"hbm": hbm, "host": host}

    # ------------------------------------------------------- prefix cache
    def match_prefix(self, request: Request) -> int:
        """Adopt cached nodes covering the longest full-page prefix of
        the request's prompt; returns the number of tokens whose KV the
        request now starts with (``num_computed_tokens`` is updated and
        the pages seed its block table).  Cold nodes (KV parked in the
        host/remote tiers) are adopted too: a fresh page is allocated
        and a restore queued — the engine injects the payload before
        the forward attends it.  At least one prompt token is always
        left to compute — its forward produces the first logits.
        Embeds-based prompts never match (their placeholder ids carry
        no content)."""
        if (not self.enable_prefix_caching
                or request.prompt_embeds is not None
                or request.num_computed_tokens
                or request.request_id in self._tables):
            return 0
        # leave >= 1 token to compute; keys memoize on the request —
        # a head-of-queue request blocked on pages re-matches every
        # scheduler step and must not re-hash its whole prompt each time
        usable = len(request.prompt_token_ids) - 1
        keys = getattr(request, "_apc_keys", None)
        if keys is None:
            keys = self.index.page_keys(request.prompt_token_ids,
                                        max_pages=usable // self.page_size)
            request._apc_keys = keys
        nodes = self.index.match(keys=keys)
        if not nodes:
            return 0
        # acquire the WHOLE match up front: referenced nodes are
        # invisible to eviction, so allocating pages for cold restores
        # below can never evict a node this very match adopted
        for node in nodes:
            self.index.acquire(node)
        adopted: list[RadixNode] = []
        restores: list[PendingRestore] = []
        restored = 0
        dead: Optional[RadixNode] = None
        for pos, node in enumerate(nodes):
            if node.page is None:
                # cold node: verify the payload still exists (the host
                # tier may have shed it with no remote edge), then give
                # it fresh HBM storage and queue the restore.  A key
                # whose extraction is queued-but-undrained (evicted
                # EARLIER IN THIS VERY schedule pass) counts as alive:
                # the engine drains extractions before restores, so
                # the payload exists by fetch time
                if (self.tiers is None
                        or not (self.tiers.has(node.key)
                                or node.key in self._extract_in_flight)):
                    dead = node
                    break
                page = self._take_free_page()
                if page is None:
                    break
                self.index.rebind_page(node, page)
                restores.append(PendingRestore(
                    request_id=request.request_id, key=node.key,
                    pages=[page], n_tokens=self.page_size,
                    start_tokens=pos * self.page_size,
                    nodes=[node]))
                restored += self.page_size
            adopted.append(node)
        for node in nodes[len(adopted):]:
            self.index.release(node)
        if (dead is not None and dead.ref == 0
                and not dead.children):
            # unbacked cold leaf: its payload is gone for good — drop
            # it so later matches don't keep stubbing their toe on it
            # (interior unbacked nodes stay: dropping them would
            # orphan live descendants)
            self.index.drop(dead)
        if not adopted:
            return 0
        self.pending_restores.extend(restores)
        self._adopted[request.request_id] = adopted
        self._tables[request.request_id] = [n.page for n in adopted]
        matched = len(adopted) * self.page_size
        request.num_computed_tokens = matched
        self.prefix_hits += 1
        self.prefix_hit_tokens += matched
        self._record_request_hit(request.request_id, matched)
        self.restored_tokens += restored
        self._stamp_pages(request)
        return matched

    def reset_prefix_cache(self) -> int:
        """Drop EVERY unreferenced cached node back to the free pool
        and purge the WHOLE tier store (reference: reset_prefix_cache
        during pause_generation, async_omni.py:771 — weight updates
        invalidate cached KV).  Nodes still referenced by live
        requests stay, but every cold copy is stale after a weight
        swap — in-tree keys, restored hot nodes' dedup copies, and
        ``park/{rid}`` preemption runs alike — so all tier payloads
        and queued extractions go: a parked victim falls back to
        recompute under the new weights, and a pending restore fails
        its fetch and unwinds through the normal lost-payload path.
        Returns the number of HBM pages released."""
        freed, _ = self.index.reset(self._pinned_pages())
        self._free.extend(freed)
        if self.tiers is not None:
            self.tiers.clear()
        self.pending_offloads = []
        self._extract_in_flight.clear()
        return len(freed)

    # ----------------------------------------------------------- eviction
    def _take_free_page(self) -> Optional[int]:
        if self._free:
            return self._free.pop()
        return self._evict_one()

    def _evict_one(self) -> Optional[int]:
        """Reclaim the LRU unreferenced, unpinned cached page.  When the
        offload policy says the page's KV earns its transfer, the node
        goes COLD (stays matchable; extraction queued for the engine to
        drain before the page is overwritten); otherwise the node — and
        its now-unmatchable cold subtree — drops outright."""
        node = self.index.pick_victim(self._pinned_pages())
        if node is None:
            return None
        if (self.offload_enabled
                and self.policy.worth_offloading_page(self.page_size)):
            page = node.page
            if not (self.tiers.has(node.key)
                    or node.key in self._extract_in_flight):
                # content not parked yet: extract before reuse.  The
                # in-flight mark keeps a same-pass match_prefix from
                # mistaking the node for dead (payload lands at drain)
                self.pending_offloads.append(PendingOffload(
                    key=node.key, pages=[page],
                    n_tokens=self.page_size))
                self._extract_in_flight.add(node.key)
            self.index.mark_cold(node, TIER_HOST)
            self.offload_evictions += 1
            return page
        page, purge = self.index.drop(node)
        doomed = set(purge)
        doomed.add(node.key)
        if self.tiers is not None:
            for key in doomed:
                self.tiers.drop(key)
        # an extraction queued for a now-dropped key must not land:
        # the drain would park its payload under a key no node
        # references — an unreachable orphan (and, keys being
        # content-addressed, a stale hit for a same-content node
        # offloaded after a weight reset)
        if self.pending_offloads:
            self.pending_offloads = [
                o for o in self.pending_offloads if o.key not in doomed]
        self._extract_in_flight -= doomed
        self.drop_evictions += 1
        return page

    # ---------------------------------------------------------- allocation
    def allocate(self, request: Request, num_new_tokens: int) -> Optional[list[int]]:
        """Grow the request's table to cover ``num_computed_tokens +
        num_new_tokens``; returns the full table, or None if out of
        pages.  Failure is side-effect free: partial growth rolls back
        and a table entry that didn't pre-exist is removed — a stale
        empty entry would permanently disable ``match_prefix`` for the
        request (its guard treats any registered table as already
        matched)."""
        rid = request.request_id
        fresh = rid not in self._tables
        table = self._tables.setdefault(rid, [])
        need = self.pages_needed(request.num_computed_tokens + num_new_tokens)
        grow = need - len(table)
        base = len(table)
        ok = grow <= self.num_free_pages
        if ok:
            for _ in range(max(grow, 0)):
                page = self._take_free_page()
                if page is None:
                    ok = False
                    break
                table.append(page)
        if not ok:
            self._free.extend(table[base:])
            del table[base:]
            if fresh:
                del self._tables[rid]
            return None
        if grow > 0:
            self._stamp_pages(request)
        return list(table)

    def adopt_streamed(self, request: Request, n_tokens: int
                       ) -> Optional[list[int]]:
        """Streamed-page admission (disaggregated prefill): allocate
        pages for ``n_tokens`` of KV that a PEER engine computed and is
        about to inject — the decode tier's receive half.  Same failure
        contract as ``allocate`` (None = out of pages, side-effect
        free); the caller injects the payload before any forward
        attends the pages, then calls ``note_streamed`` — counting at
        allocation would claim tokens the injection later rejected."""
        return self.allocate(request, n_tokens)

    def note_streamed(self, n_tokens: int) -> None:
        """Count tokens whose KV actually INJECTED from a peer engine
        (vs. prefix-cache or tier-restore adoption) — /debug/kv's
        answer to where a decode tier's KV came from."""
        self.streamed_tokens += n_tokens

    def adopt_prefix(self, request: Request, n_tokens: int
                     ) -> Optional[list[int]]:
        """Fabric-pull admission (cluster KV fabric, PR 19): allocate
        pages for ``n_tokens`` of a shared-prefix payload fetched from
        the connector store — the same side-effect-free contract as
        ``adopt_streamed``, kept as a distinct entry so the two KV
        provenances (peer handoff vs fabric pull) stay separately
        accountable.  The payload rode the kv_transfer integrity/
        deadline guards on the way in; the caller injects before any
        forward attends the pages, then calls ``note_pulled``."""
        return self.allocate(request, n_tokens)

    def note_pulled(self, n_tokens: int) -> None:
        """Count tokens whose KV actually INJECTED from a fabric pull
        (a prefix a sibling replica published) — the saved-re-prefill
        half of /debug/kv's provenance story."""
        self.prefix_pull_tokens += n_tokens

    def slot_mapping(self, request: Request, num_new_tokens: int) -> list[int]:
        """Flat slots (page*page_size + offset) for the next
        ``num_new_tokens`` tokens starting at num_computed_tokens."""
        table = self._tables[request.request_id]
        start = request.num_computed_tokens
        slots = []
        for i in range(num_new_tokens):
            pos = start + i
            slots.append(table[pos // self.page_size] * self.page_size
                         + pos % self.page_size)
        return slots

    # ---------------------------------------------------------------- free
    def free(self, request: Request) -> None:
        """Release the request's pages — unless a KV transfer pinned
        them (then they are released by ack_transfer).  Full prompt
        pages register in the radix index instead of returning to the
        free pool (they remain allocatable via LRU eviction); adopted
        shared nodes drop this request's reference."""
        table = self._tables.pop(request.request_id, None)
        if table is None:
            return
        for node in self._adopted.pop(request.request_id, ()):
            self.index.release(node)
        owned = set(self.index._by_page)
        private = [p for p in table if p not in owned]
        consumed: set[int] = set()
        if (self.enable_prefix_caching
                and request.prompt_embeds is None):
            # register this request's full, computed PROMPT pages (pages
            # become shareable once their producer completes); the
            # insert consumes only pages backing NEW nodes — positions
            # already cached by another producer free normally
            valid = min(
                len(request.prompt_token_ids) // self.page_size,
                request.num_computed_tokens // self.page_size,
                len(table))
            consumed = self.index.insert(
                request.prompt_token_ids, table[:valid], max_pages=valid)
        pinned = self._pinned_pages()
        for page in private:
            if page in consumed:
                continue  # the index owns it now (evictable, unpinned)
            if page in pinned:
                continue  # released by ack_transfer
            self._free.append(page)
        # table gone: closes the request's HBM page·seconds interval
        self._stamp_pages(request)

    # -------------------------------------------------------- park/restore
    def park_request(self, request: Request) -> int:
        """Preemption offload: queue the request's computed KV run for
        extraction to the host tier instead of discarding it (the
        engine drains the extraction this very step, before the freed
        pages are overwritten).  Returns the parked token count, or 0
        when parking is off / not worth the bytes."""
        if not self.offload_enabled:
            return 0
        # park only positions whose tokens are COMMITTED (host-visible):
        # an async in-flight step's sampled token will be discarded by
        # the lagged retire, so its KV slot may describe a token the
        # recompute re-samples differently — exclude the in-flight
        # slots and always leave >= 1 position to compute on resume
        seq_len = min(
            request.num_computed_tokens - request.num_inflight_tokens,
            request.num_tokens - 1)
        if seq_len <= 0 or not self.policy.worth_offloading(seq_len):
            return 0
        table = self._tables.get(request.request_id)
        if not table:
            return 0
        keep = self.pages_needed(seq_len)
        if keep > len(table):
            return 0
        key = park_key(request.request_id)
        self.pending_offloads.append(PendingOffload(
            key=key, pages=list(table[:keep]), n_tokens=seq_len))
        self._extract_in_flight.add(key)
        request.additional_information["_parked_len"] = seq_len
        self.parked_tokens += seq_len
        # host-tier occupancy interval opens at park (closed by
        # restore_parked / drop_park)
        self._park_time[request.request_id] = (
            keep, time.monotonic(), getattr(request, "tenant",
                                            "default"))
        return seq_len

    def park_in_flight(self, request: Request) -> bool:
        """The request's park extraction is queued but not yet drained
        (its payload can't be fetched yet — admission waits a step)."""
        return park_key(request.request_id) in self._extract_in_flight

    def note_park_extracted(self, key: str) -> None:
        self._extract_in_flight.discard(key)

    def parked_available(self, request: Request) -> bool:
        """The parked payload can be fetched right now (extraction
        drained and the tiers still hold it)."""
        return (self.tiers is not None
                and self.tiers.has(park_key(request.request_id)))

    def restore_parked(self, request: Request) -> bool:
        """Re-admit a parked request: allocate pages for its parked run,
        queue the injection, and fast-forward ``num_computed_tokens`` —
        the recompute the park exists to avoid.  Returns False when the
        payload is gone or pages don't fit (caller decides whether to
        wait or recompute)."""
        parked = request.additional_information.get("_parked_len", 0)
        key = park_key(request.request_id)
        if (not parked or self.tiers is None
                or not self.tiers.has(key)):
            return False
        table = self.allocate(request, parked)
        if table is None:
            return False
        self.pending_restores.append(PendingRestore(
            request_id=request.request_id, key=key,
            pages=table[: self.pages_needed(parked)], n_tokens=parked,
            drop_after=True))
        request.num_computed_tokens = parked
        request.additional_information.pop("_parked_len", None)
        self.restored_tokens += parked
        self._close_park(request)
        return True

    def drop_park(self, request: Request) -> None:
        """Forget a parked payload (request finished/aborted/errored
        while parked)."""
        request.additional_information.pop("_parked_len", None)
        key = park_key(request.request_id)
        self._extract_in_flight.discard(key)
        self.pending_offloads = [
            o for o in self.pending_offloads if o.key != key]
        if self.tiers is not None:
            self.tiers.drop(key)
        self._close_park(request)

    def take_pending_moves(self) -> tuple[list[PendingOffload],
                                          list[PendingRestore]]:
        offloads, self.pending_offloads = self.pending_offloads, []
        restores, self.pending_restores = self.pending_restores, []
        return offloads, restores

    def restore_failed_entries(self, request: Request,
                               failed: list[PendingRestore],
                               keep_tokens: int) -> dict[str, int]:
        """A restore came up short at drain time: the ``failed``
        entries' payloads never injected, so their nodes are bound to
        GARBAGE pages — unwind them back to cold (a later-entry payload
        may still exist and restore fine next time; the truly lost one
        is pruned by the has() check at the next match), then rewind
        the request to the contiguous ``keep_tokens`` prefix.

        Returns ``{request_id: keep_tokens}`` for OTHER requests that
        co-adopted a failed node: a second request admitted in the same
        schedule pass saw the rebound node hot (page set) and adopted
        it with NO restore entry of its own — its table references the
        same garbage page.  The caller must truncate each co-adopter at
        its first failed node and drop its scheds this step, or it
        executes attending never-injected KV (and the page, freed by
        this request's truncation, could be re-allocated while still in
        the co-adopter's table — silent cross-request corruption)."""
        failed_nodes = {id(n) for e in failed for n in e.nodes}
        released: list[int] = []
        for e in failed:
            for node in e.nodes:
                if node.page is not None:
                    # usually the page stays in the request's table and
                    # the truncate below frees it; ``released`` catches
                    # the rest (e.g. this request was already truncated
                    # as a co-adopter of an earlier failure this drain)
                    released.append(self.index.mark_cold(node, TIER_HOST))
        co: dict[str, int] = {}
        for rid, adopted in self._adopted.items():
            if rid == request.request_id:
                continue
            cut = next((i for i, n in enumerate(adopted)
                        if id(n) in failed_nodes), None)
            if cut is not None:
                co[rid] = cut * self.page_size
        self.restore_truncated(request, keep_tokens)
        if released:
            pinned = self._pinned_pages()
            live = {p for t in self._tables.values() for p in t}
            unplaced = set(self._free)
            for page in released:
                if (page in pinned or page in unplaced
                        or page in self.index._by_page or page in live):
                    continue
                self._free.append(page)
                unplaced.add(page)
        return co

    def restore_truncated(self, request: Request, keep_tokens: int
                          ) -> None:
        """Keep the contiguous ``keep_tokens`` prefix that is actually
        valid, release everything after it, and rewind
        ``num_computed_tokens`` so the scheduler recomputes the rest."""
        rid = request.request_id
        keep_pages = self.pages_needed(keep_tokens)
        table = self._tables.get(rid, [])
        adopted = self._adopted.get(rid, [])
        owned = set(self.index._by_page)
        for node in adopted[keep_pages:]:
            self.index.release(node)
        self._adopted[rid] = adopted[:keep_pages]
        self._tables[rid] = table[:keep_pages]
        pinned = self._pinned_pages()
        live = {p for t in self._tables.values() for p in t}
        for page in table[keep_pages:]:
            if page in owned or page in pinned:
                continue
            if page in live:
                # a co-adopter of the same failed-restore node still
                # references it — the LAST truncation frees it
                continue
            self._free.append(page)
        request.num_computed_tokens = min(request.num_computed_tokens,
                                          keep_tokens)
        self._stamp_pages(request)

    # --------------------------------------------------------- transfers
    def pin_for_transfer(self, request: Request, seq_len: int) -> list[int]:
        """Snapshot + pin the pages holding the first ``seq_len`` tokens
        (reference: block-id snapshot truncated to seq_len,
        omni_ar_scheduler.py:553-594).  Pins are GLOBAL refcounts:
        however the page is also owned (live table, shared cache node),
        it cannot be evicted or freed until ``ack_transfer``."""
        table = self._tables.get(request.request_id, [])
        keep = self.pages_needed(seq_len)
        snapshot = table[:keep]
        self._pinned[request.request_id] = list(snapshot)
        for page in snapshot:
            self._pin_count[page] = self._pin_count.get(page, 0) + 1
        return list(snapshot)

    def ack_transfer(self, request_id: str) -> None:
        """Extraction ACK: unpin the snapshot; pages owned by nobody
        else (no live table, not a cache node) return to the free pool
        (reference: free on kv_extracted_req_ids,
        omni_ar_scheduler.py:444).  Cached nodes simply become
        evictable again now that the pin is gone."""
        pinned = self._pinned.pop(request_id, [])
        live: Optional[set[int]] = None
        for page in pinned:
            c = self._pin_count.get(page, 0) - 1
            if c > 0:
                self._pin_count[page] = c
                continue
            self._pin_count.pop(page, None)
            if page in self.index._by_page:
                continue  # cache node: evictable via the index now
            if live is None:
                # built once per ack, not per page: a long pinned
                # snapshot over many live tables must not go quadratic
                live = {p for t in self._tables.values() for p in t}
            if page in live:
                continue  # still part of a live table
            self._free.append(page)
