"""Logging for the TPU-native omni framework.

Mirrors the behaviour of the reference's logger (vllm_omni/logger.py): a
package-scoped logger with an optional per-stage prefix taken from the
environment, so logs from disaggregated stage processes are distinguishable.
"""

from __future__ import annotations

import logging
import os
import sys

_FORMAT = "%(levelname)s %(asctime)s [%(name)s] %(message)s"
_DATEFMT = "%m-%d %H:%M:%S"

_initialized = False


def _init_root() -> None:
    global _initialized
    if _initialized:
        return
    from vllm_omni_tpu import envs

    handler = logging.StreamHandler(sys.stderr)
    # Escape % so an arbitrary prefix can't break the format string.
    prefix = envs.OMNI_TPU_LOGGING_PREFIX.replace("%", "%%")
    handler.setFormatter(logging.Formatter(prefix + _FORMAT, datefmt=_DATEFMT))
    root = logging.getLogger("vllm_omni_tpu")
    root.addHandler(handler)
    root.setLevel(envs.OMNI_TPU_LOG_LEVEL.upper())
    root.propagate = False
    _initialized = True


def init_logger(name: str) -> logging.Logger:
    """Return a logger under the package hierarchy (``vllm_omni_tpu.*``)."""
    _init_root()
    if not name.startswith("vllm_omni_tpu"):
        name = "vllm_omni_tpu." + name
    return logging.getLogger(name)
