"""CLI: ``python -m vllm_omni_tpu.entrypoints.cli serve|generate|bench``.

The TPU-native analogue of the reference's ``vllm serve <model> --omni``
interception (reference: entrypoints/cli/main.py:10-17, OmniServeCommand
cli/serve.py:42 with diffusion autodetect :55-63).
"""

from __future__ import annotations

import argparse
import json
import sys


def _add_common(p: argparse.ArgumentParser):
    p.add_argument("model", nargs="?", default=None,
                   help="model name/path (resolves an in-tree stage YAML)")
    p.add_argument("--stage-configs-path", default=None,
                   help="explicit stage-config YAML (overrides model lookup)")
    # reference-style engine arg surface (vllm serve flags; reference:
    # entrypoints/cli/serve.py + omni engine args) — applied to the
    # ENTRY stage; use --stage-override for other stages
    eng = p.add_argument_group("engine args (entry stage)")
    eng.add_argument("--tensor-parallel-size", type=int, default=None)
    eng.add_argument("--max-model-len", type=int, default=None)
    eng.add_argument("--max-num-seqs", type=int, default=None)
    eng.add_argument("--max-num-batched-tokens", type=int, default=None)
    eng.add_argument("--dtype", default=None,
                     help="bfloat16|float32|float16 — engine compute/"
                          "KV-cache dtype; WEIGHT dtype comes from the "
                          "stage YAML's model_factory_args")
    eng.add_argument("--seed", type=int, default=None)
    eng.add_argument("--enable-chunked-prefill", action="store_true",
                     default=None)
    eng.add_argument("--num-speculative-tokens", type=int, default=None)
    eng.add_argument("--async-scheduling", action="store_true",
                     default=None,
                     help="two-slot pipelined engine step: overlap host "
                          "scheduling/readback with device compute via "
                          "device-resident sampled tokens (see "
                          "docs/async_engine.md)")
    eng.add_argument("--unified-batching", action="store_true",
                     default=None,
                     help="unified scheduler packing policy: decodes "
                          "claim the token budget first and chunked "
                          "prefill becomes the mechanism.  Execution "
                          "is always unified — every non-pure-decode "
                          "step is ONE token-packed ragged dispatch "
                          "(see docs/ragged_batching.md)")
    eng.add_argument("--kv-offload", action="store_true", default=None,
                     help="tiered KV offload: evicted prefix-cache "
                          "pages and preempted requests park their KV "
                          "in a host-RAM pool (and optionally a remote "
                          "store) instead of recomputing (see "
                          "docs/kv_cache.md)")
    eng.add_argument("--kv-offload-quant", default=None,
                     choices=("none", "int8"),
                     help="cold-path payload storage: none keeps "
                          "restores bit-exact, int8 halves the bytes "
                          "over the host tunnel")
    eng.add_argument("--kv-cache-dtype", default=None,
                     choices=("auto", "int8", "bf16"),
                     help="HBM-RESIDENT paged-KV layout: int8 stores "
                          "the page pool as int8 + per-(head, page) "
                          "scales dequantized in-kernel — ~2x pages "
                          "(sessions) in the same HBM budget (see "
                          "docs/performance.md); auto/bf16 keep the "
                          "dense layout in the model dtype")
    eng.add_argument("--kv-offload-policy", default=None,
                     choices=("auto", "always", "never"),
                     help="bytes-vs-recompute admission: auto runs the "
                          "break-even math (kvcache/policy.py), "
                          "always/never pin the decision")
    eng.add_argument("--kv-host-tier-bytes", type=int, default=None,
                     help="host-RAM tier capacity; overflow demotes "
                          "LRU payloads to the remote connector (or "
                          "drops them without one)")
    eng.add_argument("--kv-offload-connector", default=None,
                     help="remote-tier transport: a connector name "
                          "(inproc|shm|tcp) wired with retry + circuit "
                          "breaker on the edge")
    eng.add_argument("--slo-ttft-ms", type=float, default=None,
                     help="per-request TTFT SLO target: finished "
                          "requests are judged against it per tenant "
                          "(slo_attainment_ratio / goodput_tokens_total "
                          "on /metrics; see docs/load_testing.md)")
    eng.add_argument("--slo-tpot-ms", type=float, default=None,
                     help="per-request TPOT (time per output token) "
                          "SLO target")
    eng.add_argument("--max-queue-depth", type=int, default=None,
                     help="admission control: arrivals past this "
                          "waiting-queue depth are shed with HTTP 429 "
                          "(shed_requests_total{reason=queue_depth}) "
                          "instead of queued into a wait they can only "
                          "lose")
    eng.add_argument("--wfq-scheduling", action="store_true",
                     default=None,
                     help="weighted-fair overload scheduling (docs/"
                          "control_plane.md): deficit-round-robin "
                          "admission over per-tenant priority weights "
                          "(x-omni-priority) and priority-ordered "
                          "shedding at max-queue-depth — low-priority "
                          "work defers under overload instead of "
                          "everyone starving equally")
    eng.add_argument("--engine-role", default=None,
                     choices=("prefill", "decode", "colocated"),
                     help="disaggregated serving role (docs/"
                          "disaggregation.md): prefill engines run "
                          "requests to the end of prompt processing "
                          "and ship paged KV to a decode tier "
                          "(kv_transfer auto-armed); decode engines "
                          "adopt streamed KV and resume as decode; "
                          "colocated (default) is the classic single-"
                          "engine shape")
    eng.add_argument("--deterministic-decode", action="store_true",
                     default=None,
                     help="pin decode batches to the top bucket so a "
                          "request's greedy stream is bit-stable under "
                          "co-batch churn (arrivals, preemptions, "
                          "offload restores); costs padded rows when "
                          "the batch runs small")
    p.add_argument(
        "--stats-path", default=None, metavar="PREFIX",
        help="stream per-stage + E2E stats to PREFIX.*.stats.jsonl")
    p.add_argument(
        "--trace-path", default=None, metavar="PREFIX",
        help="per-request distributed traces: PREFIX.trace.jsonl + "
             "PREFIX.trace.json (Perfetto-loadable Chrome trace); see "
             "docs/observability.md")
    p.add_argument(
        "--stage-override", action="append", default=[],
        metavar="N.KEY=VALUE",
        help="set engine_args KEY of stage N (repeatable); VALUE parses "
             "as JSON when possible, e.g. --stage-override "
             "2.num_steps=4 --stage-override 1.dtype='\"float32\"'")


_ENTRY_FLAGS = ("tensor_parallel_size", "max_model_len", "max_num_seqs",
                "max_num_batched_tokens", "dtype", "seed",
                "enable_chunked_prefill", "num_speculative_tokens",
                "async_scheduling", "unified_batching",
                "kv_offload", "kv_offload_quant", "kv_cache_dtype",
                "kv_offload_policy",
                "kv_host_tier_bytes", "kv_offload_connector",
                "slo_ttft_ms", "slo_tpot_ms", "max_queue_depth",
                "wfq_scheduling", "engine_role", "deterministic_decode")


def _stage_overrides(args) -> dict:
    """CLI flags -> the Omni constructor's per-stage override dict
    ({"stage0": {...}, "stage2": {...}})."""
    out: dict[str, dict] = {}
    entry = {k: getattr(args, k) for k in _ENTRY_FLAGS
             if getattr(args, k, None) is not None}
    if entry:
        out["stage0"] = entry
    for item in args.stage_override:
        try:
            target, val = item.split("=", 1)
            stage, key = target.split(".", 1)
            stage_key = f"stage{int(stage)}"
        except ValueError:
            raise SystemExit(
                f"--stage-override expects N.KEY=VALUE, got {item!r}")
        try:
            val = json.loads(val)
        except json.JSONDecodeError:
            pass  # keep the raw string
        out.setdefault(stage_key, {})[key] = val
    return out


def cmd_serve(args) -> int:
    from vllm_omni_tpu.entrypoints.openai.api_server import run_server

    run_server(
        model=args.model,
        stage_configs=args.stage_configs_path,
        host=args.host,
        port=args.port,
        stats_path=args.stats_path,
        trace_path=args.trace_path,
        **_stage_overrides(args),
    )
    return 0


def cmd_generate(args) -> int:
    from vllm_omni_tpu.entrypoints.omni import Omni

    omni = Omni(model=args.model, stage_configs=args.stage_configs_path,
                stats_path=args.stats_path, trace_path=args.trace_path,
                **_stage_overrides(args))
    sp = json.loads(args.sampling_params) if args.sampling_params else {}
    outs = omni.generate([args.prompt], [sp])
    for o in outs:
        if o.final_output_type == "text" and o.outputs:
            print(o.outputs[0].text or o.outputs[0].token_ids)
        elif o.final_output_type == "image" and o.images:
            import numpy as np

            path = f"{o.request_id}.npy"
            np.save(path, np.asarray(o.images[0]))
            print(f"image saved to {path}")
        elif "audio" in o.multimodal_output:
            import numpy as np

            path = f"{o.request_id}.f32"
            np.asarray(o.multimodal_output["audio"],
                       dtype=np.float32).tofile(path)
            print(f"audio saved to {path}")
    print(json.dumps(omni.metrics.summary(), indent=2), file=sys.stderr)
    return 0


def cmd_bench(args) -> int:
    import os
    import runpy

    # bench.py lives at the repo root, three levels above this module
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    bench_path = os.path.join(repo_root, "bench.py")
    runpy.run_path(bench_path, run_name="__main__")
    return 0


def cmd_serve_stage(args) -> int:
    from vllm_omni_tpu.entrypoints.stage_proc import run_remote_stage

    run_remote_stage(
        args.stage_configs, args.stage_id,
        connect=args.connect, discover=args.discover,
        retry_timeout=args.retry_timeout,
    )
    return 0


def cmd_bench_serve(args) -> int:
    from vllm_omni_tpu.benchmarks.serving import run_from_args

    return run_from_args(args)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="vllm-omni-tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    serve = sub.add_parser("serve", help="start the OpenAI-compatible server")
    _add_common(serve)
    serve.add_argument("--host", default="0.0.0.0")
    serve.add_argument("--port", type=int, default=8000)
    serve.set_defaults(fn=cmd_serve)

    gen = sub.add_parser("generate", help="offline one-shot generation")
    _add_common(gen)
    gen.add_argument("--prompt", required=True)
    gen.add_argument("--sampling-params", default=None,
                     help='JSON, e.g. \'{"max_tokens": 32}\'')
    gen.set_defaults(fn=cmd_generate)

    bench = sub.add_parser("bench", help="run the repo benchmark")
    bench.set_defaults(fn=cmd_bench)

    bserve = sub.add_parser(
        "bench-serve",
        help="online serving benchmark against a running server "
             "(latency percentiles; reference: vllm bench serve --omni)",
    )
    from vllm_omni_tpu.benchmarks.serving import add_cli_args

    add_cli_args(bserve)
    bserve.set_defaults(fn=cmd_bench_serve)

    sstage = sub.add_parser(
        "serve-stage",
        help="run one pipeline stage as a REMOTE worker connecting to an "
             "orchestrator on another host (cross-host stage placement; "
             "reference: Ray per-node workers, ray_utils/utils.py)",
    )
    sstage.add_argument("--stage-configs", required=True,
                        help="stage YAML (same file the orchestrator uses)")
    sstage.add_argument("--stage-id", type=int, required=True)
    sstage.add_argument("--connect", default=None,
                        help="orchestrator listener host:port")
    sstage.add_argument("--discover", default=None,
                        help="KV-store address publishing stage listeners")
    sstage.add_argument("--retry-timeout", type=float, default=120.0)
    sstage.set_defaults(fn=cmd_serve_stage)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
