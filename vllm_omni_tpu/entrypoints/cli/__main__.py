from vllm_omni_tpu.entrypoints.cli.main import main

if __name__ == "__main__":
    main()
