"""AsyncOmni: online multi-stage orchestrator with per-request streaming.

Behavioral port of the reference's AsyncOmni (reference:
entrypoints/async_omni.py:60 — per-request asyncio streaming over the same
stage pipeline, output-handler task, abort).  The in-proc TPU build steps
the stages on a dedicated engine thread (the analogue of the reference's
stage worker processes) and bridges results into per-request asyncio queues
via ``loop.call_soon_threadsafe`` — request intake and SSE streaming stay
non-blocking on the server's event loop.
"""

from __future__ import annotations

import asyncio
import itertools
import queue
import threading
from typing import Any, AsyncIterator, Optional, Union

from vllm_omni_tpu.analysis.runtime import traced
from vllm_omni_tpu.config.stage import StageConfig
from vllm_omni_tpu.entrypoints.omni import Omni
from vllm_omni_tpu.entrypoints.omni_stage import StageRequest
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.outputs import OmniRequestOutput

logger = init_logger(__name__)

_SENTINEL = object()


class AsyncOmni:
    def __init__(
        self,
        model: Optional[str] = None,
        stage_configs: Optional[Union[str, list[StageConfig]]] = None,
        **overrides: Any,
    ):
        # reuse the sync orchestrator's stage construction + dataflow
        self._omni = Omni(model=model, stage_configs=stage_configs,
                          **overrides)
        self._n_finals = sum(
            1 for s in self._omni.stages if s.config.final_output
        )
        self._intake: queue.Queue = queue.Queue()
        # request_id -> (event loop, asyncio.Queue)
        self._streams: dict[str, tuple[asyncio.AbstractEventLoop,
                                       asyncio.Queue]] = {}
        self._finals_seen: dict[str, int] = {}
        self._req_counter = itertools.count()
        self._running = True
        # pause gate (reference: pause_generation/resume_generation,
        # async_omni.py:739-782): a threading.Event so requests arriving
        # on ANY event loop and the engine thread agree on the state.
        # _pause_lock closes the gate-check -> stream-registration race:
        # a generate() that saw the gate open registers its stream
        # INSIDE the lock, so a pause clearing the event (also inside
        # the lock) is guaranteed to see it in _streams
        self._resume_event = threading.Event()
        self._resume_event.set()
        self._pause_lock = traced(threading.Lock(),
                                  "AsyncOmni._pause_lock")
        # engine-level stats heartbeat period (seconds); tests shrink it
        self._stats_interval = 10.0
        self._thread = threading.Thread(target=self._engine_loop,
                                        daemon=True, name="omni-engine")
        self._thread.start()

    # ----------------------------------------------------------- lifecycle
    def shutdown(self) -> None:
        self._running = False
        self._thread.join(timeout=10)
        self._omni.watchdog.stop()
        self._omni.alerts.stop()
        # final drain + the one Chrome-document export (the heartbeat
        # only streams JSONL)
        self._omni.flush_traces()

    @property
    def stage_configs(self):
        return self._omni.stage_configs

    @property
    def metrics(self):
        return self._omni.metrics

    @property
    def watchdog(self):
        """The orchestrator's stall watchdog (introspection)."""
        return self._omni.watchdog

    @property
    def alerts(self):
        """The orchestrator's alert engine (metrics/alerts.py)."""
        return self._omni.alerts

    @property
    def engine_thread_alive(self) -> bool:
        """Liveness of the engine loop thread — the /health answer to
        "is anything still stepping the stages"."""
        return self._thread.is_alive()

    def start_profile(self, trace_dir: str) -> None:
        """Fan a jax.profiler trace out to every stage (reference:
        profile RPC chain, omni.py:398-497)."""
        self._omni.start_profile(trace_dir)

    def stop_profile(self) -> None:
        self._omni.stop_profile()

    # ------------------------------------------------------- pause/resume
    async def pause_generation(
        self,
        *,
        wait_for_inflight_requests: bool = False,
        clear_cache: bool = True,
    ) -> None:
        """Pause generation for a weight update (reference:
        AsyncOmni.pause_generation, async_omni.py:739-773).  New
        requests block in ``generate`` until ``resume_generation``.

        ``wait_for_inflight_requests``: True drains in-flight requests
        first; False (default) aborts them immediately.
        ``clear_cache``: drop every stage engine's unreferenced APC page
        (cached KV is stale once weights change)."""
        with self._pause_lock:
            if not self._resume_event.is_set():
                return
            self._resume_event.clear()
        if wait_for_inflight_requests:
            while self._streams or not self._intake.empty():
                await asyncio.sleep(0.005)
        else:
            for rid in list(self._streams):
                self.abort(rid)
        if clear_cache:
            # even in abort mode the STAGES keep draining aborted work
            # (stream abort is best-effort); a reset before it finishes
            # would let freed pages re-register pre-swap KV into the
            # cache — wait for every stage (its _pending queue AND its
            # engine, stage.has_unfinished) to go idle first
            while (not self._intake.empty()
                   or any(getattr(s, "has_unfinished", False)
                          for s in self._omni.stages)):
                await asyncio.sleep(0.005)
            released = 0
            for stage in self._omni.stages:
                eng = getattr(stage, "engine", None)
                fn = getattr(eng, "reset_prefix_cache", None)
                if fn is not None:
                    released += fn()
            logger.info("paused: %d prefix-cache pages released",
                        released)

    async def resume_generation(self) -> None:
        """Unblock requests waiting behind ``pause_generation``."""
        self._resume_event.set()

    async def is_paused(self) -> bool:
        return not self._resume_event.is_set()

    # -------------------------------------------------------------- intake
    async def generate(
        self,
        prompt: Union[str, list[int], dict],
        sampling_params: Optional[dict] = None,
        request_id: Optional[str] = None,
        deadline_s: Optional[float] = None,
    ) -> AsyncIterator[OmniRequestOutput]:
        """Submit one request; yields one OmniRequestOutput per final stage
        (reference: AsyncOmni.generate, async_omni.py:235).
        ``deadline_s`` bounds the request end-to-end; expiry surfaces as
        a ``deadline_exceeded`` error output (HTTP 504 at the server)."""
        if request_id is None:
            request_id = f"async-{next(self._req_counter)}"
        elif request_id in self._streams:
            raise ValueError(f"request_id {request_id!r} already in flight")
        # pause gate: block intake until resume_generation (reference:
        # "New generation/encoding requests are blocked until resume").
        # The gate check and the stream registration below share
        # _pause_lock so a concurrent pause either sees this request in
        # _streams or blocks it here — never neither.
        sp = dict(sampling_params or {})
        if isinstance(prompt, dict):
            req = StageRequest(request_id=request_id, sampling_params=sp,
                               **prompt)
        elif isinstance(prompt, str):
            req = StageRequest(request_id=request_id, prompt=prompt,
                               sampling_params=sp)
        else:
            req = StageRequest(request_id=request_id,
                               prompt_token_ids=list(prompt),
                               sampling_params=sp)
        # trace context + deadline BEFORE enqueue: the engine thread may
        # drain the intake the instant the put lands.  A caller-supplied
        # trace_id (the server's traceparent / x-omni-trace-id join)
        # rides additional_information and is consumed here — the
        # journey continues the external trace instead of a fresh id
        req.trace = self._omni.trace_begin(
            request_id,
            trace_id=req.additional_information.pop("trace_id", None))
        req.deadline_s = self._omni.deadline_begin(
            request_id,
            req.deadline_s if req.deadline_s is not None else deadline_s)
        loop = asyncio.get_running_loop()
        out_q: asyncio.Queue = asyncio.Queue()
        while True:
            with self._pause_lock:
                if self._resume_event.is_set():
                    # re-check the duplicate guard HERE: two same-id
                    # calls parked behind a pause both passed the early
                    # check; the second must fail, not silently steal
                    # the first's stream
                    if request_id in self._streams:
                        raise ValueError(
                            f"request_id {request_id!r} already in "
                            "flight")
                    self._streams[request_id] = (loop, out_q)
                    self._finals_seen[request_id] = 0
                    # enqueue INSIDE the lock: a put after release could
                    # slip past a concurrent pause's intake-empty check
                    # and run mid-weight-swap.  The queue is unbounded,
                    # so the put never actually blocks:
                    # omnilint: disable=OL9 - unbounded queue put;
                    # in-lock enqueue is the pause-gate invariant
                    self._intake.put(req)
                    break
            if not self._running:
                raise RuntimeError(
                    "AsyncOmni is shut down; request rejected while "
                    "paused")
            await asyncio.sleep(0.01)
        self._omni.metrics.record_arrival(request_id)
        try:
            while True:
                item = await out_q.get()
                if item is _SENTINEL:
                    return
                if isinstance(item, Exception):
                    raise item
                yield item
        finally:
            self._streams.pop(request_id, None)
            self._finals_seen.pop(request_id, None)

    def abort(self, request_id: str) -> None:
        """Best-effort abort: drop the stream; in-flight stage work for the
        request completes and is discarded."""
        entry = self._streams.pop(request_id, None)
        if entry is not None:
            loop, q = entry
            loop.call_soon_threadsafe(q.put_nowait, _SENTINEL)
        self._omni.trace_finish(request_id)

    # --------------------------------------------------------- engine loop
    def _emit(self, request_id: str, item) -> None:
        entry = self._streams.get(request_id)
        if entry is None:
            return
        loop, q = entry
        loop.call_soon_threadsafe(q.put_nowait, item)

    def _engine_loop(self) -> None:
        omni = self._omni
        entry_stages = [s for s in omni.stages
                        if -1 in s.config.engine_input_source]
        entry_stage = entry_stages[0] if entry_stages else omni.stages[0]
        import time as _time

        # periodic engine-level stats heartbeat (reference: the
        # do_log_stats keep-alive task, omni_stage.py:1134-1146)
        last_stats = _time.monotonic()
        while self._running:
            now = _time.monotonic()
            if now - last_stats >= self._stats_interval:
                last_stats = now
                # harvest stage request stats continuously (the offline
                # path collects at end-of-generate) so long-running
                # servers aggregate + stream jsonl as they go
                omni.harvest_stage_stats()
                # JSONL only: the full Chrome rewrite is shutdown-time
                # work, not something to run on the engine thread
                omni.flush_traces(export_chrome=False)
                if self._streams:
                    summ = omni.stats_summary()
                    logger.info(
                        "stats: %d in flight, e2e p50 %.0fms, stages %s",
                        len(self._streams), summ["e2e"]["p50_ms"],
                        {i: st["tps"]
                         for i, st in summ["stages"].items()},
                    )
            # 1. drain intake
            pending = []
            try:
                while True:
                    pending.append(self._intake.get_nowait())
            except queue.Empty:
                pass
            if pending:
                try:
                    entry_stage.submit(pending)
                except Exception as e:  # bad request payloads
                    for r in pending:
                        omni.trace_finish(r.request_id)
                        self._emit(r.request_id, e)
                        self._emit(r.request_id, _SENTINEL)
            # 2. step stages + forward
            progressed = False
            for stage in omni.stages:
                try:
                    outs = stage.poll()
                except Exception as e:
                    # last resort: a poll failure can't be attributed to one
                    # request (engine-level starvation is error-finished per
                    # request inside LLMEngine.step and arrives as outputs)
                    logger.exception("stage %d poll failed", stage.stage_id)
                    for rid in list(self._streams):
                        omni.trace_finish(rid)
                        self._emit(rid, e)
                        self._emit(rid, _SENTINEL)
                    continue
                if not outs:
                    continue
                progressed = True
                # errored outputs terminate their streams and are not
                # forwarded downstream
                errs = [o for o in outs if o.is_error]
                outs = [o for o in outs if not o.is_error]
                for o in errs:
                    omni.metrics.record_finish(o.request_id)
                    omni.trace_finish(o.request_id)
                    self._emit(o.request_id, o)
                    self._emit(o.request_id, _SENTINEL)
                if not outs:
                    continue
                if stage.config.final_output:
                    for o in outs:
                        o.final_output_type = stage.config.final_output_type
                        self._emit(o.request_id, o)
                        seen = self._finals_seen.get(o.request_id, 0) + 1
                        self._finals_seen[o.request_id] = seen
                        if seen >= self._n_finals:
                            # E2E spans through the LAST final output
                            omni.metrics.record_finish(o.request_id)
                            omni.trace_finish(o.request_id)
                            self._emit(o.request_id, _SENTINEL)
                try:
                    omni._forward(stage, outs)
                except Exception as e:
                    # scope the failure to the requests in this batch
                    logger.exception("forward from stage %d failed",
                                     stage.stage_id)
                    for o in outs:
                        # terminate the stream's trace too: the sync
                        # generate() sweeps leftover contexts at the end,
                        # the online loop has no such sweep
                        omni.trace_finish(o.request_id)
                        self._emit(o.request_id, e)
                        self._emit(o.request_id, _SENTINEL)
            if not progressed and not pending:
                # idle: avoid a hot spin on the GIL
                threading.Event().wait(0.002)
