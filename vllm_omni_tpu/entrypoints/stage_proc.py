"""Process-spawned pipeline stages: cross-process stage disaggregation.

The TPU counterpart of the reference's stage worker spawn (reference:
entrypoints/omni_stage.py:394-504 — mp.Process per stage with a
``stage_ready`` handshake :733; per-stage device env via
``set_stage_devices``, stage_utils.py).  Each ``ProcStage`` owns a child
process running a full in-proc ``OmniStage`` (engine included); the
orchestrator talks to it over a framed TCP socket, so the same worker can
run on another host (stage disaggregation across TPU-VM slices — pass a
routable bind host).

Device isolation: a single TPU chip admits one process, so per-stage
``device_env`` (e.g. {"JAX_PLATFORMS": "cpu"} or TPU_VISIBLE_CHIPS
selections) is applied in the child *before* jax import — the analogue of
CUDA_VISIBLE_DEVICES stage scoping.

Frames are length-prefixed OmniSerializer payloads (tensor-aware), the
same wire format as the TCP connector.
"""

from __future__ import annotations

import multiprocessing as mp
import queue
import socket
import threading
import time
from typing import Any, Optional

from vllm_omni_tpu.analysis.runtime import traced
from vllm_omni_tpu.config.stage import StageConfig
from vllm_omni_tpu.distributed.serialization import OmniSerializer
from vllm_omni_tpu.distributed.tcp import _recv_frame, _send_frame
from vllm_omni_tpu.entrypoints.omni_stage import OmniStage, StageRequest
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.outputs import OmniRequestOutput
from vllm_omni_tpu.resilience.faults import fault_point

logger = init_logger(__name__)


def _send_msg(sock: socket.socket, msg: dict) -> None:
    _send_frame(sock, OmniSerializer.dumps(msg))


def _recv_msg(sock: socket.socket) -> Optional[dict]:
    frame = _recv_frame(sock)
    return None if frame is None else OmniSerializer.loads(frame)


class _SockChannel:
    """Framed-message channel over a connected TCP socket."""

    def __init__(self, sock: socket.socket):
        self._sock = sock

    def send(self, msg: dict) -> None:
        fault_point("chan")
        _send_msg(self._sock, msg)

    def recv(self) -> Optional[dict]:
        """Blocks; None means the peer hung up."""
        fault_point("chan")
        return _recv_msg(self._sock)

    def settimeout(self, t) -> None:
        self._sock.settimeout(t)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class _ShmChannel:
    """Framed-message channel over a pair of native shared-memory rings
    (vllm_omni_tpu.native.ShmRing — the C++ SPSC ring buffer, the
    reference's C-backed shm MessageQueue analogue).  Same-host only;
    lower latency than the socket for large tensor payloads (no kernel
    copy per byte stream)."""

    def __init__(self, tx, rx):
        self._tx = tx
        self._rx = rx
        self._timeout = None

    def send(self, msg: dict) -> None:
        fault_point("chan")
        self._tx.push(OmniSerializer.dumps(msg), timeout=60.0)

    def recv(self) -> Optional[dict]:
        fault_point("chan")
        # socket semantics: block until a message or the channel closes;
        # bounded waits keep the thread interruptible
        while True:
            if self._rx is None:
                return None
            t = self._timeout if self._timeout is not None else 1.0
            frame = self._rx.pop(timeout=t)
            if frame is not None:
                return OmniSerializer.loads(frame)
            if self._timeout is not None:
                raise socket.timeout("shm channel recv timed out")

    def settimeout(self, t) -> None:
        self._timeout = t

    def close(self) -> None:
        tx, rx, self._tx, self._rx = self._tx, self._rx, None, None
        for ring in (tx, rx):
            if ring is not None:
                ring.close()


def _primary_ip() -> str:
    """This host's primary outbound IP (no packets are sent — a UDP
    connect only selects the route)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        return socket.gethostbyname(socket.gethostname())
    finally:
        s.close()


def _start_scoped(proc, device_env: Optional[dict]) -> None:
    """Start a worker process with ``device_env`` applied to the env the
    child is SPAWNED with (temporarily mutating the parent's environ
    around start()).

    Applying device_env only inside the child's main is too late for
    platform scoping: deployment site-dirs (e.g. a TPU tunnel plugin's
    sitecustomize on PYTHONPATH) eagerly initialize their backend at
    interpreter startup, and an unhealthy chip tunnel then hangs the
    child before it reaches our code.  CPU-scoped children additionally
    drop such plugin site-dirs from PYTHONPATH (multiprocessing restores
    the parent's full sys.path afterwards, so imports are unaffected)."""
    import os

    from vllm_omni_tpu.platforms import scrub_plugin_sitedirs

    updates = dict(device_env or {})
    if (updates.get("JAX_PLATFORMS", "").startswith("cpu")
            and "PYTHONPATH" not in updates):
        updates["PYTHONPATH"] = scrub_plugin_sitedirs(
            os.environ.get("PYTHONPATH", ""))
    saved = {k: os.environ.get(k) for k in updates}
    os.environ.update({k: str(v) for k, v in updates.items()})
    try:
        proc.start()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _worker_channel(conn_info) -> "_SockChannel | _ShmChannel":
    """Child side of the orchestrator<->worker channel."""
    kind = conn_info[0]
    if kind == "tcp":
        sock = socket.create_connection(conn_info[1], timeout=60.0)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return _SockChannel(sock)
    if kind == "shm":
        from vllm_omni_tpu.native import ShmRing

        _, c2p, p2c, capacity = conn_info
        # child owns nothing; rings were created by the orchestrator
        return _ShmChannel(tx=ShmRing(c2p, owner=False),
                           rx=ShmRing(p2c, owner=False))
    raise ValueError(f"unknown transport {kind!r}")


# --------------------------------------------------------------- worker side
def _stage_worker_main(config: StageConfig, conn_info: tuple,
                       device_env: Optional[dict]) -> None:
    """Child-process entry: env scoping → engine build → ready handshake →
    serve submit/abort/shutdown (reference: _stage_worker,
    omni_stage.py:636-733)."""
    import os

    for k, v in (device_env or {}).items():
        os.environ[k] = str(v)

    chan = _worker_channel(conn_info)
    _stage_worker_serve(config, chan)


def run_remote_stage(
    stage_configs_path: str,
    stage_id: int,
    connect: Optional[str] = None,
    discover: Optional[str] = None,
    retry_timeout: float = 120.0,
) -> None:
    """Cross-HOST stage worker entry (the serve-stage CLI): resolve the
    orchestrator's listener (explicit ``connect`` host:port, or KV-store
    ``discover``), dial with retries (the orchestrator may not be up
    yet), then serve the stage over the socket — the multi-host half of
    stage disaggregation (reference: Ray per-node stage placement,
    distributed/ray_utils/utils.py)."""
    from vllm_omni_tpu.config.stage import load_stage_configs_from_yaml

    cfgs = load_stage_configs_from_yaml(stage_configs_path)
    config = next((c for c in cfgs if c.stage_id == stage_id), None)
    if config is None:
        raise ValueError(f"no stage {stage_id} in {stage_configs_path}")
    if discover:
        from vllm_omni_tpu.distributed.multihost import (
            discover_stage_address,
        )

        connect = discover_stage_address(discover, stage_id,
                                         timeout=retry_timeout)
    if not connect:
        raise ValueError("need connect='host:port' or discover=store")
    host, _, port = connect.partition(":")
    deadline = time.monotonic() + retry_timeout
    while True:
        try:
            sock = socket.create_connection((host, int(port)),
                                            timeout=5.0)
            break
        except OSError:
            if time.monotonic() >= deadline:
                raise
            time.sleep(0.5)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    # the dial timeout must NOT persist: recv() blocks for minutes while
    # the orchestrator compiles, and a lingering 5s timeout would make
    # the reader thread conclude the peer died
    sock.settimeout(None)
    # watch_parent=False: a remote worker's launcher (ssh/nohup/
    # supervisor) legitimately exits and reparents us — orchestrator
    # death shows up as socket EOF instead
    _stage_worker_serve(config, _SockChannel(sock), watch_parent=False)


def _stage_worker_serve(config: StageConfig, chan,
                        watch_parent: bool = True) -> None:
    """Engine build → ready handshake → serve loop (shared by local
    children and remote serve-stage workers).  ``watch_parent`` enables
    the getppid watchdog — only meaningful for locally-SPAWNED children
    whose parent is the orchestrator (shm rings carry no EOF)."""
    import os

    try:
        stage = OmniStage(config)
    except Exception as e:  # surface build failures to the orchestrator
        chan.send({"type": "fatal",
                   "error": f"{type(e).__name__}: {e}"})
        chan.close()
        raise
    chan.send({"type": "stage_ready", "stage_id": config.stage_id})

    inbox: queue.Queue = queue.Queue()

    def reader() -> None:
        try:
            while True:
                msg = chan.recv()
                if msg is None:
                    logger.warning("stage %d: channel EOF from "
                                   "orchestrator", config.stage_id)
                    break
                inbox.put(msg)
        except (ConnectionError, OSError) as e:
            logger.warning("stage %d: channel error: %s",
                           config.stage_id, e)
        inbox.put({"type": "shutdown"})  # orchestrator gone

    threading.Thread(target=reader, daemon=True).start()

    parent = os.getppid()
    running = True
    # redelivery dedup: a supervisor restart resubmits queued-but-
    # unstarted requests, and the at-most-once contract lives HERE — a
    # request id this worker has already accepted and not yet finished
    # is never run twice even if delivery and redelivery race.
    # Finished ids are released below: callers legitimately reuse
    # request ids across batches (Omni.generate numbers every call
    # omni-0..N), and a permanent set would silently drop the reuse —
    # and grow for the worker's lifetime.
    seen_ids: set[str] = set()
    while running:
        if watch_parent and os.getppid() != parent:
            # orchestrator died (shm rings carry no EOF the way a socket
            # does) — exit instead of holding the chip forever
            logger.warning("stage %d: orchestrator gone; shutting down",
                           config.stage_id)
            break
        # drain commands; block briefly when idle so the loop doesn't spin
        block = not stage.has_unfinished
        while True:
            try:
                msg = inbox.get(block=block, timeout=0.05)
            except queue.Empty:
                break
            block = False
            t = msg.get("type")
            if t == "submit":
                # fault site stage{N}: one step per submit frame (e.g.
                # OMNI_TPU_FAULTS="stage1:kill_after=2" crashes this
                # worker on its second batch)
                fault_point(f"stage{config.stage_id}")
                fresh = [r for r in msg["requests"]
                         if r.request_id not in seen_ids]
                dropped = len(msg["requests"]) - len(fresh)
                if dropped:
                    logger.warning(
                        "stage %d: dropped %d duplicate request(s) "
                        "(redelivery dedup)", config.stage_id, dropped)
                seen_ids.update(r.request_id for r in fresh)
                if fresh:
                    stage.submit(fresh)
            elif t == "ping":
                # liveness heartbeat: the pong reports which requests
                # have STARTED computing (entered the running batch) so
                # a supervisor restart can redeliver the rest and fail
                # only the mid-execution ones
                started: list[str] = []
                sched = getattr(stage.engine, "scheduler", None)
                if sched is not None:
                    started = [r.request_id
                               for r in getattr(sched, "running", [])]
                try:
                    chan.send({"type": "pong", "started": started})
                except (ConnectionError, OSError, ValueError):
                    pass
            elif t == "abort":
                if stage.config.stage_type == "llm":
                    stage.engine.abort_request(msg["request_id"])
            elif t == "profile_start":
                stage.start_profile(msg["trace_dir"])
            elif t == "profile_stop":
                stage.stop_profile()
                # ack AFTER jax flushed the trace: the orchestrator's
                # stop_profile blocks on this so callers can read the
                # trace dir (or shut down) without losing the profile
                chan.send({"type": "profile_stopped"})
            elif t == "shutdown":
                running = False
            else:
                logger.warning("stage %d: unknown message %r",
                               config.stage_id, t)
        if not running:
            break
        if stage.has_unfinished:
            try:
                outs = stage.poll()
            except Exception as e:
                chan.send({"type": "fatal",
                           "error": f"{type(e).__name__}: {e}"})
                raise
            if outs:
                # a finished id may be reused by a later batch — release
                # it from the redelivery dedup set
                seen_ids.difference_update(
                    o.request_id for o in outs if o.finished)
                # trace spans recorded in THIS process (engine + stage
                # spans) ride the outputs frame back to the orchestrator,
                # which merges them into the request's trace; the engine
                # metrics snapshot rides along so /metrics covers
                # process-disaggregated stages too, and the resilience
                # counters this WORKER accumulated (deadline kills at
                # its scheduler, faults fired here) ride the same frame
                # so the orchestrator's /metrics covers them
                from vllm_omni_tpu.resilience.metrics import (
                    resilience_metrics,
                )
                from vllm_omni_tpu.tracing import get_recorder

                msg = {"type": "outputs", "outputs": outs}
                spans = get_recorder().drain()
                if spans:
                    msg["spans"] = spans
                metrics = stage.engine_metrics_snapshot()
                if metrics:
                    msg["metrics"] = metrics
                resilience = resilience_metrics.snapshot()
                if resilience:
                    msg["resilience"] = resilience
                try:
                    chan.send(msg)
                except ValueError as e:
                    # frame exceeded the shm ring admission limit: tell
                    # the orchestrator with a (small) fatal message
                    chan.send({"type": "fatal",
                               "error": f"outputs too large for shm "
                                        f"transport: {e}"})
                    raise
    try:
        chan.send({"type": "bye"})
    except (ConnectionError, OSError, ValueError):
        pass
    chan.close()


# --------------------------------------------------------- orchestrator side
class ProcStage(OmniStage):
    """Orchestrator-side proxy of a stage running in a child process.

    Mirrors the in-proc OmniStage surface the orchestrator touches
    (submit / poll / has_unfinished / process_engine_inputs / stats);
    inherits the input-derivation and metrics logic, never builds a local
    engine."""

    def __init__(self, config: StageConfig,
                 device_env: Optional[dict] = None,
                 ready_timeout: float = 300.0,
                 supervised: bool = False):
        # deliberately NOT calling super().__init__ — no local engine
        self.config = config
        self.stage_id = config.stage_id
        self.tokenizer = None
        self.mm_processor = None
        self.engine = None
        self._pending: list[StageRequest] = []
        self._done: list[OmniRequestOutput] = []
        self._input_processor = config.resolve_input_processor()
        self._submit_ts: dict[str, float] = {}
        self._trace_ctx: dict[str, dict] = {}
        self.request_stats = []
        self._engine_metrics: dict = {}
        self._worker_resilience: dict = {}
        self._inflight: set[str] = set()
        self._inbox: queue.Queue = queue.Queue()
        self._fatal: Optional[str] = None
        # submit (engine loop) and profile RPC (HTTP thread) may send
        # concurrently; frames must not interleave
        self._send_lock = traced(threading.Lock(),
                                 "ProcStage._send_lock")
        self._profile_ack = threading.Event()
        # supervision (resilience/supervisor.py): a supervised stage
        # leaves in-flight requests alone when the worker dies — the
        # supervisor decides restart/redeliver/fail per request
        self._supervised = supervised
        self._device_env = device_env
        self._ready_timeout = ready_timeout
        self._remote = bool(getattr(config.runtime, "remote", False))
        # heartbeat state (ping/pong frames): last pong arrival on this
        # process's monotonic clock, and the request ids the worker
        # reported as mid-execution
        self.last_pong = time.monotonic()
        self._started_ids: set[str] = set()
        # epoch guards the reader thread across restarts: a stale
        # reader observing its (closed) channel's EOF must not latch
        # _fatal on the fresh worker
        self._epoch = 0
        self._proc = None
        self._chan = None
        self._connect_worker()

    def _connect_worker(self) -> None:
        """Spawn (or, for remote stages, await) the worker and run the
        ready handshake; called at construction and again by
        ``restart()`` after a supervised worker died."""
        config = self.config
        device_env = self._device_env
        ready_timeout = self._ready_timeout
        # transport: TCP socket (default — also works cross-host) or the
        # native shared-memory ring pair (same-host, C++ SPSC rings;
        # reference's C-backed shm MessageQueue analogue)
        transport = getattr(config.runtime, "transport", "tcp")
        if transport == "shm":
            from vllm_omni_tpu.native import ShmRing, native_available

            if not native_available():
                logger.warning(
                    "stage %d: native shm rings unavailable; "
                    "falling back to tcp", self.stage_id,
                )
                transport = "tcp"
        if transport == "shm":
            import uuid

            tag = uuid.uuid4().hex[:12]
            c2p_name = f"/omni_{tag}_c2p"
            p2c_name = f"/omni_{tag}_p2c"
            capacity = 1 << 24
            # orchestrator owns both rings (unlinked on close)
            rx = ShmRing(c2p_name, capacity=capacity, owner=True)
            tx = ShmRing(p2c_name, capacity=capacity, owner=True)
            self._chan = _ShmChannel(tx=tx, rx=rx)
            conn_info = ("shm", c2p_name, p2c_name, capacity)
            ctx = mp.get_context("spawn")
            self._proc = ctx.Process(
                target=_stage_worker_main,
                args=(config, conn_info, device_env),
                daemon=True,
            )
            try:
                _start_scoped(self._proc, device_env)
            except BaseException:
                # a spawn failure must not leak the orchestrator-owned
                # rings (closing the channel unlinks them)
                self._chan.close()
                raise
        elif transport == "tcp":
            remote = getattr(config.runtime, "remote", False)
            bind_host = (getattr(config.runtime, "bind_host", "127.0.0.1")
                         if remote else "127.0.0.1")
            bind_port = (getattr(config.runtime, "bind_port", 0)
                         if remote else 0)
            listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            listener.bind((bind_host, bind_port))
            listener.listen(1)
            if remote:
                # cross-host placement: the worker runs on ANOTHER host
                # (serve-stage CLI) and connects here; optionally publish
                # a DIALABLE address for KV-store discovery (the bind
                # address may be 0.0.0.0 or loopback — undialable from
                # the worker's host)
                self._proc = None
                port = listener.getsockname()[1]
                adv = getattr(config.runtime, "advertise_host", "")
                if not adv:
                    adv = (_primary_ip() if bind_host == "0.0.0.0"
                           else bind_host)
                addr = f"{adv}:{port}"
                discovery = getattr(config.runtime, "discovery", "")
                if discovery:
                    from vllm_omni_tpu.distributed.multihost import (
                        publish_stage_address,
                    )

                    publish_stage_address(discovery, self.stage_id, addr)
                logger.info(
                    "stage %d: waiting for REMOTE worker on %s "
                    "(serve-stage CLI on the other host)",
                    self.stage_id, addr)
            else:
                ctx = mp.get_context("spawn")
                self._proc = ctx.Process(
                    target=_stage_worker_main,
                    args=(config, ("tcp", listener.getsockname()),
                          device_env),
                    daemon=True,
                )
                _start_scoped(self._proc, device_env)
            listener.settimeout(ready_timeout)
            try:
                sock, _ = listener.accept()
            except socket.timeout:
                if self._proc is not None:
                    self._proc.terminate()
                raise TimeoutError(
                    f"stage {self.stage_id}: worker process did not "
                    f"connect within {ready_timeout}s — check the child's "
                    "device_env and engine_args (reference: stage-ready "
                    "watchdog, omni.py:352-396)"
                ) from None
            finally:
                listener.close()
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._chan = _SockChannel(sock)
        else:
            raise ValueError(f"unknown stage transport {transport!r}")
        # ready handshake: first message must be stage_ready; sliced
        # waits so a worker that dies mid-build fails fast on BOTH
        # transports (shm rings have no EOF)
        msg = None
        deadline = time.monotonic() + ready_timeout
        try:
            while time.monotonic() < deadline:
                self._chan.settimeout(2.0)
                try:
                    msg = self._chan.recv()
                    break
                except socket.timeout:
                    if self._proc is not None and not self._proc.is_alive():
                        break
            if msg is None or msg.get("type") != "stage_ready":
                err = (msg or {}).get("error",
                                      "worker hung up or timed out")
                raise RuntimeError(
                    f"stage {self.stage_id}: worker failed to become "
                    f"ready: {err}"
                )
        except BaseException:
            # every handshake-failure path must release the transport:
            # for shm the orchestrator OWNS both rings, and without the
            # close they stay linked in /dev/shm until GC happens to
            # collect this half-built stage
            if self._proc is not None:
                self._proc.terminate()
            self._chan.close()
            raise
        self._chan.settimeout(None)
        self.last_pong = time.monotonic()
        threading.Thread(target=self._reader, args=(self._epoch,),
                         daemon=True).start()

    def _reader(self, epoch: int) -> None:
        chan = self._chan
        try:
            while True:
                msg = chan.recv()
                if msg is None:
                    break
                if msg.get("type") == "profile_stopped":
                    # handled here, not in poll(): stop_profile blocks on
                    # the ack even when nothing is polling the stage
                    self._profile_ack.set()
                    continue
                if msg.get("type") == "pong":
                    # heartbeat reply; carries the mid-execution request
                    # ids so a supervisor restart knows what NOT to
                    # redeliver
                    self.last_pong = time.monotonic()
                    self._started_ids.update(msg.get("started") or ())
                    continue
                if msg.get("type") == "bye":
                    # worker's clean farewell (shutdown path): stop
                    # reading instead of parking an unhandled frame in
                    # the inbox (first omnilint OL5 harvest)
                    break
                self._inbox.put(msg)
        except (ConnectionError, OSError):
            pass
        # channel EOF is the ONLY death signal a REMOTE worker gives us
        # (self._proc is None, so poll()'s is_alive check never fires) —
        # without this, in-flight requests spin forever.  The epoch
        # check keeps a stale reader (its channel closed by restart())
        # from latching _fatal on the fresh worker.
        if (epoch == self._epoch and self._fatal is None
                and self._inflight):
            self._fatal = "worker channel closed"

    # ---------------------------------------------------------- liveness
    def _locked_send(self, frame: dict) -> None:
        """The ONE place a frame crosses the command channel.  Submit
        (engine loop), ping (heartbeat thread), and the profile/shutdown
        RPCs (server thread) all race here; interleaved writes would
        corrupt the pickle stream, so the send lock is held ACROSS the
        write — that is the lock's whole contract, not an accident."""
        with self._send_lock:
            # omnilint: disable=OL9 - the send lock IS the frame
            # serializer; holding it across the pipe write is the point
            self._chan.send(frame)

    def ping(self) -> bool:
        """Send a liveness heartbeat; the worker replies with a ``pong``
        frame (handled in ``_reader``).  Returns False when the channel
        is already known-dead."""
        if self._fatal is not None:
            return False
        try:
            self._locked_send({"type": "ping"})
            return True
        except (ConnectionError, OSError, ValueError) as e:
            self._fatal = f"ping failed: {type(e).__name__}: {e}"
            return False

    def mark_hung(self, reason: str) -> None:
        """Declare the worker dead (e.g. heartbeat misses exhausted):
        latch the fatal reason and reap the process so restart() can
        respawn cleanly."""
        if self._fatal is None:
            self._fatal = reason
        if self._proc is not None and self._proc.is_alive():
            self._proc.terminate()

    @property
    def restartable(self) -> bool:
        """Only locally-spawned workers can be restarted — a remote
        worker's lifecycle belongs to its own host's launcher."""
        return not self._remote

    @property
    def started_request_ids(self) -> set[str]:
        """Requests the worker last reported as mid-execution (from the
        heartbeat pong) — still in flight here."""
        return self._started_ids & self._inflight

    def restart(self) -> None:
        """Respawn the worker after a crash/hang (supervised stages).
        The caller (StageSupervisor) owns redelivery; this only rebuilds
        the transport + process and clears the fatal latch."""
        if not self.restartable:
            raise RuntimeError(
                f"stage {self.stage_id}: remote workers cannot be "
                "restarted by the orchestrator")
        self._epoch += 1  # detach the old reader before closing its chan
        if self._chan is not None:
            self._chan.close()
        if self._proc is not None:
            if self._proc.is_alive():
                self._proc.terminate()
            self._proc.join(5.0)
        # drop frames from the dead worker: outputs for requests the
        # supervisor is about to fail/redeliver must not resurface
        while True:
            try:
                self._inbox.get_nowait()
            except queue.Empty:
                break
        self._started_ids.clear()
        self._profile_ack.set()  # never leave a stop_profile waiter hung
        self._connect_worker()
        self._fatal = None

    # ------------------------------------------------------------- intake
    def submit(self, reqs: list[StageRequest]) -> None:
        now = time.perf_counter()
        for r in reqs:
            self._submit_ts[r.request_id] = now
            if r.trace:
                self._trace_ctx[r.request_id] = r.trace
            self._inflight.add(r.request_id)
        if self._fatal is None:
            try:
                self._locked_send({"type": "submit", "requests": reqs})
            except (ConnectionError, OSError, ValueError) as e:
                # worker died between batches: the next poll() converts
                # the whole in-flight set to per-request error outputs —
                # never abort batch-mates on healthy stages by raising.
                # Keep the exception TYPE: a bare OSError often has an
                # empty str(), and per-request error outputs must say
                # why the worker was lost, not just that it was.
                self._fatal = (f"submit failed: "
                               f"{type(e).__name__}: {e}".rstrip(": "))

    # -------------------------------------------------------------- drive
    def poll(self) -> list[OmniRequestOutput]:
        outs: list[OmniRequestOutput] = []
        while True:
            try:
                msg = self._inbox.get_nowait()
            except queue.Empty:
                break
            t = msg.get("type")
            if t == "outputs":
                outs.extend(msg["outputs"])
                spans = msg.get("spans")
                if spans:
                    # merge worker-side spans into this process's trace
                    from vllm_omni_tpu.tracing import get_recorder

                    get_recorder().extend(spans)
                metrics = msg.get("metrics")
                if metrics:
                    self._engine_metrics = metrics
                resilience = msg.get("resilience")
                if resilience:
                    # latest worker-lifetime resilience counters; merged
                    # into /metrics by prometheus.render_from_omni
                    self._worker_resilience = resilience
            elif t == "fatal":
                self._fatal = msg.get("error", "unknown")
        for o in outs:
            if o.finished:
                self._inflight.discard(o.request_id)
                self._started_ids.discard(o.request_id)
            self._record(o)
        if self._inflight and self._fatal is None \
                and self._proc is not None and not self._proc.is_alive():
            self._fatal = f"worker exited (code {self._proc.exitcode})"
        if self._supervised:
            # the supervisor owns the failure policy (restart, redeliver
            # unstarted, fail mid-execution as retryable) — never mass-
            # fail the in-flight set here
            return outs
        if self._inflight and self._fatal is not None:
            # fail every in-flight request on this stage; the pipeline
            # keeps serving requests on healthy stages
            logger.error("stage %d worker died: %s",
                         self.stage_id, self._fatal)
            for rid in sorted(self._inflight):
                o = OmniRequestOutput.from_error(
                    rid, f"stage worker died: {self._fatal}",
                    stage_id=self.stage_id)
                self._record(o)
                outs.append(o)
            self._inflight.clear()
        return outs

    @property
    def has_unfinished(self) -> bool:
        return bool(self._inflight)

    def engine_metrics_snapshot(self) -> dict:
        """Last engine snapshot shipped by the worker (rides the outputs
        frames) — the cross-process face of OmniStage's live snapshot."""
        return self._engine_metrics

    def resilience_snapshot(self) -> dict:
        """Last resilience-counter snapshot shipped by the worker
        (deadline kills at its scheduler, faults fired in its process);
        counts cover the CURRENT worker's lifetime — a restart resets
        them, which Prometheus counter semantics tolerate."""
        return self._worker_resilience

    # ----------------------------------------------------------- profiling
    def start_profile(self, trace_dir: str) -> None:
        """Profiling must run in the worker process (it owns the devices):
        ship the command over the socket (reference: PROFILER_* tasks).
        A dead worker is a logged no-op, never an exception — one broken
        stage must not abort the fan-out over healthy ones."""
        if self._fatal is not None:
            logger.warning("stage %d: skip profile_start (worker dead)",
                           self.stage_id)
            return
        try:
            self._locked_send({"type": "profile_start",
                               "trace_dir": trace_dir})
        except (ConnectionError, OSError) as e:
            self._fatal = f"profile_start failed: {e}"

    def stop_profile(self, timeout: float = 60.0, wait: bool = True) -> None:
        """Blocks until the worker acked the stop (the trace file is
        flushed by then) or ``timeout`` passes; ``wait=False`` lets a
        multi-stage fan-out send every stop first and then wait on all
        acks concurrently (bounding worst-case latency at one timeout)."""
        if self._fatal is not None:
            return
        self._profile_ack.clear()
        try:
            self._locked_send({"type": "profile_stop"})
        except (ConnectionError, OSError) as e:
            self._fatal = f"profile_stop failed: {e}"
            return
        if wait:
            self.wait_profile_ack(timeout)

    def wait_profile_ack(self, timeout: float = 60.0) -> None:
        if self._fatal is not None:
            return
        if not self._profile_ack.wait(timeout):
            logger.warning(
                "stage %d: no profile_stop ack within %.0fs (long step "
                "in flight?) — trace may be incomplete",
                self.stage_id, timeout,
            )

    # ----------------------------------------------------------- shutdown
    def shutdown(self, timeout: float = 10.0) -> None:
        try:
            self._locked_send({"type": "shutdown"})
        except (ConnectionError, OSError):
            pass
        if self._proc is not None:
            self._proc.join(timeout)
            if self._proc.is_alive():
                self._proc.terminate()
                self._proc.join(5.0)
        self._chan.close()

    def __del__(self) -> None:
        try:
            if getattr(self, "_proc", None) is not None \
                    and self._proc.is_alive():
                self._proc.terminate()
        except Exception:
            pass
