"""OpenAI-compatible API server.

Behavioral port of the reference's FastAPI server (reference:
entrypoints/openai/api_server.py:107 — /v1/chat/completions:729,
/v1/images/generations:935, /health:860, /v1/models:896, audio speech:805)
on the standard library's threading HTTP server: the runtime ships zero
web-framework dependencies, matching the native-runtime stance (handler
threads submit into AsyncOmni's event loop and stream SSE chunks back).

Run: ``python -m vllm_omni_tpu.entrypoints.cli serve <model> [--port]``.
"""

from __future__ import annotations

import asyncio
import base64
import io
import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

import numpy as np

from vllm_omni_tpu.entrypoints.async_omni import AsyncOmni
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.version import __version__

logger = init_logger(__name__)


class ServerState:
    """Owns the AsyncOmni engine + the asyncio loop it streams on."""

    def __init__(self, omni: AsyncOmni, model_name: str):
        self.omni = omni
        self.model_name = model_name
        self.loop = asyncio.new_event_loop()
        self._loop_thread = threading.Thread(
            target=self.loop.run_forever, daemon=True, name="omni-asyncio"
        )
        self._loop_thread.start()

    def shutdown(self):
        self.omni.shutdown()
        self.loop.call_soon_threadsafe(self.loop.stop)

    def entry_tokenizer(self):
        """Entry stage's tokenizer (chat-template source), if any.
        Process-disaggregated entry stages keep their tokenizer in the
        worker — chat falls back to the plain transcript there (warned
        once so the divergence from in-proc deployments is visible)."""
        for stage in self.omni._omni.stages:
            if -1 in stage.config.engine_input_source:
                if (stage.tokenizer is None
                        and stage.config.runtime.process
                        and not getattr(self, "_warned_proc_tok", False)):
                    self._warned_proc_tok = True
                    logger.warning(
                        "entry stage runs in a worker process; chat "
                        "templates are not applied (plain transcript)"
                    )
                return stage.tokenizer
        return None

    # ---------------------------------------------------------- bridging
    def collect(self, prompt, sampling_params, request_id=None) -> list:
        """Run one request to completion, returning all final outputs."""

        async def _run():
            outs = []
            async for o in self.omni.generate(prompt, sampling_params,
                                              request_id):
                outs.append(o)
            return outs

        return asyncio.run_coroutine_threadsafe(_run(), self.loop).result()

    def stream(self, prompt, sampling_params, request_id=None):
        """Sync iterator over final outputs (SSE bridging)."""
        import queue as _queue

        q: _queue.Queue = _queue.Queue()
        done = object()

        async def _run():
            try:
                async for o in self.omni.generate(prompt, sampling_params,
                                                  request_id):
                    q.put(o)
            except Exception as e:  # surfaced as an SSE error event
                q.put(e)
            finally:
                q.put(done)

        asyncio.run_coroutine_threadsafe(_run(), self.loop)
        while True:
            item = q.get()
            if item is done:
                return
            yield item

    def collect_many(self, jobs: list[tuple]) -> list[list]:
        """Run several (prompt, sampling_params, request_id) jobs
        concurrently so batching stages can batch them."""

        async def _run_all():
            async def one(prompt, sp, rid):
                outs = []
                async for o in self.omni.generate(prompt, sp, rid):
                    outs.append(o)
                return outs

            return await asyncio.gather(*(one(*j) for j in jobs))

        return asyncio.run_coroutine_threadsafe(_run_all(),
                                                self.loop).result()


def _decode_image_part(part: dict) -> np.ndarray:
    """OpenAI image_url content part -> [H, W, 3] uint8 (data: URLs with
    base64 PNG, or raw base64)."""
    url = part.get("image_url", {})
    if isinstance(url, dict):
        url = url.get("url", "")
    if url.startswith("data:"):
        b64 = url.partition(",")[2]
    else:
        b64 = url
    raw = base64.b64decode(b64)
    from PIL import Image

    img = Image.open(io.BytesIO(raw)).convert("RGB")
    return np.asarray(img)


def _decode_audio_part(part: dict) -> np.ndarray:
    """OpenAI input_audio content part -> 1-D float32 waveform.
    Formats: "wav" (stdlib wave, 16-bit PCM) or "f32le" (raw floats)."""
    spec = part.get("input_audio", {})
    raw = base64.b64decode(spec.get("data", ""))
    fmt = spec.get("format", "wav")
    if fmt == "f32le":
        return np.frombuffer(raw, np.float32).copy()
    if fmt == "wav":
        import wave

        with wave.open(io.BytesIO(raw)) as w:
            frames = w.readframes(w.getnframes())
            width = w.getsampwidth()
        if width == 2:
            return (np.frombuffer(frames, np.int16)
                    .astype(np.float32) / 32768.0)
        raise ValueError(f"unsupported wav sample width {width}")
    raise ValueError(f"unsupported audio format {fmt!r}")


def _chat_prompt_from_messages(
    messages: list[dict], tokenizer=None
) -> tuple[str, dict]:
    """Chat templating + multimodal content extraction.

    Returns (prompt_text, multi_modal_data).  Image/audio content parts
    (OpenAI ``image_url`` / ``input_audio``) are decoded into arrays and a
    textual placeholder marks their position; the stage's mm processor
    expands markers into encoder tokens (reference: _preprocess_chat with
    mm data, serving_chat.py:335).  An HF tokenizer with a chat template
    formats the transcript; the byte-tokenizer path uses a plain
    role-tagged transcript."""
    mm: dict[str, list] = {}
    norm_messages = []
    for m in messages:
        content = m.get("content", "")
        if isinstance(content, list):  # multimodal content parts
            text_parts = []
            for c in content:
                t = c.get("type")
                if t == "text":
                    text_parts.append(c.get("text", ""))
                elif t == "image_url":
                    mm.setdefault("image", []).append(_decode_image_part(c))
                elif t == "input_audio":
                    mm.setdefault("audio", []).append(_decode_audio_part(c))
            content = " ".join(text_parts)
        norm_messages.append({"role": m.get("role", "user"),
                              "content": content})
    if tokenizer is not None and hasattr(tokenizer, "apply_chat_template") \
            and getattr(tokenizer, "chat_template", None):
        prompt = tokenizer.apply_chat_template(
            norm_messages, tokenize=False, add_generation_prompt=True)
    else:
        parts = [f"{m['role']}: {m['content']}" for m in norm_messages]
        parts.append("assistant:")
        prompt = "\n".join(parts)
    return prompt, mm


def _sampling_from_body(body: dict) -> dict:
    sp = {}
    # explicit nulls mean "unset" per OpenAI semantics
    max_toks = body.get("max_completion_tokens")
    if max_toks is None:
        max_toks = body.get("max_tokens")
    if max_toks is not None:
        sp["max_tokens"] = max_toks
    for k in ("temperature", "top_p", "top_k", "seed"):
        if body.get(k) is not None:
            sp[k] = body[k]
    # extension (vLLM ships the same one): benchmark clients pin the
    # output length so token accounting is exact
    if body.get("ignore_eos") is not None:
        sp["ignore_eos"] = bool(body["ignore_eos"])
    # OpenAI logprobs: chat sends a boolean + optional top_logprobs
    # count; legacy /v1/completions sends an integer count directly
    lp = body.get("logprobs")
    if lp is not None and lp is not False:
        # chat: logprobs=true + top_logprobs=k; legacy completions:
        # logprobs=k directly (0 is valid — chosen-token logprob only)
        k = int(lp) if not isinstance(lp, bool) \
            else int(body.get("top_logprobs") or 0)
        if not 0 <= k <= 20:
            raise ValueError("top_logprobs must be within [0, 20]")
        sp["logprobs"] = k
    return sp


# SSE audio delta granularity (samples per chunk; 12000 ≈ 0.5s @ 24kHz)
_AUDIO_CHUNK_SAMPLES = 12000


def _b64_png(img: np.ndarray) -> str:
    """uint8 [H, W, 3] -> base64 PNG (PIL if present, raw fallback)."""
    try:
        from PIL import Image

        buf = io.BytesIO()
        Image.fromarray(img).save(buf, format="PNG")
        return base64.b64encode(buf.getvalue()).decode()
    except ImportError:
        return base64.b64encode(img.tobytes()).decode()


class OmniRequestHandler(BaseHTTPRequestHandler):
    state: ServerState  # injected via server class attribute
    protocol_version = "HTTP/1.1"

    # --------------------------------------------------------------- utils
    def log_message(self, fmt, *args):
        logger.debug("%s %s", self.address_string(), fmt % args)

    def _json(self, code: int, obj: dict, default=None):
        # ``default``: encoder fallback for the /debug family, whose
        # duck-typed snapshots may carry numpy scalars etc.
        data = json.dumps(obj, default=default).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _error(self, code: int, message: str, etype: str = "invalid_request_error"):
        self._json(code, {"error": {"message": message, "type": etype}})

    # error kind -> (HTTP status, OpenAI error type).  The taxonomy
    # (docs/serving.md): 400 = the client's fault; 429 "shed" =
    # admission control refused a HEALTHY server at capacity (back off,
    # then retry — the load harness maps the knee of the serving curve
    # off this status); 503 "retryable" = infra broke before any output
    # (idempotent resubmit ok); 504 = the time budget was spent
    # (response abandoned); anything else is a 500.
    _ERROR_KIND_HTTP = {
        "invalid_request": (400, "invalid_request_error"),
        "shed": (429, "overloaded"),
        "deadline_exceeded": (504, "deadline_exceeded"),
        "retryable": (503, "retryable_error"),
    }

    def _surface_error(self, outs) -> bool:
        """If any pipeline output is errored, reply with an OpenAI-style
        error (instead of HTTP 200 with an empty/garbage payload) and
        return True.  Validation failures (ValueError) map to 400."""
        err = next((o for o in outs if o.is_error), None)
        if err is None:
            return False
        msg = err.error_message or "request failed"
        code, etype = self._ERROR_KIND_HTTP.get(
            err.error_kind, (500, "internal_error"))
        self._error(code, msg, etype)
        return True

    def _tenant_info(self) -> dict:
        """Per-tenant metrics attribution + WFQ weight: the
        ``x-omni-tenant`` header rides request metadata
        (additional_information["tenant"]) into the engine, labeling
        the SLO/goodput/queue-depth series on /metrics so fleet
        dashboards can split the serving curve per tenant
        (docs/load_testing.md); ``x-omni-priority`` rides alongside it
        into ``Request.priority`` — the deficit-round-robin weight of
        the WFQ overload scheduler (docs/control_plane.md).  Both are
        CLIENT input: sanitized/clamped at the Request property, never
        trusted here."""
        info = {}
        # bound the RAW bytes at ingress (OL10 first-harvest): the
        # values ride request metadata — and every stage-handoff
        # serialization — until the Request properties sanitize at
        # use, so a megabyte header must not be carried that far.
        # Semantic sanitization stays where it was: sanitize_tenant
        # caps the label at 64 chars, sanitize_priority clamps [1, 8]
        tenant = self.headers.get("x-omni-tenant")
        if tenant:
            info["tenant"] = tenant[:256]
        priority = self.headers.get("x-omni-priority")
        if priority:
            info["priority"] = priority[:64]
        # external trace join (tracing/journey.py): a W3C traceparent
        # or x-omni-trace-id header continues the CALLER's trace id
        # through this request's journey spans — validated/bounded
        # client input; the orchestrator mints the context at arrival
        from vllm_omni_tpu.tracing import inbound_trace_id

        tid = inbound_trace_id(self.headers)
        if tid:
            info["trace_id"] = tid
        return info

    def _body(self) -> dict:
        length = int(self.headers.get("Content-Length", 0))
        if length == 0:
            return {}
        return json.loads(self.rfile.read(length))

    def _sse_start(self):
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

    def _sse_send(self, obj) -> None:
        payload = ("data: " + (obj if isinstance(obj, str)
                               else json.dumps(obj)) + "\n\n").encode()
        self.wfile.write(f"{len(payload):x}\r\n".encode() + payload + b"\r\n")

    def _sse_end(self):
        self.wfile.write(b"0\r\n\r\n")

    # --------------------------------------------------------------- GET
    def do_GET(self):
        from urllib.parse import parse_qs, urlsplit

        parts = urlsplit(self.path)
        if parts.path == "/metrics":
            return self._metrics(parse_qs(parts.query))
        if parts.path.startswith("/debug/"):
            return self._debugz(parts.path, parse_qs(parts.query))
        if self.path == "/health":
            self._health()
        elif self.path == "/v1/models":
            self._json(200, {
                "object": "list",
                "data": [{
                    "id": self.state.model_name,
                    "object": "model",
                    "owned_by": "vllm-omni-tpu",
                    "max_model_len": None,
                }],
            })
        elif self.path == "/v1/audio/voices":
            # voices declared by the stage config (reference:
            # /v1/audio/voices, api_server.py:833)
            voices = []
            for stage in self.state.omni._omni.stages:
                voices.extend(stage.config.engine_args.get("voices", ()))
            self._json(200, {"voices": voices or ["default"]})
        elif self.path == "/version":
            self._json(200, {"version": __version__})
        else:
            self._error(404, f"unknown path {self.path}")

    def _health(self):
        """Honest /health (docs/debugging.md): last-step age + engine
        liveness, 503 once the stall watchdog has tripped or the engine
        loop died — so a load balancer ejects a wedged replica instead
        of the static "ok" feeding it traffic forever."""
        from vllm_omni_tpu.introspection.debugz import health_snapshot

        omni = getattr(self.state.omni, "_omni", self.state.omni)
        alive = getattr(self.state.omni, "engine_thread_alive", None)
        code, body = health_snapshot(omni, engine_thread_alive=alive)
        self._json(code, body)

    def _debugz(self, path: str, query: dict):
        """``/debug/z`` introspection family (docs/debugging.md): live
        JSON views of engines, requests, KV occupancy, the flight-
        recorder ring, thread stacks, and the watchdog.  Read-only."""
        from vllm_omni_tpu.introspection import debugz

        omni = getattr(self.state.omni, "_omni", self.state.omni)
        if path == "/debug/z":
            return self._json(200, debugz.debug_index(), default=str)
        if path == "/debug/engine":
            return self._json(200, debugz.debug_engine(omni),
                              default=str)
        if path == "/debug/requests":
            return self._json(200, debugz.debug_requests(omni),
                              default=str)
        if path == "/debug/kv":
            return self._json(200, debugz.debug_kv(omni), default=str)
        if path == "/debug/flightrecorder":
            try:
                tail = int(query.get("n", [0])[0]) or None
            except (TypeError, ValueError):
                return self._error(400, "n must be an integer")
            return self._json(
                200, debugz.debug_flightrecorder(omni, tail=tail),
                default=str)
        if path == "/debug/stacks":
            return self._json(200, debugz.debug_stacks(), default=str)
        if path == "/debug/watchdog":
            return self._json(200, debugz.debug_watchdog(omni),
                              default=str)
        if path == "/debug/disagg":
            # disagg-router view (docs/disaggregation.md): replica
            # health/drain state, in-flight request phases, failover
            # ledger; {"enabled": false} on non-disagg deployments
            return self._json(200, debugz.debug_disagg(omni),
                              default=str)
        if path == "/debug/controlplane":
            # control-plane view (docs/control_plane.md): sensors,
            # in-flight re-role/scale operation, action ring;
            # {"enabled": false} on uncontrolled deployments
            return self._json(200, debugz.debug_controlplane(omni),
                              default=str)
        if path == "/debug/alerts":
            # omnipulse rule states + transition ring + dump-cooldown
            # self-view ({"enabled": false} without an alert engine)
            return self._json(200, debugz.debug_alerts(omni),
                              default=str)
        if path == "/debug/tenants":
            # per-stage heavy-hitter attribution boards (top-k per
            # consumption meter, with error bounds)
            return self._json(200, debugz.debug_tenants(omni),
                              default=str)
        if path == "/debug/cache":
            # fleet cache-economics board (docs/disaggregation.md):
            # replica digests, duplicated prefixes, regret ledger;
            # {"enabled": false} on non-disagg deployments
            return self._json(200, debugz.debug_cache(omni),
                              default=str)
        if path == "/debug/trace":
            # trace-layer self-view (docs/observability.md): recorder
            # occupancy, spans_dropped, writer paths, last export
            return self._json(200, debugz.debug_trace(omni),
                              default=str)
        return self._error(404, f"unknown debug path {path}; "
                           f"see /debug/z")

    def _metrics(self, query: dict):
        """``GET /metrics``: Prometheus text exposition (the scrape
        surface); ``/metrics?format=json`` keeps the JSON summary."""
        omni = getattr(self.state.omni, "_omni", self.state.omni)
        # device memory snapshot (per-process accounting analogue,
        # reference: worker/gpu_memory_utils.py NVML probes)
        from vllm_omni_tpu.platforms import current_platform

        p = current_platform()
        device = {
            "platform": p.name,
            "kind": p.device_kind(),
            "hbm_bytes": p.hbm_bytes(),
        }
        if query.get("format", ["prometheus"])[0] == "json":
            summary = (omni.stats_summary()
                       if hasattr(omni, "stats_summary")
                       else omni.metrics.summary())
            summary["device"] = device
            return self._json(200, summary)
        from vllm_omni_tpu.metrics.prometheus import render_from_omni

        data = render_from_omni(omni, device=device).encode()
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    # --------------------------------------------------------------- POST
    def do_POST(self):
        try:
            body = self._body()
        except (json.JSONDecodeError, ValueError) as e:
            return self._error(400, f"bad JSON: {e}")
        try:
            if self.path in ("/start_profile", "/stop_profile"):
                # gated + server-chosen directory (the reference gates the
                # torch-profiler endpoint behind VLLM_TORCH_PROFILER_DIR
                # the same way): a client must never control filesystem
                # paths or toggle tracing on an ungated server
                from vllm_omni_tpu import envs

                trace_dir = envs.OMNI_TPU_PROFILER_DIR
                if not trace_dir:
                    return self._error(
                        403,
                        "profiling disabled: set OMNI_TPU_PROFILER_DIR "
                        "on the server to enable",
                    )
                if self.path == "/start_profile":
                    self.state.omni.start_profile(trace_dir)
                    self._json(200, {"status": "profiling",
                                     "trace_dir": trace_dir})
                else:
                    self.state.omni.stop_profile()
                    self._json(200, {"status": "stopped",
                                     "trace_dir": trace_dir})
            elif self.path == "/v1/chat/completions":
                self._chat_completions(body)
            elif self.path == "/v1/completions":
                self._completions(body)
            elif self.path == "/v1/images/edits":
                self._images_edits(body)
            elif self.path == "/v1/images/generations":
                self._images_generations(body)
            elif self.path == "/v1/audio/speech":
                self._audio_speech(body)
            elif self.path == "/v1/videos":
                self._videos(body)
            else:
                self._error(404, f"unknown path {self.path}")
        except BrokenPipeError:
            pass
        except Exception as e:
            logger.exception("request failed")
            try:
                self._error(500, str(e), "internal_error")
            except Exception:
                pass

    # ------------------------------------------------------ chat/completions
    def _chat_completions(self, body: dict):
        messages = body.get("messages")
        if not messages:
            return self._error(400, "messages required")
        try:
            prompt_text, mm = _chat_prompt_from_messages(
                messages, tokenizer=self.state.entry_tokenizer())
        except Exception as e:
            # any failure decoding client-supplied content (corrupt wav ->
            # wave.Error, non-string url -> AttributeError, bad base64,
            # ...) is the client's fault, never a 500
            return self._error(400, f"bad multimodal content: {e}")
        info = self._tenant_info()
        if mm or info:
            prompt: Any = {"prompt": prompt_text}
            if mm:
                prompt["multi_modal_data"] = mm
            if info:
                prompt["additional_information"] = info
        else:
            prompt = prompt_text
        try:
            sp = _sampling_from_body(body)
        except ValueError as e:
            return self._error(400, str(e))
        rid = f"chatcmpl-{uuid.uuid4().hex[:16]}"
        created = int(time.time())
        try:
            n = int(body.get("n") or 1)
        except (TypeError, ValueError):
            return self._error(400, "n must be an integer")
        if not 1 <= n <= 16:
            return self._error(400, "n must be within [1, 16]")
        if body.get("stream"):
            if n > 1:
                return self._error(400, "streaming with n > 1 is not "
                                   "supported")
            stream_iter = self.state.stream(prompt, sp, rid)
            # peek the FIRST item before committing to SSE: an error
            # before any output (shed at admission, expired deadline,
            # invalid prompt) still gets its REAL HTTP status — a 429
            # buried inside a 200 SSE stream would hide the back-off
            # contract from every streaming client
            first = next(stream_iter, None)
            if isinstance(first, Exception):
                return self._error(500, str(first), "internal_error")
            if first is not None and first.is_error:
                self._surface_error([first])
                return
            self._sse_start()
            if first is not None:
                for chunk in self._chat_chunks(first, rid, created):
                    self._sse_send(chunk)
            for out in stream_iter:
                if isinstance(out, Exception):
                    self._sse_send({"error": {"message": str(out),
                                              "type": "internal_error",
                                              "code": 500}})
                    break
                if out.is_error:
                    # mid-stream failure: the status line is long gone,
                    # so the SSE error event carries the taxonomy
                    # (type + would-be HTTP code) for clients to act on
                    code, etype = self._ERROR_KIND_HTTP.get(
                        out.error_kind, (500, "internal_error"))
                    self._sse_send({"error": {
                        "message": out.error_message or "request failed",
                        "type": etype, "code": code}})
                    break
                for chunk in self._chat_chunks(out, rid, created):
                    self._sse_send(chunk)
            self._sse_send("[DONE]")
            self._sse_end()
            return
        # n choices fan out as independent requests with distinct seeds
        # (vLLM n>1 semantics; batching stages batch them) — n == 1 is
        # the one-job case of the same loop
        base_seed = sp.get("seed")
        jobs = []
        for i in range(n):
            spi = dict(sp)
            if base_seed is not None and n > 1:
                spi["seed"] = int(base_seed) + i
            jobs.append((prompt, spi, rid if n == 1 else f"{rid}-{i}"))
        all_outs = self.state.collect_many(jobs)
        choices = []
        n_prompt = n_out = 0
        for i, outs in enumerate(all_outs):
            if self._surface_error(outs):
                return
            text_out = next(
                (o for o in outs if o.final_output_type == "text"),
                outs[0] if outs else None)
            if text_out is None:
                return self._error(500, "pipeline produced no output",
                                   "internal_error")
            message: dict[str, Any] = {
                "role": "assistant",
                "content": (text_out.outputs[0].text
                            if text_out.outputs else None),
            }
            # multimodal finals ride OpenAI-style audio/images extensions
            # (reference: audio/image choices, serving_chat.py:1617,1683)
            for o in outs:
                if o.final_output_type == "audio" \
                        and "audio" in o.multimodal_output:
                    wav = np.asarray(o.multimodal_output["audio"],
                                     np.float32)
                    message["audio"] = {
                        "id": f"audio-{rid}-{i}",
                        "data": base64.b64encode(wav.tobytes()).decode(),
                        "format": "f32le",
                    }
                elif o.final_output_type == "image" and o.images:
                    message["images"] = [
                        {"b64_json": _b64_png(np.asarray(img))}
                        for img in o.images
                    ]
            n_prompt = len(text_out.prompt_token_ids)
            n_out += sum(len(c.token_ids) for c in text_out.outputs)
            choice = {
                "index": i,
                "message": message,
                "finish_reason": (text_out.outputs[0].finish_reason
                                  if text_out.outputs else None),
            }
            lp = (text_out.outputs[0].logprobs
                  if text_out.outputs else None)
            if lp is not None:
                choice["logprobs"] = {"content": self._logprob_content(
                    text_out.outputs[0].token_ids, lp)}
            choices.append(choice)
        self._json(200, {
            "id": rid,
            "object": "chat.completion",
            "created": created,
            "model": body.get("model", self.state.model_name),
            "choices": choices,
            "usage": {
                "prompt_tokens": n_prompt,
                "completion_tokens": n_out,
                "total_tokens": n_prompt + n_out,
            },
        })

    def _logprob_content(self, token_ids, entries) -> list:
        """Runner logprob entries -> OpenAI response shape, tokens
        decoded through the entry tokenizer when available."""
        tok = self.state.entry_tokenizer()

        def decode(tid):
            if tok is None:
                return str(tid)
            try:
                # convert_ids_to_tokens keeps partial-UTF8 BPE pieces
                # faithful (decode() would emit U+FFFD for them)
                if hasattr(tok, "convert_ids_to_tokens"):
                    return tok.convert_ids_to_tokens([int(tid)])[0]
                return tok.decode([int(tid)])
            except Exception:
                return str(tid)

        content = []
        for tid, e in zip(token_ids, entries):
            content.append({
                "token": decode(tid),
                "logprob": e["logprob"],
                "top_logprobs": [
                    {"token": decode(i), "logprob": v}
                    for i, v in zip(e["top_ids"], e["top_logprobs"])
                ],
            })
        return content

    def _chat_chunks(self, out, rid: str, created: int):
        base = {
            "id": rid,
            "object": "chat.completion.chunk",
            "created": created,
            "model": self.state.model_name,
        }
        if out.final_output_type == "text" and out.outputs:
            choice = {
                "index": 0,
                "delta": {"role": "assistant",
                          "content": out.outputs[0].text},
                "finish_reason": out.outputs[0].finish_reason,
            }
            if out.outputs[0].logprobs is not None:
                choice["logprobs"] = {"content": self._logprob_content(
                    out.outputs[0].token_ids, out.outputs[0].logprobs)}
            yield {**base, "choices": [choice]}
        elif out.final_output_type == "audio" and "audio" in out.multimodal_output:
            # stream the waveform in bounded chunks so playback can start
            # before the full clip is serialized (reference: chunked audio
            # deltas, serving_chat.py:539 + chunk adapter)
            wav = np.asarray(out.multimodal_output["audio"], np.float32)
            chunk = max(1, _AUDIO_CHUNK_SAMPLES)
            for lo in range(0, len(wav), chunk):
                yield {**base, "choices": [{
                    "index": 0,
                    "delta": {"audio": {
                        "data": base64.b64encode(
                            wav[lo: lo + chunk].tobytes()).decode(),
                        "format": "f32le",
                    }},
                    "finish_reason": None,
                }]}

    # ---------------------------------------------------------- completions
    def _completions(self, body: dict):
        prompt = body.get("prompt")
        if prompt is None:
            return self._error(400, "prompt required")
        # OpenAI prompt forms: str | [str, ...] | [int, ...] (token ids)
        if isinstance(prompt, str):
            prompts = [prompt]
        elif isinstance(prompt, list) and prompt and all(
                isinstance(p, str) for p in prompt):
            prompts = prompt
        elif isinstance(prompt, list) and all(
                isinstance(p, int) for p in prompt):
            prompts = [prompt]
        else:
            return self._error(400, "prompt must be a string, list of "
                               "strings, or list of token ids")
        try:
            sp = _sampling_from_body(body)
        except ValueError as e:
            return self._error(400, str(e))
        rid = f"cmpl-{uuid.uuid4().hex[:16]}"
        info = self._tenant_info()

        def _wrap(p):
            # tenant attribution rides the dict prompt form; a fresh
            # info dict per job (mutable metadata must not be shared)
            if not info:
                return p
            if isinstance(p, str):
                return {"prompt": p, "additional_information": dict(info)}
            return {"prompt_token_ids": list(p),
                    "additional_information": dict(info)}

        jobs = [(_wrap(p), sp, f"{rid}-{i}")
                for i, p in enumerate(prompts)]
        all_outs = self.state.collect_many(jobs)
        choices = []
        for i, outs in enumerate(all_outs):
            if self._surface_error(outs):
                return
            text_out = next(
                (o for o in outs if o.final_output_type == "text"), None)
            if text_out is None:
                return self._error(500, "no text output", "internal_error")
            choice = {
                "index": i,
                "text": text_out.outputs[0].text,
                "finish_reason": text_out.outputs[0].finish_reason,
            }
            entries = text_out.outputs[0].logprobs
            if entries is not None:
                content = self._logprob_content(
                    text_out.outputs[0].token_ids, entries)
                choice["logprobs"] = {  # legacy completions shape
                    "tokens": [c["token"] for c in content],
                    "token_logprobs": [c["logprob"] for c in content],
                    "top_logprobs": [
                        {t["token"]: t["logprob"]
                         for t in c["top_logprobs"]}
                        for c in content],
                    "text_offset": [],
                }
            choices.append(choice)
        self._json(200, {
            "id": rid,
            "object": "text_completion",
            "created": int(time.time()),
            "model": body.get("model", self.state.model_name),
            "choices": choices,
        })

    def _apply_lora_field(self, body: dict, sp: dict):
        """Per-request LoRA through the Images API (reference payload
        {"lora": {"name", "path", "scale"}},
        tests/e2e/online_serving/test_images_generations_lora.py).
        Returns an error string after responding, or None."""
        lora = body.get("lora")
        if lora is None:
            return None
        if isinstance(lora, str):
            lora = {"name": lora}
        if not isinstance(lora, dict) or not (
                lora.get("name") or lora.get("path")):
            self._error(400, "lora must be {'name'|'path'[, 'scale']}")
            return "bad lora"
        lora = dict(lora)
        lora.setdefault("name", lora.get("path"))
        sp.setdefault("extra", {})["lora"] = lora
        return None

    # ------------------------------------------------- images/generations
    def _images_generations(self, body: dict):
        prompt = body.get("prompt")
        if not prompt:
            return self._error(400, "prompt required")
        sp: dict[str, Any] = {}
        if body.get("size"):
            try:
                w, h = body["size"].lower().split("x")
                sp["width"], sp["height"] = int(w), int(h)
            except ValueError:
                return self._error(400, f"bad size {body['size']!r}")
        for k in ("num_inference_steps", "guidance_scale", "seed"):
            if body.get(k) is not None:
                sp[k] = body[k]
        err = self._apply_lora_field(body, sp)
        if err:
            return
        n = int(body.get("n", 1))
        rid = f"img-{uuid.uuid4().hex[:16]}"
        # submit all n at once so the diffusion stage can batch them
        jobs = [(prompt, sp, f"{rid}-{i}") for i in range(n)]
        data = []
        for outs in self.state.collect_many(jobs):
            if self._surface_error(outs):
                return
            for o in outs:
                if o.final_output_type == "image":
                    data.extend(
                        {"b64_json": _b64_png(np.asarray(img))}
                        for img in o.images
                    )
        self._json(200, {"created": int(time.time()), "data": data})

    def _images_edits(self, body: dict):
        """Image editing / image-conditioned generation (reference:
        /v1/images/edits, api_server.py:1051): a base64 input image rides
        ``sampling_params.image`` into an image-conditioned pipeline
        (image-edit or I2V-style conditioning)."""
        prompt = body.get("prompt")
        if not prompt:
            return self._error(400, "prompt required")
        image_b64 = body.get("image")
        if not image_b64:
            return self._error(400, "image required (base64 PNG)")
        try:
            img = _decode_image_part(
                {"image_url": {"url": image_b64}})
        except Exception as e:
            return self._error(400, f"bad image: {e}")
        sp: dict[str, Any] = {}
        if body.get("size"):
            try:
                w, h = body["size"].lower().split("x")
                sp["width"], sp["height"] = int(w), int(h)
            except ValueError:
                return self._error(400, f"bad size {body['size']!r}")
        for k in ("num_inference_steps", "guidance_scale", "seed"):
            if body.get(k) is not None:
                sp[k] = body[k]
        err = self._apply_lora_field(body, sp)
        if err:
            return
        sp["image"] = img
        rid = f"imgedit-{uuid.uuid4().hex[:16]}"
        outs = self.state.collect(prompt, sp, rid)
        if self._surface_error(outs):
            return
        data = []
        for o in outs:
            if o.final_output_type == "image" and o.images:
                data.extend({"b64_json": _b64_png(np.asarray(im))}
                            for im in o.images)
            elif o.final_output_type == "video" and o.images:
                # image-conditioned video pipelines return frames; ship
                # frame 0 as the edited still
                vid = np.asarray(o.images[0])
                if vid.ndim == 4:
                    data.append({"b64_json": _b64_png(vid[0])})
        self._json(200, {"created": int(time.time()), "data": data})

    # ------------------------------------------------------------ videos
    def _videos(self, body: dict):
        """Video generation (reference: /v1/videos, api_server.py:1528).
        Returns frames as base64 raw RGB plus geometry metadata."""
        prompt = body.get("prompt")
        if not prompt:
            return self._error(400, "prompt required")
        sp: dict[str, Any] = {}
        if body.get("size"):
            try:
                w, h = body["size"].lower().split("x")
                sp["width"], sp["height"] = int(w), int(h)
            except ValueError:
                return self._error(400, f"bad size {body['size']!r}")
        for k in ("num_inference_steps", "guidance_scale", "seed",
                  "num_frames", "fps"):
            if body.get(k) is not None:
                sp[k] = body[k]
        rid = f"video-{uuid.uuid4().hex[:16]}"
        outs = self.state.collect(prompt, sp, rid)
        if self._surface_error(outs):
            return
        video = next(
            (o.multimodal_output.get("video",
                                     o.images[0] if o.images else None)
             for o in outs if o.final_output_type == "video"),
            None,
        )
        if video is None:
            return self._error(500, "pipeline produced no video",
                               "internal_error")
        arr = np.asarray(video)
        self._json(200, {
            "created": int(time.time()),
            "data": [{
                "b64_rgb": base64.b64encode(arr.tobytes()).decode(),
                "shape": list(arr.shape),  # [F, H, W, 3]
                "dtype": str(arr.dtype),
            }],
        })

    # ------------------------------------------------------- audio/speech
    def _audio_speech(self, body: dict):
        text = body.get("input")
        if not text:
            return self._error(400, "input required")
        rid = f"speech-{uuid.uuid4().hex[:16]}"
        # a named voice rides additional_information to the vocoder
        # stage, which resolves it through its voice registry
        # (reference: speech request voice -> speaker assets)
        voice = body.get("voice")
        prompt = ({"prompt": text,
                   "additional_information": {"voice": voice}}
                  if voice else text)
        outs = self.state.collect(prompt, {}, rid)
        if self._surface_error(outs):
            return
        audio = next(
            (o.multimodal_output["audio"] for o in outs
             if o.final_output_type == "audio"
             and "audio" in o.multimodal_output),
            None,
        )
        if audio is None:
            return self._error(500, "pipeline produced no audio",
                               "internal_error")
        raw = np.asarray(audio, np.float32).tobytes()
        self.send_response(200)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)


def build_server(
    model: Optional[str] = None,
    stage_configs=None,
    host: str = "0.0.0.0",
    port: int = 8000,
    **overrides,
) -> tuple[ThreadingHTTPServer, ServerState]:
    omni = AsyncOmni(model=model, stage_configs=stage_configs, **overrides)
    state = ServerState(omni, model or "omni")
    handler = type("BoundHandler", (OmniRequestHandler,), {"state": state})
    server = ThreadingHTTPServer((host, port), handler)
    return server, state


def run_server(model=None, stage_configs=None, host="0.0.0.0", port=8000,
               **overrides):
    server, state = build_server(model, stage_configs, host, port, **overrides)
    logger.info("vllm-omni-tpu API server on %s:%d", host, port)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        state.shutdown()
        server.server_close()
