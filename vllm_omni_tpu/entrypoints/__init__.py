from vllm_omni_tpu.entrypoints.omni import Omni
from vllm_omni_tpu.entrypoints.omni_stage import OmniStage, StageRequest

__all__ = ["Omni", "OmniStage", "StageRequest"]
