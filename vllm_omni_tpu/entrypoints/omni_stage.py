"""OmniStage: one pipeline stage wrapping an engine.

Behavioral port of the reference's OmniStage (reference:
entrypoints/omni_stage.py:236 — config parse, worker spawn, submit/
try_collect, process_engine_inputs deriving next-stage inputs).  Where the
reference always spawns a worker process per stage, the TPU-native default
is **in-proc**: a stage is an engine object stepped by the orchestrator's
polling loop (one Python controller per host; pjit does the fan-out).
Process isolation across TPU slices arrives with the TCP connector — the
stage surface (submit / poll / collect) is transport-agnostic.

Engine selection mirrors stage_type (llm | diffusion) from the stage YAML
(reference: omni_stage.py:248-344).
"""

from __future__ import annotations

import importlib
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from vllm_omni_tpu.config.stage import StageConfig
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.metrics.stats import StageRequestStats
from vllm_omni_tpu.outputs import OmniRequestOutput
from vllm_omni_tpu.sampling_params import SamplingParams

logger = init_logger(__name__)


@dataclass
class StageRequest:
    """Transport-level request entering a stage (the analogue of the
    reference's per-stage task dicts in _stage_worker)."""

    request_id: str
    # AR stages: token ids; diffusion stages: text prompt
    prompt_token_ids: Optional[list[int]] = None
    prompt: Optional[str] = None
    sampling_params: dict[str, Any] = field(default_factory=dict)
    prompt_embeds: Optional[Any] = None
    additional_information: dict[str, Any] = field(default_factory=dict)
    # raw media for multimodal thinker stages: {"image": [...], "audio":
    # [...]} — run through the stage's mm_processor at submit (reference:
    # multimodal chat messages -> OmniInputProcessor)
    multi_modal_data: Optional[dict[str, Any]] = None
    # per-request trace context ({"trace_id", "request_id"}), created at
    # Omni/AsyncOmni arrival and re-stamped on every stage handoff by the
    # orchestrator — a plain dict so it survives the stage_proc sockets
    # and connector edges through OmniSerializer (tracing/trace.py)
    trace: Optional[dict[str, Any]] = None
    # REMAINING end-to-end time budget in seconds, decremented by the
    # orchestrator on every stage handoff (resilience/deadline.py) — a
    # plain float for the same serialization reasons as ``trace``.
    # Receiving stages convert it to their own monotonic expiry and
    # enforce it at admission + every step; <= 0 means already expired.
    deadline_s: Optional[float] = None


def _import_obj(path: str):
    mod, _, attr = path.partition(":")
    return getattr(importlib.import_module(mod), attr)


def _auto_llm_factory(model, stage_id: int):
    """Resolve an llm stage's model factory from a bare ``model`` path:
    a .gguf file takes the GGUF intake, a checkpoint directory resolves
    its architecture through OmniModelRegistry (the arch front door —
    reference: model resolution, model_executor/models/registry.py:65 +
    arg_utils.py:96-97 gguf load_format).  Returns (factory, args)."""
    import os

    if isinstance(model, str) and model.endswith(".gguf") \
            and os.path.isfile(model):
        from vllm_omni_tpu.model_loader.gguf_loader import load_gguf_lm

        return load_gguf_lm, {"model_dir": model}
    if isinstance(model, str) and os.path.isdir(model):
        from vllm_omni_tpu.config.stage import _arch_of
        from vllm_omni_tpu.models.registry import OmniModelRegistry

        arch = _arch_of(model)
        if arch and arch in OmniModelRegistry.supported():
            return (OmniModelRegistry.resolve(arch),
                    {"model_dir": model})
        raise ValueError(
            f"stage {stage_id}: architecture {arch!r} not in the AR "
            f"registry ({OmniModelRegistry.supported()}); set "
            "engine_args.model_factory explicitly")
    raise ValueError(
        f"stage {stage_id}: llm stages need engine_args.model_factory "
        "('pkg.mod:fn' -> (params, cfg, eos_id)) or a checkpoint "
        "path/.gguf in engine_args.model")


def _sp_equal(a: dict, b: dict) -> bool:
    """Value equality for merged sampling-param dicts, tolerating array
    values (conditioning tensors in ``extra``) that make plain dict ==
    raise on ambiguous truthiness."""
    if a.keys() != b.keys():
        return False
    for k in a:
        va, vb = a[k], b[k]
        if va is vb:
            continue
        if isinstance(va, dict) and isinstance(vb, dict):
            if not _sp_equal(va, vb):
                return False
            continue
        try:
            if bool(va == vb):
                continue
            return False
        except (ValueError, TypeError):
            import numpy as np

            if not (np.shape(va) == np.shape(vb)
                    and bool(np.all(np.asarray(va) == np.asarray(vb)))):
                return False
    return True


class OmniStage:
    def __init__(self, config: StageConfig):
        self.config = config
        self.stage_id = config.stage_id
        self.tokenizer = None  # set for llm stages in _build_engine
        self.mm_processor = None  # multimodal front end (set in _build_engine)
        self.engine = self._build_engine()
        self._pending: list[StageRequest] = []
        self._done: list[OmniRequestOutput] = []
        self._input_processor = config.resolve_input_processor()
        self._submit_ts: dict[str, float] = {}
        self._trace_ctx: dict[str, dict] = {}
        self.request_stats: list[StageRequestStats] = []
        # spans/metrics from the engine must carry the pipeline position
        if hasattr(self.engine, "stage_id"):
            self.engine.stage_id = self.stage_id
        from vllm_omni_tpu.metrics.profiler import StageProfiler

        self.profiler = StageProfiler(self.stage_id)

    # ----------------------------------------------------------- profiling
    def start_profile(self, trace_dir: str) -> None:
        """Begin a jax.profiler trace for this stage (reference:
        PROFILER_START task, omni_stage.py:740-777)."""
        self.profiler.start(trace_dir)

    def stop_profile(self) -> None:
        self.profiler.stop()

    # -------------------------------------------------------- engine build
    def _build_engine(self):
        args = dict(self.config.engine_args)
        if self.config.stage_type == "llm":
            factory = args.pop("model_factory", None)
            if factory is None:
                # arch front door: a bare ``model`` path resolves its
                # loader from the checkpoint itself — a .gguf file via
                # the GGUF intake (reference: arg_utils.py:96-97), a
                # safetensors dir via config.json architectures
                # (OmniModelRegistry)
                factory, auto_args = _auto_llm_factory(
                    args.get("model"), self.stage_id)
                fa = args.get("model_factory_args") or {}
                fa.update(auto_args)
                args["model_factory_args"] = fa
            if isinstance(factory, str):
                factory = _import_obj(factory)
            factory_args = args.pop("model_factory_args", {}) or {}
            if factory_args.get("model_dir") == "required":
                # a SEPARATE checkpoint the user must supply (e.g. a
                # speech tokenizer) — fail with guidance instead of a
                # weight-coverage error against the wrong directory
                raise ValueError(
                    f"stage {self.stage_id} needs its own checkpoint "
                    "(separate from the model path) — set it with "
                    f"--stage-override '{self.stage_id}."
                    'model_factory_args={"model_dir": "/path"}\'')
            params, model_cfg, eos = factory(**factory_args)
            # voice registry: engine_args.voices maps name -> conditioning
            # assets (speaker_embedding / reference_mel); the serving
            # layer advertises the names (/v1/audio/voices) and vocoder
            # models resolve them per request (batch_conditioning)
            voices = args.get("voices")
            if isinstance(voices, dict) and hasattr(model_cfg, "voices"):
                model_cfg.voices = {
                    name: entry for name, entry in voices.items()
                    if isinstance(entry, dict)
                }
            # multimodal front end (thinker stages): factory builds the
            # encoder+placeholder processor around the model's embed table
            # (reference: Qwen3OmniMoeThinkerMultiModalProcessor)
            mm_factory = args.pop("mm_processor", None)
            if mm_factory is not None:
                if isinstance(mm_factory, str):
                    mm_factory = _import_obj(mm_factory)
                self.mm_processor = mm_factory(
                    params, model_cfg,
                    **(args.pop("mm_processor_args", {}) or {}),
                )
            from vllm_omni_tpu.engine import EngineConfig, LLMEngine

            known = EngineConfig.__dataclass_fields__
            eng_kwargs = {k: v for k, v in args.items() if k in known}
            if isinstance(eng_kwargs.get("kv_transfer"), dict):
                from vllm_omni_tpu.core.scheduler import KVTransferConfig

                eng_kwargs["kv_transfer"] = KVTransferConfig(
                    **eng_kwargs["kv_transfer"]
                )
            # Tokenizer only where text crosses the boundary: entry stages
            # encode string prompts, text-final stages decode outputs.
            # Intermediate codec stages (talker) must NOT decode their token
            # ids into byte-garbage "text".
            is_text_stage = (
                -1 in self.config.engine_input_source
                or (self.config.final_output
                    and self.config.final_output_type == "text")
            )
            if is_text_stage and getattr(model_cfg, "vocab_size", None):
                from vllm_omni_tpu.utils.tokenizer import load_tokenizer

                self.tokenizer = load_tokenizer(
                    args.get("model"), model_cfg.vocab_size
                )
            # MTP draft head for spec decode (talker stages): factory
            # builds draft_fn around the backbone params
            draft_fn = None
            draft_factory = args.pop("draft_factory", None)
            if (draft_factory is not None
                    and eng_kwargs.get("num_speculative_tokens", 0) > 0):
                if isinstance(draft_factory, str):
                    draft_factory = _import_obj(draft_factory)
                draft_fn = draft_factory(
                    params, model_cfg,
                    eng_kwargs["num_speculative_tokens"],
                )
            # EngineConfig(warmup=...) in the stage YAML precompiles the
            # bucketed executables inside LLMEngine.__init__ — before
            # the stage reports ready, so traffic never hits a compile
            engine = LLMEngine(params, model_cfg, EngineConfig(**eng_kwargs),
                               eos_token_id=eos, draft_fn=draft_fn)
            if engine.config.kv_transfer is not None:
                # extracted KV rides the stage output (D2H2D v1); the
                # consuming stage's input processor forwards it into
                # additional_information["kv_payload"] for injection
                from vllm_omni_tpu.distributed.kv_transfer import (
                    make_output_kv_sink,
                )

                engine.kv_transfer_sink = make_output_kv_sink()
            return engine
        elif self.config.stage_type == "diffusion":
            from vllm_omni_tpu.config.diffusion import OmniDiffusionConfig
            from vllm_omni_tpu.diffusion.engine import DiffusionEngine

            od = OmniDiffusionConfig.from_kwargs(**args)
            return DiffusionEngine.make_engine(od)
        raise ValueError(f"unknown stage_type {self.config.stage_type!r}")

    # ------------------------------------------------------------- intake
    def submit(self, reqs: list[StageRequest]) -> None:
        now = time.perf_counter()
        for r in reqs:
            self._submit_ts[r.request_id] = now
            if r.trace:
                self._trace_ctx[r.request_id] = r.trace
        if self.config.stage_type == "llm":
            defaults = dict(self.config.default_sampling_params)
            for r in reqs:
                if (r.prompt_token_ids is None and r.prompt is not None
                        and self.tokenizer is not None):
                    r.prompt_token_ids = self.tokenizer.encode(r.prompt)
                sp_kwargs = {**defaults, **r.sampling_params}
                known = SamplingParams.__dataclass_fields__
                sp = SamplingParams(
                    **{k: v for k, v in sp_kwargs.items() if k in known}
                )
                mm_kwargs = {}
                if r.multi_modal_data and self.mm_processor is None:
                    # silently treating placeholders as ordinary text would
                    # produce wrong output — reject loudly instead
                    self.engine.add_errored_request(
                        r.request_id,
                        "request has multi_modal_data but this stage has "
                        "no mm_processor configured",
                    )
                    continue
                if r.multi_modal_data and self.mm_processor is not None:
                    try:
                        processed = self.mm_processor(
                            list(r.prompt_token_ids or []),
                            r.multi_modal_data,
                        )
                    except (ValueError, TypeError, KeyError) as e:
                        # one bad image/audio must not break batch-mates:
                        # surface as a per-request error output (same
                        # contract as scheduler intake rejection)
                        self.engine.add_errored_request(
                            r.request_id,
                            f"multimodal processing failed: {e}",
                        )
                        continue
                    r.prompt_token_ids = processed.prompt_token_ids
                    r.prompt_embeds = processed.prompt_embeds
                    mm_kwargs = dict(
                        mrope_positions=processed.mrope_positions,
                        mrope_delta=processed.mrope_delta,
                    )
                    ds = getattr(processed, "deepstack_embeds", None)
                    if ds is not None:
                        mm_kwargs["deepstack_embeds"] = ds
                info = dict(r.additional_information)
                if r.trace:
                    # engine-level spans (queue_wait/prefill/decode/
                    # sampling) key off the request's trace context
                    info["trace"] = dict(r.trace)
                # upstream-extracted KV prefix lands in this engine's cache
                # (receive half of the transfer manager)
                injected_kv = info.pop("kv_payload", None)
                from vllm_omni_tpu.resilience.deadline import expiry_ts

                self.engine.add_request(
                    list(r.prompt_token_ids or []), sp,
                    request_id=r.request_id,
                    prompt_embeds=r.prompt_embeds,
                    additional_information=info,
                    injected_kv=injected_kv,
                    # remaining budget -> this process's monotonic clock
                    deadline_ts=expiry_ts(r.deadline_s),
                    **mm_kwargs,
                )
        else:
            from vllm_omni_tpu.resilience.deadline import expiry_ts

            for r in reqs:
                # diffusion engines have no scheduler admission: the
                # batch assembly in _run_diffusion_batch enforces this
                r._deadline_ts = expiry_ts(r.deadline_s)
            self._pending.extend(reqs)

    # -------------------------------------------------------------- drive
    def poll(self) -> list[OmniRequestOutput]:
        """Advance the stage's engine and return newly finished outputs
        (the in-proc analogue of try_collect, omni_stage.py:572)."""
        outs: list[OmniRequestOutput] = []
        if self.config.stage_type == "llm":
            if self.engine.has_unfinished_requests:
                outs = self.engine.step()
        else:
            outs = self._run_diffusion_batch()
        decode_text = (self.tokenizer is not None
                       and self.config.final_output_type == "text")
        for o in outs:
            o.stage_id = self.stage_id
            if decode_text:
                for c in o.outputs:
                    if c.text is None:
                        c.text = self.tokenizer.decode(c.token_ids)
            self._record(o)
        return outs

    @property
    def has_unfinished(self) -> bool:
        if self.config.stage_type == "llm":
            return self.engine.has_unfinished_requests
        return bool(self._pending)

    def _merged_sp_kwargs(self, r: StageRequest) -> dict[str, Any]:
        # memoized per request: requests can sit in _pending across many
        # polls and the merge/compare runs in the hot polling loop
        cached = getattr(r, "_merged_sp", None)
        if cached is not None:
            return cached
        from vllm_omni_tpu.diffusion.request import OmniDiffusionSamplingParams

        defaults = dict(self.config.default_sampling_params)
        merged = {**defaults, **r.sampling_params}
        known = OmniDiffusionSamplingParams.__dataclass_fields__
        merged = {k: v for k, v in merged.items() if k in known}
        r._merged_sp = merged
        return merged

    def _run_diffusion_batch(self) -> list[OmniRequestOutput]:
        if not self._pending:
            return []
        from vllm_omni_tpu.resilience.deadline import (
            deadline_output,
            expired,
        )

        # deadline enforcement at batch assembly (the diffusion analogue
        # of scheduler admission): a queued request whose budget ran out
        # terminates as deadline_exceeded instead of burning a full
        # denoising run
        live, dead = [], []
        for r in self._pending:
            (dead if expired(getattr(r, "_deadline_ts", None))
             else live).append(r)
        if dead:
            # poll() records these like any other batch outcome
            self._pending = live
            return [deadline_output(r.request_id, self.stage_id,
                                    "expired in diffusion queue")
                    for r in dead]
        from vllm_omni_tpu.diffusion.request import (
            OmniDiffusionRequest,
            OmniDiffusionSamplingParams,
        )

        # Batch only requests whose effective sampling params match the
        # head request's — a diffusion batch shares one geometry/steps/seed
        # (reference batches under the identical-sampling-params constraint,
        # omni_stage.py:797-843; ADVICE r1 medium: batching mixed params
        # silently applied the first request's params to all). Plain dict
        # equality, not a repr key: repr truncates large arrays and is
        # insertion-order sensitive.
        merged = [self._merged_sp_kwargs(r) for r in self._pending]
        head = merged[0]
        batch: list[StageRequest] = []
        rest: list[StageRequest] = []
        limit = max(1, self.config.runtime.max_batch_size)
        for r, m in zip(self._pending, merged):
            if len(batch) < limit and _sp_equal(m, head):
                batch.append(r)
            else:
                rest.append(r)
        self._pending = rest
        sp = OmniDiffusionSamplingParams(**head)
        req = OmniDiffusionRequest(
            prompt=[r.prompt or "" for r in batch],
            sampling_params=sp,
            request_ids=[r.request_id for r in batch],
        )
        t0, w0 = time.perf_counter(), time.time()
        try:
            diff_outs = self.engine.step(req)
        except Exception as e:
            # Scope the failure to this batch's requests (ADVICE r1 low:
            # a poll exception must not take down unrelated streams).
            logger.exception(
                "stage %d: diffusion batch failed (%d reqs)",
                self.stage_id, len(batch),
            )
            from vllm_omni_tpu.diffusion.request import InvalidRequestError

            kind = ("invalid_request" if isinstance(e, InvalidRequestError)
                    else "internal")
            return [
                OmniRequestOutput.from_error(
                    r.request_id, f"{type(e).__name__}: {e}",
                    stage_id=self.stage_id, kind=kind,
                )
                for r in batch
            ]
        dur = time.perf_counter() - t0
        from vllm_omni_tpu.tracing import get_recorder

        rec = get_recorder()
        for r in batch:
            rec.record(r.trace, "diffusion_generate", w0, dur,
                       stage_id=self.stage_id,
                       args={"batch": len(batch),
                             "steps": sp.num_inference_steps})
        return [
            OmniRequestOutput.from_diffusion(
                o.request_id, [o.data], final_output_type=o.output_type
            )
            for o in diff_outs
        ]

    # --------------------------------------------- next-stage input derive
    def process_engine_inputs(
        self, upstream_outputs: list[OmniRequestOutput]
    ) -> list[StageRequest]:
        """Derive this stage's inputs from upstream outputs (reference:
        omni_stage.py:585-634; default: prev output token ids become the
        next prompt, custom fn hook for model-specific wiring)."""
        if self._input_processor is not None:
            return self._input_processor(self.config, upstream_outputs)
        reqs = []
        for out in upstream_outputs:
            token_ids = out.outputs[0].token_ids if out.outputs else []
            text = out.outputs[0].text if out.outputs else None
            reqs.append(StageRequest(
                request_id=out.request_id,
                prompt_token_ids=list(token_ids),
                prompt=text,
            ))
        return reqs

    # ------------------------------------------------------------- metrics
    def engine_metrics_snapshot(self) -> dict:
        """Step-level engine metrics for /metrics; {} when the engine
        exposes none (ProcStage overrides with the worker's last shipped
        snapshot)."""
        fn = getattr(self.engine, "metrics_snapshot", None)
        return fn() if fn is not None else {}

    def _record(self, out: OmniRequestOutput) -> None:
        t0 = self._submit_ts.pop(out.request_id, None)
        gen_ms = (time.perf_counter() - t0) * 1e3 if t0 else 0.0
        ctx = self._trace_ctx.pop(out.request_id, None)
        if ctx is not None:
            # stage-granularity span: submit to output (covers queue +
            # compute; for proc stages it additionally covers transport)
            from vllm_omni_tpu.tracing import get_recorder

            get_recorder().record(
                ctx, "stage", time.time() - gen_ms / 1e3, gen_ms / 1e3,
                stage_id=self.stage_id, cat="stage",
                args={"tokens_out": sum(len(c.token_ids)
                                        for c in out.outputs)},
            )
        self.request_stats.append(StageRequestStats(
            request_id=out.request_id,
            stage_id=self.stage_id,
            tokens_in=len(out.prompt_token_ids),
            tokens_out=sum(len(c.token_ids) for c in out.outputs),
            gen_ms=gen_ms,
        ))
