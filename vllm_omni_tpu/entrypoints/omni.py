"""Omni: the offline multi-stage pipeline orchestrator.

Behavioral port of the reference's Omni entrypoint (reference:
entrypoints/omni.py:513 ``generate``; _run_generation polling loop
:640-910 — seed stage-0, forward stage→stage via connectors, yield at
final_output stages, per-stage + E2E metrics).

The polling loop keeps the reference's dataflow contract:

  user prompts → stage[0] → (process_engine_inputs) → stage[1] → … →
  OmniRequestOutput at every stage marked final_output

with connector-mediated edges (in-proc by default; shm/tcp for
cross-process stages) and the metrics aggregator recording per-stage
stats and transfer-edge bytes.
"""

from __future__ import annotations

import time
from typing import Any, Optional, Sequence, Union

from vllm_omni_tpu.config.stage import (
    StageConfig,
    load_stage_configs_from_model,
    load_stage_configs_from_yaml,
)
from vllm_omni_tpu.distributed.connectors import ConnectorFactory, make_key
from vllm_omni_tpu.entrypoints.omni_stage import OmniStage, StageRequest
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.metrics.stats import OrchestratorAggregator
from vllm_omni_tpu.outputs import OmniRequestOutput
from vllm_omni_tpu.tracing import TraceWriter, get_recorder, new_trace_context

logger = init_logger(__name__)


class Omni:
    def __init__(
        self,
        model: Optional[str] = None,
        stage_configs: Optional[Union[str, list[StageConfig]]] = None,
        stats_path: Optional[str] = None,
        trace_path: Optional[str] = None,
        **overrides: Any,
    ):
        if stage_configs is None:
            if model is None:
                raise ValueError("need model name or stage_configs")
            configs = load_stage_configs_from_model(model)
        elif isinstance(stage_configs, str):
            configs = load_stage_configs_from_yaml(stage_configs)
        else:
            configs = stage_configs
        known = {f"stage{cfg.stage_id}" for cfg in configs}
        bad = [k for k in overrides
               if k.startswith("stage") and k not in known]
        if bad:
            raise ValueError(
                f"overrides target nonexistent stages {bad}; pipeline "
                f"has {sorted(known)}")
        for cfg in configs:
            cfg.engine_args.update(overrides.get(f"stage{cfg.stage_id}", {}))
        self.stage_configs = configs
        # HBM budgeting for co-located (in-proc) stages: validate the
        # declared fractions BEFORE any engine allocates, snapshot after
        # each build (reference: per-process NVML accounting,
        # worker/gpu_memory_utils.py:22-124)
        from vllm_omni_tpu.platforms.memory import StageMemoryAccountant

        self.memory_accountant = StageMemoryAccountant()
        colocated = [c for c in configs if not c.runtime.process]
        declared = {c.stage_id: float(c.engine_args["gpu_memory_utilization"])
                    for c in colocated
                    if c.engine_args.get("gpu_memory_utilization")
                    is not None}
        undeclared = [c for c in colocated
                      if c.stage_id not in declared]
        # undeclared stages share whatever budget the declared ones left;
        # no leftover means the declared fractions already consume the
        # device — fail HERE, not with a RESOURCE_EXHAUSTED mid-request
        leftover = 1.0 - sum(declared.values())
        if undeclared and leftover <= 1e-6:
            raise ValueError(
                "declared gpu_memory_utilization fractions "
                f"({declared}) leave no HBM for stages "
                f"{[c.stage_id for c in undeclared]} sharing the "
                "device; declare fractions for every co-located stage")
        default = leftover / len(undeclared) if undeclared else 0.0
        for c in colocated:
            # register() rejects fractions outside (0, 1] — an explicit
            # 0.0 is a config error, not a skip
            self.memory_accountant.register(
                c.stage_id, declared.get(c.stage_id, default))
        self.memory_accountant.validate()
        if colocated:
            # baseline is only consumed by in-proc snapshots; touching
            # the platform here for an all-process config would acquire
            # the TPU in the parent before the children can
            self.memory_accountant.capture_baseline()
        # process-disaggregated stages spawn workers (ready handshake
        # inside ProcStage); in-proc stages build engines directly
        self.stages = []
        for cfg in configs:
            if cfg.runtime.process:
                from vllm_omni_tpu.entrypoints.stage_proc import ProcStage

                env = cfg.runtime.device_env
                if not env:
                    # env-sniffed default (CUDA_VISIBLE_DEVICES analogue)
                    # — deliberately NOT current_platform(): that would
                    # initialize jax in the parent and acquire the TPU
                    # the children need
                    from vllm_omni_tpu.platforms import (
                        default_stage_device_env,
                    )

                    env = default_stage_device_env(cfg.runtime.devices)
                if getattr(cfg.runtime, "supervise", True):
                    # supervised by default: worker crash/hang becomes
                    # restart + redeliver instead of a dead stage
                    # (resilience/supervisor.py)
                    from vllm_omni_tpu.resilience.supervisor import (
                        StageSupervisor,
                    )

                    self.stages.append(
                        StageSupervisor(cfg, device_env=env))
                else:
                    self.stages.append(ProcStage(cfg, device_env=env))
            else:
                self.stages.append(OmniStage(cfg))
                self.memory_accountant.snapshot(cfg.stage_id)
        self.metrics = OrchestratorAggregator(len(configs), stats_path)
        # per-request distributed tracing: a trace context created at
        # arrival, re-stamped on every stage handoff, closed at final
        # output.  ``trace_path`` is a path prefix like ``stats_path``
        # ({prefix}.trace.jsonl + {prefix}.trace.json Chrome trace);
        # OMNI_TPU_TRACE_PATH is the env fallback.
        if trace_path is None:
            from vllm_omni_tpu import envs

            trace_path = envs.OMNI_TPU_TRACE_PATH or None
        self._trace_writer = (TraceWriter(trace_path)
                              if trace_path else None)
        self._trace_ctx: dict[str, dict] = {}
        self._trace_arrival: dict[str, float] = {}
        # end-to-end request deadlines (resilience/deadline.py): the
        # authoritative monotonic expiry lives HERE; handoffs ship the
        # remaining budget.  OMNI_TPU_DEFAULT_DEADLINE_S > 0 applies a
        # fleet-wide default to requests that don't set their own.
        from vllm_omni_tpu import envs as _envs

        self._default_deadline_s: Optional[float] = (
            _envs.OMNI_TPU_DEFAULT_DEADLINE_S or None)
        self._deadline_ts: dict[str, float] = {}
        # connector per pipeline edge (from->to), from stage YAML
        # output_connectors; in-proc default
        self._edge_connectors = {}
        for cfg in configs:
            for to_str, spec in cfg.output_connectors.items():
                spec = dict(spec)
                name = spec.pop("connector", "inproc")
                self._edge_connectors[(cfg.stage_id, int(to_str))] = (
                    ConnectorFactory.create(name, **spec)
                )
        # stall watchdog (introspection/watchdog.py): every in-proc
        # engine registers a progress probe; supervised process stages
        # feed the same trip machinery through their heartbeat state.
        # The monitor thread only starts when OMNI_TPU_WATCHDOG_S > 0 —
        # the object always exists so /debug/watchdog and the /health
        # snapshot have one source of truth (and tests can drive
        # check_once with a fake clock).
        from vllm_omni_tpu.introspection import StallWatchdog

        deadline = float(_envs.OMNI_TPU_WATCHDOG_S or 0.0)
        self.watchdog = StallWatchdog(deadline_s=deadline or 60.0)
        for stage in self.stages:
            eng = getattr(stage, "engine", None)
            if eng is not None and hasattr(eng, "introspect_progress"):
                self.watchdog.add_engine(
                    f"stage{stage.stage_id}/engine", eng)
            elif hasattr(stage, "_restart_policy"):  # StageSupervisor
                self.watchdog.add_supervisor(
                    f"stage{stage.stage_id}/supervisor", stage)
        if deadline > 0:
            self.watchdog.start()
        # omnipulse alerting (metrics/alerts.py): the detection layer
        # over the sensors above — multi-window burn-rate rules over
        # the SLO/shed/queue/saturation registries, the watchdog trip
        # surfaced as a firing `engine_stalled` alert (one source of
        # truth for "this replica is wedged"), and alert-triggered
        # evidence capture through the flight-recorder dump path.
        # Same lifecycle stance as the watchdog: the object always
        # exists (one source of truth for /debug/alerts + /health);
        # the evaluation thread only starts when OMNI_TPU_ALERTS_S > 0
        from vllm_omni_tpu.metrics.alerts import (
            AlertEngine,
            build_default_rules,
        )

        alert_interval = float(_envs.OMNI_TPU_ALERTS_S or 0.0)
        self.alerts = AlertEngine(build_default_rules(self),
                                  interval_s=alert_interval or 5.0)
        # evidence riders: a firing alert's bundle carries the fleet
        # cache-economics board when a disagg router is attached
        # (getattr-defensive — most deployments have no router), so a
        # prefix_hit_rate_low page records WHICH prefixes scattered
        self.alerts.add_evidence_provider(
            "cache_board",
            lambda: (lambda c: c.board() if c is not None else None)(
                getattr(getattr(self, "router", None), "cache", None)))
        self.watchdog.on_trip(
            lambda doc: self.alerts.force_firing(
                "engine_stalled", reason="watchdog trip"))
        if alert_interval > 0:
            self.alerts.start()

    # ------------------------------------------------------------- tracing
    @property
    def tracing_enabled(self) -> bool:
        return self._trace_writer is not None

    def trace_begin(self, request_id: str,
                    trace_id: Optional[str] = None) -> Optional[dict]:
        """Create the request's trace context at arrival (None when
        tracing is disabled — every recording call downstream no-ops).

        ``trace_id``: an EXTERNAL trace id to join (the OpenAI server's
        ``traceparent`` / ``x-omni-trace-id`` headers, already
        validated) — the request's spans continue the caller's trace
        instead of minting a fresh id.  An explicit join also enables
        recording without a writer: the caller opted this one request
        into tracing, and the bounded recorder absorbs it."""
        if self._trace_writer is None and trace_id is None:
            return None
        ctx = new_trace_context(request_id)
        if trace_id:
            ctx["trace_id"] = str(trace_id)
        self._trace_ctx[request_id] = ctx
        self._trace_arrival[request_id] = time.time()
        return ctx

    def deadline_begin(self, request_id: str,
                       deadline_s: Optional[float]) -> Optional[float]:
        """Arm the request's end-to-end deadline at arrival (None — and
        no env default — means unbounded).  Returns the budget used."""
        if deadline_s is None:
            deadline_s = self._default_deadline_s
        if deadline_s is not None:
            self._deadline_ts[request_id] = (time.monotonic()
                                             + float(deadline_s))
        return deadline_s

    def trace_finish(self, request_id: str) -> None:
        """Close the request's trace at final output: emits the
        whole-lifetime "request" span on the orchestrator track."""
        self._deadline_ts.pop(request_id, None)
        ctx = self._trace_ctx.pop(request_id, None)
        t0 = self._trace_arrival.pop(request_id, None)
        if ctx is None or t0 is None:
            return
        get_recorder().record(ctx, "request", t0, time.time() - t0,
                              stage_id=-1, cat="request")

    def flush_traces(self, export_chrome: bool = True) -> None:
        """Drain recorded spans into the trace files (offline: called at
        end-of-generate; online: every stats heartbeat + shutdown).

        ``export_chrome=False`` appends the JSONL only — rewriting the
        complete Chrome document (json.dump of up to 200k spans) every
        heartbeat would stall the engine loop, spiking in-flight ITL; the
        heartbeat streams, and the document is written at shutdown (or
        rebuilt offline from the JSONL)."""
        if self._trace_writer is None:
            return
        self._trace_writer.write(get_recorder().drain())
        if export_chrome:
            self._trace_writer.export_chrome()

    # ------------------------------------------------------------ dataflow
    def _consumers(self, stage_id: int) -> list[OmniStage]:
        return [s for s in self.stages
                if stage_id in s.config.engine_input_source]

    def _forward(self, from_stage: OmniStage,
                 outputs: list[OmniRequestOutput]) -> None:
        """Ship finished outputs to every consumer stage, riding the edge
        connector when one is configured (reference: try_send_via_connector,
        omni.py:868-878)."""
        import os

        force_ser = os.environ.get(
            "OMNI_TPU_FORCE_CONNECTOR_SERIALIZATION") == "1"
        from vllm_omni_tpu.resilience.deadline import clamp_timeout

        for consumer in self._consumers(from_stage.stage_id):
            reqs = consumer.process_engine_inputs(outputs)
            # re-stamp the trace context AND the remaining deadline
            # budget on every handoff: the default input processor (and
            # custom ones) build fresh StageRequests that would
            # otherwise drop both at the stage boundary.  The budget is
            # re-derived from the orchestrator's clock each time, so a
            # slow stage shrinks what downstream stages get; a <= 0
            # remainder is still shipped — the consumer's admission
            # turns it into the DeadlineExceeded output.
            now_mono = time.monotonic()
            for r in reqs:
                ctx = self._trace_ctx.get(r.request_id)
                if ctx is not None:
                    r.trace = ctx
                dts = self._deadline_ts.get(r.request_id)
                if dts is not None:
                    r.deadline_s = dts - now_mono
            edge = (from_stage.stage_id, consumer.stage_id)
            conn = self._edge_connectors.get(edge)
            if (conn is not None and getattr(conn, "zero_copy", False)
                    and not force_ser):
                # same address space: hand the objects over — a
                # put-then-get on the same thread measures serialization,
                # not transport (VERDICT r2 weak #5)
                conn = None
            t0, w0 = time.perf_counter(), time.time()
            req_bytes: dict[str, int] = {}
            if conn is not None:
                nbytes = 0
                for r in reqs:
                    key = make_key(r.request_id, *edge)
                    n = conn.put(key, r.__dict__)
                    req_bytes[r.request_id] = n
                    nbytes += n
                shipped = []
                for r in reqs:
                    key = make_key(r.request_id, *edge)
                    # the wait for an edge payload never outlives the
                    # request's deadline
                    dts = self._deadline_ts.get(r.request_id)
                    payload = conn.get(key,
                                       timeout=clamp_timeout(30.0, dts))
                    if payload is None:
                        if dts is not None \
                                and time.monotonic() >= dts:
                            # expired waiting on the edge: hand the
                            # in-memory request over; the consumer's
                            # admission rejects it as DeadlineExceeded
                            shipped.append(r)
                            continue
                        raise TimeoutError(f"connector lost {key}")
                    shipped.append(StageRequest(**payload))
                self.metrics.record_transfer(
                    *edge, nbytes, (time.perf_counter() - t0) * 1e3
                )
                reqs = shipped
            dur = time.perf_counter() - t0
            rec = get_recorder()
            for r in reqs:
                # zero-copy handoffs record a (near-zero) span too, so a
                # trace always shows every edge a request crossed
                rec.record(r.trace, "transfer", w0, dur,
                           stage_id=consumer.stage_id, cat="transfer",
                           args={"edge": f"{edge[0]}->{edge[1]}",
                                 "bytes": req_bytes.get(r.request_id, 0)})
            consumer.submit(reqs)

    # ------------------------------------------------------------ generate
    def generate(
        self,
        prompts: Sequence[Union[str, dict, list[int]]],
        sampling_params_list: Optional[Sequence[dict]] = None,
        deadline_s: Optional[float] = None,
    ) -> list[OmniRequestOutput]:
        """Run the full pipeline over the prompts (reference: omni.py:570).

        Prompt forms: token-id list (AR stage-0), str (diffusion stage-0 or
        tokenizer-equipped AR), or dict with explicit StageRequest fields.
        ``deadline_s`` bounds each request end-to-end (dict prompts may
        carry a per-request ``deadline_s`` overriding it); an expired
        request terminates with a ``deadline_exceeded`` error output.
        """
        sp_list = list(sampling_params_list or [{}] * len(prompts))
        if len(sp_list) != len(prompts):
            raise ValueError("sampling_params_list length mismatch")
        seed: list[StageRequest] = []
        for i, (p, sp) in enumerate(zip(prompts, sp_list)):
            rid = f"omni-{i}"
            if isinstance(p, dict):
                seed.append(StageRequest(request_id=rid, sampling_params=sp, **p))
            elif isinstance(p, str):
                seed.append(StageRequest(request_id=rid, prompt=p,
                                         sampling_params=sp))
            else:
                seed.append(StageRequest(request_id=rid,
                                         prompt_token_ids=list(p),
                                         sampling_params=sp))
            self.metrics.record_arrival(rid)
            seed[-1].trace = self.trace_begin(
                rid, trace_id=seed[-1].additional_information.pop(
                    "trace_id", None))
            # deadline armed at arrival; the seed request carries the
            # full budget into stage 0's admission
            seed[-1].deadline_s = self.deadline_begin(
                rid,
                seed[-1].deadline_s if seed[-1].deadline_s is not None
                else deadline_s)

        expected = {r.request_id for r in seed}
        n_finals = max(1, sum(1 for s in self.stages
                              if s.config.final_output))
        entry = [s for s in self.stages if -1 in s.config.engine_input_source]
        (entry[0] if entry else self.stages[0]).submit(seed)

        # a request may surface at several final_output stages (e.g. thinker
        # text AND code2wav audio, reference: omni.py:818-844 yields per
        # final stage) — collect all, ordered by stage
        finals: dict[str, list[OmniRequestOutput]] = {}
        # polling loop (reference hot loop, omni.py:738-741)
        while any(s.has_unfinished for s in self.stages):
            for stage in self.stages:
                outs = stage.poll()
                if not outs:
                    continue
                # Errored outputs terminate their request here: they are
                # surfaced to the caller from whichever stage failed and
                # never forwarded downstream.
                errs = [o for o in outs if o.is_error]
                outs = [o for o in outs if not o.is_error]
                for o in errs:
                    finals.setdefault(o.request_id, []).append(o)
                    self.metrics.record_finish(o.request_id)
                    self.trace_finish(o.request_id)
                if stage.config.final_output:
                    for o in outs:
                        o.final_output_type = stage.config.final_output_type
                        finals.setdefault(o.request_id, []).append(o)
                        # E2E spans through the LAST final stage (the
                        # aggregator evicts on finish, so an early call
                        # would freeze e2e at the first final output)
                        if len(finals[o.request_id]) >= n_finals:
                            self.metrics.record_finish(o.request_id)
                            self.trace_finish(o.request_id)
                if outs:
                    self._forward(stage, outs)
        self.harvest_stage_stats()
        # requests lost in the pipeline must not leak trace/deadline state
        for r in seed:
            self._trace_ctx.pop(r.request_id, None)
            self._trace_arrival.pop(r.request_id, None)
            self._deadline_ts.pop(r.request_id, None)
        self.flush_traces()
        missing = expected - set(finals)
        if missing:
            logger.warning("requests lost in pipeline: %s", sorted(missing))
        return [o for r in seed for o in finals.get(r.request_id, [])]

    def harvest_stage_stats(self) -> None:
        """Drain per-stage request stats into the aggregator (called at
        end-of-generate offline, and every heartbeat online)."""
        for stage in self.stages:
            for s in stage.request_stats:
                self.metrics.record_stage_request(s)
            stage.request_stats.clear()

    def stats_summary(self) -> dict:
        """Aggregator summary enriched with per-stage engine counters
        (prefix-cache hits for in-proc AR stages) and the step-level
        engine snapshots (scheduler depth, KV utilization, TTFT/TPOT/ITL
        — the JSON face of the Prometheus exposition)."""
        summ = self.metrics.summary()
        for stage in self.stages:
            eng = getattr(stage, "engine", None)
            pcs = getattr(eng, "prefix_cache_stats", None)
            if pcs and pcs.get("enabled"):
                summ["stages"].setdefault(stage.config.stage_id, {})[
                    "prefix_cache"] = {k: pcs[k]
                                       for k in ("hits", "hit_tokens")}
        summ["engines"] = {
            stage.stage_id: stage.engine_metrics_snapshot()
            for stage in self.stages
        }
        return summ

    def shutdown(self) -> None:
        """Stop process-disaggregated stage workers (no-op for in-proc
        stages)."""
        self.watchdog.stop()
        self.alerts.stop()
        self.flush_traces()
        for stage in self.stages:
            stop = getattr(stage, "shutdown", None)
            if callable(stop):
                stop()

    # ------------------------------------------------------------ profiling
    def start_profile(self, trace_dir: str) -> None:
        """Fan a jax.profiler trace out to every stage (reference:
        Omni.start_profile RPC chain, omni.py:398-497); traces land under
        ``trace_dir/stage_{id}`` in XPlane format."""
        for stage in self.stages:
            stage.start_profile(trace_dir)

    def stop_profile(self) -> None:
        # two-phase for proc stages: send every stop first, then wait on
        # the acks — serial stop+wait would stack timeouts per stage
        waiters = []
        for stage in self.stages:
            if hasattr(stage, "wait_profile_ack"):
                stage.stop_profile(wait=False)
                waiters.append(stage)
            else:
                stage.stop_profile()
        for stage in waiters:
            stage.wait_profile_ack()
