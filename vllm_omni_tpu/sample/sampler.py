"""Batched token sampler (greedy / temperature / top-k / top-p).

TPU-native replacement for vLLM's GPU sampler as used by the reference's
AR runner (reference: worker/gpu_ar_model_runner.py:441-444 `_sample`).
Per-request sampling params are vectorized into device arrays so one jitted
function serves any mixed batch — greedy requests ride the same kernel with
temperature 0 handled via argmax selection, avoiding a recompile per
param combination.

Stateless: the caller supplies a fold-in of (seed, step) per request so
resampling a step is deterministic (needed for spec-decode verify later).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional

import functools

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.sampling_params import SamplingParams

_NEG_INF = -1e30


@dataclass
class SamplingTensors:
    temperature: jax.Array  # [B] f32
    top_k: jax.Array        # [B] i32 (0 = off)
    top_p: jax.Array        # [B] f32
    keys: jax.Array         # [B, 2] u32 PRNG keys
    # retained inputs of the key derivation so a cached instance can be
    # re-keyed for a new step without redoing the host-side assembly
    # (pure-decode batches keep the same params for hundreds of steps)
    seeds: Optional[jax.Array] = None   # [B] u32
    salts: Optional[jax.Array] = None   # [B] u32

    def rekey(self, step: int) -> "SamplingTensors":
        """Same batch, new step: only the PRNG keys depend on the step
        index, so a cached instance is reused by swapping keys (one tiny
        fused dispatch instead of rebuilding four arrays)."""
        if self.seeds is None or self.salts is None:
            raise ValueError("rekey needs seeds/salts retained by build()")
        keys = _build_keys(self.seeds, self.salts,
                           jnp.asarray(step, jnp.uint32))
        return dataclasses.replace(self, keys=keys)

    @staticmethod
    def build(
        params: list[SamplingParams],
        step: int,
        base_seed: int = 0,
        salts: Optional[list[int]] = None,
    ):
        """``salts`` (e.g. a stable hash of each request_id) decorrelate
        unseeded requests from each other; explicit per-request seeds remain
        fully deterministic regardless of salt/base_seed."""
        temp = np.array([p.temperature for p in params], np.float32)
        top_k = np.array([p.top_k for p in params], np.int32)
        top_p = np.array([p.top_p for p in params], np.float32)
        if salts is None:
            salts = list(range(len(params)))
        # one vectorized dispatch for the whole batch (per-request PRNGKey/
        # fold_in chains would cost ~4 tiny device ops per row per step)
        seeds = np.array(
            [p.seed if p.seed is not None else base_seed for p in params],
            np.uint32,
        )
        salt_arr = np.array(
            [0 if p.seed is not None else (s & 0x7FFFFFFF)
             for p, s in zip(params, salts)],
            np.uint32,
        )
        seeds_dev = jnp.asarray(seeds)
        salts_dev = jnp.asarray(salt_arr)
        keys = _build_keys(seeds_dev, salts_dev,
                           jnp.asarray(step, jnp.uint32))
        return SamplingTensors(
            temperature=jnp.asarray(temp),
            top_k=jnp.asarray(top_k),
            top_p=jnp.asarray(top_p),
            keys=jnp.asarray(keys),
            seeds=seeds_dev,
            salts=salts_dev,
        )


@jax.jit
def _build_keys(seeds: jax.Array, salts: jax.Array, step: jax.Array):
    """[B] seeds + [B] salts + scalar step -> [B, 2] key data, vmapped into
    a single compiled dispatch. Seeded requests pass salt 0 so their stream
    depends only on (seed, step)."""

    def one(seed, salt):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), salt)
        return jax.random.key_data(jax.random.fold_in(key, step))

    return jax.vmap(one)(seeds, salts)


def _mask_top_k(logits: jax.Array, k: jax.Array) -> jax.Array:
    """Per-row top-k mask; k==0 disables."""
    vocab = logits.shape[-1]
    sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]
    k_eff = jnp.where(k <= 0, vocab, jnp.minimum(k, vocab))
    thresh = jnp.take_along_axis(sorted_desc, (k_eff - 1)[:, None], axis=-1)
    return jnp.where(logits < thresh, _NEG_INF, logits)


def _mask_top_p(logits: jax.Array, p: jax.Array) -> jax.Array:
    """Nucleus mask: keep the smallest prefix of the sorted distribution
    with cumulative prob >= p (always keeps the argmax)."""
    sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep entries where the cumulative mass *before* them is < p
    keep = (cum - probs) < p[:, None]
    thresh = jnp.min(
        jnp.where(keep, sorted_desc, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits < thresh, _NEG_INF, logits)


def _filtered_scaled(logits, temperature, top_k, top_p):
    """Temperature-scaled logits with top-k/top-p masks applied — the
    ONE definition of the sampling distribution, shared by the draw path
    (``sample_tokens``) and the spec-decode verify path
    (``filtered_probs``), which must score exactly the distribution the
    draw path samples from.  Each mask costs a full-vocab sort, so it
    runs only when some SAMPLING row requests it (greedy rows' filters
    are discarded downstream and must not trip the predicate — OpenAI
    clients routinely send top_p alongside temperature=0)."""
    safe_t = jnp.where(temperature <= 0.0, 1.0, temperature)
    scaled = logits / safe_t[:, None]
    sampling = temperature > 0.0
    scaled = jax.lax.cond(
        jnp.any(sampling & (top_k > 0)),
        lambda x: _mask_top_k(x, top_k), lambda x: x, scaled)
    return jax.lax.cond(
        jnp.any(sampling & (top_p < 1.0)),
        lambda x: _mask_top_p(x, top_p), lambda x: x, scaled)


@jax.jit
def sample_tokens(
    logits: jax.Array,       # [B, vocab]
    temperature: jax.Array,  # [B]
    top_k: jax.Array,        # [B]
    top_p: jax.Array,        # [B]
    keys: jax.Array,         # [B, 2] key data
) -> jax.Array:
    """Returns sampled token ids [B] i32.

    The top-k/top-p masks each cost a FULL-vocab sort per row — the
    dominant non-matmul work in a decode step (two sorts of
    [B, 151936] f32) — so an all-greedy batch (the common serving case,
    and every step inside the greedy multi-step decode scan) skips the
    whole sampling branch with a lax.cond rather than computing it and
    discarding it through the final where."""
    logits = logits.astype(jnp.float32)
    greedy_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def _sampled(_):
        scaled = _filtered_scaled(logits, temperature, top_k, top_p)

        def draw(key_data, row):
            return jax.random.categorical(
                jax.random.wrap_key_data(key_data), row)

        return jax.vmap(draw)(keys, scaled).astype(jnp.int32)

    sampled_ids = jax.lax.cond(
        jnp.any(temperature > 0.0), _sampled, lambda _: greedy_ids, None)
    return jnp.where(temperature <= 0.0, greedy_ids, sampled_ids)


@jax.jit
def spec_verify_tokens(
    cand_logits: jax.Array,  # [S, V, vocab] logits at candidate rows
    drafts: jax.Array,       # [S, V-1] draft token ids (0-padded)
    n_cand: jax.Array,       # [S] candidates per row (1 = plain sample)
    temperature: jax.Array,  # [S]
    top_k: jax.Array,        # [S]
    top_p: jax.Array,        # [S]
    keys: jax.Array,         # [S, 2] key data
) -> tuple[jax.Array, jax.Array]:
    """ON-DEVICE speculative verify + accept for a batch of candidate
    rows — the accept-mask rebuild of the split path's host-side
    ``_run_spec_decode`` loop (which paid an argmax ``device_get``, a
    filtered-probs ``device_get``, and a numpy RNG walk per verify
    step).  Returns ``(tokens [S, V] i32, counts [S] i32)``: row ``s``
    emits ``tokens[s, :counts[s]]``.

    Row semantics (``V`` = 1 + max draft length; row ``s`` carries
    ``n_cand[s] - 1`` real drafts):

    - plain rows (``n_cand == 1``): ``counts == 1`` and ``tokens[:, 0]``
      is EXACTLY ``sample_tokens(cand_logits[:, 0], ...)`` — greedy
      argmax or the same categorical draw from the same key, so folding
      plain sampling and verify into one executable changes no stream.
    - greedy verify (``temperature == 0``): accept the longest draft
      prefix matching per-position argmax, then the bonus argmax — the
      split path's accept loop, bit-identical.
    - sampled verify: rejection sampling against the filtered target
      distribution (accept draft ``d_j`` w.p. ``p_j(d_j)``; on
      rejection draw the replacement from ``p_j`` with ``d_j`` excluded
      and renormalized; full acceptance draws the bonus from the last
      candidate's distribution) — the emitted stream is exactly
      p-distributed.  Randomness is a deterministic per-(request, step,
      position) stream derived from ``keys``.
    """
    s, v, vocab = cand_logits.shape
    logits = cand_logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [S, V]
    if v > 1:
        j_idx = jnp.arange(v - 1)
        has_draft = j_idx[None, :] < (n_cand - 1)[:, None]  # [S, V-1]
        g_match = (drafts == greedy[:, :-1]) & has_draft
        g_counts = 1 + jnp.sum(
            jnp.cumprod(g_match.astype(jnp.int32), axis=1), axis=1)
    else:
        g_counts = jnp.ones((s,), jnp.int32)

    def _sampled(_):
        flat = logits.reshape(s * v, vocab)
        rep = lambda x: jnp.repeat(x, v)  # noqa: E731
        scaled = _filtered_scaled(flat, rep(temperature), rep(top_k),
                                  rep(top_p)).reshape(s, v, vocab)
        base = jax.vmap(jax.random.wrap_key_data)(keys)
        # position-0 draw on the SAME stream as sample_tokens: a plain
        # row folded into the verify executable samples identically
        draw0 = jax.vmap(jax.random.categorical)(
            base, scaled[:, 0]).astype(jnp.int32)
        if v == 1:
            return draw0[:, None], jnp.ones((s,), jnp.int32)
        probs = jax.nn.softmax(scaled, axis=-1)  # [S, V, vocab]
        # acceptance tests: u_j < p_j(d_j), stopped at the first miss
        u = jax.vmap(lambda k: jax.vmap(
            lambda j: jax.random.uniform(jax.random.fold_in(k, 1 + j))
        )(j_idx))(base)
        p_draft = jnp.take_along_axis(
            probs[:, :-1], drafts[..., None].astype(jnp.int32),
            axis=-1)[..., 0]
        accept = (u < p_draft) & has_draft
        r = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1),
                    axis=1)                       # accepted drafts
        counts = (r + 1).astype(jnp.int32)
        # replacement draw per draft position: p_j \ {d_j} renormalized
        # (log-space categorical == draw from the renormalized dist);
        # a degenerate p_j(d_j) == 1 falls back to the argmax like the
        # split path's host loop did
        excl = jnp.where(jax.nn.one_hot(drafts, vocab, dtype=bool),
                         0.0, probs[:, :-1])
        excl_logits = jnp.where(excl > 0, jnp.log(excl), _NEG_INF)
        rkeys = jax.vmap(lambda k: jax.vmap(
            lambda j: jax.random.fold_in(k, 1001 + j))(j_idx))(base)
        repl = jax.vmap(jax.vmap(jax.random.categorical))(
            rkeys, excl_logits).astype(jnp.int32)
        repl = jnp.where(excl.sum(-1) > 0, repl,
                         jnp.argmax(probs[:, :-1], axis=-1)
                         .astype(jnp.int32))
        # bonus draw from the row's LAST candidate distribution
        last = jnp.maximum(n_cand - 1, 0).astype(jnp.int32)
        bonus_logits = jnp.take_along_axis(
            jnp.where(probs > 0, jnp.log(probs), _NEG_INF),
            last[:, None, None], axis=1)[:, 0]
        bonus = jax.vmap(jax.random.categorical)(
            jax.vmap(lambda k: jax.random.fold_in(k, 2001))(base),
            bonus_logits).astype(jnp.int32)
        # assemble: accepted drafts below r, replacement-or-bonus at r
        pad = jnp.zeros((s, 1), jnp.int32)
        drafts_pad = jnp.concatenate(
            [drafts.astype(jnp.int32), pad], axis=1)     # [S, V]
        repl_pad = jnp.concatenate([repl, pad], axis=1)
        at_r = jnp.where(
            r == (n_cand - 1),
            bonus, jnp.take_along_axis(repl_pad, r[:, None], axis=1)[:, 0])
        pos = jnp.arange(v)[None, :]
        toks = jnp.where(pos == r[:, None], at_r[:, None], drafts_pad)
        # plain rows keep the sample_tokens-identical position-0 draw
        plain = (n_cand <= 1)
        toks = toks.at[:, 0].set(jnp.where(plain, draw0, toks[:, 0]))
        counts = jnp.where(plain, 1, counts)
        return toks, counts

    s_toks, s_counts = jax.lax.cond(
        jnp.any(temperature > 0.0), _sampled,
        lambda _: (greedy, g_counts), None)
    is_greedy = temperature <= 0.0
    tokens = jnp.where(is_greedy[:, None], greedy, s_toks)
    counts = jnp.where(is_greedy, g_counts, s_counts)
    return tokens.astype(jnp.int32), counts.astype(jnp.int32)


@functools.partial(jax.jit, static_argnums=(2,))
def compute_logprobs(
    logits: jax.Array,   # [B, vocab]
    tokens: jax.Array,   # [B] sampled ids
    k: int,
):
    """Log-softmax logprob of each sampled token plus the top-k
    alternatives (OpenAI logprobs semantics; reference rides vLLM's
    sampler logprobs)."""
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    chosen = jnp.take_along_axis(lp, tokens[:, None].astype(jnp.int32),
                                 axis=-1)[:, 0]
    if k <= 0:
        b = logits.shape[0]
        return chosen, jnp.zeros((b, 0), jnp.float32),             jnp.zeros((b, 0), jnp.int32)
    top_v, top_i = jax.lax.top_k(lp, k)
    return chosen, top_v, top_i.astype(jnp.int32)


@jax.jit
def filtered_probs(
    logits: jax.Array,       # [B, vocab]
    temperature: jax.Array,  # [B]
    top_k: jax.Array,        # [B]
    top_p: jax.Array,        # [B]
) -> jax.Array:
    """Per-row TARGET distribution under the request's sampling params
    (temperature + top-k/top-p filtering); temperature 0 rows become a
    one-hot at the argmax.  The spec-decode verify step scores draft
    tokens against exactly the distribution ``sample_tokens`` would draw
    from (reference: rejection sampling in the verify path,
    gpu_ar_model_runner.py:466-497)."""
    logits = logits.astype(jnp.float32)
    vocab = logits.shape[-1]
    greedy = jax.nn.one_hot(jnp.argmax(logits, axis=-1), vocab)
    probs = jax.nn.softmax(
        _filtered_scaled(logits, temperature, top_k, top_p), axis=-1)
    return jnp.where((temperature <= 0.0)[:, None], greedy, probs)
