from vllm_omni_tpu.sample.sampler import SamplingTensors, sample_tokens

__all__ = ["SamplingTensors", "sample_tokens"]
