from vllm_omni_tpu.parallel.mesh import (
    AXIS_DP,
    AXIS_CFG,
    AXIS_EP,
    AXIS_PP,
    AXIS_RING,
    AXIS_TP,
    AXIS_ULYSSES,
    MESH_AXES,
    MeshConfig,
    build_mesh,
    single_device_mesh,
)

__all__ = [
    "AXIS_DP",
    "AXIS_CFG",
    "AXIS_EP",
    "AXIS_PP",
    "AXIS_RING",
    "AXIS_TP",
    "AXIS_ULYSSES",
    "MESH_AXES",
    "MeshConfig",
    "build_mesh",
    "single_device_mesh",
]
