"""Prefill context parallelism + VAE patch parallelism.

Two more reference parallelism strategies (SURVEY §2.11):

- **Prefill context parallel** (reference: prefill_context_parallel_size
  passthrough, entrypoints/omni_stage.py:94,101 → upstream vLLM CP): an AR
  prompt's causal forward sharded over the sequence axis — each device
  holds a contiguous chunk, attention runs as *causal* ring attention
  (parallel/context.py) so KV blocks rotate over ICI instead of
  all-gathering the full sequence.
- **VAE patch parallel** (reference: distributed/vae_patch_parallel.py —
  spatial tiling with explicit halo exchange): on TPU the tiling IS a
  GSPMD sharding: annotate the latent height axis over the mesh and XLA
  inserts the convolution halo exchanges itself — no hand-written halo
  code, and the same decoder serves 1..N devices.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.models.common.transformer import (
    TransformerConfig,
    _layer_step,
    _rope_tables,
)
from vllm_omni_tpu.ops import rms_norm
from vllm_omni_tpu.parallel.context import ring_attention


def forward_hidden_cp(
    params,
    cfg: TransformerConfig,
    token_ids: jax.Array,  # [B, S] — S divisible by the cp degree
    mesh: Mesh,
    axis: str = "sp",
) -> jax.Array:
    """Causal full-sequence forward with the sequence sharded over
    ``axis`` (prefill context parallelism).  Numerically equal to
    ``forward_hidden`` (tests pin it on the virtual CPU mesh); each
    device's attention sees remote KV blocks via the causal ring.
    """
    b, s = token_ids.shape
    n = mesh.shape[axis]
    if s % n:
        raise ValueError(f"seq len {s} not divisible by cp degree {n}")
    if cfg.mrope_sections is not None:
        positions = jnp.broadcast_to(jnp.arange(s)[None, None, :],
                                     (b, 3, s))
        pos_spec = P(None, None, axis)
    else:
        positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        pos_spec = P(None, axis)

    def local_fn(p, tokens, pos):
        bl, sl = tokens.shape
        x = nn.embedding(p["embed"], tokens)
        cos, sin = _rope_tables(cfg, pos)

        def attend(q, k, v):
            # KV stays at Hkv heads: the flash kernel handles GQA natively,
            # so each ring rotation ships 1/group the bytes a repeated
            # [B, S, H, D] KV would
            return ring_attention(
                q.reshape(bl, sl, cfg.num_heads, cfg.head_dim),
                k.reshape(bl, sl, cfg.num_kv_heads, cfg.head_dim),
                v.reshape(bl, sl, cfg.num_kv_heads, cfg.head_dim),
                axis, causal=True,
            )

        for layer in p["layers"]:
            x = _layer_step(layer, cfg, x, cos, sin, attend)
        return rms_norm(x, p["final_norm"]["w"], cfg.rms_eps)

    fn = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(P(), P(None, axis), pos_spec),
        out_specs=P(None, axis),
        check_vma=False,
    )
    return fn(params, token_ids, positions)


def make_patch_parallel_decoder(
    vae_decode_fn,
    mesh: Mesh,
    axis: str = "sp",
    out_sharded: bool = True,
):
    """Build a VAE decoder with the latent height axis sharded over
    ``axis`` — construct ONCE and reuse; the returned callable carries the
    jitted executable, so per-image calls pay only the decode.

    GSPMD partitions the convolutions spatially and inserts the halo
    exchanges the reference writes by hand (vae_patch_parallel.py); the
    decoded image comes back sharded the same way (or fully replicated
    with ``out_sharded=False``).
    """
    lat_sharding = NamedSharding(mesh, P(None, axis, None, None))
    out_spec = (NamedSharding(mesh, P(None, axis, None, None))
                if out_sharded else NamedSharding(mesh, P()))
    fn = jax.jit(vae_decode_fn, out_shardings=out_spec)

    def decode(params, latents):
        return fn(params, jax.device_put(latents, lat_sharding))

    return decode


def place_replicated(params, mesh: Mesh):
    """Replicate a param tree on the mesh (do once at load, not per call)."""
    return jax.device_put(params, NamedSharding(mesh, P()))


def patch_parallel_decode(
    vae_decode_fn,
    params,
    latents: jax.Array,  # [B, h, w, C]
    mesh: Mesh,
    axis: str = "sp",
    out_sharded: bool = True,
):
    """One-shot convenience over ``make_patch_parallel_decoder`` — traces
    and places per call; production paths should build the decoder once."""
    decode = make_patch_parallel_decoder(vae_decode_fn, mesh, axis,
                                         out_sharded)
    return decode(place_replicated(params, mesh), latents)
