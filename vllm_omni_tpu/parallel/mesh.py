"""Device-mesh construction — the TPU-native analogue of the reference's
``initialize_model_parallel`` (reference: vllm_omni/diffusion/distributed/
parallel_state.py:624 and RankGenerator order "tp-sp-pp-cfg-dp" at :170).

Where the reference builds N orthogonal NCCL process-group families
(DP x CFG x SP(ulysses x ring) x PP x TP) and a 938-LoC GroupCoordinator on
top, the TPU-native design is a single ``jax.sharding.Mesh`` with one named
axis per parallelism strategy.  XLA inserts the collectives:

=============== ======================= =============================
reference group mesh axis               collective mechanism
=============== ======================= =============================
_TP             ``tp``                  psum / sharded matmul (pjit)
_SP ulysses     ``ulysses``             lax.all_to_all over heads/seq
_SP ring        ``ring``                lax.ppermute blockwise KV
_CFG            ``cfg``                 pbroadcast/psum combine
_PP             ``pp``                  ppermute microbatch handoff
_DP             ``dp``                  fully-replicated params, batch shard
=============== ======================= =============================

Axis ordering matters for ICI locality: JAX lays devices out with the *last*
mesh axis fastest-varying, so ``tp`` (highest-bandwidth collectives) occupies
adjacent devices, mirroring the reference's "tp fastest" rank order
(parallel_state.py:170).  ``dp`` is outermost — suitable for the DCN boundary
on multi-slice deployments.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DP = "dp"
AXIS_CFG = "cfg"
AXIS_PP = "pp"
AXIS_RING = "ring"
AXIS_ULYSSES = "ulysses"
AXIS_TP = "tp"

AXIS_EP = "ep"

# Outermost -> innermost (innermost varies fastest over the device list).
MESH_AXES: tuple[str, ...] = (
    AXIS_DP,
    AXIS_CFG,
    AXIS_PP,
    AXIS_EP,
    AXIS_RING,
    AXIS_ULYSSES,
    AXIS_TP,
)


@dataclass(frozen=True)
class MeshConfig:
    """Parallel degrees for one stage.

    Field-for-field coverage of the reference's ``DiffusionParallelConfig``
    (vllm_omni/diffusion/data.py:28-52): data/cfg/sequence(=ulysses x ring)/
    pipeline/tensor parallel sizes.  ``sequence_parallel_size`` in the
    reference is the product ``ulysses_degree * ring_degree``
    (validated at parallel_state.py:688-699); here the factors are explicit.
    """

    data_parallel_size: int = 1
    cfg_parallel_size: int = 1
    pipeline_parallel_size: int = 1
    # expert parallel: shards the stacked-E MoE weight axis (reference: EP
    # via vLLM fused-MoE all-to-all, SURVEY.md §2.11)
    expert_parallel_size: int = 1
    ring_degree: int = 1
    ulysses_degree: int = 1
    tensor_parallel_size: int = 1

    @property
    def sequence_parallel_size(self) -> int:
        return self.ring_degree * self.ulysses_degree

    @property
    def world_size(self) -> int:
        return (
            self.data_parallel_size
            * self.cfg_parallel_size
            * self.pipeline_parallel_size
            * self.expert_parallel_size
            * self.ring_degree
            * self.ulysses_degree
            * self.tensor_parallel_size
        )

    @property
    def axis_sizes(self) -> tuple[int, ...]:
        return (
            self.data_parallel_size,
            self.cfg_parallel_size,
            self.pipeline_parallel_size,
            self.expert_parallel_size,
            self.ring_degree,
            self.ulysses_degree,
            self.tensor_parallel_size,
        )

    def validate(self, n_devices: int) -> None:
        for name, size in zip(MESH_AXES, self.axis_sizes):
            if size < 1:
                raise ValueError(f"mesh axis {name!r} must be >=1, got {size}")
        if self.cfg_parallel_size not in (1, 2):
            # CFG parallel = positive/negative guidance branch split
            # (reference: distributed/cfg_parallel.py:21; data.py:49).
            raise ValueError(
                f"cfg_parallel_size must be 1 or 2, got {self.cfg_parallel_size}"
            )
        if self.world_size != n_devices:
            raise ValueError(
                f"mesh degrees {dict(zip(MESH_AXES, self.axis_sizes))} "
                f"require {self.world_size} devices, have {n_devices}"
            )

    @staticmethod
    def from_dict(d: dict) -> "MeshConfig":
        """Accept both our names and the reference's stage-YAML spellings."""
        alias = {
            "dp": "data_parallel_size",
            "cfg": "cfg_parallel_size",
            "pp": "pipeline_parallel_size",
            "ep": "expert_parallel_size",
            "tp": "tensor_parallel_size",
            "ulysses": "ulysses_degree",
            "ring": "ring_degree",
            "sequence_parallel_size": None,  # handled below
        }
        kwargs: dict[str, int] = {}
        sp: Optional[int] = None
        for k, v in d.items():
            if k == "sequence_parallel_size":
                sp = int(v)
                continue
            if k in alias and alias[k]:
                field = alias[k]
            elif k in MeshConfig.__dataclass_fields__:
                field = k
            else:
                raise KeyError(f"unknown parallel config key {k!r}")
            if field in kwargs and kwargs[field] != int(v):
                raise ValueError(
                    f"conflicting values for {field!r}: "
                    f"{kwargs[field]} vs {v}"
                )
            kwargs[field] = int(v)
        cfg = MeshConfig(**kwargs)
        if sp is not None and cfg.sequence_parallel_size != sp:
            if cfg.ring_degree == 1 and cfg.ulysses_degree == 1:
                # Bare sequence_parallel_size defaults to all-ulysses, the
                # same default the reference applies (data.py:40-46).
                cfg = MeshConfig(
                    **{**kwargs, "ulysses_degree": sp, "ring_degree": 1}
                )
            else:
                raise ValueError(
                    "sequence_parallel_size "
                    f"{sp} != ulysses*ring {cfg.sequence_parallel_size}"
                )
        return cfg


def build_mesh(
    config: MeshConfig,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the stage mesh over the given devices (default: all local)."""
    if devices is None:
        devices = jax.devices()
    config.validate(len(devices))
    dev_array = np.asarray(devices).reshape(config.axis_sizes)
    return Mesh(dev_array, MESH_AXES)


def single_device_mesh(device: Optional[jax.Device] = None) -> Mesh:
    if device is None:
        device = jax.devices()[0]
    return build_mesh(MeshConfig(), [device])
