"""Sequence/context-parallel attention strategies.

TPU-native re-design of the reference's parallel attention stack
(SURVEY.md §2.11):

- **Ulysses** (reference: attention/parallel/ulysses.py:29 + SeqAllToAll4D,
  comm.py:103): here a single ``jax.lax.all_to_all`` over the ``ulysses``
  mesh axis redistributes heads<->sequence around a local flash attention.
- **Ring** (reference: attention/backends/ring_flash_attn.py:13-120 +
  RingComm comm.py:228): blockwise KV rotation via ``jax.lax.ppermute``
  with numerically-stable LSE merging (the reference's
  ``update_out_and_lse``, ring/ring_utils.py).
- **USP hybrid** (reference: set_seq_parallel_pg,
  parallel_state.py:477-622): ulysses inside ring — heads are
  redistributed within each ulysses group, KV blocks rotate around the
  ring axis.
- **Joint text prefix** (reference: ring.py:38-45, ulysses.py:33-39): DiT
  joint text+image attention keeps the text KV replicated; it is attended
  once as a static prefix chunk and merged via LSE, exactly the reference's
  "joint_tensor as static ring prefix" semantics.

All functions are written to run inside ``shard_map`` over a mesh built by
``vllm_omni_tpu.parallel.mesh.build_mesh``; sequence shards live on the
(ring, ulysses) axes.  Collectives ride ICI; XLA overlaps the ppermute with
the per-step flash kernel.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from vllm_omni_tpu.ops.attention import flash_attention


def _joint_kv_mask(local_k, joint_mask):
    """KV mask for [local KV ++ joint text KV]: local tokens are all real,
    text tokens follow ``joint_mask`` ([B, S_text], 1=real 0=pad)."""
    if joint_mask is None:
        return None
    b, s_local = local_k.shape[:2]
    return jnp.concatenate(
        [jnp.ones((b, s_local), jnp.int32), joint_mask.astype(jnp.int32)],
        axis=1,
    )


def _merge_lse(o1, lse1, o2, lse2):
    """Merge two partial attention results with logsumexp weighting.

    o: [B, S, H, D]; lse: [B, H, S].  Stable for lse == -inf chunks.
    """
    m = jnp.maximum(lse1, lse2)
    # Guard fully-empty chunks (both -inf).
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    w1 = jnp.exp(lse1 - m_safe)
    w2 = jnp.exp(lse2 - m_safe)
    den = w1 + w2
    den_safe = jnp.where(den == 0.0, 1.0, den)
    w1n = (w1 / den_safe)[..., None].swapaxes(1, 2)  # [B, S, H, 1]
    w2n = (w2 / den_safe)[..., None].swapaxes(1, 2)
    o = o1.astype(jnp.float32) * w1n + o2.astype(jnp.float32) * w2n
    lse = m_safe + jnp.log(den_safe)
    lse = jnp.where(den == 0.0, -jnp.inf, lse)
    return o.astype(o1.dtype), lse


def ring_attention(
    q: jax.Array,  # [B, S_local, H, D] (seq sharded over ring axis)
    k: jax.Array,
    v: jax.Array,
    ring_axis: str,
    joint_k: Optional[jax.Array] = None,  # [B, S_text, H, D] replicated
    joint_v: Optional[jax.Array] = None,
    joint_mask: Optional[jax.Array] = None,  # [B, S_text] 1=real, 0=pad
    causal: bool = False,
) -> jax.Array:
    """Blockwise ring attention (DiT long-sequence attention; causal mode
    for AR prefill context parallelism).

    Each step attends the local Q against the currently-held KV block, then
    rotates the KV block to the next ring neighbour with ``ppermute``.
    Partial results merge via LSE.  The replicated joint text KV is attended
    once at step 0 (reference ring_flash_attn.py:72-79 behaviour);
    ``joint_mask`` zeroes attention mass on padded text tokens.

    ``causal=True`` (no joint stream): with sequence chunks laid out in
    ring order, the KV block held after s rotations came from device
    (idx - s) mod n; its global offset relative to the local queries is
    (idx - j) * S_local — earlier chunks attend fully, the own chunk
    causally, later chunks not at all (the flash kernel's per-sequence
    q_offsets express all three as one masked call, and fully-masked
    blocks merge neutrally through the LSE).
    """
    n = jax.lax.axis_size(ring_axis)

    if causal and joint_k is not None:
        raise ValueError("causal ring attention has no joint text stream")

    k0, v0 = k, v
    kv_mask = None
    if joint_k is not None:
        kj = jnp.concatenate([k0, joint_k], axis=1)
        vj = jnp.concatenate([v0, joint_v], axis=1)
        kv_mask = _joint_kv_mask(k0, joint_mask)
    else:
        kj, vj = k0, v0
    o, lse = flash_attention(
        q, kj, vj, causal=causal, kv_mask=kv_mask, return_lse=True
    )

    if n == 1:
        return o

    perm = [(i, (i + 1) % n) for i in range(n)]
    idx = jax.lax.axis_index(ring_axis)
    b, c = q.shape[0], q.shape[1]

    def step(carry, s):
        o_acc, lse_acc, k_cur, v_cur = carry
        k_nxt = jax.lax.ppermute(k_cur, ring_axis, perm)
        v_nxt = jax.lax.ppermute(v_cur, ring_axis, perm)
        if causal:
            j = jnp.mod(idx - s, n)  # origin device of this KV block
            offset = (idx - j) * c
            o_i, lse_i = flash_attention(
                q, k_nxt, v_nxt, causal=True, return_lse=True,
                q_offsets=jnp.broadcast_to(offset, (b,)),
            )
        else:
            o_i, lse_i = flash_attention(
                q, k_nxt, v_nxt, causal=False, return_lse=True
            )
        o_acc, lse_acc = _merge_lse(o_acc, lse_acc, o_i, lse_i)
        return (o_acc, lse_acc, k_nxt, v_nxt), None

    (o, lse, _, _), _ = jax.lax.scan(
        step, (o, lse, k0, v0), jnp.arange(1, n)
    )
    return o


def _scatter_heads(x, axis):
    # [B, S/u, H, D] -> [B, S, H/u, D]
    return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)


def _gather_heads(x, axis):
    # [B, S, H/u, D] -> [B, S/u, H, D]
    return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)


def _slice_joint_heads(joint_k, joint_v, ulysses_axis, h):
    """Slice replicated joint KV to this rank's head group (the reference's
    ulysses.py:33-39 semantics)."""
    u = jax.lax.axis_size(ulysses_axis)
    idx = jax.lax.axis_index(ulysses_axis)
    hh = h // u
    jk = jax.lax.dynamic_slice_in_dim(joint_k, idx * hh, hh, axis=2)
    jv = jax.lax.dynamic_slice_in_dim(joint_v, idx * hh, hh, axis=2)
    return jk, jv


def ulysses_attention(
    q: jax.Array,  # [B, S_local, H, D] (seq sharded over ulysses axis)
    k: jax.Array,
    v: jax.Array,
    ulysses_axis: str,
    causal: bool = False,
    joint_k: Optional[jax.Array] = None,
    joint_v: Optional[jax.Array] = None,
    joint_mask: Optional[jax.Array] = None,
    inner_fn=None,
) -> jax.Array:
    """Ulysses sequence parallelism: all_to_all heads<->sequence.

    After the first all_to_all each rank holds the *full* (or ring-local)
    sequence for H/u heads; ``inner_fn(q, k, v, joint_k, joint_v, joint_mask)``
    runs the local attention (default: dense flash); the second all_to_all
    restores the sequence sharding.
    """
    h = q.shape[2]
    qg = _scatter_heads(q, ulysses_axis)
    kg = _scatter_heads(k, ulysses_axis)
    vg = _scatter_heads(v, ulysses_axis)
    jk = jv = None
    if joint_k is not None:
        jk, jv = _slice_joint_heads(joint_k, joint_v, ulysses_axis, h)
    if inner_fn is None:
        kv_mask = None
        if jk is not None:
            kv_mask = _joint_kv_mask(kg, joint_mask)
            kg = jnp.concatenate([kg, jk], axis=1)
            vg = jnp.concatenate([vg, jv], axis=1)
        o = flash_attention(qg, kg, vg, causal=causal, kv_mask=kv_mask)
    else:
        o = inner_fn(qg, kg, vg, jk, jv, joint_mask)
    return _gather_heads(o, ulysses_axis)


def _text_stream_attention(
    qt, kt, vt, ki, vi, txt_mask, ulysses_axis, ring_axis
):
    """Attention output for the replicated text stream of a joint
    (text+image) block under sequence parallelism.

    Text queries must attend [text KV ++ ALL image KV], but the image KV is
    sharded over (ring, ulysses).  Each rank computes a partial over its
    local image KV (plus, on SP rank 0 only, the text KV so it is counted
    exactly once), and the partials merge with an LSE-weighted psum over
    the SP axes — the cross-rank generalization of ``_merge_lse``.
    """
    sp_axes = (ring_axis, ulysses_axis)
    is_first = (
        (jax.lax.axis_index(ring_axis) == 0)
        & (jax.lax.axis_index(ulysses_axis) == 0)
    )
    b = qt.shape[0]
    s_txt = kt.shape[1]
    tmask = (jnp.ones((b, s_txt), jnp.int32) if txt_mask is None
             else txt_mask.astype(jnp.int32))
    # Text KV participates only on the first SP rank.
    tmask = tmask * is_first.astype(jnp.int32)
    k_loc = jnp.concatenate([kt, ki], axis=1)
    v_loc = jnp.concatenate([vt, vi], axis=1)
    mask = jnp.concatenate(
        [tmask, jnp.ones((b, ki.shape[1]), jnp.int32)], axis=1
    )
    o_p, lse_p = flash_attention(
        qt, k_loc, v_loc, causal=False, kv_mask=mask, return_lse=True
    )
    m = jax.lax.pmax(lse_p, sp_axes)  # [B, H, S_txt]
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    w = jnp.exp(lse_p - m_safe)  # [B, H, S]
    w_o = w.swapaxes(1, 2)[..., None]  # [B, S, H, 1]
    num = jax.lax.psum(o_p.astype(jnp.float32) * w_o, sp_axes)
    den = jax.lax.psum(w, sp_axes)
    den_safe = jnp.where(den == 0.0, 1.0, den)
    return (num / den_safe.swapaxes(1, 2)[..., None]).astype(qt.dtype)


def joint_sp_attention(
    qi, ki, vi,  # image stream [B, S_img/sp, H, D], seq sharded
    qt, kt, vt,  # text stream [B, S_txt, H, D], replicated
    txt_mask: Optional[jax.Array] = None,  # [B, S_txt]
    ulysses_axis: str = "ulysses",
    ring_axis: str = "ring",
):
    """Joint text+image DiT attention under USP sequence parallelism.

    Returns (img_o, txt_o) — the ``attn_fn`` contract of
    ``qwen_image.transformer.block_forward``.  Image queries run USP
    (ulysses all_to_all + ring KV rotation) with the replicated text KV as
    the joint prefix; text queries use partial-LSE merging across the SP
    shards (reference semantics: ulysses.py:33-39, ring.py:38-45).
    """
    img_o = usp_attention(
        qi, ki, vi, ulysses_axis=ulysses_axis, ring_axis=ring_axis,
        joint_k=kt, joint_v=vt, joint_mask=txt_mask,
    )
    txt_o = _text_stream_attention(
        qt, kt, vt, ki, vi, txt_mask, ulysses_axis, ring_axis
    )
    return img_o, txt_o


def usp_attention(
    q: jax.Array,  # [B, S_local, H, D]; seq sharded over (ring, ulysses)
    k: jax.Array,
    v: jax.Array,
    ulysses_axis: str = "ulysses",
    ring_axis: str = "ring",
    joint_k: Optional[jax.Array] = None,
    joint_v: Optional[jax.Array] = None,
    joint_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """USP hybrid: ulysses head redistribution nested inside ring KV
    rotation (sequence_parallel_size = ulysses_degree x ring_degree)."""
    u = jax.lax.axis_size(ulysses_axis)
    r = jax.lax.axis_size(ring_axis)
    if u == 1 and r == 1:
        kv_mask = None
        if joint_k is not None:
            kv_mask = _joint_kv_mask(k, joint_mask)
            k = jnp.concatenate([k, joint_k], axis=1)
            v = jnp.concatenate([v, joint_v], axis=1)
        return flash_attention(q, k, v, causal=False, kv_mask=kv_mask)
    if r == 1:
        return ulysses_attention(
            q, k, v, ulysses_axis,
            joint_k=joint_k, joint_v=joint_v, joint_mask=joint_mask,
        )
    return ulysses_attention(
        q,
        k,
        v,
        ulysses_axis,
        joint_k=joint_k,
        joint_v=joint_v,
        joint_mask=joint_mask,
        inner_fn=lambda qg, kg, vg, jk, jv, jm: ring_attention(
            qg, kg, vg, ring_axis, joint_k=jk, joint_v=jv, joint_mask=jm
        ),
    )
