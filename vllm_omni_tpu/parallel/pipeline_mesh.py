"""Shared diffusion-pipeline mesh wiring.

One helper for what the reference does per-arch at registry time
(reference: SP plan application, vllm_omni/diffusion/registry.py:122-294,
and parallel degree plumbing, diffusion/data.py:28-52): given a pipeline's
mesh, decide which parallel axes it can honor, REFUSE the ones it can't
(a mesh axis silently ignored is a lie to the user — VERDICT r2 weak #3),
and hand out the standard building blocks:

- ``validate(supported)``: raise on active-but-unsupported axes
- ``place(params)``: replicate a param tree on the mesh
- ``batch_sharding(ndim)``: NamedSharding putting batch over (cfg, dp)
- ``self_attn_fn(...)``: shard_map USP self-attention (Wan video /
  StableAudio audio tokens — sequence over ring x ulysses)
- ``joint_attn_fn(...)``: shard_map USP joint attention (MMDiT streams,
  image sharded + text replicated) — the Qwen-Image wiring, shared

Pipelines keep their single-device code path untouched when no axis is
active (``wiring.off``).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from vllm_omni_tpu.logger import init_logger

logger = init_logger(__name__)

BATCH_AXES = ("cfg", "dp")
SEQ_AXES = ("ring", "ulysses")


class MeshWiring:
    def __init__(self, mesh, pipeline: str = "pipeline"):
        self.mesh = mesh
        self.pipeline = pipeline
        self.ax = (dict(zip(mesh.axis_names, mesh.devices.shape))
                   if mesh is not None else {})

    # ------------------------------------------------------------- sizes
    def size(self, name: str) -> int:
        return self.ax.get(name, 1)

    @property
    def off(self) -> bool:
        return self.mesh is None

    @property
    def active(self) -> set[str]:
        return {k for k, v in self.ax.items() if v > 1}

    @property
    def sp(self) -> int:
        return self.size("ring") * self.size("ulysses")

    @property
    def batch(self) -> int:
        return self.size("cfg") * self.size("dp")

    # -------------------------------------------------------- validation
    def validate(self, supported: set[str]) -> "MeshWiring":
        """Raise if the mesh has an active axis this pipeline cannot
        honor — a silent fallback to single-device execution is worse
        than an error."""
        bad = self.active - set(supported)
        if bad:
            raise ValueError(
                f"{self.pipeline} does not support mesh axes "
                f"{sorted(bad)} (supported: {sorted(supported)}); "
                "rebuild the mesh without them"
            )
        return self

    # --------------------------------------------------------- placement
    def place(self, params):
        if self.mesh is None:
            return jax.device_put(params)
        return jax.device_put(params, NamedSharding(self.mesh, P()))

    def batch_sharding(self, ndim: int, batch_dim: int = 0,
                       seq_dim: Optional[int] = None) -> NamedSharding:
        """Activations: batch over (cfg, dp), optionally a token axis over
        (ring, ulysses)."""
        spec: list = [None] * ndim
        spec[batch_dim] = BATCH_AXES
        if seq_dim is not None:
            spec[seq_dim] = SEQ_AXES
        return NamedSharding(self.mesh, P(*spec))

    def constrain(self, x, ndim=None, batch_dim: int = 0,
                  seq_dim: Optional[int] = None):
        if self.mesh is None:
            return x
        return jax.lax.with_sharding_constraint(
            x, self.batch_sharding(x.ndim, batch_dim, seq_dim))

    # --------------------------------------------------------- attention
    def _divisibility_ok(self, n_heads: int, seq_len: int,
                         batch: int) -> bool:
        u = self.size("ulysses")
        tp = self.size("tp")
        if (seq_len % self.sp or n_heads % max(tp, 1)
                or (n_heads // max(tp, 1)) % u or batch % self.batch):
            logger.warning(
                "%s: mesh %s does not divide (seq=%d, heads=%d, "
                "batch=%d); falling back to GSPMD-partitioned dense "
                "attention", self.pipeline, self.ax, seq_len, n_heads,
                batch)
            return False
        return True

    def self_attn_fn(self, n_heads: int, seq_len: int, batch: int):
        """shard_map USP self-attention for single-stream DiTs (Wan /
        StableAudio): q/k/v [B, S, H, D] with S over (ring, ulysses) and
        B over (cfg, dp).  Returns None when shapes don't divide (dense
        attention still runs, GSPMD-partitioned)."""
        if self.mesh is None or self.sp == 1:
            return None
        if not self._divisibility_ok(n_heads, seq_len, batch):
            return None
        from jax import shard_map

        from vllm_omni_tpu.parallel.context import usp_attention

        spec = P(BATCH_AXES, SEQ_AXES, "tp", None)
        inner = shard_map(
            functools.partial(usp_attention, ulysses_axis="ulysses",
                              ring_axis="ring"),
            mesh=self.mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )

        def attn_fn(q, k, v):
            return inner(q, k, v)

        return attn_fn

    def joint_attn_fn(self, n_heads: int, seq_len: int, batch: int):
        """shard_map USP joint attention for MMDiT double streams (image
        sharded, text replicated) — the contract of
        ``qwen_image.transformer.block_forward``'s ``attn_fn``."""
        if self.mesh is None:
            return None
        if self.sp == 1 and self.size("tp") == 1:
            return None
        if not self._divisibility_ok(n_heads, seq_len, batch):
            return None
        from jax import shard_map

        from vllm_omni_tpu.parallel.context import joint_sp_attention

        img_spec = P(BATCH_AXES, SEQ_AXES, "tp", None)
        txt_spec = P(BATCH_AXES, None, "tp", None)
        mask_spec = P(BATCH_AXES, None)
        inner = shard_map(
            functools.partial(joint_sp_attention, ulysses_axis="ulysses",
                              ring_axis="ring"),
            mesh=self.mesh,
            in_specs=(img_spec,) * 3 + (txt_spec,) * 3 + (mask_spec,),
            out_specs=(img_spec, txt_spec),
        )

        def attn_fn(qi, ki, vi, qt, kt, vt, txt_kv_mask):
            if txt_kv_mask is None:
                txt_kv_mask = jnp.ones(qt.shape[:2], jnp.int32)
            img_o, txt_o = inner(qi, ki, vi, qt, kt, vt, txt_kv_mask)
            return (img_o.reshape(*img_o.shape[:2], -1),
                    txt_o.reshape(*txt_o.shape[:2], -1))

        return attn_fn
