"""Pipeline parallelism for DiT block stacks (GPipe over shard_map).

Reference: the diffusion PipelineGroupCoordinator
(vllm_omni/diffusion/distributed/group_coordinator.py:548 — send/recv
groups between pipeline ranks).  The TPU-native shape: transformer blocks
STACK into leading-axis arrays sharded over the ``pp`` mesh axis (each
rank holds num_layers/pp blocks — the per-device weight-memory win), and
one shard_map program runs the classic microbatch schedule: at tick t,
rank r processes microbatch ``t - r`` through its local blocks
(lax.scan) and hands the activations to rank r+1 with ``ppermute``.
T = M + pp - 1 ticks drain the pipeline; outputs accumulate on the last
rank and a psum (zeros elsewhere) broadcasts them back.

No Send/Recv coordinator processes, no stream management: the schedule is
data flow inside one jitted SPMD program.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from vllm_omni_tpu.logger import init_logger

logger = init_logger(__name__)


def stack_blocks(blocks: list) -> dict:
    """List of per-block param trees -> one tree of [L, ...] leaves
    (the leading axis is the pp shard axis)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)


def pp_block_specs(stacked, axis: str = "pp"):
    """shard_map in_specs for a stacked block tree: leading axis over
    ``axis``."""
    from jax.sharding import PartitionSpec as P

    return jax.tree.map(lambda _: P(axis), stacked)


def microbatch(tree, m: int):
    """[B, ...] leaves -> [M, B/m, ...]."""
    return jax.tree.map(
        lambda x: x.reshape(m, x.shape[0] // m, *x.shape[1:]), tree)


def unmicrobatch(tree):
    return jax.tree.map(
        lambda x: x.reshape(x.shape[0] * x.shape[1], *x.shape[2:]), tree)


def pipeline_apply(
    local_blocks,
    mb_carry,            # pytree with leading [M, bm, ...] microbatches
    scan_fn: Callable,   # (local_blocks, carry) -> carry
    axis: str = "pp",
):
    """Run the microbatch pipeline INSIDE shard_map over ``axis``.

    ``mb_carry`` must be replicated across pp ranks (each rank picks its
    own microbatch per tick); returns the processed microbatches,
    replicated.
    """
    n = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    leaves = jax.tree.leaves(mb_carry)
    m_count = leaves[0].shape[0]
    ticks = m_count + n - 1

    def pick(tree, m):
        mc = jnp.clip(m, 0, m_count - 1)
        return jax.tree.map(
            lambda x: lax.dynamic_index_in_dim(x, mc, 0, keepdims=False),
            tree)

    buf0 = pick(mb_carry, jnp.int32(0))
    outs0 = jax.tree.map(jnp.zeros_like, mb_carry)

    def tick(t, state):
        buf, outs = state
        m = t - idx  # microbatch this rank works on (may be out of range)
        # stage input: rank 0 reads the embedded microbatch, later ranks
        # take what the previous rank ppermuted over
        fresh = pick(mb_carry, m)
        x = jax.tree.map(
            lambda a, b: jnp.where(idx == 0, a, b), fresh, buf)
        y = scan_fn(local_blocks, x)
        valid = jnp.logical_and(m >= 0, m < m_count)
        write = jnp.logical_and(valid, idx == n - 1)
        mc = jnp.clip(m, 0, m_count - 1)
        outs = jax.tree.map(
            lambda o, v: jnp.where(
                write,
                lax.dynamic_update_index_in_dim(o, v, mc, 0),
                o),
            outs, y)
        # hand activations to the next rank
        buf = jax.tree.map(
            lambda v: lax.ppermute(
                v, axis, [(i, (i + 1) % n) for i in range(n)]),
            y)
        return buf, outs

    _, outs = lax.fori_loop(0, ticks, tick, (buf0, outs0))
    # outputs live on the last rank only; zeros elsewhere -> psum is a
    # broadcast
    outs = jax.tree.map(
        lambda o: lax.psum(jnp.where(idx == n - 1, o, jnp.zeros_like(o)),
                           axis),
        outs)
    return outs
