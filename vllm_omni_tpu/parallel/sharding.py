"""Sharding helpers: NamedSharding constructors + sequence shard/gather.

Replaces the reference's hook-based SP sharding utilities
(vllm_omni/diffusion/distributed/sp_sharding.py:27,74,104 — sp_shard /
sp_gather / sp_shard_with_padding) with compiler-visible shardings: instead
of torch forward hooks slicing tensors per rank, we annotate arrays with
``NamedSharding`` / use ``shard_map`` and let XLA partition.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from vllm_omni_tpu.parallel.mesh import (
    AXIS_EP,
    AXIS_RING,
    AXIS_TP,
    AXIS_ULYSSES,
)

# The sequence axis of DiT activations is sharded over both SP factors;
# equivalent to the reference's ulysses x ring decomposition of
# sequence_parallel_size (parallel_state.py:477-622).
SP_AXES = (AXIS_RING, AXIS_ULYSSES)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def seq_sharded(mesh: Mesh, seq_dim: int = 1, ndim: int = 3) -> NamedSharding:
    """Activation sharding with the sequence dimension split over SP axes.

    Default layout [batch, seq, hidden] matches DiT hidden states.
    """
    spec = [None] * ndim
    spec[seq_dim] = SP_AXES
    return NamedSharding(mesh, P(*spec))


def heads_sharded(mesh: Mesh, head_dim_index: int = 2, ndim: int = 4) -> NamedSharding:
    """Attention-layout sharding [batch, seq, heads, head_dim] with heads
    split over the ulysses axis — the post-all-to-all layout of Ulysses SP
    (reference: attention/parallel/ulysses.py:29)."""
    spec: list = [None] * ndim
    spec[head_dim_index] = AXIS_ULYSSES
    return NamedSharding(mesh, P(*spec))


def tp_col_sharded(mesh: Mesh) -> NamedSharding:
    """Column-parallel weight [in, out]: out split over tp."""
    return NamedSharding(mesh, P(None, AXIS_TP))


def tp_row_sharded(mesh: Mesh) -> NamedSharding:
    """Row-parallel weight [in, out]: in split over tp."""
    return NamedSharding(mesh, P(AXIS_TP, None))


def sp_pad_len(seq_len: int, sp_size: int) -> int:
    """Padding needed so the sequence divides the SP degree; mirrors
    sp_shard_with_padding (sp_sharding.py:104)."""
    return (-seq_len) % sp_size


def pad_to_multiple(x: jax.Array, axis: int, multiple: int) -> jax.Array:
    pad = (-x.shape[axis]) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def with_sharding(x: jax.Array, sharding: Optional[NamedSharding]) -> jax.Array:
    if sharding is None:
        return x
    return jax.lax.with_sharding_constraint(x, sharding)


# Tensor-parallel layout for DiT weight trees (reference: TP linears in
# diffusion parallel_state.py:768-775): QKV/up projections are
# column-parallel (output dim over tp), output/down projections
# row-parallel (input dim over tp). GSPMD propagates the activation
# shardings and inserts the row-parallel psums.
DIT_TP_COL = frozenset({
    "to_q", "to_k", "to_v", "add_q", "add_k", "add_v",
    "img_mlp1", "txt_mlp1", "img_mod", "txt_mod",
})
DIT_TP_ROW = frozenset({"to_out", "to_add_out", "img_mlp2", "txt_mlp2"})


def dit_param_spec(path: tuple[str, ...]) -> P:
    """PartitionSpec for one DiT weight-tree leaf, addressed by its tree
    path.  Matrix weights ("w") of attention/MLP projections split over
    the tp axis; everything else (biases, norms, embeddings) replicates."""
    leaf = path[-1] if path else ""
    parent = path[-2] if len(path) >= 2 else ""
    if leaf == "w" and parent in DIT_TP_COL:
        return P(None, AXIS_TP)
    if leaf == "w" and parent in DIT_TP_ROW:
        return P(AXIS_TP, None)
    # weight-only quantized leaves (diffusion/quantization.py): w_q keeps
    # the float weight's layout; the per-out-channel scale shards with the
    # out axis (column-parallel) and replicates otherwise
    if leaf == "w_q" and parent in DIT_TP_COL:
        return P(None, AXIS_TP)
    if leaf == "w_q" and parent in DIT_TP_ROW:
        return P(AXIS_TP, None)
    if leaf == "w_scale" and parent in DIT_TP_COL:
        return P(AXIS_TP)
    return P()


def shard_dit_params(params, mesh: Mesh):
    """Place a DiT param tree on the mesh with the TP layout above."""

    def place(tree, path=()):
        if isinstance(tree, dict):
            return {k: place(v, path + (k,)) for k, v in tree.items()}
        if isinstance(tree, list):
            return [place(v, path + (str(i),)) for i, v in enumerate(tree)]
        return jax.device_put(
            tree, NamedSharding(mesh, dit_param_spec(path))
        )

    return place(params)


# --------------------------------------------------------------- AR TP
# Megatron col/row layout for the AR transformer (models/common/
# transformer.py param tree); reference: tensor_parallel_size in the
# stage YAML (model_executor/stage_configs/qwen3_omni_moe.yaml:27).
AR_TP_COL = frozenset({"q_proj", "k_proj", "v_proj", "gate_up", "lm_head"})
AR_TP_ROW = frozenset({"o_proj", "down"})


def ar_param_spec(path: tuple[str, ...]) -> P:
    """PartitionSpec for one AR-transformer leaf by tree path.  Columns
    (head/MLP output dims) over tp for q/k/v/gate_up/lm_head; rows for
    o_proj/down; MoE expert ffn dims likewise; the rest replicates
    (embed table included — vocab stays whole for the gather-free embed
    lookup)."""
    leaf = path[-1] if path else ""
    parent = path[-2] if len(path) >= 2 else ""
    if parent in AR_TP_COL and leaf in ("w", "b"):
        return P(None, AXIS_TP) if leaf == "w" else P(AXIS_TP)
    if parent in AR_TP_ROW and leaf == "w":
        return P(AXIS_TP, None)
    if parent == "experts":
        if leaf == "gate_up":
            return P(None, None, AXIS_TP)
        if leaf == "down":
            return P(None, AXIS_TP, None)
    return P()


def _interleave_gate_up(w, tp: int):
    """Re-order fused [*, 2I] gate_up columns so a contiguous 1/tp column
    shard holds [gate_j ; up_j] — silu_mul's local halves then line up
    with the matching down-row shard."""
    *lead, two_i = w.shape
    i = two_i // 2
    if i % tp:
        raise ValueError(f"intermediate size {i} not divisible by tp={tp}")
    w = w.reshape(*lead, 2, tp, i // tp)
    w = jnp.swapaxes(w, -3, -2)  # [*, tp, 2, I/tp]
    return w.reshape(*lead, two_i)


def ar_param_specs_tree(params):
    """Spec pytree matching ``params``' structure (for shard_map
    in_specs)."""

    def walk(tree, path=()):
        if isinstance(tree, dict):
            return {k: walk(v, path + (k,)) for k, v in tree.items()}
        if isinstance(tree, list):
            return [walk(v, path + (str(i),)) for i, v in enumerate(tree)]
        return ar_param_spec(path)

    return walk(params)


def shard_ar_params(params, mesh: Mesh):
    """Place an AR param tree on the mesh in the TP layout (and interleave
    fused gate_up columns so local shards stay [gate_j ; up_j])."""
    tp = dict(zip(mesh.axis_names, mesh.devices.shape)).get(AXIS_TP, 1)

    def place(tree, path=()):
        if isinstance(tree, dict):
            return {k: place(v, path + (k,)) for k, v in tree.items()}
        if isinstance(tree, list):
            return [place(v, path + (str(i),)) for i, v in enumerate(tree)]
        leaf = path[-1] if path else ""
        parent = path[-2] if len(path) >= 2 else ""
        arr = tree
        if tp > 1 and ((parent == "experts" and leaf == "gate_up")
                       or (leaf == "w" and parent == "gate_up")):
            arr = _interleave_gate_up(jnp.asarray(arr), tp)
        return jax.device_put(
            arr, NamedSharding(mesh, ar_param_spec(path)))

    return place(params)


def ar_kv_cache_spec(quantized: bool = False):
    """Paged KV caches [Hkv, pages, page_size, D]: KV heads over tp.

    The quantized layout shards each half's (data, scale) pair the same
    way — both lead with the Hkv axis — so the spec tree mirrors the
    cache pytree (ops/paged_attention.py int8 layout)."""
    if quantized:
        half = (P(AXIS_TP, None, None, None), P(AXIS_TP, None))
        return (half, half)
    spec = P(AXIS_TP, None, None, None)
    return (spec, spec)


def shard_moe_params(params, mesh: Mesh):
    """Place a transformer param tree with MoE expert weights sharded over
    the ``ep`` mesh axis (stacked leading-E axis) and everything else
    replicated — GSPMD then partitions the expert einsums and inserts the
    combine psum (the XLA analogue of the reference's all-to-all EP
    dispatch, SURVEY.md §2.11)."""

    def place(tree, path=()):
        if isinstance(tree, dict):
            return {k: place(v, path + (k,)) for k, v in tree.items()}
        if isinstance(tree, list):
            return [place(v, path + (str(i),)) for i, v in enumerate(tree)]
        spec = P(AXIS_EP) if "experts" in path else P()
        return jax.device_put(tree, NamedSharding(mesh, spec))

    return place(params)
