"""Sharding helpers: NamedSharding constructors + sequence shard/gather.

Replaces the reference's hook-based SP sharding utilities
(vllm_omni/diffusion/distributed/sp_sharding.py:27,74,104 — sp_shard /
sp_gather / sp_shard_with_padding) with compiler-visible shardings: instead
of torch forward hooks slicing tensors per rank, we annotate arrays with
``NamedSharding`` / use ``shard_map`` and let XLA partition.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from vllm_omni_tpu.parallel.mesh import (
    AXIS_EP,
    AXIS_RING,
    AXIS_TP,
    AXIS_ULYSSES,
)

# The sequence axis of DiT activations is sharded over both SP factors;
# equivalent to the reference's ulysses x ring decomposition of
# sequence_parallel_size (parallel_state.py:477-622).
SP_AXES = (AXIS_RING, AXIS_ULYSSES)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def seq_sharded(mesh: Mesh, seq_dim: int = 1, ndim: int = 3) -> NamedSharding:
    """Activation sharding with the sequence dimension split over SP axes.

    Default layout [batch, seq, hidden] matches DiT hidden states.
    """
    spec = [None] * ndim
    spec[seq_dim] = SP_AXES
    return NamedSharding(mesh, P(*spec))


def heads_sharded(mesh: Mesh, head_dim_index: int = 2, ndim: int = 4) -> NamedSharding:
    """Attention-layout sharding [batch, seq, heads, head_dim] with heads
    split over the ulysses axis — the post-all-to-all layout of Ulysses SP
    (reference: attention/parallel/ulysses.py:29)."""
    spec: list = [None] * ndim
    spec[head_dim_index] = AXIS_ULYSSES
    return NamedSharding(mesh, P(*spec))


def tp_col_sharded(mesh: Mesh) -> NamedSharding:
    """Column-parallel weight [in, out]: out split over tp."""
    return NamedSharding(mesh, P(None, AXIS_TP))


def tp_row_sharded(mesh: Mesh) -> NamedSharding:
    """Row-parallel weight [in, out]: in split over tp."""
    return NamedSharding(mesh, P(AXIS_TP, None))


def sp_pad_len(seq_len: int, sp_size: int) -> int:
    """Padding needed so the sequence divides the SP degree; mirrors
    sp_shard_with_padding (sp_sharding.py:104)."""
    return (-seq_len) % sp_size


def pad_to_multiple(x: jax.Array, axis: int, multiple: int) -> jax.Array:
    pad = (-x.shape[axis]) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def with_sharding(x: jax.Array, sharding: Optional[NamedSharding]) -> jax.Array:
    if sharding is None:
        return x
    return jax.lax.with_sharding_constraint(x, sharding)


# Tensor-parallel layout for DiT weight trees (reference: TP linears in
# diffusion parallel_state.py:768-775): QKV/up projections are
# column-parallel (output dim over tp), output/down projections
# row-parallel (input dim over tp). GSPMD propagates the activation
# shardings and inserts the row-parallel psums.
DIT_TP_COL = frozenset({
    "to_q", "to_k", "to_v", "add_q", "add_k", "add_v",
    "img_mlp1", "txt_mlp1", "img_mod", "txt_mod",
})
DIT_TP_ROW = frozenset({"to_out", "to_add_out", "img_mlp2", "txt_mlp2"})


def dit_param_spec(path: tuple[str, ...]) -> P:
    """PartitionSpec for one DiT weight-tree leaf, addressed by its tree
    path.  Matrix weights ("w") of attention/MLP projections split over
    the tp axis; everything else (biases, norms, embeddings) replicates."""
    leaf = path[-1] if path else ""
    parent = path[-2] if len(path) >= 2 else ""
    if leaf == "w" and parent in DIT_TP_COL:
        return P(None, AXIS_TP)
    if leaf == "w" and parent in DIT_TP_ROW:
        return P(AXIS_TP, None)
    # weight-only quantized leaves (diffusion/quantization.py): w_q keeps
    # the float weight's layout; the per-out-channel scale shards with the
    # out axis (column-parallel) and replicates otherwise
    if leaf == "w_q" and parent in DIT_TP_COL:
        return P(None, AXIS_TP)
    if leaf == "w_q" and parent in DIT_TP_ROW:
        return P(AXIS_TP, None)
    if leaf == "w_scale" and parent in DIT_TP_COL:
        return P(AXIS_TP)
    return P()


def shard_dit_params(params, mesh: Mesh):
    """Place a DiT param tree on the mesh with the TP layout above."""

    def place(tree, path=()):
        if isinstance(tree, dict):
            return {k: place(v, path + (k,)) for k, v in tree.items()}
        if isinstance(tree, list):
            return [place(v, path + (str(i),)) for i, v in enumerate(tree)]
        return jax.device_put(
            tree, NamedSharding(mesh, dit_param_spec(path))
        )

    return place(params)


def shard_moe_params(params, mesh: Mesh):
    """Place a transformer param tree with MoE expert weights sharded over
    the ``ep`` mesh axis (stacked leading-E axis) and everything else
    replicated — GSPMD then partitions the expert einsums and inserts the
    combine psum (the XLA analogue of the reference's all-to-all EP
    dispatch, SURVEY.md §2.11)."""

    def place(tree, path=()):
        if isinstance(tree, dict):
            return {k: place(v, path + (k,)) for k, v in tree.items()}
        if isinstance(tree, list):
            return [place(v, path + (str(i),)) for i, v in enumerate(tree)]
        spec = P(AXIS_EP) if "experts" in path else P()
        return jax.device_put(tree, NamedSharding(mesh, spec))

    return place(params)
