"""DisaggService: the in-proc disaggregated topology, served async.

Builds N prefill + M decode ``LLMEngine`` replicas from one model and
steps the ``DisaggRouter`` on a dedicated engine thread, bridging
results into per-request asyncio queues — the same contract
``AsyncOmni`` exposes, so the open-loop load harness
(``loadgen.run_inproc``) and the serving layer drive a disaggregated
topology exactly like a colocated one.  ``python -m
vllm_omni_tpu.disagg`` runs this as a standalone smoke against a tiny
random-weight model (scripts/disagg.sh rides it).

The router is single-threaded by design (replica engines are stepped
by exactly one thread); intake crosses the thread boundary through a
queue, never by touching router state from the event loop.
"""

from __future__ import annotations

import asyncio
import dataclasses
import itertools
import queue
import threading
import time
from typing import Any, AsyncIterator, Optional, Union

from vllm_omni_tpu.disagg.roles import ROLE_COLOCATED, ROLE_DECODE, ROLE_PREFILL
from vllm_omni_tpu.disagg.router import DisaggRouter, EngineReplica
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.outputs import OmniRequestOutput

logger = init_logger(__name__)

_SENTINEL = object()


def build_inproc_router(params, model_cfg, base_config,
                        n_prefill: int, n_decode: int,
                        eos_token_id: Optional[int] = None,
                        connector=None, **router_kwargs) -> DisaggRouter:
    """Build an in-proc topology: ``n_prefill`` prefill-role engines +
    ``n_decode`` decode-role engines from one (params, model config)
    pair.  Either count at 0 builds colocated-role replicas instead —
    the single-tier shape the degradation ladder falls back to.
    Replica chaos sites are ``replica{i}`` with prefill replicas
    numbered first (resilience/faults.py)."""
    from vllm_omni_tpu.engine import LLMEngine

    prefills: list[EngineReplica] = []
    decodes: list[EngineReplica] = []
    index = 0
    if n_prefill <= 0 or n_decode <= 0:
        # single-tier topology: colocated replicas in the decode pool
        # (dispatch falls through to the survivor path)
        cfg = dataclasses.replace(base_config,
                                  engine_role=ROLE_COLOCATED)
        for _ in range(max(n_prefill, n_decode, 1)):
            eng = LLMEngine(params, model_cfg, cfg,
                            eos_token_id=eos_token_id)
            decodes.append(EngineReplica(
                f"colocated{index}", eng, ROLE_COLOCATED, index))
            index += 1
        return DisaggRouter([], decodes, connector=connector,
                            **router_kwargs)
    pre_cfg = dataclasses.replace(base_config, engine_role=ROLE_PREFILL)
    dec_cfg = dataclasses.replace(base_config, engine_role=ROLE_DECODE)
    for _ in range(n_prefill):
        eng = LLMEngine(params, model_cfg, pre_cfg,
                        eos_token_id=eos_token_id)
        prefills.append(EngineReplica(
            f"prefill{index}", eng, ROLE_PREFILL, index))
        index += 1
    for _ in range(n_decode):
        eng = LLMEngine(params, model_cfg, dec_cfg,
                        eos_token_id=eos_token_id)
        decodes.append(EngineReplica(
            f"decode{index}", eng, ROLE_DECODE, index))
        index += 1
    return DisaggRouter(prefills, decodes, connector=connector,
                        **router_kwargs)


class DisaggService:
    """Async facade over a ``DisaggRouter`` (AsyncOmni-shaped).

    ``controlplane``: an optional ``ControlPlane`` (docs/
    control_plane.md).  Its decision thread only READS fleet state;
    the mutations it emits are applied HERE, on the engine thread,
    between router steps (``controlplane.actuate``) — the router stays
    single-threaded.  The service starts the controller's thread and
    stops it at shutdown."""

    def __init__(self, router: DisaggRouter, controlplane=None):
        self.router = router
        self.controlplane = controlplane
        self._intake: queue.Queue = queue.Queue()
        self._req_counter = itertools.count()
        self._streams: dict[str, tuple[asyncio.AbstractEventLoop,
                                       asyncio.Queue]] = {}
        self._running = True
        self._thread = threading.Thread(target=self._engine_loop,
                                        daemon=True,
                                        name="disagg-engine")
        self._thread.start()
        if controlplane is not None:
            controlplane.start()

    # ----------------------------------------------------------- lifecycle
    def shutdown(self) -> None:
        self._running = False
        if self.controlplane is not None:
            self.controlplane.stop()
        self._thread.join(timeout=10)

    @property
    def engine_thread_alive(self) -> bool:
        return self._thread.is_alive()

    # -------------------------------------------------------------- intake
    async def generate(
        self,
        prompt: Union[list[int], dict],
        sampling_params: Optional[dict] = None,
        request_id: Optional[str] = None,
        deadline_s: Optional[float] = None,
    ) -> AsyncIterator[OmniRequestOutput]:
        """Submit one request; yields its final output (errors included
        — the taxonomy rides ``error_kind`` exactly like AsyncOmni).
        Prompt forms: token-id list, or a dict with
        ``prompt_token_ids`` (+ optional ``additional_information``)."""
        if isinstance(prompt, dict):
            toks = prompt.get("prompt_token_ids")
            info = dict(prompt.get("additional_information") or {})
        else:
            toks, info = list(prompt), {}
        if toks is None:
            raise ValueError(
                "DisaggService needs prompt_token_ids (no tokenizer "
                "runs in front of the router)")
        if request_id is None:
            request_id = f"disagg-{next(self._req_counter)}"
        # external trace join (tracing/journey.py): a caller-supplied
        # trace_id (the OpenAI server's traceparent / x-omni-trace-id)
        # mints this request's journey context so router + replica
        # spans continue the caller's trace instead of a fresh one
        tid = info.pop("trace_id", None)
        if tid and "trace" not in info:
            info["trace"] = {"trace_id": str(tid),
                             "request_id": request_id}
        if request_id in self._streams:
            raise ValueError(
                f"request_id {request_id!r} already in flight")
        loop = asyncio.get_running_loop()
        out_q: asyncio.Queue = asyncio.Queue()
        self._streams[request_id] = (loop, out_q)
        self._intake.put((request_id, toks, dict(sampling_params or {}),
                          deadline_s, info))
        try:
            while True:
                item = await out_q.get()
                if item is _SENTINEL:
                    return
                if isinstance(item, Exception):
                    raise item
                yield item
        finally:
            self._streams.pop(request_id, None)

    # --------------------------------------------------------- engine loop
    def _emit(self, request_id: str, item: Any) -> None:
        entry = self._streams.get(request_id)
        if entry is None:
            return
        loop, q = entry
        try:
            loop.call_soon_threadsafe(q.put_nowait, item)
        except RuntimeError:
            # the client's event loop closed with the stream still
            # registered (abandoned generator): drop the stream — one
            # dead client must never take the engine thread (and every
            # other in-flight request) down with it
            logger.warning("dropping stream %s: client loop closed",
                           request_id)
            self._streams.pop(request_id, None)

    def _engine_loop(self) -> None:
        router = self.router
        while self._running:
            pending = []
            try:
                while True:
                    pending.append(self._intake.get_nowait())
            except queue.Empty:
                pass
            for rid, toks, sp, deadline_s, info in pending:
                try:
                    router.submit(toks, sp, request_id=rid,
                                  deadline_s=deadline_s,
                                  additional_information=info)
                except Exception as e:
                    self._emit(rid, e)
                    self._emit(rid, _SENTINEL)
            try:
                router.step()
            except Exception:
                # a step must never kill the engine thread: the router
                # already scopes failures to replicas/requests, so an
                # escape here is a bug — log it and keep serving (the
                # same stance as AsyncOmni's per-stage poll guard)
                logger.exception("router step failed; continuing")
            if self.controlplane is not None:
                try:
                    # apply the controller's pending intents ON THIS
                    # thread — the only one allowed to mutate the
                    # router (drain/flip/scale are router mutations)
                    self.controlplane.actuate(router)
                except Exception:
                    logger.exception(
                        "controlplane actuation failed; continuing")
            for out in router.poll():
                self._emit(out.request_id, out)
                self._emit(out.request_id, _SENTINEL)
            if not router.has_unfinished and not pending:
                # idle: avoid a hot spin on the GIL
                time.sleep(0.002)

    # ------------------------------------------------------ introspection
    def render_metrics(self) -> str:
        """Full Prometheus exposition of the topology: per-replica
        engine snapshots (stage label = replica index) + the
        process-global resilience/disagg counters + the handoff
        histogram."""
        from vllm_omni_tpu.metrics.prometheus import render_exposition
        from vllm_omni_tpu.resilience.metrics import resilience_metrics

        snaps = {r.index: (r.engine.metrics_snapshot()
                           if not r.dead else {})
                 for r in self.router.replicas}
        return render_exposition(
            {}, snaps,
            resilience=resilience_metrics.snapshot(),
            disagg=self.router.disagg_snapshot())

    def debug_snapshot(self) -> dict:
        return self.router.debug_snapshot()


__all__ = ["DisaggService", "build_inproc_router", "ROLE_PREFILL",
           "ROLE_DECODE"]
