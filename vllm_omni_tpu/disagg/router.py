"""Request router over N prefill + M decode engine replicas.

The fault-tolerance half of disaggregated serving
(docs/disaggregation.md): splitting one engine into two tiers doubles
the ways a request can die — a prefill replica crashing mid-stream, a
KV handoff stalling, a decode tier with no healthy peers — so the
router owns the machinery that makes the topology survivable:

- **health-driven ejection**: every step consumes each replica's honest
  health answer (PR 8 semantics: 503 = stalled/dead); unhealthy
  replicas leave the dispatch rotation and re-admit on recovery.
- **least-loaded dispatch**: among healthy, undrained replicas of a
  tier, the one with the smallest queue depth wins (the same signal
  ``request_queue_depth``/``phase_saturation_ratio`` export).
- **bounded-retry failover**: a request whose prefill replica dies is
  replayed on a surviving one — idempotent via request id, mirroring
  the supervisor's exactly-once redelivery (a replica that already
  completed the id returns its cached outcome instead of recomputing).
  A decode-side adoption that times out or fails its integrity check
  degrades to local recompute instead of erroring — the PR 6
  lost-payload path generalized across hosts.
- **degradation ladder**: when the peer tier has zero healthy replicas
  the router falls back to colocated serving on whichever tier
  survives (``degraded_mode`` 0/1 on /metrics); with NO healthy
  replica anywhere, arrivals shed with the PR 7 429 taxonomy.  Drain
  mode quiesces a replica for rolling restarts without dropping its
  in-flight decodes.

Failure semantics: an EJECTED (unhealthy) replica keeps stepping its
in-flight work — ejection only stops NEW dispatch; only a DEAD replica
(crashed step) triggers failover of its in-flight requests.  Replica
crash detection is exception-based: any exception escaping a replica's
step — including ``InjectedFault`` from the ``replica{N}`` chaos sites
— marks it dead.

Counters ride the process-global resilience registry
(``failover_total{reason}``, ``kv_handoff_bytes_total{dir}``,
``router_healthy_replicas{role}``, ``degraded_mode``) so any /metrics
render in the process shows them; the ``kv_handoff_seconds`` histogram
renders through the exposition's ``disagg`` block.

Concurrency contract (omnirace-audited): the router is SINGLE-THREADED
by design and therefore lock-free — exactly one thread (DisaggService's
``disagg-engine`` loop) calls ``submit``/``step``/``poll``/``drain``;
intake crosses the thread boundary through ``DisaggService._intake``
(a queue), never by touching ``_ctx``/``_payloads``/``_finished`` from
the event loop.  The shared state it DOES touch — resilience_metrics,
the handoff Histogram, connector stores — is the locked kind, and
those locks are traced under ``OMNI_TPU_LOCK_CHECK=1`` in the disagg
suites.  Grow a second router thread and the lock-free dicts here stop
being safe: add a lock and declare it in LOCK_GUARDS first.
"""

from __future__ import annotations

import time
import uuid
from collections import OrderedDict
from dataclasses import dataclass, field, replace
from hashlib import blake2b
from typing import Any, Optional

import numpy as np

from vllm_omni_tpu.disagg import roles
from vllm_omni_tpu.disagg.roles import (
    ROLE_COLOCATED,
    ROLE_DECODE,
    ROLE_PREFILL,
)
from vllm_omni_tpu.distributed.connectors import (
    ConnectorFactory,
    OmniConnectorBase,
)
from vllm_omni_tpu.distributed.kv_transfer import (
    KVDeadlineExceeded,
    recv_kv,
    ship_kv,
)
from vllm_omni_tpu.kvcache.quant import (
    payload_seq_len,
    payload_wire_nbytes,
    trim_payload,
)
from vllm_omni_tpu.kvcache.radix import chain_page_keys
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.metrics.cache_economics import (
    AFFINITY_HIT,
    AFFINITY_LOAD_OVERRIDE,
    AFFINITY_MISS,
    CacheEconomics,
)
from vllm_omni_tpu.metrics.stats import Histogram
from vllm_omni_tpu.outputs import OmniRequestOutput
from vllm_omni_tpu.resilience.deadline import (
    DEADLINE_EXCEEDED,
    RETRYABLE,
    expiry_ts,
    remaining_s,
)
from vllm_omni_tpu.resilience.faults import fault_point
from vllm_omni_tpu.resilience.metrics import resilience_metrics
from vllm_omni_tpu.sampling_params import SamplingParams
from vllm_omni_tpu.tracing import journey

logger = init_logger(__name__)

#: handoff-latency buckets (seconds) — in-proc handoffs land in the
#: sub-ms buckets, cross-host ones in the tail
HANDOFF_BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

#: cache-economics digest cadence: radix digests refresh every
#: DIGEST_STRIDE router steps (a digest is O(DIGEST_MAX_NODES) host
#: work per replica — stride keeps it off every tick without letting
#: the fleet board go stale), bounded to DIGEST_MAX_NODES entries
DIGEST_STRIDE = 8
DIGEST_MAX_NODES = 64
#: pages of a prompt hashed for dispatch-regret scoring — matches the
#: digest depth bound (coverage beyond the digest horizon is invisible
#: anyway, so hashing further is wasted host work)
DISPATCH_KEY_PAGES = DIGEST_MAX_NODES

#: affinity dispatch defaults (omniaffinity): the score is
#: ``expected_hit_tokens * affinity_weight - queue_depth * load_weight``
#: — with the defaults, one queued request outweighs 16 tokens of
#: expected hit, so affinity steers only when the cache win is real
#: and load stays the primary balancer under pressure
AFFINITY_WEIGHT = 1.0
LOAD_WEIGHT = 16.0
#: hysteresis floor: hits below this many pages never override plain
#: least-loaded dispatch (a 1-page hit is noise, not a placement
#: signal) — also the minimum fabric-pull gain worth the fetch
AFFINITY_FLOOR_PAGES = 2
#: cold-path stickiness: a cold prefix sticks to its rendezvous owner
#: until the owner trails the least-loaded candidate by more than this
#: many queue slots.  Without slack the second cold arrival bounces
#: off the owner the moment its queue is non-empty, and an identical
#: prefix gets prefilled on every replica before the first digest
#: refresh can steer anything (DIGEST_STRIDE staleness window).
COLD_OWNER_SLACK = 4

#: cluster-KV-fabric bounds: at most this many prefix publications per
#: router step (each is a host-side slice + store put on the one
#: engine-stepping thread), a prefix must be requested this many times
#: before it earns a publication, and the fabric index/store hold at
#: most FABRIC_CAP entries (LRU) with demand counts capped at
#: PREFIX_SEEN_CAP distinct keys
PUBLISH_BUDGET_PER_STEP = 2
PUBLISH_MIN_SEEN = 2
FABRIC_CAP = 128
PREFIX_SEEN_CAP = 4096
#: per-replica dispatched-key memory (LRU): the router's own record of
#: which prefixes it already routed to each replica — the freshness
#: floor under digest staleness (a digest refreshes on a stride; the
#: router knows what it placed between strides)
REPLICA_KEYS_CAP = 2048


class EngineReplica:
    """One engine behind the router: role + liveness + idempotent
    submission.  ``index`` is process-wide (prefill replicas first) and
    names the replica's chaos site ``replica{index}``
    (resilience/faults.py) — ``fail_step``/``drop_after`` there crash
    the replica in-proc (``kill_after`` stays a process-level fault for
    real worker processes)."""

    def __init__(self, replica_id: str, engine, role: str, index: int):
        self.replica_id = replica_id
        self.engine = engine
        self.role = role
        self.index = index
        # fleet span identity (tracing/journey.py): the engine's own
        # spans (queue_wait/prefill/decode/dispatch/retire) render on
        # this replica's Perfetto track instead of colliding with its
        # same-process siblings on one stage row.  Plain attribute set
        # — works on any engine object, read by LLMEngine's recorder
        # calls; a role flip re-stamps it (router.set_role).
        engine.span_tags = {"replica_id": replica_id, "role": role}
        self.dead = False
        self.ejected = False     # health-driven: out of dispatch rotation
        self.drained = False     # operator-driven: quiescing for restart
        self.death_reason: Optional[str] = None
        # test hook: override the health probe ((code, body) like the
        # server's /health) to fake LB-visible state transitions
        self.health_fn = None
        # exactly-once submission ledger: a redelivered id the engine
        # already saw is dropped (mirrors the supervisor's worker-side
        # seen_ids dedup)
        self._submitted: set[str] = set()

    # ------------------------------------------------------------ probes
    @property
    def queue_depth(self) -> int:
        s = self.engine.scheduler
        return len(s.waiting) + len(s.running)

    @property
    def in_rotation(self) -> bool:
        return not (self.dead or self.ejected or self.drained)

    def health(self) -> tuple[int, dict]:
        """The replica's honest health answer (PR 8 semantics): 503
        once dead — a load balancer must eject a wedged replica, and
        the router consumes the same contract."""
        if self.health_fn is not None:
            return self.health_fn()
        if self.dead:
            return 503, {"status": "dead",
                         "reason": self.death_reason}
        return 200, {"status": "ok", "role": self.role,
                     "queue_depth": self.queue_depth}

    @property
    def quiesced(self) -> bool:
        """True when a draining replica finished its in-flight work and
        can be restarted without dropping anything."""
        return not self.engine.has_unfinished_requests

    # ----------------------------------------------------------- serving
    def submit(self, request_id: str, prompt_token_ids: list[int],
               sampling_params: SamplingParams, **kwargs) -> bool:
        """Idempotent add_request: a duplicate id (failover replay
        racing a slow original, supervisor-style redelivery) is dropped
        — the first submission's outcome stands."""
        if self.dead:
            raise ConnectionError(
                f"replica {self.replica_id} is dead")
        if request_id in self._submitted:
            return False
        self._submitted.add(request_id)
        self.engine.add_request(prompt_token_ids, sampling_params,
                                request_id=request_id, **kwargs)
        return True

    def abort(self, request_id: str) -> None:
        if not self.dead:
            self.engine.abort_request(request_id)

    def step(self) -> list[OmniRequestOutput]:
        """One engine step under the replica's chaos site.  ANY escape
        — injected or real — marks the replica dead: a half-stepped
        engine's state can no longer be trusted, exactly like a crashed
        worker process; the router fails its requests over."""
        if self.dead:
            return []
        try:
            fault_point(f"replica{self.index}")
            if not self.engine.has_unfinished_requests:
                return []
            return self.engine.step()
        except Exception as e:
            self.dead = True
            self.death_reason = f"{type(e).__name__}: {e}"
            logger.warning("replica %s died: %s", self.replica_id,
                           self.death_reason)
            return []

    def revive(self) -> None:
        """Operator/test hook: bring a crashed replica back (the
        in-proc analogue of a supervisor restart).  Its engine state is
        whatever survived the crash — in-flight requests were already
        failed over, so only NEW dispatch lands here.  The submission
        ledger clears with the death: ids stranded in it would
        otherwise silently swallow a post-revive resubmission of the
        same request id."""
        self.dead = False
        self.death_reason = None
        self._submitted.clear()


@dataclass
class _ReqCtx:
    """Router-side lifecycle of one request across the tiers."""

    request_id: str
    prompt_token_ids: list[int]
    sampling_params: SamplingParams
    info: dict[str, Any] = field(default_factory=dict)
    deadline_ts: Optional[float] = None
    # "prefill" -> "handoff" -> "decode"; degraded/recompute paths run
    # as "colocated" on whichever replica took them
    phase: str = ROLE_PREFILL
    replica: Optional[EngineReplica] = None
    attempts: int = 0
    first_token: Optional[int] = None
    # finish metadata captured from the prefill output when the request
    # terminates at the prefill tier (max_tokens==1 / EOS first token)
    handoff_since_step: int = 0
    # chain-hash page keys of the prompt (router page size), computed
    # once per request and reused by affinity scoring, regret metering
    # and the fabric publish/pull legs
    keys: Optional[list[str]] = None
    # the affinity decision doc for this placement (None = affinity off
    # or the placement was a failover replay, which is affinity-blind)
    affinity: Optional[dict] = None

    @property
    def trace(self) -> Optional[dict]:
        """The request's trace context (journey spans); None = untraced."""
        return self.info.get("trace")


class DisaggRouter:
    def __init__(self, prefills: list[EngineReplica],
                 decodes: list[EngineReplica],
                 connector: Optional[OmniConnectorBase] = None,
                 tp_shards: int = 1,
                 max_failover_attempts: int = 3,
                 handoff_timeout_s: float = 5.0,
                 payload_wait_steps: int = 16,
                 affinity_routing: bool = True,
                 affinity_weight: float = AFFINITY_WEIGHT,
                 load_weight: float = LOAD_WEIGHT,
                 affinity_floor_pages: int = AFFINITY_FLOOR_PAGES,
                 cold_owner_slack: int = COLD_OWNER_SLACK,
                 publish_budget: int = PUBLISH_BUDGET_PER_STEP):
        self.prefills = list(prefills)
        self.decodes = list(decodes)
        self.replicas = self.prefills + self.decodes
        if not self.replicas:
            raise ValueError("router needs at least one replica")
        # the handoff transport; in-proc topologies default to a
        # private inproc namespace (the router ships then receives —
        # same put-then-get shape Omni._forward uses for stage edges)
        self.connector = connector or ConnectorFactory.create(
            "inproc", namespace=f"disagg-{uuid.uuid4().hex[:8]}")
        self.tp_shards = tp_shards
        self.max_failover_attempts = max_failover_attempts
        self.handoff_timeout_s = handoff_timeout_s
        self.payload_wait_steps = payload_wait_steps
        self._ctx: dict[str, _ReqCtx] = {}
        self._finished: list[OmniRequestOutput] = []
        # prefill engines hand their extracted payloads to the router
        # (not the stage-output rider): keyed by request id until the
        # handoff ships
        self._payloads: dict[str, list] = {}
        for r in self.prefills:
            r.engine.kv_transfer_sink = self._kv_sink
        self.handoff_seconds = Histogram(buckets=HANDOFF_BUCKETS_S)
        # same-address-space fast path (the Omni._forward stance): a
        # zero_copy connector hands the host arrays over without the
        # serialize->store->deserialize round trip — which would
        # otherwise run on the ONE thread stepping every replica.  The
        # handoff chaos site still fires on this path, and
        # OMNI_TPU_FORCE_CONNECTOR_SERIALIZATION=1 pins the full wire
        # path (integrity/corruption tests ride it).  Read once: the
        # flag can't change after process start.
        import os

        self._zero_copy = (
            getattr(self.connector, "zero_copy", False)
            and os.environ.get(
                "OMNI_TPU_FORCE_CONNECTOR_SERIALIZATION") != "1")
        # lifetime ledgers (also mirrored into the resilience registry
        # for /metrics): handoffs completed, failovers per reason, sheds
        self.handoffs = 0
        self.failovers: dict[str, int] = {}
        self.sheds = 0
        self.degraded = False
        self._steps = 0
        # fleet cache-economics board (metrics/cache_economics.py):
        # radix digests folded in on a step stride, every dispatch
        # scored for wasted re-prefill against them.  The board has
        # its own lock (HTTP threads read /metrics + /debug/cache);
        # the router side stays on the single engine-stepping thread
        # per the contract above.
        self.cache = CacheEconomics(
            bytes_per_token=self._kv_bytes_per_token())
        # --- prefix-affinity dispatch (omniaffinity, ROADMAP item 3):
        # score healthy candidates by expected prefix hit against their
        # live digests, blended with load; failover replays stay
        # affinity-blind (a dead owner must never pin a request)
        self.affinity_routing = affinity_routing
        self.affinity_weight = float(affinity_weight)
        self.load_weight = float(load_weight)
        self.affinity_floor_pages = int(affinity_floor_pages)
        self.cold_owner_slack = int(cold_owner_slack)
        # page size for request-side chain keys (homogeneous fleets;
        # _note_cache_dispatch re-hashes if a replica disagrees)
        self._page_size = 1
        for r in self.replicas:
            try:
                self._page_size = int(
                    r.engine.scheduler.kv.page_size) or 1
                break
            except Exception:
                continue
        # --- the remote tier as a cluster KV fabric: prefill engines
        # publish completed shared-prefix pages into the connector
        # store (bounded budget, demand-gated), and a chosen replica
        # that misses a published prefix pulls it instead of
        # re-prefilling.  All router-thread-only state (the
        # single-threaded contract above).
        self.publish_budget = int(publish_budget)
        self._publish_left = self.publish_budget
        # chain key -> dispatch demand count (LRU-capped)
        self._prefix_seen: OrderedDict[str, int] = OrderedDict()
        # replica_id -> LRU of chain keys already dispatched there:
        # the digest is stride-stale, but the router knows what it
        # placed in between — a replica that just prefilled a prefix
        # must not be "helped" with a fabric pull that would shadow
        # its own radix hit
        self._replica_keys: dict[str, OrderedDict[str, int]] = {}
        # chain key -> {tokens, pages, layers} of the published payload
        self._fabric: OrderedDict[str, dict] = OrderedDict()
        # zero-copy fast path: published slices held in-process
        self._fabric_payloads: dict[str, list] = {}
        self.prefix_pull_seconds = Histogram(buckets=HANDOFF_BUCKETS_S)
        self._refresh_digests()
        self._refresh_health()

    # ------------------------------------------------------------- sinks
    def _kv_sink(self, request, payload: list) -> None:
        self._payloads[request.request_id] = payload

    # --------------------------------------------------- cache economics
    def _kv_bytes_per_token(self) -> int:
        """Per-token KV footprint from the first replica whose memory
        ledger reports the kv_pages component (bytes / page-pool token
        capacity).  Best-effort: 0 when unavailable — token counts are
        the board's primary unit, bytes a rendering convenience."""
        for r in self.replicas:
            try:
                kv = r.engine.scheduler.kv
                comp = r.engine.memory.snapshot()["components"]
                kv_bytes = int(comp["kv_pages"]["bytes"])
                capacity = int(kv.num_pages) * int(kv.page_size)
                if kv_bytes > 0 and capacity > 0:
                    return kv_bytes // capacity
            except Exception:
                continue
        return 0

    def _refresh_digests(self) -> None:
        """Fold every live replica's radix digest + cumulative
        hit/prefill token counters into the cache board.  Bounded host
        work per replica (DIGEST_MAX_NODES node entries, O(1) subtree
        counts — kvcache/radix.py digest); engines without prefix
        caching simply never export."""
        for r in self.replicas:
            if r.dead:
                # a dead replica's cached pages are gone with it — a
                # stale digest would fake peer coverage that no longer
                # exists (accumulated fleet counters stay)
                self.cache.forget_replica(r.replica_id)
                continue
            try:
                kv = r.engine.scheduler.kv
                if not getattr(kv, "enable_prefix_caching", False):
                    continue
                sm = getattr(r.engine, "step_metrics", None)
                self.cache.observe_digest(
                    r.replica_id, kv.index.digest(DIGEST_MAX_NODES),
                    hit_tokens=int(kv.prefix_hit_tokens),
                    prefill_tokens=int(
                        getattr(sm, "prefill_tokens", 0) or 0))
            except Exception:
                # a replica that cannot digest must not take the
                # router down — the board just goes stale for it
                continue

    def _page_keys(self, ctx: "_ReqCtx") -> list[str]:
        """The request's chain-hash page keys at the ROUTER page size,
        computed once and cached on the ctx — affinity scoring, regret
        metering and the fabric legs all walk the same keys."""
        if ctx.keys is None:
            ctx.keys = [h for _, h in chain_page_keys(
                ctx.prompt_token_ids, self._page_size,
                max_pages=DISPATCH_KEY_PAGES)]
        return ctx.keys

    def _note_prefix_demand(self, keys: list[str]) -> None:
        """Count dispatch demand per chain key (LRU-capped): a prefix
        requested PUBLISH_MIN_SEEN times earns fabric publication."""
        seen = self._prefix_seen
        for key in keys:
            seen[key] = seen.get(key, 0) + 1
            seen.move_to_end(key)
        while len(seen) > PREFIX_SEEN_CAP:
            seen.popitem(last=False)

    def _note_cache_dispatch(self, ctx: "_ReqCtx",
                             replica: EngineReplica) -> dict:
        """Score one placement against the fleet digests and meter the
        regret: per-reason duplicate counters on the board, per-tenant
        redundancy on the chosen engine's attribution sketch.  Returns
        the expected-hit doc for the dispatch span args."""
        try:
            page_size = replica.engine.scheduler.kv.page_size
        except Exception:
            page_size = 1
        if page_size == self._page_size:
            keys = self._page_keys(ctx)
        else:
            keys = [h for _, h in chain_page_keys(
                ctx.prompt_token_ids, page_size,
                max_pages=DISPATCH_KEY_PAGES)]
        if self.affinity_routing:
            self._note_prefix_demand(keys)
            rec = self._replica_keys.setdefault(
                replica.replica_id, OrderedDict())
            for key in keys:
                rec[key] = self._steps
                rec.move_to_end(key)
            while len(rec) > REPLICA_KEYS_CAP:
                rec.popitem(last=False)
        doc = self.cache.note_dispatch(
            replica.replica_id, keys,
            tenant=ctx.info.get("tenant"),
            request_id=ctx.request_id)
        wasted = doc.get("wasted_tokens", 0)
        if wasted:
            attr = getattr(replica.engine, "attribution", None)
            if attr is not None:
                attr.add(ctx.info.get("tenant"),
                         "duplicate_prefill_tokens", wasted)
        return doc

    def _resolve_prefix_hit(self, ctx: "_ReqCtx",
                            replica: EngineReplica) -> None:
        """Retire the request's open dispatch entry with the engine's
        actual prefix-hit count and stamp the expected-vs-actual
        receipt on the journey timeline."""
        try:
            actual = replica.engine.scheduler.kv.take_request_hit(
                ctx.request_id)
        except Exception:
            actual = 0
        doc = self.cache.resolve_dispatch(ctx.request_id, actual)
        if doc is not None:
            journey.journey_instant(
                ctx.trace, journey.SPAN_PREFIX_HIT,
                replica_id=replica.replica_id, role=replica.role,
                args={"expected_hit_tokens":
                          doc.get("expected_hit_tokens", 0),
                      "peer_hit_tokens": doc.get("peer_hit_tokens", 0),
                      "actual_hit_tokens": actual,
                      "wasted_tokens": doc.get("wasted_tokens", 0)})

    # ------------------------------------------------- cluster KV fabric
    def _maybe_publish_prefix(self, ctx: "_ReqCtx",
                              payload: list) -> None:
        """Publish the deepest in-demand shared-prefix slice of a
        completed prefill payload into the connector store.  Bounded:
        per-step publish budget, demand gate (PUBLISH_MIN_SEEN
        dispatches), floor-page minimum, FABRIC_CAP LRU on the index.
        The published slice is a COPY — it outlives the publishing
        replica (that is the point: the fabric is the fleet's cache,
        not a pointer into one engine's HBM)."""
        if not self.affinity_routing or self._publish_left <= 0:
            return
        keys = self._page_keys(ctx)
        best_i = -1
        for i, key in enumerate(keys):
            if key in self._fabric:
                continue
            if self._prefix_seen.get(key, 0) >= PUBLISH_MIN_SEEN:
                best_i = i
        if best_i + 1 < self.affinity_floor_pages:
            return
        key = keys[best_i]
        tokens = (best_i + 1) * self._page_size
        try:
            seq_len = payload_seq_len(payload)
        except Exception:
            return
        if tokens > seq_len:
            return
        # format-agnostic page-aligned slice (tokens is a page
        # multiple, so quantized scales never split a page), copied so
        # the fabric entry outlives the publishing replica

        def copy_half(half):
            if isinstance(half, (tuple, list)):
                return tuple(np.asarray(a).copy() for a in half)
            return np.asarray(half).copy()

        sliced = [(copy_half(k), copy_half(v))
                  for k, v in trim_payload(payload, tokens,
                                           self._page_size)]
        if self._zero_copy:
            self._fabric_payloads[key] = sliced
        else:
            try:
                ship_kv(self.connector, f"prefix/{key}", sliced)
            except Exception as e:
                logger.warning("prefix publish %s failed (%s: %s)",
                               key[:12], type(e).__name__, e)
                return
        self._publish_left -= 1
        self._fabric[key] = {"tokens": tokens, "pages": best_i + 1,
                             "layers": len(sliced)}
        self._fabric.move_to_end(key)
        self.cache.note_publish(tokens)
        while len(self._fabric) > FABRIC_CAP:
            self._drop_fabric(next(iter(self._fabric)))

    def _drop_fabric(self, key: str) -> None:
        """Evict one fabric entry: index row, zero-copy payload, and
        (wire path) the connector keys ship_kv left behind."""
        entry = self._fabric.pop(key, None)
        self._fabric_payloads.pop(key, None)
        if entry is not None and not self._zero_copy:
            self.connector.cleanup(f"prefix/{key}/meta")
            for i in range(int(entry.get("layers", 0))):
                self.connector.cleanup(f"prefix/{key}/L{i}")

    def _fetch_prefix(self, key: str, ctx: "_ReqCtx") -> list:
        """Fetch a published prefix payload.  The wire path rides the
        kv_transfer integrity/deadline guards (KVIntegrityError on a
        torn stream, KVDeadlineExceeded past the request budget) and
        RE-PUBLISHES after the read — connector gets pop."""
        fault_point("prefix_pull")
        if self._zero_copy:
            payload = self._fabric_payloads.get(key)
            if payload is None:
                raise KeyError(f"fabric payload {key[:12]} vanished")
            return payload
        payload = recv_kv(self.connector, f"prefix/{key}",
                          timeout=self.handoff_timeout_s,
                          deadline_ts=ctx.deadline_ts)
        ship_kv(self.connector, f"prefix/{key}", payload)
        return payload

    def _maybe_pull_prefix(self, ctx: "_ReqCtx",
                           replica: EngineReplica) -> dict:
        """When the chosen replica's digest misses a prefix the fabric
        holds, pull it and inject instead of re-prefilling.  Returns
        the extra ``_submit_to`` kwargs ({} = no injection).  ANY fetch
        failure degrades to plain recompute — the lost-payload
        contract; an integrity failure also evicts the entry (its bytes
        can no longer be trusted)."""
        if not self.affinity_routing or not self._fabric:
            return {}
        keys = self._page_keys(ctx)
        best_i = -1
        for i, key in enumerate(keys):
            if key in self._fabric:
                best_i = i
        local_pages = (ctx.affinity or {}).get("expected_hit_pages", 0)
        # freshness floor: the digest refreshes on a stride, but the
        # router knows which prefixes it ALREADY routed here — a
        # replica that just prefilled this prefix would only have its
        # own radix hit shadowed by an injected pull
        rec = self._replica_keys.get(replica.replica_id)
        if rec:
            for i in range(len(keys) - 1, -1, -1):
                if keys[i] in rec:
                    local_pages = max(local_pages, i + 1)
                    break
        if best_i < 0 or \
                (best_i + 1) - local_pages < self.affinity_floor_pages:
            return {}
        key = keys[best_i]
        tokens = int(self._fabric[key]["tokens"])
        src = self.cache.key_src(key)
        t0, w0 = time.perf_counter(), time.time()
        try:
            payload = self._fetch_prefix(key, ctx)
        except Exception as e:
            logger.warning(
                "prefix pull %s for %s failed (%s: %s); replica "
                "recomputes", key[:12], ctx.request_id,
                type(e).__name__, e)
            self._drop_fabric(key)
            self.cache.note_pull(0, ok=False)
            return {}
        self.prefix_pull_seconds.observe(time.perf_counter() - t0)
        n = payload_wire_nbytes(payload)
        resilience_metrics.inc("kv_prefix_pull_bytes_total", n,
                               src=src)
        self.cache.note_pull(tokens, ok=True)
        journey.record_journey(
            ctx.trace, journey.SPAN_PREFIX_PULL, w0,
            time.perf_counter() - t0,
            replica_id=replica.replica_id, role=replica.role,
            cat="handoff",
            args={"key": key, "tokens": tokens, "bytes": n,
                  "src": src, "pages": best_i + 1})
        return {"injected_kv": payload,
                "extra_info": {"prefix_pull": {"tokens": tokens,
                                               "src": src}}}

    # ------------------------------------------------------------ health
    def _refresh_health(self) -> None:
        """Probe every replica's /health contract; eject non-200s from
        rotation, re-admit recovered ones, refresh the tier gauges."""
        for r in self.replicas:
            try:
                code, _ = r.health()
            except Exception:
                code = 503
            healthy = code == 200 and not r.dead
            if healthy and r.ejected:
                logger.info("replica %s recovered; re-admitting",
                            r.replica_id)
            if not healthy and not r.ejected:
                # freshly ejected: its digest must stop steering
                # affinity NOW, not at the next stride — the coverage
                # may have died with the replica, and a stale digest
                # would keep pinning requests to it the moment it
                # re-admits with a cold cache.  Dead replicas forget
                # entirely; a live-but-unhealthy one keeps its counter
                # baseline (invalidate) so re-admission never
                # double-counts its lifetime hit/prefill totals.
                if r.dead:
                    self.cache.forget_replica(r.replica_id)
                else:
                    self.cache.invalidate_digest(r.replica_id)
                self._replica_keys.pop(r.replica_id, None)
            r.ejected = not healthy
        self.refresh_gauges()

    def refresh_gauges(self) -> None:
        """Recompute ``router_healthy_replicas{role}`` and
        ``degraded_mode`` from the CURRENT replica states — without
        re-probing health.  Split out of the dispatch-path refresh so
        pollers that never dispatch (the health prober, the control
        plane's sensor tick) see live values: an idle or fully-
        quiesced fleet used to show whatever the last dispatch left
        behind."""
        for role, pool in ((ROLE_PREFILL, self.prefills),
                           (ROLE_DECODE, self.decodes)):
            if pool:
                resilience_metrics.set_gauge(
                    "router_healthy_replicas",
                    sum(1 for r in pool if r.in_rotation), role=role)
        self.degraded = bool(
            (self.prefills and not self._healthy(self.prefills))
            or (self.decodes and not self._healthy(self.decodes)))
        resilience_metrics.set_gauge("degraded_mode",
                                     1 if self.degraded else 0)

    def _healthy(self, pool: list[EngineReplica]
                 ) -> list[EngineReplica]:
        return [r for r in pool if r.in_rotation]

    def _pick(self, pool: list[EngineReplica],
              avoid: Optional[EngineReplica] = None
              ) -> Optional[EngineReplica]:
        """Least-loaded healthy replica of ``pool`` (stable on ties).
        ``avoid`` steers a failover replay away from the replica that
        just failed the request — unless it is the only one left."""
        healthy = self._healthy(pool)
        if avoid is not None:
            healthy = [r for r in healthy if r is not avoid] or healthy
        if not healthy:
            return None
        return min(healthy, key=lambda r: r.queue_depth)

    # -------------------------------------------------- affinity dispatch
    @staticmethod
    def _owner_weight(salt: str, replica_id: str) -> int:
        """Rendezvous (highest-random-weight) hash of (salt, replica):
        every router ranks the same candidates identically, so cold
        prefixes converge onto one owner — and when that owner leaves
        rotation only ITS salts re-home (no global reshuffle, unlike
        modular hashing).  The salt is the deepest chain key when the
        request carries prompt pages (identical prefixes converge even
        across tenants — the shared-system-prompt case) and the tenant
        otherwise."""
        return int.from_bytes(
            blake2b(f"{salt}|{replica_id}".encode(),
                    digest_size=8).digest(), "big")

    def _least_loaded_owner(self, healthy: list[EngineReplica],
                            tenant: Optional[str],
                            keys: list[str]) -> EngineReplica:
        """Cold-prefix placement: converge on the rendezvous owner of
        the prefix identity (the chain key at the affinity-floor depth;
        tenant when the prompt has no pages) while the owner trails the
        least-loaded candidate by at most ``cold_owner_slack`` queue
        slots — past that, load wins and ties break toward the owner.
        The floor-depth key — NOT the deepest — is the identity:
        deeper keys mix in each request's unique suffix and scatter
        requests that share a system prompt, while the floor depth is
        exactly the shallowest overlap worth routing on.  No tenant
        means no owner: plain ``_pick`` order (stable first),
        bit-identical to the cache-blind router."""
        chosen = min(healthy, key=lambda r: r.queue_depth)
        if tenant is None:
            return chosen
        salt = (keys[min(len(keys), self.affinity_floor_pages) - 1]
                if keys else tenant)
        owner = max(healthy, key=lambda r: self._owner_weight(
            salt, r.replica_id))
        if owner.queue_depth <= chosen.queue_depth + self.cold_owner_slack:
            return owner
        depth = chosen.queue_depth
        tied = [r for r in healthy if r.queue_depth == depth]
        if len(tied) == 1:
            return chosen
        return max(tied, key=lambda r: self._owner_weight(
            salt, r.replica_id))

    def _pick_affinity(self, pool: list[EngineReplica],
                       ctx: "_ReqCtx") -> Optional[EngineReplica]:
        """Prefix-affinity placement: among healthy replicas of
        ``pool``, score ``expected_hit_tokens * affinity_weight -
        queue_depth * load_weight`` against the live digests.  The
        hysteresis floor keeps sub-``affinity_floor_pages`` hits from
        overriding load balancing (those fall to the cold path), and
        score ties break on the tenant's rendezvous owner.  Only first
        placements come here — failover replays use plain ``_pick``
        (affinity-blind by contract)."""
        healthy = self._healthy(pool)
        if not healthy:
            return None
        keys = self._page_keys(ctx)
        tenant = ctx.info.get("tenant")
        cov = self.cache.expected_hits(
            [r.replica_id for r in healthy], keys)
        floor_tokens = self.affinity_floor_pages * self._page_size
        best_hit = max(hit for _, hit in cov.values())
        if best_hit < floor_tokens:
            chosen = self._least_loaded_owner(healthy, tenant, keys)
            outcome = AFFINITY_MISS
        else:
            def score(r: EngineReplica) -> float:
                return (cov[r.replica_id][1] * self.affinity_weight
                        - r.queue_depth * self.load_weight)

            top = max(score(r) for r in healthy)
            tied = [r for r in healthy if score(r) >= top - 1e-9]
            chosen = (tied[0] if tenant is None or len(tied) == 1
                      else max(tied, key=lambda r: self._owner_weight(
                          tenant, r.replica_id)))
            outcome = (AFFINITY_HIT
                       if cov[chosen.replica_id][1] >= floor_tokens
                       else AFFINITY_LOAD_OVERRIDE)
        doc = {
            "request_id": ctx.request_id,
            "tenant": tenant,
            "outcome": outcome,
            "chosen": chosen.replica_id,
            "expected_hit_pages": cov[chosen.replica_id][0],
            "expected_hit_tokens": cov[chosen.replica_id][1],
            "best_hit_tokens": best_hit,
            "queue_depth": chosen.queue_depth,
        }
        ctx.affinity = doc
        self.cache.note_affinity(doc)
        resilience_metrics.inc("router_affinity_dispatch_total",
                               outcome=outcome)
        return chosen

    # -------------------------------------------------------- drain mode
    def drain(self, replica_id: str) -> None:
        """Quiesce a replica for a rolling restart: it leaves the
        dispatch rotation but KEEPS stepping until its in-flight
        requests finish (``quiesced(replica_id)`` says when)."""
        self._replica(replica_id).drained = True

    def undrain(self, replica_id: str) -> None:
        self._replica(replica_id).drained = False

    def quiesced(self, replica_id: str) -> bool:
        return self._replica(replica_id).quiesced

    def _replica(self, replica_id: str) -> EngineReplica:
        for r in self.replicas:
            if r.replica_id == replica_id:
                return r
        raise KeyError(f"unknown replica {replica_id!r}")

    # ---------------------------------------------- fleet actuation
    # (the control plane's actuator family, docs/control_plane.md —
    # called on the ROUTER THREAD only, like every other mutator here)
    def set_role(self, replica_id: str, role: str) -> None:
        """Live re-roling: flip a DRAINED, QUIESCED replica between the
        prefill and decode tiers (drain -> quiesce -> flip -> undrain
        is the caller's sequence; this is the flip).  The replica moves
        pools, its engine re-arms/disarms the KV-transfer trigger
        (LLMEngine.set_engine_role), and the prefill payload sink is
        (un)wired.  The replica STAYS drained — re-admission is the
        caller's explicit undrain, so a half-finished sequence never
        accidentally takes traffic."""
        if role not in (ROLE_PREFILL, ROLE_DECODE):
            raise ValueError(
                f"re-role target must be prefill|decode, got {role!r}")
        r = self._replica(replica_id)
        if r.dead:
            raise RuntimeError(f"replica {replica_id} is dead")
        if r.role == role:
            return
        if not (r.drained and r.quiesced):
            raise RuntimeError(
                f"replica {replica_id} must be drained and quiesced "
                "before a role flip (in-flight streams survive the "
                "drain; the flip itself must see an empty engine)")
        flip = getattr(r.engine, "set_engine_role", None)
        if flip is not None:
            flip(role)
        for pool in (self.prefills, self.decodes):
            if r in pool:
                pool.remove(r)
        from_role = r.role
        if role == ROLE_PREFILL:
            self.prefills.append(r)
            r.engine.kv_transfer_sink = self._kv_sink
        else:
            self.decodes.append(r)
            r.engine.kv_transfer_sink = None
        r.role = role
        # re-stamp the fleet span identity: post-flip engine spans must
        # carry the NEW role on the replica's track
        r.engine.span_tags = {"replica_id": r.replica_id, "role": role}
        self.replicas = self.prefills + self.decodes
        self._zero_gauge_if_emptied(from_role)
        self.refresh_gauges()

    def add_replica(self, replica: EngineReplica) -> None:
        """Scale-up actuation: admit a freshly built replica into its
        role's pool.  The caller decides when it takes traffic (a
        cold replica typically enters DRAINED and is undrained after
        its warmup window — the controller's cold-start model)."""
        if any(r.replica_id == replica.replica_id
               for r in self.replicas):
            raise ValueError(
                f"replica id {replica.replica_id!r} already exists")
        if replica.role == ROLE_PREFILL:
            self.prefills.append(replica)
            replica.engine.kv_transfer_sink = self._kv_sink
        else:
            self.decodes.append(replica)
        self.replicas = self.prefills + self.decodes
        self.refresh_gauges()

    def remove_replica(self, replica_id: str) -> EngineReplica:
        """Scale-down actuation: remove a replica that is DEAD or
        (drained and quiesced) — scale-down only ever happens via
        drain, so no in-flight request is dropped.  Returns the removed
        replica (the caller owns teardown)."""
        r = self._replica(replica_id)
        if not r.dead and not (r.drained and r.quiesced):
            raise RuntimeError(
                f"replica {replica_id} must be dead, or drained and "
                "quiesced, before removal")
        if len(self.replicas) <= 1:
            raise RuntimeError(
                "refusing to remove the last replica of the topology")
        for pool in (self.prefills, self.decodes):
            if r in pool:
                pool.remove(r)
        self.replicas = self.prefills + self.decodes
        self._zero_gauge_if_emptied(r.role)
        self.cache.forget_replica(replica_id)
        self._replica_keys.pop(replica_id, None)
        self.refresh_gauges()
        return r

    def _zero_gauge_if_emptied(self, role: str) -> None:
        """An emptied pool's gauge must drop to 0 even though the
        refresh loop skips empty pools (colocated topologies never
        emit the absent tier's series — but a tier that EXISTED and
        emptied, via removal OR a role flip, must not freeze its last
        value on /metrics)."""
        pool = self.prefills if role == ROLE_PREFILL else self.decodes
        if not pool and role in (ROLE_PREFILL, ROLE_DECODE):
            resilience_metrics.set_gauge(
                "router_healthy_replicas", 0, role=role)

    # ------------------------------------------------------------ intake
    def submit(self, prompt_token_ids: list[int],
               sampling_params: Optional[SamplingParams | dict] = None,
               request_id: Optional[str] = None,
               deadline_s: Optional[float] = None,
               additional_information: Optional[dict] = None) -> str:
        """Route one request.  Healthy prefill + decode tiers run the
        disaggregated path; a missing tier degrades to colocated on the
        survivor; nothing healthy sheds with the 429 taxonomy (the
        server is not serving — backing off is the client's move)."""
        if request_id is None:
            request_id = f"disagg-{uuid.uuid4().hex[:12]}"
        sp = self._normalize_sp(sampling_params)
        ctx = _ReqCtx(
            request_id=request_id,
            prompt_token_ids=list(prompt_token_ids),
            sampling_params=sp,
            info=dict(additional_information or {}),
            deadline_ts=expiry_ts(deadline_s),
        )
        self._ctx[request_id] = ctx
        self._dispatch(ctx)
        return request_id

    @staticmethod
    def _normalize_sp(sp) -> SamplingParams:
        if isinstance(sp, SamplingParams):
            return sp
        known = SamplingParams.__dataclass_fields__
        return SamplingParams(**{k: v for k, v in (sp or {}).items()
                                 if k in known})

    def _dispatch(self, ctx: _ReqCtx,
                  avoid: Optional[EngineReplica] = None) -> None:
        """(Re)place a request on the topology according to the
        degradation ladder."""
        t0, w0 = time.perf_counter(), time.time()
        # affinity applies to FIRST placements only: failover replays
        # (avoid set / attempts > 0) fall back to plain least-loaded so
        # a dead owner can never pin its tenants' requests
        affinity = (self.affinity_routing and avoid is None
                    and ctx.attempts == 0)
        # the tier that will run the PREFILL work is the one affinity
        # steers: the prefill pool on a two-tier topology, the decode
        # pool when it alone exists (single-tier colocated serving)
        prefill = (self._pick_affinity(self.prefills, ctx)
                   if affinity and self.prefills
                   else self._pick(self.prefills, avoid=avoid))
        decode = (self._pick_affinity(self.decodes, ctx)
                  if affinity and self.decodes and not self.prefills
                  else self._pick(self.decodes, avoid=avoid))
        if prefill is not None and decode is not None:
            # the disaggregated fast path: prompt processing + first
            # token on the prefill tier (max_tokens clamped to 1 — the
            # decode tier owns the rest of the stream)
            ctx.phase = ROLE_PREFILL
            ctx.replica = prefill
            # pull BEFORE the dispatch is metered: _note_cache_dispatch
            # records this request's keys as the replica's coverage,
            # which must not mask a genuinely cold replica from the
            # pull decision
            pull = self._maybe_pull_prefix(ctx, prefill) \
                if affinity else {}
            exp = self._note_cache_dispatch(ctx, prefill)
            self._submit_to(prefill, ctx,
                            replace(ctx.sampling_params, max_tokens=1),
                            **pull)
            journey.record_journey(
                ctx.trace, journey.SPAN_DISPATCH, w0,
                time.perf_counter() - t0,
                args={"replica": prefill.replica_id,
                      "phase": ROLE_PREFILL, "attempt": ctx.attempts,
                      "expected_hit_tokens":
                          exp.get("expected_hit_tokens", 0),
                      "peer_hit_tokens": exp.get("peer_hit_tokens", 0),
                      "affinity_outcome":
                          (ctx.affinity or {}).get("outcome")})
            return
        survivor = decode or prefill or self._pick(self.replicas,
                                                   avoid=avoid)
        if survivor is None:
            # nothing healthy anywhere: shed per the PR 7 taxonomy —
            # 429, distinct from 503 (broke mid-request) and 504
            # (budget spent)
            self.sheds += 1
            journey.journey_instant(
                ctx.trace, journey.SPAN_SHED,
                args={"attempt": ctx.attempts})
            self._finish(ctx, OmniRequestOutput.from_error(
                ctx.request_id,
                "no healthy replica in any tier; retry with backoff",
                kind="shed"))
            return
        ctx.phase = ROLE_COLOCATED
        ctx.replica = survivor
        exp = self._note_cache_dispatch(ctx, survivor)
        self._submit_to(survivor, ctx, ctx.sampling_params,
                        suppress_kv_transfer=True)
        # a colocated placement on a two-tier topology is a
        # degradation-ladder transition — a distinct span name so the
        # ladder reads directly off the timeline
        name = (journey.SPAN_DEGRADED if (self.prefills and self.decodes)
                else journey.SPAN_DISPATCH)
        journey.record_journey(
            ctx.trace, name, w0, time.perf_counter() - t0,
            args={"replica": survivor.replica_id,
                  "phase": ROLE_COLOCATED, "attempt": ctx.attempts,
                  "expected_hit_tokens":
                      exp.get("expected_hit_tokens", 0),
                  "peer_hit_tokens": exp.get("peer_hit_tokens", 0)})

    def _submit_to(self, replica: EngineReplica, ctx: _ReqCtx,
                   sp: SamplingParams,
                   suppress_kv_transfer: bool = False,
                   extra_info: Optional[dict] = None,
                   **kwargs) -> None:
        # deadline re-stamped across every hop: the remaining budget is
        # re-derived and converted back to an expiry, the same dance
        # the orchestrator does on stage handoffs — a slow prefill tier
        # shrinks what the decode tier gets
        info = dict(ctx.info)
        if extra_info:
            info.update(extra_info)
        if suppress_kv_transfer:
            # colocated placement on a prefill-role engine: nobody
            # will consume an extracted payload — don't pay the
            # whole-prompt device→host copy for it
            info["disable_kv_transfer"] = True
        try:
            accepted = replica.submit(
                ctx.request_id, ctx.prompt_token_ids, sp,
                deadline_ts=expiry_ts(remaining_s(ctx.deadline_ts)),
                additional_information=info, **kwargs)
        except Exception:
            # replica died between pick and submit: re-route
            self._failover(ctx, "dispatch_failed")
            return
        if not accepted:
            # the duplicate guard swallowed the id (a stale ledger
            # entry, e.g. the replica crashed with it in flight and
            # was revived): a silently-dropped submit would hang the
            # request forever — treat it as a failed dispatch instead
            self._failover(ctx, "dispatch_failed")

    # -------------------------------------------------------------- step
    def step(self) -> None:
        """One router tick: refresh health, step every live replica
        (drained and ejected ones included — their in-flight work must
        finish), route outputs, ship pending handoffs, fail over
        requests stranded on dead replicas."""
        self._steps += 1
        self._publish_left = self.publish_budget
        self._refresh_health()
        if self._steps % DIGEST_STRIDE == 0:
            self._refresh_digests()
        for replica in self.replicas:
            for out in replica.step():
                self._on_output(replica, out)
        self._pump_handoffs()
        self._reap_dead()

    def poll(self) -> list[OmniRequestOutput]:
        out, self._finished = self._finished, []
        return out

    @property
    def has_unfinished(self) -> bool:
        return bool(self._ctx)

    # ------------------------------------------------------ output logic
    def _finish(self, ctx: _ReqCtx, out: OmniRequestOutput) -> None:
        self._ctx.pop(ctx.request_id, None)
        self._payloads.pop(ctx.request_id, None)
        # a request that never reached prefill output (shed, error,
        # budget exhausted) leaves its dispatch expectation open —
        # drop it so the board's pending table stays bounded
        self.cache.abandon_dispatch(ctx.request_id)
        self._finished.append(out)

    def _on_output(self, replica: EngineReplica,
                   out: OmniRequestOutput) -> None:
        # the id's run on THIS replica is over: lift the duplicate
        # guard so a later failover may legitimately replay it here
        # (the guard exists for concurrent duplicates, not history)
        replica._submitted.discard(out.request_id)
        ctx = self._ctx.get(out.request_id)
        if ctx is None or ctx.replica is not replica:
            # stale output from a pre-failover replica: the replay's
            # outcome is authoritative, this one is discarded (the
            # idempotency contract)
            return
        if out.is_error:
            # client-meaningful taxonomy passes through (400/429/504 —
            # a colocated engine would answer the same); an INTERNAL
            # error is a replica-scoped failure and fails over like a
            # crash (bounded)
            if out.error_kind in ("invalid_request", "shed",
                                  DEADLINE_EXCEEDED):
                self._finish(ctx, out)
            else:
                self._failover(ctx, "replica_error")
            return
        if ctx.phase in (ROLE_PREFILL, ROLE_COLOCATED):
            # first output from the replica that ran the prefill: join
            # the ACTUAL prefix hit onto the dispatch-time expectation
            # (same thread that steps the engine — no race with the
            # kv manager's dict).  Idempotent: the board pops the open
            # entry, so a decode-tier terminal can't double-count.
            self._resolve_prefix_hit(ctx, replica)
        if ctx.phase == ROLE_PREFILL:
            toks = out.outputs[0].token_ids if out.outputs else []
            reason = (out.outputs[0].finish_reason
                      if out.outputs else None)
            if not toks:
                self._failover(ctx, "prefill_no_token")
                return
            ctx.first_token = int(toks[0])
            if (ctx.sampling_params.max_tokens <= 1
                    or reason == "stop"):
                # the stream is already complete at the prefill tier
                # (one-token request, or the first token hit EOS/stop):
                # the prefill output IS the final answer
                self._finish(ctx, out)
                return
            ctx.phase = "handoff"
            ctx.handoff_since_step = self._steps
            return
        # decode or colocated: terminal
        self._finish(ctx, out)

    # ----------------------------------------------------------- handoff
    def _pump_handoffs(self) -> None:
        """Ship extracted prefill KV to the decode tier and adopt it.
        Every failure on this edge degrades — recompute on the decode
        tier, never a dropped or corrupted request."""
        for ctx in [c for c in self._ctx.values()
                    if c.phase == "handoff"]:
            payload = self._payloads.pop(ctx.request_id, None)
            if payload is None:
                # extraction still in flight on the prefill engine; a
                # dead replica is handled by _reap_dead, a stuck
                # extraction by the bounded wait
                if (self._steps - ctx.handoff_since_step
                        > self.payload_wait_steps):
                    self._adopt_or_recompute(ctx, None,
                                             "payload_stalled")
                continue
            # the fabric publish leg: completed prefill payloads are
            # the only place whole-prefix KV exists host-side — carve
            # the in-demand shared slice off before the handoff ships
            self._maybe_publish_prefix(ctx, payload)
            zero_copy = self._zero_copy
            t0 = time.perf_counter()
            received = None
            # ship/recv journey spans: the ship leg renders on the
            # PREFILL replica's track (it produced the payload), the
            # recv leg on the router track (transport + merge happen
            # here) — args carry bytes/layers/tier so the timeline
            # answers "how big and over what" without the metrics page
            prefill_replica = ctx.replica
            tier = "zero_copy" if zero_copy else type(
                self.connector).__name__
            try:
                t_ship, w_ship = time.perf_counter(), time.time()
                if zero_copy:
                    fault_point("handoff")
                    n = payload_wire_nbytes(payload)
                    received = payload
                else:
                    n = roles.ship_handoff(
                        self.connector, ctx.request_id, payload,
                        tp_shards=self.tp_shards)
                journey.record_journey(
                    ctx.trace, journey.SPAN_HANDOFF_SHIP, w_ship,
                    time.perf_counter() - t_ship,
                    replica_id=(prefill_replica.replica_id
                                if prefill_replica else "?"),
                    role=ROLE_PREFILL, cat="handoff",
                    args={"bytes": n, "layers": len(payload),
                          "tp_shards": self.tp_shards, "tier": tier})
                resilience_metrics.inc("kv_handoff_bytes_total",
                                       n, dir="out")
                # per-tenant handoff-byte attribution on the SHIPPING
                # engine's sketch (one add per handoff; the recv leg
                # is the same bytes — counting both would double it)
                attr = (getattr(prefill_replica.engine, "attribution",
                                None)
                        if prefill_replica is not None else None)
                if attr is not None:
                    attr.add(ctx.info.get("tenant"), "handoff_bytes", n)
                if not zero_copy:
                    t_recv, w_recv = time.perf_counter(), time.time()
                    received = roles.recv_handoff(
                        self.connector, ctx.request_id,
                        timeout=self.handoff_timeout_s,
                        deadline_ts=ctx.deadline_ts)
                    journey.record_journey(
                        ctx.trace, journey.SPAN_HANDOFF_RECV, w_recv,
                        time.perf_counter() - t_recv, cat="handoff",
                        args={"bytes": n, "layers": len(payload),
                              "tier": tier})
                resilience_metrics.inc("kv_handoff_bytes_total", n,
                                       dir="in")
            except KVDeadlineExceeded:
                # the budget died in transit: 504, not a connector
                # timeout — and not a recompute the client stopped
                # waiting for
                roles.cleanup_handoff(self.connector, ctx.request_id,
                                      len(payload), self.tp_shards)
                from vllm_omni_tpu.resilience.deadline import (
                    deadline_output,
                )

                self._finish(ctx, deadline_output(
                    ctx.request_id, 0, "KV handoff"))
                continue
            except Exception as e:
                logger.warning(
                    "handoff for %s failed (%s: %s); decode tier "
                    "recomputes", ctx.request_id, type(e).__name__, e)
                roles.cleanup_handoff(self.connector, ctx.request_id,
                                      len(payload), self.tp_shards)
            if received is not None:
                # delivered handoffs only: a failed transfer's
                # timeout-to-give-up is not a handoff latency — it
                # would bury the real p99 under timeout spikes
                self.handoff_seconds.observe(time.perf_counter() - t0)
            self._adopt_or_recompute(
                ctx, received,
                None if received is not None else "handoff_failed")

    def _adopt_or_recompute(self, ctx: _ReqCtx,
                            payload: Optional[list],
                            fail_reason: Optional[str]) -> None:
        """Place the post-prefill remainder on the decode tier: adopt
        the streamed KV when it arrived intact, else recompute the
        whole prompt locally (greedy recompute re-derives the same
        stream — the lost-payload contract)."""
        decode = self._pick(self.decodes) or self._pick(self.prefills)
        if decode is None:
            self._failover(ctx, "no_decode_tier")
            return
        if fail_reason is not None:
            self._note_failover(fail_reason)
        ctx.phase = ROLE_DECODE if payload is not None \
            else ROLE_COLOCATED
        ctx.replica = decode
        try:
            if payload is not None:
                t0, w0 = time.perf_counter(), time.time()
                roles.adopt_prefill(
                    decode.engine, ctx.request_id,
                    ctx.prompt_token_ids, ctx.first_token, payload,
                    ctx.sampling_params,
                    deadline_ts=expiry_ts(remaining_s(ctx.deadline_ts)),
                    additional_information=ctx.info)
                journey.record_journey(
                    ctx.trace, journey.SPAN_ADOPT, w0,
                    time.perf_counter() - t0,
                    replica_id=decode.replica_id, role=decode.role,
                    cat="handoff",
                    args={"tokens": len(ctx.prompt_token_ids),
                          "layers": len(payload)})
                decode._submitted.add(ctx.request_id)
                self.handoffs += 1
            else:
                # full local recompute: first token re-derived too, so
                # the stream matches what a colocated engine serves
                # (kv_transfer suppressed — the fallback target may be
                # a prefill-role survivor and nobody consumes it)
                self._submit_to(decode, ctx, ctx.sampling_params,
                                suppress_kv_transfer=True)
                journey.journey_instant(
                    ctx.trace, journey.SPAN_ADOPT,
                    replica_id=decode.replica_id, role=decode.role,
                    cat="handoff",
                    args={"recompute": True, "reason": fail_reason})
        except Exception:
            self._failover(ctx, "adoption_failed")

    # ---------------------------------------------------------- failover
    def _note_failover(self, reason: str) -> None:
        self.failovers[reason] = self.failovers.get(reason, 0) + 1
        resilience_metrics.inc("failover_total", reason=reason)

    def _failover(self, ctx: _ReqCtx, reason: str) -> None:
        """Replay a request whose replica (or handoff) failed.  Bounded:
        past ``max_failover_attempts`` the request fails fast with the
        503 retryable kind — it produced no client-visible output, so
        an idempotent client may resubmit.  The over-budget exit counts
        NO failover: ``failover_total`` is re-routes performed, and it
        must reconcile with the ledger."""
        if ctx.attempts >= self.max_failover_attempts:
            journey.journey_instant(
                ctx.trace, journey.SPAN_FAILOVER,
                args={"reason": reason, "attempt": ctx.attempts,
                      "outcome": "budget_exhausted"})
            self._finish(ctx, OmniRequestOutput.from_error(
                ctx.request_id,
                f"request failed after {ctx.attempts} failover "
                f"attempt(s) (last: {reason}); safe to resubmit",
                kind=RETRYABLE))
            return
        ctx.attempts += 1
        self._note_failover(reason)
        journey.journey_instant(
            ctx.trace, journey.SPAN_FAILOVER,
            args={"reason": reason, "attempt": ctx.attempts,
                  "from_replica": (ctx.replica.replica_id
                                   if ctx.replica is not None else None)})
        ctx.first_token = None
        self._payloads.pop(ctx.request_id, None)
        self._dispatch(ctx, avoid=ctx.replica)

    def _reap_dead(self) -> None:
        """Fail over every request stranded on a dead replica.  Phase
        matters only for the metric reason: any replay restarts from
        the prompt (prefill KV on a dead replica is gone; decode
        progress was never client-visible in the final-output API)."""
        for ctx in list(self._ctx.values()):
            r = ctx.replica
            if r is None or not r.dead:
                continue
            reason = ("prefill_replica_died"
                      if ctx.phase in (ROLE_PREFILL, "handoff")
                      else "decode_replica_died")
            self._failover(ctx, reason)

    # ------------------------------------------------------ introspection
    def disagg_snapshot(self) -> dict:
        """The exposition's ``disagg`` block: the handoff + fabric-pull
        histograms + the fleet cache-economics counters/gauges."""
        return {"handoff_seconds": self.handoff_seconds.snapshot(),
                "prefix_pull_seconds":
                    self.prefix_pull_seconds.snapshot(),
                "cache": self.cache.exposition()}

    def debug_snapshot(self) -> dict:
        """/debug/disagg: replica table + in-flight request phases +
        the failover/handoff ledgers.  Read-only host state."""
        return {
            "enabled": True,
            "degraded_mode": self.degraded,
            "steps": self._steps,
            "replicas": [{
                "replica_id": r.replica_id,
                "role": r.role,
                "index": r.index,
                "dead": r.dead,
                "death_reason": r.death_reason,
                "ejected": r.ejected,
                "drained": r.drained,
                "quiesced": r.quiesced,
                "queue_depth": r.queue_depth,
            } for r in self.replicas],
            "requests": [{
                "request_id": c.request_id,
                "phase": c.phase,
                "replica": (c.replica.replica_id
                            if c.replica is not None else None),
                "attempts": c.attempts,
                "deadline_remaining_s": remaining_s(c.deadline_ts),
            } for c in self._ctx.values()],
            "counters": {
                "handoffs": self.handoffs,
                "failovers": dict(self.failovers),
                "sheds": self.sheds,
                "fabric_entries": len(self._fabric),
            },
        }
