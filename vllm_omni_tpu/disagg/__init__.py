"""Disaggregated prefill/decode serving (docs/disaggregation.md).

Prefill and decode as first-class engine roles
(``EngineConfig.engine_role``), per-layer TPLA-sharded KV handoff
between the tiers (``roles``), and the fault-tolerance machinery that
makes the split survivable (``router``: health-driven ejection,
least-loaded dispatch, bounded-retry failover, graceful degradation
back to colocated serving, drain mode).  ``service`` wraps an in-proc
topology in the AsyncOmni-shaped async contract.
"""

from vllm_omni_tpu.disagg.roles import (  # noqa: F401
    ROLE_COLOCATED,
    ROLE_DECODE,
    ROLE_PREFILL,
    ROLES,
    adopt_prefill,
    handoff_key,
    merge_kv_shards,
    recv_handoff,
    shard_kv_payload,
    ship_handoff,
)
from vllm_omni_tpu.disagg.router import (  # noqa: F401
    DisaggRouter,
    EngineReplica,
)
from vllm_omni_tpu.disagg.service import (  # noqa: F401
    DisaggService,
    build_inproc_router,
)

__all__ = [
    "ROLES", "ROLE_PREFILL", "ROLE_DECODE", "ROLE_COLOCATED",
    "handoff_key", "shard_kv_payload", "merge_kv_shards",
    "ship_handoff", "recv_handoff", "adopt_prefill",
    "DisaggRouter", "EngineReplica", "DisaggService",
    "build_inproc_router",
]
