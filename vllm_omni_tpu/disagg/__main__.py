"""Standalone disaggregation smoke: ``python -m vllm_omni_tpu.disagg``.

Builds an in-proc N-prefill × M-decode topology over a tiny
random-weight transformer, serves a batch of greedy requests through
the router (optionally under an ``OMNI_TPU_FAULTS`` chaos plan from the
environment), and verifies every completed stream bit-identical
against a colocated single-engine oracle.  Exit 0 = the topology
served and matched; the CI gate (scripts/disagg.sh) runs this after
the test matrix.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m vllm_omni_tpu.disagg",
        description="in-proc disaggregated prefill/decode smoke")
    ap.add_argument("--prefill", type=int, default=2,
                    help="prefill replicas (default 2)")
    ap.add_argument("--decode", type=int, default=1,
                    help="decode replicas (default 1)")
    ap.add_argument("--requests", type=int, default=4)
    ap.add_argument("--max-tokens", type=int, default=6)
    ap.add_argument("--steps", type=int, default=2000,
                    help="router step budget before declaring a hang")
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from vllm_omni_tpu.disagg.service import build_inproc_router
    from vllm_omni_tpu.engine import EngineConfig, LLMEngine
    from vllm_omni_tpu.models.common import transformer as tfm
    from vllm_omni_tpu.sampling_params import SamplingParams

    model_cfg = tfm.TransformerConfig.tiny(vocab_size=64)
    params = tfm.init_params(jax.random.PRNGKey(0), model_cfg,
                             jnp.float32)
    base = EngineConfig(num_pages=64, page_size=4, max_model_len=128,
                        max_num_seqs=4, dtype=jnp.float32)
    prompts = [[(7 * i + j) % 64 for j in range(8)]
               for i in range(args.requests)]
    sp = SamplingParams(temperature=0.0, max_tokens=args.max_tokens)

    oracle = LLMEngine(params, model_cfg, base)
    want = [o.outputs[0].token_ids
            for o in oracle.generate([list(p) for p in prompts], sp)]

    router = build_inproc_router(params, model_cfg, base,
                                 args.prefill, args.decode)
    rids = [router.submit(list(p), sp, request_id=f"smoke-{i}")
            for i, p in enumerate(prompts)]
    finished: dict[str, object] = {}
    for _ in range(args.steps):
        if not router.has_unfinished:
            break
        router.step()
        for out in router.poll():
            finished[out.request_id] = out
    for out in router.poll():
        finished[out.request_id] = out

    mismatches, errors = [], []
    for i, rid in enumerate(rids):
        out = finished.get(rid)
        if out is None or out.is_error:
            errors.append({"request_id": rid,
                           "error": (out.error_message
                                     if out is not None else "lost")})
        elif out.outputs[0].token_ids != want[i]:
            mismatches.append({"request_id": rid,
                               "got": out.outputs[0].token_ids,
                               "want": want[i]})
    report = {
        "topology": {"prefill": args.prefill, "decode": args.decode},
        "requests": args.requests,
        "completed": len(finished) - len(errors),
        "errors": errors,
        "mismatches": mismatches,
        "router": router.debug_snapshot()["counters"],
    }
    print(json.dumps(report, indent=2, default=str))
    return 1 if (mismatches or errors) else 0


if __name__ == "__main__":
    sys.exit(main())
