"""Engine roles + the prefill→decode KV handoff protocol.

Disaggregated prefill/decode serving (docs/disaggregation.md) splits
one engine into two tiers: PREFILL engines run requests to the end of
prompt processing (plus the first sampled token) and ship the paged KV
per-layer through ``distributed/kv_transfer.py``; DECODE engines adopt
the streamed pages into their ``KVCacheManager`` and resume through the
decode executable — the PR 6 resume-as-decode rule, which is what keeps
a disaggregated greedy stream bit-identical to the colocated oracle
(prefill tier and oracle share the full-prompt prefill executable;
decode tier and oracle share the decode executable; no position is ever
computed by a third shape).

TPLA-style sharding ("TPLA: Tensor Parallel Latent Attention for
Efficient Disaggregated Prefill and Decode Inference", PAPERS.md): the
transferred KV is sharded along the tensor-parallel axis — the KV-head
axis of the dense [Hkv, seq, D] payload — so each decode shard receives
only its slice, cutting per-link transfer volume by the TP degree.
Shards ship under ``{key}/tp{r}`` and a top-level ``{key}/meta`` names
the shard count, so a decode rank fetches exactly one subkey family.

``fault_point("handoff")`` wraps both directions: the chaos matrix
(resilience/faults.py) injects drops/delays on this edge exactly like
any other connector edge, deterministic and seeded.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from vllm_omni_tpu.distributed.connectors import OmniConnectorBase
from vllm_omni_tpu.distributed.kv_transfer import (
    KVDeadlineExceeded,
    KVIntegrityError,
    recv_kv,
    ship_kv,
)
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.resilience.faults import fault_point
from vllm_omni_tpu.resilience.retry import RetryPolicy

logger = init_logger(__name__)

#: valid EngineConfig.engine_role values
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
ROLE_COLOCATED = "colocated"
ROLES = (ROLE_PREFILL, ROLE_DECODE, ROLE_COLOCATED)

# handoff puts/gets ride the same shallow retry stance as kv_transfer:
# the router's failover IS the outer retry layer
_HANDOFF_RETRY = RetryPolicy(max_attempts=2)


def handoff_key(request_id: str) -> str:
    """Connector key family of one request's prefill→decode handoff."""
    return f"disagg/{request_id}"


# ------------------------------------------------------- TPLA sharding
def _shard_half(half, lo: int, hi: int):
    """Head-slice one cache half.  A quantized (data, scale) half
    slices BOTH arrays — each leads with the Hkv axis."""
    if isinstance(half, (tuple, list)):
        return (half[0][lo:hi], half[1][lo:hi])
    return half[lo:hi]


def _merge_half(halves):
    if isinstance(halves[0], (tuple, list)):
        return (np.concatenate([h[0] for h in halves], axis=0),
                np.concatenate([h[1] for h in halves], axis=0))
    return np.concatenate(halves, axis=0)


def shard_kv_payload(payload: list, num_shards: int) -> list[list]:
    """Split a per-layer KV payload into ``num_shards`` slices along
    the KV-head (tensor-parallel) axis — dense ``[(k, v)]``
    ([Hkv, seq, D] arrays) or the quantized wire layout
    ``[((kq, ks), (vq, vs))]`` (data AND per-page scales both slice on
    their leading Hkv axis).  Requires Hkv % num_shards == 0 — the
    same divisibility the TP attention sharding itself requires."""
    if num_shards <= 1:
        return [payload]
    first = payload[0][0]
    heads = int(np.asarray(
        first[0] if isinstance(first, (tuple, list)) else first
    ).shape[0])
    if heads % num_shards:
        raise ValueError(
            f"cannot shard {heads} KV heads into {num_shards} slices")
    per = heads // num_shards
    return [
        [(_shard_half(k, r * per, (r + 1) * per),
          _shard_half(v, r * per, (r + 1) * per))
         for k, v in payload]
        for r in range(num_shards)
    ]


def merge_kv_shards(shards: list[list]) -> list:
    """Inverse of ``shard_kv_payload``: concatenate per-layer slices
    back along the KV-head axis (shards in rank order), either
    layout."""
    if len(shards) == 1:
        return shards[0]
    return [
        (_merge_half([s[i][0] for s in shards]),
         _merge_half([s[i][1] for s in shards]))
        for i in range(len(shards[0]))
    ]


# ----------------------------------------------------- handoff ship/recv
def ship_handoff(conn: OmniConnectorBase, request_id: str,
                 payload: list, tp_shards: int = 1,
                 retry: Optional[RetryPolicy] = None) -> int:
    """Ship one request's prefill KV to the decode tier: TP-shard the
    payload, put each shard's layer stream plus a top-level meta naming
    the shard count.  Returns total bytes shipped.  Raises the
    transport's ConnectionError/TimeoutError family on failure — the
    router maps that to failover/recompute."""
    from vllm_omni_tpu.resilience.retry import call_with_retry

    fault_point("handoff")
    retry = retry or _HANDOFF_RETRY
    key = handoff_key(request_id)
    shards = shard_kv_payload(payload, tp_shards)
    # the meta put retries like every sibling put — one transient blip
    # here would otherwise discard the whole prefill result
    total = call_with_retry(
        lambda: conn.put(f"{key}/meta", {"tp_shards": len(shards)}),
        site=f"handoff:{key}/meta", policy=retry)
    for r, shard in enumerate(shards):
        total += ship_kv(conn, f"{key}/tp{r}", shard, retry=retry)
    return total


def recv_handoff(conn: OmniConnectorBase, request_id: str,
                 timeout: float = 30.0,
                 deadline_ts: Optional[float] = None,
                 shard: Optional[int] = None,
                 retry: Optional[RetryPolicy] = None) -> list:
    """Receive one request's handoff.  ``shard`` fetches exactly one TP
    slice (a decode TP rank pulls only its slice — the TPLA bandwidth
    win); None fetches and merges every shard (the single-controller
    in-proc topology).  Integrity violations raise ``KVIntegrityError``
    and a spent end-to-end budget raises ``KVDeadlineExceeded`` — the
    caller degrades to recompute or 504, never injects garbage."""
    from vllm_omni_tpu.resilience.deadline import clamp_timeout, expired
    from vllm_omni_tpu.resilience.retry import call_with_retry

    fault_point("handoff")
    retry = retry or _HANDOFF_RETRY
    key = handoff_key(request_id)
    # retried like every other operation on this edge: one transient
    # blip at the meta get must not discard a shipped prefill result
    meta = call_with_retry(
        lambda: conn.get(f"{key}/meta",
                         timeout=clamp_timeout(timeout, deadline_ts)),
        site=f"handoff:{key}/meta", policy=retry,
        deadline_ts=deadline_ts)
    if meta is None:
        if expired(deadline_ts):
            raise KVDeadlineExceeded(
                f"handoff {key}: deadline exceeded waiting for meta")
        raise TimeoutError(f"handoff {key}: meta missing within "
                           f"{timeout:.1f}s")
    n = int(meta.get("tp_shards", 1))
    if shard is not None:
        return recv_kv(conn, f"{key}/tp{shard}", timeout,
                       retry=retry, deadline_ts=deadline_ts)
    shards = [recv_kv(conn, f"{key}/tp{r}", timeout, retry=retry,
                      deadline_ts=deadline_ts)
              for r in range(n)]
    return merge_kv_shards(shards)


def cleanup_handoff(conn: OmniConnectorBase, request_id: str,
                    num_layers: int, tp_shards: int = 1) -> None:
    """Best-effort cleanup of a handoff that will never be consumed
    (adoption failed, request finished at prefill) so abandoned
    payloads don't accumulate in the connector store."""
    key = handoff_key(request_id)
    try:
        conn.cleanup(f"{key}/meta")
        for r in range(max(tp_shards, 1)):
            conn.cleanup(f"{key}/tp{r}/meta")
            for i in range(num_layers):
                conn.cleanup(f"{key}/tp{r}/L{i}")
    except Exception:  # cleanup must never mask the original failure
        logger.debug("handoff cleanup failed for %s", request_id,
                     exc_info=True)


# ------------------------------------------------------------- adoption
def adopt_prefill(engine, request_id: str, prompt_token_ids: list[int],
                  first_token: int, payload: list,
                  sampling_params, deadline_ts: Optional[float] = None,
                  additional_information: Optional[dict[str, Any]] = None,
                  ) -> str:
    """Decode-side adoption: admit the request with the streamed
    full-prompt KV plus the prefill tier's first sampled token, so it
    resumes through the DECODE executable (scheduler resume-as-decode).
    A payload the engine rejects (layer-count/shape mismatch) degrades
    to local recompute inside ``_inject_prefix_kv`` — adoption never
    errors a request that recompute could still serve."""
    return engine.add_request(
        prompt_token_ids, sampling_params, request_id=request_id,
        injected_kv=payload, injected_first_token=first_token,
        deadline_ts=deadline_ts,
        additional_information=dict(additional_information or {}),
    )


__all__ = [
    "ROLES", "ROLE_PREFILL", "ROLE_DECODE", "ROLE_COLOCATED",
    "handoff_key", "shard_kv_payload", "merge_kv_shards",
    "ship_handoff", "recv_handoff", "cleanup_handoff", "adopt_prefill",
    "KVIntegrityError", "KVDeadlineExceeded",
]
