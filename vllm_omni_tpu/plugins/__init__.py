"""Plugin loading: entry points + env-listed modules.

Behavioral port of the reference's plugin system (reference:
vllm_omni/plugins/__init__.py:24,61 — entry-point groups
``vllm_omni.general_plugins`` (arbitrary setup hooks) and
``vllm_omni.platform_plugins`` (platform-class providers), loaded once at
package import).

Two discovery paths:
- **entry points**: installed packages exposing the groups
  ``vllm_omni_tpu.general_plugins`` / ``vllm_omni_tpu.platform_plugins``;
- **env modules**: ``OMNI_TPU_PLUGINS=mod1,mod2`` imports each module and
  calls its ``register()`` (development / air-gapped images where nothing
  can be pip-installed).

A platform plugin's entry point (or ``register()``) returns
``(backend_name, platform_cls)``, registered via
``platforms.register_platform`` so detection prefers it.
"""

from __future__ import annotations

import os
from typing import Optional

from vllm_omni_tpu.logger import init_logger

logger = init_logger(__name__)

GENERAL_GROUP = "vllm_omni_tpu.general_plugins"
PLATFORM_GROUP = "vllm_omni_tpu.platform_plugins"

_loaded = False


def _entry_points(group: str):
    from importlib.metadata import entry_points

    try:
        return list(entry_points(group=group))
    except TypeError:  # pragma: no cover - pre-3.10 fallback
        return list(entry_points().get(group, ()))


def _apply_platform(result) -> None:
    from vllm_omni_tpu.platforms import register_platform

    if result is None:
        return
    name, cls = result
    register_platform(name, cls)
    logger.info("registered platform plugin %r", name)


def load_plugins(reload: bool = False) -> int:
    """Load every discovered plugin; returns how many loaded.  Idempotent
    unless ``reload`` (the reference loads once at import,
    plugins/__init__.py:61)."""
    global _loaded
    if _loaded and not reload:
        return 0
    _loaded = True
    n = 0
    for ep in _entry_points(GENERAL_GROUP):
        try:
            hook = ep.load()
            hook()
            n += 1
            logger.info("loaded general plugin %r", ep.name)
        except Exception as e:
            logger.warning("general plugin %r failed: %s", ep.name, e)
    for ep in _entry_points(PLATFORM_GROUP):
        try:
            _apply_platform(ep.load()())
            n += 1
        except Exception as e:
            logger.warning("platform plugin %r failed: %s", ep.name, e)
    env = os.environ.get("OMNI_TPU_PLUGINS", "")
    for mod_name in filter(None, (m.strip() for m in env.split(","))):
        try:
            import importlib

            mod = importlib.import_module(mod_name)
            result = mod.register()
            # a register() may return a platform tuple or None
            if isinstance(result, tuple):
                _apply_platform(result)
            n += 1
            logger.info("loaded env plugin %r", mod_name)
        except Exception as e:
            logger.warning("env plugin %r failed: %s", mod_name, e)
    return n


def plugins_loaded() -> bool:
    return _loaded
