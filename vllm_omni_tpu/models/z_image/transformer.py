"""Z-Image single-stream DiT (functional JAX).

Reference: vllm_omni/diffusion/models/z_image/z_image_transformer.py:546
``ZImageTransformer2DModel`` — a unified-sequence architecture unlike the
MMDiT double streams: image tokens and caption tokens are refined
separately (2 modulated noise-refiner blocks / 2 unmodulated
context-refiner blocks), then CONCATENATED into one sequence processed by
30 shared blocks.  Blocks are llama-flavored: GQA attention with per-head
QK RMSNorm, sandwich RMSNorms around both sublayers, tanh-gated AdaLN
(4 chunks from a 256-dim conditioning vector), SwiGLU FFN with hidden
``dim/3*8``.  RoPE is 3-axis (frame/H/W) over integer coordinate ids;
caption tokens occupy frame slots 1..cap_len on the frame axis and the
image grid starts after them (z_image_transformer.py:772-827).

TPU-first: static shapes (uniform batch geometry replaces the reference's
ragged per-item lists + SEQ_MULTI_OF padding), one jitted forward, rope
tables computed from the grid at trace time.  Rope pair convention is
half-split like the rest of this repo — re-verify against the checkpoint
at weight-port time.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.ops import flash_attention, rms_norm

ADALN_EMBED_DIM = 256


@dataclass(frozen=True)
class ZImageDiTConfig:
    in_channels: int = 16
    patch_size: int = 2
    dim: int = 3840
    num_layers: int = 30
    num_refiner_layers: int = 2
    num_heads: int = 30
    num_kv_heads: int = 30
    cap_feat_dim: int = 2560
    rope_theta: float = 256.0
    axes_dims: tuple[int, int, int] = (32, 48, 48)
    t_scale: float = 1000.0
    norm_eps: float = 1e-5

    @property
    def head_dim(self) -> int:
        return self.dim // self.num_heads

    @property
    def ffn_dim(self) -> int:
        return int(self.dim / 3 * 8)

    @property
    def adaln_dim(self) -> int:
        return min(self.dim, ADALN_EMBED_DIM)

    @staticmethod
    def tiny() -> "ZImageDiTConfig":
        return ZImageDiTConfig(
            in_channels=4, dim=96, num_layers=2, num_refiner_layers=1,
            num_heads=4, num_kv_heads=2, cap_feat_dim=64,
            axes_dims=(8, 8, 8),
        )


def _block_init(key, cfg: ZImageDiTConfig, modulation: bool, dtype):
    k = jax.random.split(key, 6)
    d = cfg.dim
    q_dim = cfg.num_heads * cfg.head_dim
    kv_dim = cfg.num_kv_heads * cfg.head_dim
    p = {
        "to_q": nn.linear_init(k[0], d, q_dim, bias=False, dtype=dtype),
        "to_k": nn.linear_init(k[1], d, kv_dim, bias=False, dtype=dtype),
        "to_v": nn.linear_init(k[2], d, kv_dim, bias=False, dtype=dtype),
        "out": nn.linear_init(k[3], q_dim, d, bias=False, dtype=dtype),
        "norm_q": nn.rmsnorm_init(cfg.head_dim, dtype),
        "norm_k": nn.rmsnorm_init(cfg.head_dim, dtype),
        "attn_norm1": nn.rmsnorm_init(d, dtype),
        "attn_norm2": nn.rmsnorm_init(d, dtype),
        "ffn_norm1": nn.rmsnorm_init(d, dtype),
        "ffn_norm2": nn.rmsnorm_init(d, dtype),
        # fused SwiGLU [w1; w3]
        "w13": nn.linear_init(k[4], d, 2 * cfg.ffn_dim, bias=False,
                              dtype=dtype),
        "w2": nn.linear_init(k[5], cfg.ffn_dim, d, bias=False, dtype=dtype),
    }
    if modulation:
        p["adaln"] = nn.linear_init(
            jax.random.fold_in(key, 7), cfg.adaln_dim, 4 * d, dtype=dtype)
    return p


def init_params(key, cfg: ZImageDiTConfig, dtype=jnp.float32):
    keys = jax.random.split(key, cfg.num_layers + 2 * cfg.num_refiner_layers
                            + 8)
    d = cfg.dim
    p_in = cfg.patch_size * cfg.patch_size * cfg.in_channels
    params = {
        "x_embed": nn.linear_init(keys[0], p_in, d, dtype=dtype),
        "cap_norm": nn.rmsnorm_init(cfg.cap_feat_dim, dtype),
        "cap_embed": nn.linear_init(keys[1], cfg.cap_feat_dim, d,
                                    dtype=dtype),
        "t_in1": nn.linear_init(keys[2], 256, 1024, dtype=dtype),
        "t_in2": nn.linear_init(keys[3], 1024, cfg.adaln_dim, dtype=dtype),
        "final_adaln": nn.linear_init(keys[4], cfg.adaln_dim, d,
                                      dtype=dtype),
        "final_out": nn.linear_init(keys[5], d, p_in, dtype=dtype),
        "noise_refiner": [],
        "context_refiner": [],
        "layers": [],
    }
    ki = 6
    for _ in range(cfg.num_refiner_layers):
        params["noise_refiner"].append(
            _block_init(keys[ki], cfg, True, dtype))
        ki += 1
    for _ in range(cfg.num_refiner_layers):
        params["context_refiner"].append(
            _block_init(keys[ki], cfg, False, dtype))
        ki += 1
    for _ in range(cfg.num_layers):
        params["layers"].append(_block_init(keys[ki], cfg, True, dtype))
        ki += 1
    return params


def _axis_angles(pos, half, theta):
    inv = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    return pos.astype(jnp.float32)[:, None] * inv[None, :]


def rope_angles(cfg: ZImageDiTConfig, coords: jax.Array):
    """coords [S, 3] integer (frame, row, col) ids -> angles
    [S, head_dim//2] (reference RopeEmbedder, z_image_transformer.py:493)."""
    halves = [d // 2 for d in cfg.axes_dims]
    parts = [
        _axis_angles(coords[:, i], h, cfg.rope_theta)
        for i, h in enumerate(halves)
    ]
    ang = jnp.concatenate(parts, axis=-1)
    return jnp.cos(ang), jnp.sin(ang)


def _rope_apply(x, cos, sin):
    d = x.shape[-1]
    x1 = x[..., : d // 2].astype(jnp.float32)
    x2 = x[..., d // 2:].astype(jnp.float32)
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    return jnp.concatenate(
        [x1 * c - x2 * s, x2 * c + x1 * s], axis=-1).astype(x.dtype)


def _block(p, cfg: ZImageDiTConfig, x, freqs, adaln=None, attn_fn=None):
    b, s, _ = x.shape
    eps = cfg.norm_eps
    if "adaln" in p:
        mod = nn.linear(p["adaln"], adaln)[:, None, :]
        scale_msa, gate_msa, scale_mlp, gate_mlp = jnp.split(mod, 4, -1)
        gate_msa, gate_mlp = jnp.tanh(gate_msa), jnp.tanh(gate_mlp)
        scale_msa, scale_mlp = 1.0 + scale_msa, 1.0 + scale_mlp
    else:
        scale_msa = gate_msa = scale_mlp = gate_mlp = None

    h = rms_norm(x, p["attn_norm1"]["w"], eps)
    if scale_msa is not None:
        h = h * scale_msa
    q = rms_norm(
        nn.linear(p["to_q"], h).reshape(b, s, -1, cfg.head_dim),
        p["norm_q"]["w"], eps)
    k = rms_norm(
        nn.linear(p["to_k"], h).reshape(b, s, -1, cfg.head_dim),
        p["norm_k"]["w"], eps)
    v = nn.linear(p["to_v"], h).reshape(b, s, -1, cfg.head_dim)
    cos, sin = freqs
    q = _rope_apply(q, cos, sin)
    k = _rope_apply(k, cos, sin)
    if attn_fn is not None:
        o = attn_fn(q, k, v)
    else:
        o = flash_attention(q, k, v, causal=False)
    o = nn.linear(p["out"], o.reshape(b, s, -1))
    o = rms_norm(o, p["attn_norm2"]["w"], eps)
    x = x + (gate_msa * o if gate_msa is not None else o)

    h = rms_norm(x, p["ffn_norm1"]["w"], eps)
    if scale_mlp is not None:
        h = h * scale_mlp
    w13 = nn.linear(p["w13"], h)
    g, u = jnp.split(w13, 2, axis=-1)
    y = nn.linear(p["w2"], jax.nn.silu(g) * u)
    y = rms_norm(y, p["ffn_norm2"]["w"], eps)
    return x + (gate_mlp * y if gate_mlp is not None else y)


def forward(
    params,
    cfg: ZImageDiTConfig,
    img_tokens: jax.Array,  # [B, S_img, patch^2 * in_channels]
    cap_feats: jax.Array,   # [B, S_cap, cap_feat_dim]
    timesteps: jax.Array,   # [B] in [0, 1]
    grid_hw: tuple[int, int],
    cap_mask=None,          # [B, S_cap] (currently informational)
    attn_fn=None,
) -> jax.Array:
    """Velocity prediction [B, S_img, patch^2 * in_channels]."""
    gh, gw = grid_hw
    b, s_img, _ = img_tokens.shape
    s_cap = cap_feats.shape[1]
    assert s_img == gh * gw, (s_img, gh, gw)

    temb = nn.timestep_embedding(timesteps * cfg.t_scale, 256)
    adaln = nn.linear(
        params["t_in2"],
        jax.nn.silu(nn.linear(params["t_in1"],
                              temb.astype(img_tokens.dtype))))

    # coordinate ids: caption rides the frame axis starting at 1; the
    # image grid's frame coordinate starts right after the caption
    cap_coords = jnp.stack(
        [jnp.arange(s_cap) + 1, jnp.zeros(s_cap, jnp.int32),
         jnp.zeros(s_cap, jnp.int32)], axis=-1)
    img_f = jnp.full((s_img,), s_cap + 1, jnp.int32)
    img_r = jnp.arange(gh).repeat(gw)
    img_c = jnp.tile(jnp.arange(gw), gh)
    img_coords = jnp.stack([img_f, img_r, img_c], axis=-1)
    cap_freqs = rope_angles(cfg, cap_coords)
    img_freqs = rope_angles(cfg, img_coords)
    uni_freqs = tuple(
        jnp.concatenate([i, c], axis=0)
        for i, c in zip(img_freqs, cap_freqs))

    x = nn.linear(params["x_embed"], img_tokens)
    for blk in params["noise_refiner"]:
        x = _block(blk, cfg, x, img_freqs, adaln)

    cap = nn.linear(params["cap_embed"],
                    rms_norm(cap_feats, params["cap_norm"]["w"],
                             cfg.norm_eps))
    for blk in params["context_refiner"]:
        cap = _block(blk, cfg, cap, cap_freqs)

    # unified sequence: image first, caption after (UnifiedPrepare,
    # z_image_transformer.py:93-103)
    u = jnp.concatenate([x, cap], axis=1)
    for blk in params["layers"]:
        u = _block(blk, cfg, u, uni_freqs, adaln, attn_fn=attn_fn)

    # final layer over the image tokens
    scale = 1.0 + nn.linear(params["final_adaln"], jax.nn.silu(adaln))
    out = nn.layernorm({}, u[:, :s_img]) * scale[:, None, :]
    return nn.linear(params["final_out"], out)


def flops_per_token(cfg: ZImageDiTConfig) -> float:
    """Rough matmul FLOPs/token for MFU accounting."""
    d = cfg.dim
    return 2 * (4 * d * d + 3 * d * cfg.ffn_dim)
