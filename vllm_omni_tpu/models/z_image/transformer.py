"""Z-Image single-stream DiT (functional JAX).

Reference: vllm_omni/diffusion/models/z_image/z_image_transformer.py:546
``ZImageTransformer2DModel`` — a unified-sequence architecture unlike the
MMDiT double streams: image tokens and caption tokens are refined
separately (2 modulated noise-refiner blocks / 2 unmodulated
context-refiner blocks), then CONCATENATED into one sequence processed by
30 shared blocks.  Blocks are llama-flavored: GQA attention with per-head
QK RMSNorm, sandwich RMSNorms around both sublayers, tanh-gated AdaLN
(4 chunks from a 256-dim conditioning vector), SwiGLU FFN with hidden
``dim/3*8``.  RoPE is 3-axis (frame/H/W) over integer coordinate ids;
caption tokens occupy frame slots 1..cap_len on the frame axis and the
image grid starts after them (z_image_transformer.py:772-827).

TPU-first: static shapes (uniform batch geometry replaces the reference's
ragged per-item lists + SEQ_MULTI_OF padding), one jitted forward, rope
tables computed from the grid at trace time.  Rope pair convention is
half-split like the rest of this repo — re-verify against the checkpoint
at weight-port time.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.ops import flash_attention, rms_norm

ADALN_EMBED_DIM = 256


@dataclass(frozen=True)
class ZImageDiTConfig:
    in_channels: int = 16
    patch_size: int = 2
    dim: int = 3840
    num_layers: int = 30
    num_refiner_layers: int = 2
    num_heads: int = 30
    num_kv_heads: int = 30
    cap_feat_dim: int = 2560
    rope_theta: float = 256.0
    axes_dims: tuple[int, int, int] = (32, 48, 48)
    t_scale: float = 1000.0
    norm_eps: float = 1e-5
    # rotary pairing: False = half-split (TPU-native default), True =
    # interleaved pairs — the trained-checkpoint convention (reference
    # RotaryEmbedding(is_neox_style=False), z_image_transformer.py:305);
    # from_pretrained sets this
    rope_interleaved: bool = False
    # sequence length multiple the reference pads to (SEQ_MULTI_OF):
    # per-item caption spans round up to it (learned cap_pad embeds) and
    # the image sequence pads to it (x_pad embeds, ids (0,0,0))
    seq_multiple: int = 32

    @property
    def head_dim(self) -> int:
        return self.dim // self.num_heads

    @property
    def ffn_dim(self) -> int:
        return int(self.dim / 3 * 8)

    @property
    def adaln_dim(self) -> int:
        return min(self.dim, ADALN_EMBED_DIM)

    @staticmethod
    def tiny() -> "ZImageDiTConfig":
        return ZImageDiTConfig(
            in_channels=4, dim=96, num_layers=2, num_refiner_layers=1,
            num_heads=4, num_kv_heads=2, cap_feat_dim=64,
            axes_dims=(8, 8, 8),
        )


def _block_init(key, cfg: ZImageDiTConfig, modulation: bool, dtype):
    k = jax.random.split(key, 6)
    d = cfg.dim
    q_dim = cfg.num_heads * cfg.head_dim
    kv_dim = cfg.num_kv_heads * cfg.head_dim
    p = {
        "to_q": nn.linear_init(k[0], d, q_dim, bias=False, dtype=dtype),
        "to_k": nn.linear_init(k[1], d, kv_dim, bias=False, dtype=dtype),
        "to_v": nn.linear_init(k[2], d, kv_dim, bias=False, dtype=dtype),
        "out": nn.linear_init(k[3], q_dim, d, bias=False, dtype=dtype),
        "norm_q": nn.rmsnorm_init(cfg.head_dim, dtype),
        "norm_k": nn.rmsnorm_init(cfg.head_dim, dtype),
        "attn_norm1": nn.rmsnorm_init(d, dtype),
        "attn_norm2": nn.rmsnorm_init(d, dtype),
        "ffn_norm1": nn.rmsnorm_init(d, dtype),
        "ffn_norm2": nn.rmsnorm_init(d, dtype),
        # fused SwiGLU [w1; w3]
        "w13": nn.linear_init(k[4], d, 2 * cfg.ffn_dim, bias=False,
                              dtype=dtype),
        "w2": nn.linear_init(k[5], cfg.ffn_dim, d, bias=False, dtype=dtype),
    }
    if modulation:
        p["adaln"] = nn.linear_init(
            jax.random.fold_in(key, 7), cfg.adaln_dim, 4 * d, dtype=dtype)
    return p


def init_params(key, cfg: ZImageDiTConfig, dtype=jnp.float32):
    keys = jax.random.split(key, cfg.num_layers + 2 * cfg.num_refiner_layers
                            + 8)
    d = cfg.dim
    p_in = cfg.patch_size * cfg.patch_size * cfg.in_channels
    params = {
        "x_embed": nn.linear_init(keys[0], p_in, d, dtype=dtype),
        "cap_norm": nn.rmsnorm_init(cfg.cap_feat_dim, dtype),
        "cap_embed": nn.linear_init(keys[1], cfg.cap_feat_dim, d,
                                    dtype=dtype),
        "t_in1": nn.linear_init(keys[2], 256, 1024, dtype=dtype),
        "t_in2": nn.linear_init(keys[3], 1024, cfg.adaln_dim, dtype=dtype),
        "final_adaln": nn.linear_init(keys[4], cfg.adaln_dim, d,
                                      dtype=dtype),
        "final_out": nn.linear_init(keys[5], d, p_in, dtype=dtype),
        # learned pad embeddings replacing padded positions post-embed
        # (reference x_pad_token / cap_pad_token,
        # z_image_transformer.py:721-722,888-921)
        "x_pad": (0.02 * jax.random.normal(
            jax.random.fold_in(keys[5], 1), (1, d))).astype(dtype),
        "cap_pad": (0.02 * jax.random.normal(
            jax.random.fold_in(keys[5], 2), (1, d))).astype(dtype),
        "noise_refiner": [],
        "context_refiner": [],
        "layers": [],
    }
    ki = 6
    for _ in range(cfg.num_refiner_layers):
        params["noise_refiner"].append(
            _block_init(keys[ki], cfg, True, dtype))
        ki += 1
    for _ in range(cfg.num_refiner_layers):
        params["context_refiner"].append(
            _block_init(keys[ki], cfg, False, dtype))
        ki += 1
    for _ in range(cfg.num_layers):
        params["layers"].append(_block_init(keys[ki], cfg, True, dtype))
        ki += 1
    return params


def _axis_angles(pos, half, theta):
    inv = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    return pos.astype(jnp.float32)[..., None] * inv


def rope_angles(cfg: ZImageDiTConfig, coords: jax.Array):
    """coords [..., 3] integer (frame, row, col) ids -> angles
    [..., head_dim//2] (reference RopeEmbedder,
    z_image_transformer.py:493).  Leading dims may include the batch —
    caption lengths are per-item, so the image frame coordinate is
    data-dependent per item."""
    halves = [d // 2 for d in cfg.axes_dims]
    parts = [
        _axis_angles(coords[..., i], h, cfg.rope_theta)
        for i, h in enumerate(halves)
    ]
    ang = jnp.concatenate(parts, axis=-1)
    return jnp.cos(ang), jnp.sin(ang)


def _rope_apply(x, cos, sin, interleaved: bool = False):
    # x [B, S, H, D]; cos/sin [B, S, D//2] (per-item tables)
    c = cos[:, :, None, :]
    s = sin[:, :, None, :]
    if interleaved:
        x1 = x[..., 0::2].astype(jnp.float32)
        x2 = x[..., 1::2].astype(jnp.float32)
        out = jnp.stack([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
        return out.reshape(x.shape).astype(x.dtype)
    d = x.shape[-1]
    x1 = x[..., : d // 2].astype(jnp.float32)
    x2 = x[..., d // 2:].astype(jnp.float32)
    return jnp.concatenate(
        [x1 * c - x2 * s, x2 * c + x1 * s], axis=-1).astype(x.dtype)


def _block(p, cfg: ZImageDiTConfig, x, freqs, adaln=None, attn_fn=None):
    b, s, _ = x.shape
    eps = cfg.norm_eps
    if "adaln" in p:
        mod = nn.linear(p["adaln"], adaln)[:, None, :]
        scale_msa, gate_msa, scale_mlp, gate_mlp = jnp.split(mod, 4, -1)
        gate_msa, gate_mlp = jnp.tanh(gate_msa), jnp.tanh(gate_mlp)
        scale_msa, scale_mlp = 1.0 + scale_msa, 1.0 + scale_mlp
    else:
        scale_msa = gate_msa = scale_mlp = gate_mlp = None

    h = rms_norm(x, p["attn_norm1"]["w"], eps)
    if scale_msa is not None:
        h = h * scale_msa
    q = rms_norm(
        nn.linear(p["to_q"], h).reshape(b, s, -1, cfg.head_dim),
        p["norm_q"]["w"], eps)
    k = rms_norm(
        nn.linear(p["to_k"], h).reshape(b, s, -1, cfg.head_dim),
        p["norm_k"]["w"], eps)
    v = nn.linear(p["to_v"], h).reshape(b, s, -1, cfg.head_dim)
    cos, sin = freqs
    q = _rope_apply(q, cos, sin, interleaved=cfg.rope_interleaved)
    k = _rope_apply(k, cos, sin, interleaved=cfg.rope_interleaved)
    if attn_fn is not None:
        o = attn_fn(q, k, v)
    else:
        o = flash_attention(q, k, v, causal=False)
    o = nn.linear(p["out"], o.reshape(b, s, -1))
    o = rms_norm(o, p["attn_norm2"]["w"], eps)
    x = x + (gate_msa * o if gate_msa is not None else o)

    h = rms_norm(x, p["ffn_norm1"]["w"], eps)
    if scale_mlp is not None:
        h = h * scale_mlp
    w13 = nn.linear(p["w13"], h)
    g, u = jnp.split(w13, 2, axis=-1)
    y = nn.linear(p["w2"], jax.nn.silu(g) * u)
    y = rms_norm(y, p["ffn_norm2"]["w"], eps)
    return x + (gate_mlp * y if gate_mlp is not None else y)


def forward(
    params,
    cfg: ZImageDiTConfig,
    img_tokens: jax.Array,  # [B, S_img, patch^2 * in_channels]
    cap_feats: jax.Array,   # [B, S_cap, cap_feat_dim]
    timesteps: jax.Array,   # [B] in [0, 1]
    grid_hw: tuple[int, int],
    cap_mask=None,          # [B, S_cap] 1=real; pads get the learned pad embed
    attn_fn=None,
) -> jax.Array:
    """Velocity prediction [B, S_img, patch^2 * in_channels].

    Reference padding semantics (z_image_transformer.py:770-921): each
    item's caption span rounds up to ``seq_multiple`` with the LEARNED
    cap_pad embedding at continued frame coordinates; batch-level
    caption padding beyond an item's rounded span carries zero
    embeddings at ids (0,0,0); the image grid's frame coordinate starts
    at that item's rounded caption length + 1; the image sequence rounds
    up to ``seq_multiple`` with x_pad embeddings at ids (0,0,0).  All
    pad positions are fully attended (the reference runs attention
    unmasked)."""
    gh, gw = grid_hw
    b, s_img, _ = img_tokens.shape
    s_cap = cap_feats.shape[1]
    assert s_img == gh * gw, (s_img, gh, gw)
    sm = cfg.seq_multiple

    temb = nn.timestep_embedding(timesteps * cfg.t_scale, 256)
    adaln = nn.linear(
        params["t_in2"],
        jax.nn.silu(nn.linear(params["t_in1"],
                              temb.astype(img_tokens.dtype))))

    # per-item caption spans: real length -> rounded (cap_pad) span
    if cap_mask is None:
        real_len = jnp.full((b,), s_cap, jnp.int32)
    else:
        real_len = cap_mask.astype(jnp.int32).sum(axis=1)
    span = jnp.minimum(-(-real_len // sm) * sm, s_cap)  # [B]
    j = jnp.arange(s_cap)
    in_span = j[None, :] < span[:, None]                # [B, S_cap]
    cap_f = jnp.where(in_span, 1 + j[None, :], 0)
    zeros_c = jnp.zeros((b, s_cap), jnp.int32)
    cap_coords = jnp.stack([cap_f, zeros_c, zeros_c], axis=-1)

    pad_img = (-s_img) % sm
    img_f = jnp.broadcast_to((span + 1)[:, None], (b, s_img))
    img_r = jnp.broadcast_to(jnp.arange(gh).repeat(gw)[None],
                             (b, s_img))
    img_c = jnp.broadcast_to(jnp.tile(jnp.arange(gw), gh)[None],
                             (b, s_img))
    img_coords = jnp.stack([img_f, img_r, img_c], axis=-1)
    if pad_img:
        img_coords = jnp.concatenate(
            [img_coords, jnp.zeros((b, pad_img, 3), img_coords.dtype)],
            axis=1)
    cap_freqs = rope_angles(cfg, cap_coords)
    # batch-level caption padding beyond an item's rounded span carries
    # ZEROED rope tables (reference pad_sequence pads cap_cos/cap_sin
    # with 0.0, z_image_transformer.py:929-931) — cos=sin=0 annihilates
    # those pad keys in every attention layer
    cap_freqs = tuple(f * in_span[..., None] for f in cap_freqs)
    img_freqs = rope_angles(cfg, img_coords)
    uni_freqs = tuple(
        jnp.concatenate([i, c], axis=1)
        for i, c in zip(img_freqs, cap_freqs))

    x = nn.linear(params["x_embed"], img_tokens)
    if pad_img:
        x = jnp.concatenate(
            [x, jnp.broadcast_to(
                params["x_pad"][None].astype(x.dtype),
                (b, pad_img, x.shape[-1]))], axis=1)
    for blk in params["noise_refiner"]:
        x = _block(blk, cfg, x, img_freqs, adaln)

    cap = nn.linear(params["cap_embed"],
                    rms_norm(cap_feats, params["cap_norm"]["w"],
                             cfg.norm_eps))
    if cap_mask is not None:
        is_real = cap_mask.astype(bool)
        cap = jnp.where(is_real[..., None], cap,
                        params["cap_pad"][None, :, :].astype(cap.dtype))
        # batch padding beyond the item's rounded span: zero embeddings
        cap = jnp.where(in_span[..., None], cap,
                        jnp.zeros_like(cap))
    for blk in params["context_refiner"]:
        cap = _block(blk, cfg, cap, cap_freqs)

    # unified sequence: image first, caption after (UnifiedPrepare,
    # z_image_transformer.py:93-103)
    u = jnp.concatenate([x, cap], axis=1)
    for blk in params["layers"]:
        u = _block(blk, cfg, u, uni_freqs, adaln, attn_fn=attn_fn)

    # final layer over the (un-padded) image tokens
    scale = 1.0 + nn.linear(params["final_adaln"], jax.nn.silu(adaln))
    out = nn.layernorm({}, u[:, :s_img]) * scale[:, None, :]
    return nn.linear(params["final_out"], out)


def flops_per_token(cfg: ZImageDiTConfig) -> float:
    """Rough matmul FLOPs/token for MFU accounting."""
    d = cfg.dim
    return 2 * (4 * d * d + 3 * d * cfg.ffn_dim)
