"""Z-Image text->image pipeline.

Reference: vllm_omni/diffusion/models/z_image/pipeline_z_image.py
(registry entry ZImagePipeline, diffusion/registry.py:16-102).
Structure: Qwen3-style text encode -> FlowMatch euler denoise with
dynamic shift -> AutoencoderKL decode.  Z-Image quirks carried over:
the DiT receives REVERSED normalized time ``(1000 - t)/1000`` and
predicts the NEGATIVE velocity (pipeline_z_image.py:545-618), and CFG is
true classifier-free guidance over a doubled batch.

The from_pretrained path matches the reference's text conditioning
exactly: tokenizer right padding, ``hidden_states[-2]`` (penultimate
layer, no final norm), caption span bucketed to a multiple of 32 so the
image grid's frame coordinate matches training.  The byte-tokenizer
random-init path keeps using final hidden states.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.diffusion import cache as step_cache
from vllm_omni_tpu.diffusion import scheduler as fm
from vllm_omni_tpu.diffusion.request import (
    DiffusionOutput,
    InvalidRequestError,
    OmniDiffusionRequest,
)
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common.transformer import (
    TransformerConfig,
    forward_hidden,
    init_params as init_text_params,
)
from vllm_omni_tpu.models.qwen_image import vae as vae_mod
from vllm_omni_tpu.models.qwen_image.vae import VAEConfig
from vllm_omni_tpu.models.z_image import transformer as zdit
from vllm_omni_tpu.models.z_image.transformer import ZImageDiTConfig
from vllm_omni_tpu.utils.tokenizer import ByteTokenizer

logger = init_logger(__name__)


@dataclass(frozen=True)
class ZImagePipelineConfig:
    text: TransformerConfig = field(default_factory=TransformerConfig)
    dit: ZImageDiTConfig = field(default_factory=ZImageDiTConfig)
    vae: VAEConfig = field(default_factory=VAEConfig)
    max_text_len: int = 64
    scheduler: str = "euler"
    steps_bucket: int = 64

    @staticmethod
    def tiny() -> "ZImagePipelineConfig":
        return ZImagePipelineConfig(
            text=TransformerConfig.tiny(vocab_size=256),
            dit=ZImageDiTConfig.tiny(),
            vae=VAEConfig.tiny(),
            max_text_len=32,
        )


class ZImagePipeline:
    """Text -> image (unified-sequence single-stream DiT)."""

    output_type = "image"

    def __init__(self, config: ZImagePipelineConfig, dtype=jnp.bfloat16,
                 seed: int = 0, mesh=None, cache_config=None,
                 init_weights: bool = True):
        from vllm_omni_tpu.parallel.pipeline_mesh import MeshWiring

        self.cfg = config
        self.dtype = dtype
        self.mesh = mesh
        self.cache_config = cache_config
        self.wiring = MeshWiring(mesh, type(self).__name__).validate(
            {"dp", "cfg", "ring", "ulysses"})
        if config.text.hidden_size != config.dit.cap_feat_dim:
            raise ValueError(
                "text hidden_size must equal dit cap_feat_dim")
        if config.dit.in_channels != config.vae.latent_channels:
            raise ValueError(
                "dit in_channels must equal vae latent_channels")
        self.tokenizer = ByteTokenizer(config.text.vocab_size)
        self.hf_tokenizer = None  # set by from_pretrained
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
        logger.info("Initializing ZImagePipeline (dtype=%s)", dtype)
        if init_weights:
            self.text_params = self.wiring.place(
                init_text_params(k1, config.text, dtype))
            self.dit_params = self.wiring.place(
                zdit.init_params(k2, config.dit, dtype))
            self.vae_params = self.wiring.place(
                vae_mod.init_decoder(k3, config.vae, dtype))
        else:
            self.text_params = self.dit_params = self.vae_params = None
        self._denoise_cache: dict = {}
        self._text_encode_jit = jax.jit(
            lambda p, i: forward_hidden(p, self.cfg.text, i))
        # HF convention: the DiT conditions on hidden_states[-2] (the
        # penultimate layer's raw output, pipeline_z_image.py:261-266)
        self._text_encode_hf_jit = jax.jit(
            lambda p, i: forward_hidden(p, self.cfg.text, i,
                                        drop_last_layers=1,
                                        apply_final_norm=False))
        self._vae_decode_jit = jax.jit(
            lambda pp, l: vae_mod.decode(pp, self.cfg.vae, l))

    def encode_prompt(self, prompts: list[str]):
        if self.hf_tokenizer is not None:
            return self._encode_prompt_hf(prompts)
        ids, lens = self.tokenizer.batch_encode(prompts,
                                                self.cfg.max_text_len)
        hidden = self._text_encode_jit(self.text_params, jnp.asarray(ids))
        mask = (np.arange(self.cfg.max_text_len)[None, :]
                < lens[:, None]).astype(np.int32)
        return hidden, jnp.asarray(mask)

    def _encode_prompt_hf(self, prompts: list[str]):
        """Reference encode (pipeline_z_image.py:236-272): wrap in the
        Qwen chat template (enable_thinking=True), tokenize with right
        padding, take hidden_states[-2].  The caption span is bucketed
        to a multiple of 32 of the longest real length (reference
        SEQ_MULTI_OF padding, z_image_transformer.py:775-787) so the
        image grid's frame coordinate stays faithful while shapes remain
        bucketed for XLA."""
        tok = self.hf_tokenizer
        texts = []
        for p in prompts:
            msg = [{"role": "user", "content": p}]
            try:
                texts.append(tok.apply_chat_template(
                    msg, tokenize=False, add_generation_prompt=True,
                    enable_thinking=True))
            except Exception:
                # tokenizer without a chat template (synthetic tests):
                # the Qwen thinking layout, spelled out
                texts.append(f"<|im_start|>user\n{p}<|im_end|>\n"
                             "<|im_start|>assistant\n<think>\n")
        tok.padding_side = "right"
        enc = tok(texts, padding="max_length", truncation=True,
                  max_length=self.cfg.max_text_len)
        ids = np.asarray(enc["input_ids"], np.int32)
        mask = np.asarray(enc["attention_mask"], np.int32)
        # at least one 32-token bucket: an empty negative prompt has
        # zero real tokens, and a zero-length caption would collapse the
        # sequence (pads carry the learned cap_pad embedding, so a
        # one-bucket empty caption is well-defined conditioning)
        longest = max(1, int(mask.sum(axis=1).max()))
        bucket = min(self.cfg.max_text_len, -(-longest // 32) * 32)
        ids, mask = ids[:, :bucket], mask[:, :bucket]
        hidden = self._text_encode_hf_jit(self.text_params,
                                          jnp.asarray(ids))
        return hidden.astype(self.dtype), jnp.asarray(mask)

    @classmethod
    def from_pretrained(cls, model_dir: str, dtype=jnp.bfloat16,
                        seed: int = 0, mesh=None, cache_config=None,
                        max_text_len: int = 512) -> "ZImagePipeline":
        """Build from a diffusers-format Z-Image checkpoint
        (transformer/ + Qwen3 text_encoder/ + tokenizer/ + AutoencoderKL
        vae/ + scheduler/)."""
        import os

        from transformers import AutoTokenizer

        from vllm_omni_tpu.model_loader import diffusers_loader as dl
        from vllm_omni_tpu.models.z_image import loader as zloader

        dl.load_model_index(model_dir)
        dit_params, dit_cfg = zloader.load_z_image_dit(
            os.path.join(model_dir, "transformer"), dtype=dtype)
        text_params, text_cfg = dl.load_text_encoder(
            os.path.join(model_dir, "text_encoder"), dtype=dtype)
        vae_tree, vae_cfg = dl.load_image_vae(
            os.path.join(model_dir, "vae"), dtype=dtype, decoder=True)
        config = ZImagePipelineConfig(
            text=text_cfg, dit=dit_cfg, vae=vae_cfg,
            max_text_len=max_text_len)
        pipe = cls(config, dtype=dtype, seed=seed, mesh=mesh,
                   cache_config=cache_config, init_weights=False)
        pipe.dit_params = pipe.wiring.place(dit_params)
        pipe.text_params = pipe.wiring.place(text_params)
        pipe.vae_params = pipe.wiring.place(vae_tree["decoder"])
        pipe.hf_tokenizer = AutoTokenizer.from_pretrained(
            os.path.join(model_dir, "tokenizer"))
        return pipe

    def _denoise_fn(self, grid_h, grid_w, sched_len, batch2=0):
        key = (grid_h, grid_w, sched_len) + (
            (batch2,) if self.mesh is not None else ())
        if key in self._denoise_cache:
            return self._denoise_cache[key]
        cfg = self.cfg
        wiring = self.wiring
        # unified sequence = image + caption tokens; SP shards the image
        # part only through GSPMD (Z-Image's own shard boundary is the
        # unified sequence — the shard_map joint contract doesn't fit the
        # single-stream layout, so SP rides GSPMD constraints here)
        cache_cfg = self.cache_config

        @jax.jit
        def run(dit_params, latents, cap, cap_mask, neg_cap, neg_mask,
                sigmas, timesteps, gscale, num_steps):
            schedule = fm.FlowMatchSchedule(sigmas=sigmas,
                                            timesteps=timesteps)
            do_cfg = neg_cap is not None
            cap_all = (jnp.concatenate([cap, neg_cap], 0)
                       if do_cfg else cap)
            mask_all = (jnp.concatenate([cap_mask, neg_mask], 0)
                        if do_cfg else cap_mask)

            def eval_velocity(lat, i):
                # Z-Image time runs 0 at pure noise -> 1 at the image:
                # feed (1000 - t)/1000 == 1 - sigma
                t = jnp.broadcast_to(
                    1.0 - schedule.sigmas[i], (lat.shape[0],))
                lat_in = jnp.concatenate([lat, lat], 0) if do_cfg else lat
                lat_in = wiring.constrain(lat_in, seq_dim=1)
                t_in = jnp.concatenate([t, t], 0) if do_cfg else t
                out = zdit.forward(
                    dit_params, cfg.dit, lat_in, cap_all, t_in,
                    (grid_h, grid_w), cap_mask=mask_all,
                )
                v = -out  # the model predicts the negative velocity
                if do_cfg:
                    v_pos, v_neg = jnp.split(v, 2, axis=0)
                    v = v_neg + gscale * (v_pos - v_neg)
                return v

            return step_cache.run_denoise_loop(
                cache_cfg, schedule, eval_velocity, latents, num_steps,
                solver=cfg.scheduler)

        self._denoise_cache[key] = run
        return run

    def forward(self, req: OmniDiffusionRequest) -> list[DiffusionOutput]:
        sp = req.sampling_params
        cfg = self.cfg
        ratio = cfg.vae.spatial_ratio
        patch = cfg.dit.patch_size
        mult = ratio * patch
        if sp.height % mult or sp.width % mult:
            raise InvalidRequestError(
                f"height/width must be multiples of {mult}")
        if sp.num_inference_steps < 1:
            raise InvalidRequestError("num_inference_steps must be >= 1")
        grid_h = sp.height // ratio // patch
        grid_w = sp.width // ratio // patch
        seq_len = grid_h * grid_w
        prompts = req.prompt
        b = len(prompts)

        do_cfg = sp.guidance_scale > 1.0
        neg_cap = neg_mask = None
        if do_cfg:
            # one joint encode: positive and negative captions share the
            # caption bucket, so the CFG halves concatenate and the
            # image grid sits at one frame coordinate for both
            both, both_mask = self.encode_prompt(
                list(prompts) + [sp.negative_prompt] * b)
            cap, neg_cap = both[:b], both[b:]
            cap_mask, neg_mask = both_mask[:b], both_mask[b:]
        else:
            cap, cap_mask = self.encode_prompt(prompts)

        seed = (sp.seed if sp.seed is not None
                else int(np.random.randint(0, 2 ** 31 - 1)))
        noise = jax.random.normal(
            jax.random.PRNGKey(seed),
            (b, seq_len, patch * patch * cfg.dit.in_channels),
            jnp.float32,
        ).astype(self.dtype)

        num_steps = sp.num_inference_steps
        mu = fm.compute_dynamic_shift_mu(seq_len)
        schedule = fm.make_schedule(
            num_steps, use_dynamic_shifting=True, mu=mu)
        sched_len = max(num_steps, cfg.steps_bucket)
        sigmas = jnp.zeros((sched_len + 1,)).at[: num_steps + 1].set(
            schedule.sigmas)
        timesteps = jnp.zeros((sched_len,)).at[:num_steps].set(
            schedule.timesteps)
        run = self._denoise_fn(grid_h, grid_w, sched_len,
                               batch2=(2 * b if do_cfg else b))
        latents, skipped = run(
            self.dit_params, noise, cap, cap_mask, neg_cap, neg_mask,
            sigmas, timesteps, jnp.float32(sp.guidance_scale),
            jnp.int32(num_steps))
        self.last_skipped_steps = int(skipped)

        # unpack [B, gh*gw, p*p*C] -> [B, H_lat, W_lat, C] and decode
        c = cfg.vae.latent_channels
        x = latents.reshape(b, grid_h, grid_w, patch, patch, c)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(
            b, grid_h * patch, grid_w * patch, c)
        img = self._vae_decode_jit(self.vae_params, x.astype(jnp.float32))
        img = np.asarray(jnp.clip((img + 1.0) * 127.5, 0, 255)
                         .astype(jnp.uint8))
        return [
            DiffusionOutput(request_id=req.request_ids[i],
                            prompt=prompts[i], data=img[i],
                            output_type="image")
            for i in range(b)
        ]
