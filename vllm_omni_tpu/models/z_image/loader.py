"""Diffusers-format Z-Image transformer loader.

Streams a ZImageTransformer2DModel directory into
models/z_image/transformer.py params.  Checkpoint names follow the
reference's named_parameters (z_image_transformer.py:597-726):
``all_x_embedder.{p}-{f}``, ``t_embedder.mlp.{0,2}``,
``cap_embedder.{0,1}``, ``{x,cap}_pad_token``,
``all_final_layer.{p}-{f}.{linear,adaLN_modulation.1}``, and per block
``attention.{to_q,to_k,to_v,norm_q,norm_k,to_out.0}``,
``feed_forward.{w1,w3,w2}`` (w1/w3 fuse into our ``w13``),
``{attention,ffn}_norm{1,2}``, ``adaLN_modulation.0``.
"""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp

from vllm_omni_tpu.models.flux.loader import load_routed
from vllm_omni_tpu.models.z_image.transformer import (
    ZImageDiTConfig,
    init_params,
)


def dit_config_from_diffusers(d: dict) -> ZImageDiTConfig:
    return ZImageDiTConfig(
        in_channels=d.get("in_channels", 16),
        patch_size=tuple(d.get("all_patch_size", (2,)))[0],
        dim=d.get("dim", 3840),
        num_layers=d.get("n_layers", 30),
        num_refiner_layers=d.get("n_refiner_layers", 2),
        num_heads=d.get("n_heads", 30),
        num_kv_heads=d.get("n_kv_heads", 30),
        cap_feat_dim=d.get("cap_feat_dim", 2560),
        rope_theta=d.get("rope_theta", 256.0),
        axes_dims=tuple(d.get("axes_dims", (32, 48, 48))),
        t_scale=d.get("t_scale", 1000.0),
        norm_eps=d.get("norm_eps", 1e-5),
        rope_interleaved=True,  # trained-checkpoint pairing
    )


def _routing(cfg: ZImageDiTConfig) -> dict:
    r: dict[str, tuple] = {}

    def lin(hf, *path, bias=True):
        r[f"{hf}.weight"] = ("direct", path + ("w",))
        if bias:
            r[f"{hf}.bias"] = ("direct", path + ("b",))

    pf = f"{cfg.patch_size}-1"
    lin(f"all_x_embedder.{pf}", "x_embed")
    lin("t_embedder.mlp.0", "t_in1")
    lin("t_embedder.mlp.2", "t_in2")
    r["cap_embedder.0.weight"] = ("direct", ("cap_norm", "w"))
    lin("cap_embedder.1", "cap_embed")
    r["x_pad_token"] = ("raw", ("x_pad",))
    r["cap_pad_token"] = ("raw", ("cap_pad",))
    lin(f"all_final_layer.{pf}.linear", "final_out")
    lin(f"all_final_layer.{pf}.adaLN_modulation.1", "final_adaln")

    def block(hf_prefix, *path, modulation):
        lin(f"{hf_prefix}.attention.to_q", *path, "to_q", bias=False)
        lin(f"{hf_prefix}.attention.to_k", *path, "to_k", bias=False)
        lin(f"{hf_prefix}.attention.to_v", *path, "to_v", bias=False)
        lin(f"{hf_prefix}.attention.to_out.0", *path, "out", bias=False)
        for nm in ("norm_q", "norm_k"):
            r[f"{hf_prefix}.attention.{nm}.weight"] = (
                "direct", path + (nm, "w"))
        for nm in ("attention_norm1", "attention_norm2", "ffn_norm1",
                   "ffn_norm2"):
            ours = {"attention_norm1": "attn_norm1",
                    "attention_norm2": "attn_norm2"}.get(nm, nm)
            r[f"{hf_prefix}.{nm}.weight"] = ("direct", path + (ours, "w"))
        for s, nm in enumerate(("w1", "w3")):
            r[f"{hf_prefix}.feed_forward.{nm}.weight"] = (
                "fuse", path + ("w13", "w"), s, 2)
        lin(f"{hf_prefix}.feed_forward.w2", *path, "w2", bias=False)
        if modulation:
            lin(f"{hf_prefix}.adaLN_modulation.0", *path, "adaln")

    for i in range(cfg.num_refiner_layers):
        block(f"noise_refiner.{i}", "noise_refiner", i, modulation=True)
        block(f"context_refiner.{i}", "context_refiner", i,
              modulation=False)
    for i in range(cfg.num_layers):
        block(f"layers.{i}", "layers", i, modulation=True)
    return r


def load_z_image_dit(model_dir: str, cfg: ZImageDiTConfig = None,
                     dtype=jnp.bfloat16):
    if cfg is None:
        with open(os.path.join(model_dir, "config.json")) as f:
            cfg = dit_config_from_diffusers(json.load(f))
    shapes = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg, jnp.float32))
    return load_routed(model_dir, _routing(cfg), shapes, dtype), cfg
