"""SD3 MMDiT transformer (functional JAX).

Reference: vllm_omni/diffusion/models/sd3/sd3_transformer.py:383
``SD3Transformer2DModel`` — double-stream joint-attention blocks with NO
rotary embeddings: position comes from a fixed 2-D sincos table center-
cropped to the sample grid (PatchEmbed ``pos_embed_max_size``,
:75-104,383-420).  Conditioning combines the timestep sinusoid with the
projected pooled text vector; per-head QK RMSNorm is optional
(SD3.5 ``qk_norm="rms_norm"``, SD3.0 none); SD3.5-medium additionally
runs a SECOND self-attention branch on listed layers
(``dual_attention_layers`` + SD35AdaLayerNormZeroX, 9-chunk modulation);
the LAST block is ``context_pre_only``: its text stream is normalized by
AdaLayerNormContinuous, feeds the joint attention, and is then dropped.

TPU-first: the patch conv is expressed as a packed-token matmul (the
loader reshapes the conv kernel), attention is the Pallas flash kernel,
the whole stack stays one jitted computation.  Joint layout is
text-first like the rest of the repo — without rope the concat order is
arbitrary as long as the split-back matches.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from vllm_omni_tpu.models.common import nn
from vllm_omni_tpu.ops import flash_attention, rms_norm


@dataclass(frozen=True)
class SD3DiTConfig:
    in_channels: int = 16
    out_channels: int = 16
    patch_size: int = 2
    num_layers: int = 24
    num_heads: int = 24
    head_dim: int = 64
    joint_dim: int = 4096    # concatenated CLIP(-padded)/T5 text width
    pooled_dim: int = 2048   # CLIP-L + bigG pooled widths
    pos_embed_max_size: int = 192
    mlp_ratio: float = 4.0
    qk_norm: bool = False    # SD3.5 checkpoints: True
    dual_attention_layers: tuple = ()  # SD3.5-medium: range(13)

    @property
    def inner_dim(self) -> int:
        return self.num_heads * self.head_dim

    @staticmethod
    def tiny() -> "SD3DiTConfig":
        # joint/pooled widths match TransformerConfig.tiny()'s hidden
        # (the random-init single-encoder path)
        return SD3DiTConfig(
            in_channels=4, out_channels=4, num_layers=2, num_heads=4,
            head_dim=16, joint_dim=64, pooled_dim=64,
            pos_embed_max_size=8, qk_norm=True,
            dual_attention_layers=(0,),
        )


def init_params(key, cfg: SD3DiTConfig, dtype=jnp.float32):
    inner = cfg.inner_dim
    mlp = int(inner * cfg.mlp_ratio)
    p_in = cfg.patch_size ** 2 * cfg.in_channels
    keys = jax.random.split(key, cfg.num_layers + 10)
    p = {
        "patch_proj": nn.linear_init(keys[0], p_in, inner, dtype=dtype),
        # fixed 2-D sincos table (checkpoints persist it; random init
        # here only feeds shape/flow tests)
        "pos_embed": (0.02 * jax.random.normal(
            keys[1], (cfg.pos_embed_max_size ** 2, inner))).astype(dtype),
        "ctx_in": nn.linear_init(keys[2], cfg.joint_dim, inner,
                                 dtype=dtype),
        "time_in1": nn.linear_init(keys[3], 256, inner, dtype=dtype),
        "time_in2": nn.linear_init(keys[4], inner, inner, dtype=dtype),
        "pooled_in1": nn.linear_init(keys[5], cfg.pooled_dim, inner,
                                     dtype=dtype),
        "pooled_in2": nn.linear_init(keys[6], inner, inner, dtype=dtype),
        "norm_out_mod": nn.linear_init(keys[7], inner, 2 * inner,
                                       dtype=dtype),
        "proj_out": nn.linear_init(
            keys[8], inner, cfg.patch_size ** 2 * cfg.out_channels,
            dtype=dtype),
        "blocks": [],
    }
    for i in range(cfg.num_layers):
        k = jax.random.split(keys[i + 10], 16)
        last = i == cfg.num_layers - 1
        dual = i in cfg.dual_attention_layers
        blk = {
            "img_mod": nn.linear_init(
                k[0], inner, (9 if dual else 6) * inner, dtype=dtype),
            "to_q": nn.linear_init(k[1], inner, inner, dtype=dtype),
            "to_k": nn.linear_init(k[2], inner, inner, dtype=dtype),
            "to_v": nn.linear_init(k[3], inner, inner, dtype=dtype),
            "add_q": nn.linear_init(k[4], inner, inner, dtype=dtype),
            "add_k": nn.linear_init(k[5], inner, inner, dtype=dtype),
            "add_v": nn.linear_init(k[6], inner, inner, dtype=dtype),
            "to_out": nn.linear_init(k[7], inner, inner, dtype=dtype),
            "img_mlp1": nn.linear_init(k[8], inner, mlp, dtype=dtype),
            "img_mlp2": nn.linear_init(k[9], mlp, inner, dtype=dtype),
        }
        if cfg.qk_norm:
            for nm in ("norm_q", "norm_k", "norm_added_q",
                       "norm_added_k"):
                blk[nm] = nn.rmsnorm_init(cfg.head_dim, dtype)
        if last:
            # context_pre_only: AdaLayerNormContinuous on the text side
            blk["ctx_ada"] = nn.linear_init(k[10], inner, 2 * inner,
                                            dtype=dtype)
        else:
            blk["txt_mod"] = nn.linear_init(k[10], inner, 6 * inner,
                                            dtype=dtype)
            blk["to_add_out"] = nn.linear_init(k[11], inner, inner,
                                               dtype=dtype)
            blk["txt_mlp1"] = nn.linear_init(k[12], inner, mlp,
                                             dtype=dtype)
            blk["txt_mlp2"] = nn.linear_init(k[13], mlp, inner,
                                             dtype=dtype)
        if dual:
            blk["to_q2"] = nn.linear_init(k[14], inner, inner,
                                          dtype=dtype)
            blk["to_k2"] = nn.linear_init(
                jax.random.fold_in(k[14], 1), inner, inner, dtype=dtype)
            blk["to_v2"] = nn.linear_init(
                jax.random.fold_in(k[14], 2), inner, inner, dtype=dtype)
            blk["to_out2"] = nn.linear_init(k[15], inner, inner,
                                            dtype=dtype)
            if cfg.qk_norm:
                blk["norm_q2"] = nn.rmsnorm_init(cfg.head_dim, dtype)
                blk["norm_k2"] = nn.rmsnorm_init(cfg.head_dim, dtype)
        p["blocks"].append(blk)
    return p


def _heads(x, h):
    b, s, _ = x.shape
    return x.reshape(b, s, h, -1)


def _maybe_rms(blk, name, x):
    if name in blk:
        return rms_norm(x, blk[name]["w"])
    return x


def _mod_ln(x, shift, scale):
    return nn.layernorm({}, x) * (1.0 + scale[:, None, :]) \
        + shift[:, None, :]


def _block(blk, cfg: SD3DiTConfig, img, txt, temb_act, kv_mask, last):
    h = cfg.num_heads
    s_txt = txt.shape[1]
    img_mod = nn.linear(blk["img_mod"], temb_act)
    if "to_q2" in blk:
        (shift_msa, scale_msa, gate_msa, shift_mlp, scale_mlp, gate_mlp,
         shift_msa2, scale_msa2, gate_msa2) = jnp.split(img_mod, 9, -1)
        # SD35AdaLayerNormZeroX: BOTH normalized views come from the
        # block INPUT (before any residual)
        img_n2_pre = _mod_ln(img, shift_msa2, scale_msa2)
    else:
        (shift_msa, scale_msa, gate_msa, shift_mlp, scale_mlp,
         gate_mlp) = jnp.split(img_mod, 6, -1)
        shift_msa2 = None
    img_n = _mod_ln(img, shift_msa, scale_msa)

    if last:
        # AdaLayerNormContinuous (scale first, then shift)
        mod = nn.linear(blk["ctx_ada"], temb_act)
        c_scale, c_shift = jnp.split(mod, 2, axis=-1)
        txt_n = _mod_ln(txt, c_shift, c_scale)
        c_gate_msa = None
    else:
        txt_mod = nn.linear(blk["txt_mod"], temb_act)
        (c_shift_msa, c_scale_msa, c_gate_msa, c_shift_mlp, c_scale_mlp,
         c_gate_mlp) = jnp.split(txt_mod, 6, -1)
        txt_n = _mod_ln(txt, c_shift_msa, c_scale_msa)

    qi = _maybe_rms(blk, "norm_q", _heads(nn.linear(blk["to_q"], img_n), h))
    ki = _maybe_rms(blk, "norm_k", _heads(nn.linear(blk["to_k"], img_n), h))
    vi = _heads(nn.linear(blk["to_v"], img_n), h)
    qt = _maybe_rms(blk, "norm_added_q",
                    _heads(nn.linear(blk["add_q"], txt_n), h))
    kt = _maybe_rms(blk, "norm_added_k",
                    _heads(nn.linear(blk["add_k"], txt_n), h))
    vt = _heads(nn.linear(blk["add_v"], txt_n), h)
    q = jnp.concatenate([qt, qi], axis=1)
    k = jnp.concatenate([kt, ki], axis=1)
    v = jnp.concatenate([vt, vi], axis=1)
    o = flash_attention(q, k, v, causal=False, kv_mask=kv_mask)
    txt_o = o[:, :s_txt].reshape(*txt.shape[:2], -1)
    img_o = o[:, s_txt:].reshape(*img.shape[:2], -1)

    img = img + gate_msa[:, None, :] * nn.linear(blk["to_out"], img_o)
    if shift_msa2 is not None:
        # dual attention: a second SELF-attention branch over the image
        # stream, reading the BLOCK-INPUT normalized view
        # (SD3.5-medium, sd3_transformer.py:330-356)
        q2 = _maybe_rms(blk, "norm_q2",
                        _heads(nn.linear(blk["to_q2"], img_n2_pre), h))
        k2 = _maybe_rms(blk, "norm_k2",
                        _heads(nn.linear(blk["to_k2"], img_n2_pre), h))
        v2 = _heads(nn.linear(blk["to_v2"], img_n2_pre), h)
        o2 = flash_attention(q2, k2, v2, causal=False)
        o2 = o2.reshape(*img.shape[:2], -1)
        img = img + gate_msa2[:, None, :] * nn.linear(blk["to_out2"], o2)

    img_nf = _mod_ln(img, shift_mlp, scale_mlp)
    img = img + gate_mlp[:, None, :] * nn.linear(
        blk["img_mlp2"],
        jax.nn.gelu(nn.linear(blk["img_mlp1"], img_nf), approximate=True))

    if last:
        return img, txt
    txt = txt + c_gate_msa[:, None, :] * nn.linear(blk["to_add_out"],
                                                   txt_o)
    txt_nf = _mod_ln(txt, c_shift_mlp, c_scale_mlp)
    txt = txt + c_gate_mlp[:, None, :] * nn.linear(
        blk["txt_mlp2"],
        jax.nn.gelu(nn.linear(blk["txt_mlp1"], txt_nf), approximate=True))
    return img, txt


def cropped_pos_embed(params, cfg: SD3DiTConfig, gh: int, gw: int):
    """Center-crop the (max, max) sincos table to the sample grid
    (diffusers PatchEmbed.cropped_pos_embed)."""
    m = cfg.pos_embed_max_size
    table = params["pos_embed"].reshape(m, m, cfg.inner_dim)
    top = (m - gh) // 2
    left = (m - gw) // 2
    return table[top:top + gh, left:left + gw].reshape(
        gh * gw, cfg.inner_dim)


def forward(
    params,
    cfg: SD3DiTConfig,
    img_tokens: jax.Array,  # [B, gh*gw, patch^2*in_channels] packed
    txt_states: jax.Array,  # [B, S_txt, joint_dim]
    pooled: jax.Array,      # [B, pooled_dim]
    timesteps: jax.Array,   # [B] in [0, 1000)
    grid_hw: tuple[int, int],
    txt_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Velocity prediction [B, gh*gw, patch^2*out_channels]."""
    gh, gw = grid_hw
    b = img_tokens.shape[0]
    img = nn.linear(params["patch_proj"], img_tokens)
    img = img + cropped_pos_embed(params, cfg, gh, gw)[None].astype(
        img.dtype)
    txt = nn.linear(params["ctx_in"], txt_states)

    temb = nn.timestep_embedding(timesteps, 256).astype(img.dtype)
    temb = nn.linear(params["time_in2"],
                     jax.nn.silu(nn.linear(params["time_in1"], temb)))
    temb = temb + nn.linear(
        params["pooled_in2"],
        jax.nn.silu(nn.linear(params["pooled_in1"], pooled)))
    temb_act = jax.nn.silu(temb)

    kv_mask = None
    if txt_mask is not None:
        kv_mask = jnp.concatenate(
            [txt_mask.astype(jnp.int32),
             jnp.ones((b, img.shape[1]), jnp.int32)], axis=1)

    n = len(params["blocks"])
    for i, blk in enumerate(params["blocks"]):
        img, txt = _block(blk, cfg, img, txt, temb_act, kv_mask,
                          last=(i == n - 1))

    mod = nn.linear(params["norm_out_mod"], temb_act)
    scale, shift = jnp.split(mod, 2, axis=-1)
    img = nn.layernorm({}, img) * (1.0 + scale[:, None, :]) \
        + shift[:, None, :]
    return nn.linear(params["proj_out"], img)
