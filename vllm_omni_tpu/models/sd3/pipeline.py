"""Stable Diffusion 3 text-to-image pipeline (CFG MMDiT).

Reference: vllm_omni/diffusion/models/sd3/ (registry entry SD3,
diffusion/registry.py:16-102).  SD3's MMDiT is the pure double-stream
joint-attention shape — exactly the Flux transformer with zero
single-stream blocks and no guidance embedding (flux/transformer.py
config switches), which is the point of the shared MMDiT abstraction:
one block implementation serves Qwen-Image, Flux AND SD3.  Unlike the
guidance-distilled Flux, SD3 runs classifier-free guidance as a doubled
batch (positive + negative prompts per step).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.diffusion import cache as step_cache
from vllm_omni_tpu.diffusion import scheduler as fm
from vllm_omni_tpu.diffusion.request import (
    DiffusionOutput,
    InvalidRequestError,
    OmniDiffusionRequest,
)
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common.transformer import (
    TransformerConfig,
    forward_hidden,
    init_params as init_text_params,
)
from vllm_omni_tpu.models.flux import transformer as fdit
from vllm_omni_tpu.models.flux.transformer import FluxDiTConfig
from vllm_omni_tpu.models.qwen_image import vae as vae_mod
from vllm_omni_tpu.models.qwen_image.vae import VAEConfig
from vllm_omni_tpu.utils.tokenizer import ByteTokenizer

logger = init_logger(__name__)


def _sd3_dit(base: FluxDiTConfig) -> FluxDiTConfig:
    """Force the SD3 shape: double-stream only, CFG instead of embedded
    guidance."""
    return dataclasses.replace(
        base, num_single_blocks=0, guidance_embed=False)


@dataclass(frozen=True)
class SD3PipelineConfig:
    text: TransformerConfig = field(default_factory=TransformerConfig)
    dit: FluxDiTConfig = field(
        default_factory=lambda: _sd3_dit(FluxDiTConfig(
            num_double_blocks=24)))
    vae: VAEConfig = field(default_factory=VAEConfig)
    max_text_len: int = 64
    shift: float = 3.0
    pack: int = 2
    scheduler: str = "euler"

    @staticmethod
    def tiny() -> "SD3PipelineConfig":
        return SD3PipelineConfig(
            text=TransformerConfig.tiny(vocab_size=256),
            dit=_sd3_dit(FluxDiTConfig.tiny()),
            vae=VAEConfig.tiny(),
        )


class SD3Pipeline:
    """Text -> image with classifier-free guidance."""

    output_type = "image"

    @property
    def geometry_multiple(self) -> int:
        return self.cfg.vae.spatial_ratio * self.cfg.pack

    def __init__(self, config: SD3PipelineConfig, dtype=jnp.bfloat16,
                 seed: int = 0, mesh=None, cache_config=None):
        from vllm_omni_tpu.parallel.pipeline_mesh import MeshWiring

        self.cfg = config
        self.dtype = dtype
        self.mesh = mesh
        self.cache_config = cache_config
        # batch parallelism (dp + CFG halves); SP/TP for the
        # double-stream blocks are not wired — refuse, don't ignore
        self.wiring = MeshWiring(mesh, type(self).__name__).validate(
            {"dp", "cfg"})
        if config.dit.num_single_blocks != 0 or config.dit.guidance_embed:
            raise ValueError(
                "SD3 is double-stream-only with CFG: num_single_blocks "
                "must be 0 and guidance_embed False (use _sd3_dit)"
            )
        if config.text.hidden_size != config.dit.ctx_dim:
            raise ValueError("text hidden_size must equal dit ctx_dim")
        if config.dit.pooled_dim != config.text.hidden_size:
            raise ValueError("pooled_dim must equal text hidden_size")
        want_in = config.vae.latent_channels * config.pack ** 2
        if config.dit.in_channels != want_in:
            raise ValueError(
                f"dit.in_channels must be latent*pack^2 = {want_in}")
        self.tokenizer = ByteTokenizer(config.text.vocab_size)
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
        logger.info("Initializing SD3Pipeline params (dtype=%s)", dtype)
        self.text_params = self.wiring.place(
            init_text_params(k1, config.text, dtype))
        self.dit_params = self.wiring.place(
            fdit.init_params(k2, config.dit, dtype))
        self.vae_params = self.wiring.place(
            vae_mod.init_decoder(k3, config.vae, dtype))
        self._denoise_cache: dict = {}
        self._text_encode_jit = jax.jit(
            lambda p, i: forward_hidden(p, self.cfg.text, i))
        self._vae_decode_jit = jax.jit(
            lambda pp, l: vae_mod.decode(pp, self.cfg.vae, l))

    # ------------------------------------------------------------- encode
    def encode_prompt(self, prompts: list[str]):
        ids, lens = self.tokenizer.batch_encode(prompts,
                                                self.cfg.max_text_len)
        hidden = self._text_encode_jit(self.text_params, jnp.asarray(ids))
        mask = (np.arange(self.cfg.max_text_len)[None, :]
                < lens[:, None]).astype(np.int32)
        mask = jnp.asarray(mask)
        denom = jnp.maximum(mask.sum(axis=1, keepdims=True), 1)
        pooled = (hidden * mask[..., None]).sum(axis=1) / denom
        return hidden, mask, pooled.astype(hidden.dtype)

    # ------------------------------------------------------------ denoise
    def _denoise_fn(self, grid_h, grid_w, sched_len):
        key = (grid_h, grid_w, sched_len)
        if key in self._denoise_cache:
            return self._denoise_cache[key]
        cfg = self.cfg
        cache_cfg = self.cache_config

        @jax.jit
        def run(dit_params, latents, ctx, ctx_mask, pooled, neg_ctx,
                neg_mask, neg_pooled, sigmas, timesteps, gscale, num_steps):
            schedule = fm.FlowMatchSchedule(sigmas=sigmas,
                                            timesteps=timesteps)
            b = latents.shape[0]
            do_cfg = neg_ctx is not None
            if do_cfg:
                ctx_all = jnp.concatenate([ctx, neg_ctx], 0)
                mask_all = jnp.concatenate([ctx_mask, neg_mask], 0)
                pooled_all = jnp.concatenate([pooled, neg_pooled], 0)
            else:
                ctx_all, mask_all, pooled_all = ctx, ctx_mask, pooled

            def eval_velocity(lat, i):
                t = jnp.broadcast_to(timesteps[i], (lat.shape[0],))
                lat_in = jnp.concatenate([lat, lat], 0) if do_cfg else lat
                # CFG halves ride the cfg axis, requests the dp axis
                lat_in = self.wiring.constrain(lat_in)
                t_in = jnp.concatenate([t, t], 0) if do_cfg else t
                v = fdit.forward(
                    dit_params, cfg.dit, lat_in, ctx_all, pooled_all, t_in,
                    (grid_h, grid_w), txt_mask=mask_all,
                )
                if do_cfg:
                    v_pos, v_neg = jnp.split(v, 2, axis=0)
                    v = v_neg + gscale * (v_pos - v_neg)
                return v

            del b
            return step_cache.run_denoise_loop(
                cache_cfg, schedule, eval_velocity, latents, num_steps,
                solver=cfg.scheduler)

        self._denoise_cache[key] = run
        return run

    # ------------------------------------------------------------ forward
    def forward(self, req: OmniDiffusionRequest) -> list[DiffusionOutput]:
        sp = req.sampling_params
        cfg = self.cfg
        mult = self.geometry_multiple
        if sp.height % mult or sp.width % mult:
            raise InvalidRequestError(
                f"height/width must be multiples of {mult}")
        lat_h = sp.height // cfg.vae.spatial_ratio
        lat_w = sp.width // cfg.vae.spatial_ratio
        gh, gw = lat_h // cfg.pack, lat_w // cfg.pack
        prompts = req.prompt
        b = len(prompts)

        ctx, ctx_mask, pooled = self.encode_prompt(prompts)
        do_cfg = sp.guidance_scale > 1.0
        neg_ctx = neg_mask = neg_pooled = None
        if do_cfg:
            neg_ctx, neg_mask, neg_pooled = self.encode_prompt(
                [sp.negative_prompt] * b)
        seed = (sp.seed if sp.seed is not None
                else int(np.random.randint(0, 2 ** 31 - 1)))
        noise = jax.random.normal(
            jax.random.PRNGKey(seed),
            (b, gh * gw, cfg.dit.in_channels), self.dtype,
        )
        num_steps = sp.num_inference_steps
        sched_len = max(8, 1 << (num_steps - 1).bit_length())
        schedule = fm.make_schedule(num_steps, shift=cfg.shift)
        sigmas = jnp.zeros((sched_len + 1,)).at[: num_steps + 1].set(
            schedule.sigmas)
        timesteps = jnp.zeros((sched_len,)).at[:num_steps].set(
            schedule.timesteps)
        run = self._denoise_fn(gh, gw, sched_len)
        latents, skipped = run(
            self.dit_params, noise, ctx, ctx_mask, pooled, neg_ctx,
            neg_mask, neg_pooled, sigmas, timesteps,
            jnp.float32(sp.guidance_scale), jnp.int32(num_steps))
        self.last_skipped_steps = int(skipped)

        c = cfg.vae.latent_channels
        p = cfg.pack
        lat = latents.reshape(b, gh, gw, p, p, c).transpose(0, 1, 3, 2, 4, 5)
        lat = lat.reshape(b, lat_h, lat_w, c)
        imgs = np.asarray(self._vae_decode_jit(self.vae_params, lat))
        imgs = ((np.clip(imgs, -1, 1) + 1) * 127.5).astype(np.uint8)
        return [
            DiffusionOutput(
                request_id=req.request_ids[i], prompt=prompts[i],
                data=imgs[i], output_type="image",
            )
            for i in range(b)
        ]
