"""Stable Diffusion 3 text-to-image pipeline (CFG MMDiT).

Reference: vllm_omni/diffusion/models/sd3/ (registry entry SD3,
diffusion/registry.py:16-102; pipeline_sd3.py:164-427).  SD3 runs true
classifier-free guidance over a doubled batch; its transformer
(models/sd3/transformer.py) is the rope-free MMDiT with a cropped
sincos position table and a context_pre_only final block.

Text conditioning (from_pretrained): CLIP-L + CLIP-bigG penultimate
hiddens concatenated on the feature axis, zero-padded to the T5 width,
then concatenated with the T5 hidden states along the sequence;
pooled = [CLIP-L projected pooled; bigG projected pooled]
(pipeline_sd3.py:277-427).  The byte-tokenizer random-init path keeps a
single in-house encoder with masked-mean pooling.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.diffusion import cache as step_cache
from vllm_omni_tpu.diffusion import scheduler as fm
from vllm_omni_tpu.diffusion.request import (
    DiffusionOutput,
    InvalidRequestError,
    OmniDiffusionRequest,
)
from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common import clip_text as clip_mod
from vllm_omni_tpu.models.common import t5 as t5_mod
from vllm_omni_tpu.models.common.transformer import (
    TransformerConfig,
    forward_hidden,
    init_params as init_text_params,
)
from vllm_omni_tpu.models.qwen_image import vae as vae_mod
from vllm_omni_tpu.models.qwen_image.vae import VAEConfig
from vllm_omni_tpu.models.sd3 import transformer as sdit
from vllm_omni_tpu.models.sd3.transformer import SD3DiTConfig
from vllm_omni_tpu.utils.tokenizer import ByteTokenizer

logger = init_logger(__name__)


@dataclass(frozen=True)
class SD3PipelineConfig:
    text: TransformerConfig = field(default_factory=TransformerConfig)
    dit: SD3DiTConfig = field(default_factory=SD3DiTConfig)
    vae: VAEConfig = field(default_factory=VAEConfig)
    # real checkpoints: CLIP-L + CLIP-bigG towers beside the T5 (text)
    clip: "clip_mod.CLIPTextConfig | None" = None
    clip2: "clip_mod.CLIPTextConfig | None" = None
    max_text_len: int = 64
    clip_text_len: int = 77
    shift: float = 3.0
    scheduler: str = "euler"

    @property
    def pack(self) -> int:
        return self.dit.patch_size

    @staticmethod
    def tiny() -> "SD3PipelineConfig":
        return SD3PipelineConfig(
            text=TransformerConfig.tiny(vocab_size=256),
            dit=SD3DiTConfig.tiny(),
            vae=VAEConfig.tiny(),
        )


class SD3Pipeline:
    """Text -> image with classifier-free guidance."""

    output_type = "image"

    @property
    def geometry_multiple(self) -> int:
        return self.cfg.vae.spatial_ratio * self.cfg.pack

    def __init__(self, config: SD3PipelineConfig, dtype=jnp.bfloat16,
                 seed: int = 0, mesh=None, cache_config=None,
                 init_weights: bool = True):
        from vllm_omni_tpu.parallel.pipeline_mesh import MeshWiring

        self.cfg = config
        self.dtype = dtype
        self.mesh = mesh
        self.cache_config = cache_config
        # batch parallelism (dp + CFG halves); SP/TP for the
        # double-stream blocks are not wired — refuse, don't ignore
        self.wiring = MeshWiring(mesh, type(self).__name__).validate(
            {"dp", "cfg"})
        if not isinstance(config.dit, SD3DiTConfig):
            raise ValueError(
                "SD3Pipeline needs an SD3DiTConfig (the rope-free "
                "double-stream MMDiT, models/sd3/transformer.py) — got "
                f"{type(config.dit).__name__}")
        self._t5_text = isinstance(config.text, t5_mod.T5Config)
        text_width = (config.text.d_model if self._t5_text
                      else config.text.hidden_size)
        if config.clip is None:
            if text_width != config.dit.joint_dim:
                raise ValueError(
                    "text hidden_size must equal dit joint_dim")
            if config.dit.pooled_dim != text_width:
                raise ValueError(
                    "pooled_dim must equal text hidden size (masked-"
                    "mean pooling)")
        else:
            if config.clip2 is None:
                raise ValueError("SD3 needs both CLIP towers")
        if config.dit.in_channels != config.vae.latent_channels:
            raise ValueError(
                "dit.in_channels must equal vae latent_channels (the "
                "patch packing is the transformer's patch_size)")
        self.tokenizer = ByteTokenizer(config.text.vocab_size)
        self.hf_t5_tokenizer = None
        self.hf_clip_tokenizer = None
        self.hf_clip2_tokenizer = None
        self.clip_params = None
        self.clip2_params = None
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
        logger.info("Initializing SD3Pipeline params (dtype=%s)", dtype)
        if init_weights:
            self.text_params = self.wiring.place(
                init_text_params(k1, config.text, dtype))
            self.dit_params = self.wiring.place(
                sdit.init_params(k2, config.dit, dtype))
            self.vae_params = self.wiring.place(
                vae_mod.init_decoder(k3, config.vae, dtype))
        else:
            self.text_params = self.dit_params = self.vae_params = None
        self._denoise_cache: dict = {}
        if self._t5_text:
            self._text_encode_jit = jax.jit(
                lambda p, i, m: t5_mod.forward(p, self.cfg.text, i, m))
        else:
            self._text_encode_jit = jax.jit(
                lambda p, i: forward_hidden(p, self.cfg.text, i))
        if config.clip is not None:
            self._clip_encode_jit = jax.jit(
                lambda p, i: clip_mod.forward(
                    p, self.cfg.clip, i, return_penultimate=True))
            self._clip2_encode_jit = jax.jit(
                lambda p, i: clip_mod.forward(
                    p, self.cfg.clip2, i, return_penultimate=True))
        self._vae_decode_jit = jax.jit(
            lambda pp, l: vae_mod.decode(pp, self.cfg.vae, l))

    # ------------------------------------------------------------- encode
    def encode_prompt(self, prompts: list[str]):
        """Returns (ctx [B, S, joint_dim], mask [B, S], pooled)."""
        if self.cfg.clip is not None:
            return self._encode_prompt_hf(prompts)
        ids, lens = self.tokenizer.batch_encode(prompts,
                                                self.cfg.max_text_len)
        hidden = self._text_encode_jit(self.text_params, jnp.asarray(ids))
        mask = (np.arange(self.cfg.max_text_len)[None, :]
                < lens[:, None]).astype(np.int32)
        mask = jnp.asarray(mask)
        denom = jnp.maximum(mask.sum(axis=1, keepdims=True), 1)
        pooled = (hidden * mask[..., None]).sum(axis=1) / denom
        return hidden, mask, pooled.astype(hidden.dtype)

    def _clip_tower(self, tok, params, jit, prompts):
        enc = tok(prompts, padding="max_length", truncation=True,
                  max_length=self.cfg.clip_text_len)
        ids = jnp.asarray(np.asarray(enc["input_ids"], np.int32))
        _, pooled, penult = jit(params, ids)
        return penult, pooled

    def _encode_prompt_hf(self, prompts: list[str]):
        """CLIP-L ++ bigG penultimate hiddens (feature axis), zero-
        padded to the T5 width, then the T5 hiddens along the sequence;
        pooled = projected pooled vectors concatenated
        (pipeline_sd3.py:277-427)."""
        h1, p1 = self._clip_tower(self.hf_clip_tokenizer,
                                  self.clip_params,
                                  self._clip_encode_jit, list(prompts))
        h2, p2 = self._clip_tower(self.hf_clip2_tokenizer,
                                  self.clip2_params,
                                  self._clip2_encode_jit, list(prompts))
        clip_h = jnp.concatenate([h1, h2], axis=-1)
        enc = self.hf_t5_tokenizer(
            list(prompts), padding="max_length", truncation=True,
            max_length=self.cfg.max_text_len)
        ids = jnp.asarray(np.asarray(enc["input_ids"], np.int32))
        t5_mask = jnp.ones(ids.shape, jnp.int32)
        t5_h = self._text_encode_jit(self.text_params, ids, t5_mask)
        clip_h = jnp.pad(
            clip_h, ((0, 0), (0, 0),
                     (0, t5_h.shape[-1] - clip_h.shape[-1])))
        ctx = jnp.concatenate([clip_h, t5_h], axis=1).astype(self.dtype)
        pooled = jnp.concatenate([p1, p2], axis=-1).astype(self.dtype)
        mask = jnp.ones(ctx.shape[:2], jnp.int32)
        return ctx, mask, pooled

    @classmethod
    def from_pretrained(cls, model_dir: str, dtype=jnp.bfloat16,
                        seed: int = 0, mesh=None, cache_config=None,
                        max_text_len: int = 256) -> "SD3Pipeline":
        """Build from a diffusers-format SD3/SD3.5 checkpoint
        (transformer/ + CLIP-L text_encoder/ + CLIP-bigG text_encoder_2/
        + T5 text_encoder_3/ + tokenizers + AutoencoderKL vae/)."""
        import json
        import os

        from transformers import AutoTokenizer

        from vllm_omni_tpu.model_loader import diffusers_loader as dl
        from vllm_omni_tpu.models.sd3 import loader as sd3_loader

        dl.load_model_index(model_dir)
        dit_params, dit_cfg = sd3_loader.load_sd3_dit(
            os.path.join(model_dir, "transformer"), dtype=dtype)

        def clip_tower(sub):
            d = os.path.join(model_dir, sub)
            with open(os.path.join(d, "config.json")) as f:
                ccfg = clip_mod.CLIPTextConfig.from_hf(json.load(f))
            cp, _ = clip_mod.load_clip_text(d, cfg=ccfg, dtype=dtype)
            return cp, ccfg

        clip_params, clip_cfg = clip_tower("text_encoder")
        clip2_params, clip2_cfg = clip_tower("text_encoder_2")
        te3 = os.path.join(model_dir, "text_encoder_3")
        with open(os.path.join(te3, "config.json")) as f:
            text_cfg = t5_mod.T5Config.from_hf(json.load(f))
        text_params, _ = t5_mod.load_t5(te3, cfg=text_cfg, dtype=dtype)
        vae_tree, vae_cfg = dl.load_image_vae(
            os.path.join(model_dir, "vae"), dtype=dtype, decoder=True)
        sched = dl.scheduler_config(model_dir)
        config = SD3PipelineConfig(
            text=text_cfg, dit=dit_cfg, vae=vae_cfg, clip=clip_cfg,
            clip2=clip2_cfg, max_text_len=max_text_len,
            clip_text_len=clip_cfg.max_positions,
            shift=sched.get("shift", 3.0),
        )
        pipe = cls(config, dtype=dtype, seed=seed, mesh=mesh,
                   cache_config=cache_config, init_weights=False)
        pipe.dit_params = pipe.wiring.place(dit_params)
        pipe.text_params = pipe.wiring.place(text_params)
        pipe.clip_params = pipe.wiring.place(clip_params)
        pipe.clip2_params = pipe.wiring.place(clip2_params)
        pipe.vae_params = pipe.wiring.place(vae_tree["decoder"])
        pipe.hf_clip_tokenizer = AutoTokenizer.from_pretrained(
            os.path.join(model_dir, "tokenizer"))
        pipe.hf_clip2_tokenizer = AutoTokenizer.from_pretrained(
            os.path.join(model_dir, "tokenizer_2"))
        pipe.hf_t5_tokenizer = AutoTokenizer.from_pretrained(
            os.path.join(model_dir, "tokenizer_3"))
        return pipe

    # ------------------------------------------------------------ denoise
    def _denoise_fn(self, grid_h, grid_w, sched_len):
        key = (grid_h, grid_w, sched_len)
        if key in self._denoise_cache:
            return self._denoise_cache[key]
        cfg = self.cfg
        cache_cfg = self.cache_config

        @jax.jit
        def run(dit_params, latents, ctx, ctx_mask, pooled, neg_ctx,
                neg_mask, neg_pooled, sigmas, timesteps, gscale, num_steps):
            schedule = fm.FlowMatchSchedule(sigmas=sigmas,
                                            timesteps=timesteps)
            do_cfg = neg_ctx is not None
            if do_cfg:
                ctx_all = jnp.concatenate([ctx, neg_ctx], 0)
                mask_all = jnp.concatenate([ctx_mask, neg_mask], 0)
                pooled_all = jnp.concatenate([pooled, neg_pooled], 0)
            else:
                ctx_all, mask_all, pooled_all = ctx, ctx_mask, pooled

            def eval_velocity(lat, i):
                t = jnp.broadcast_to(timesteps[i], (lat.shape[0],))
                lat_in = jnp.concatenate([lat, lat], 0) if do_cfg else lat
                # CFG halves ride the cfg axis, requests the dp axis
                lat_in = self.wiring.constrain(lat_in)
                t_in = jnp.concatenate([t, t], 0) if do_cfg else t
                v = sdit.forward(
                    dit_params, cfg.dit, lat_in, ctx_all, pooled_all,
                    t_in, (grid_h, grid_w), txt_mask=mask_all,
                )
                if do_cfg:
                    v_pos, v_neg = jnp.split(v, 2, axis=0)
                    v = v_neg + gscale * (v_pos - v_neg)
                return v

            return step_cache.run_denoise_loop(
                cache_cfg, schedule, eval_velocity, latents, num_steps,
                solver=cfg.scheduler)

        self._denoise_cache[key] = run
        return run

    # ------------------------------------------------------------ forward
    def forward(self, req: OmniDiffusionRequest) -> list[DiffusionOutput]:
        sp = req.sampling_params
        cfg = self.cfg
        mult = self.geometry_multiple
        if sp.height % mult or sp.width % mult:
            raise InvalidRequestError(
                f"height/width must be multiples of {mult}")
        lat_h = sp.height // cfg.vae.spatial_ratio
        lat_w = sp.width // cfg.vae.spatial_ratio
        gh, gw = lat_h // cfg.pack, lat_w // cfg.pack
        if gh > cfg.dit.pos_embed_max_size or \
                gw > cfg.dit.pos_embed_max_size:
            raise InvalidRequestError(
                f"grid {gh}x{gw} exceeds pos_embed_max_size "
                f"{cfg.dit.pos_embed_max_size}")
        prompts = req.prompt
        b = len(prompts)

        ctx, ctx_mask, pooled = self.encode_prompt(prompts)
        do_cfg = sp.guidance_scale > 1.0
        neg_ctx = neg_mask = neg_pooled = None
        if do_cfg:
            neg_ctx, neg_mask, neg_pooled = self.encode_prompt(
                [sp.negative_prompt] * b)
        seed = (sp.seed if sp.seed is not None
                else int(np.random.randint(0, 2 ** 31 - 1)))
        noise = jax.random.normal(
            jax.random.PRNGKey(seed),
            (b, gh * gw, cfg.dit.in_channels * cfg.pack ** 2),
            self.dtype,
        )
        num_steps = sp.num_inference_steps
        sched_len = max(8, 1 << (num_steps - 1).bit_length())
        schedule = fm.make_schedule(num_steps, shift=cfg.shift)
        sigmas = jnp.zeros((sched_len + 1,)).at[: num_steps + 1].set(
            schedule.sigmas)
        timesteps = jnp.zeros((sched_len,)).at[:num_steps].set(
            schedule.timesteps)
        run = self._denoise_fn(gh, gw, sched_len)
        latents, skipped = run(
            self.dit_params, noise, ctx, ctx_mask, pooled, neg_ctx,
            neg_mask, neg_pooled, sigmas, timesteps,
            jnp.float32(sp.guidance_scale), jnp.int32(num_steps))
        self.last_skipped_steps = int(skipped)

        c = cfg.vae.latent_channels
        p = cfg.pack
        lat = latents.reshape(b, gh, gw, p, p, c).transpose(0, 1, 3, 2, 4, 5)
        lat = lat.reshape(b, lat_h, lat_w, c)
        imgs = np.asarray(self._vae_decode_jit(self.vae_params, lat))
        imgs = ((np.clip(imgs, -1, 1) + 1) * 127.5).astype(np.uint8)
        return [
            DiffusionOutput(
                request_id=req.request_ids[i], prompt=prompts[i],
                data=imgs[i], output_type="image",
            )
            for i in range(b)
        ]
