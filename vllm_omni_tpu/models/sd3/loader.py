"""Diffusers-format SD3 transformer loader.

Streams an SD3Transformer2DModel directory into
models/sd3/transformer.py params.  The patch conv kernel
``pos_embed.proj.weight`` [inner, C, p, p] reshapes into the packed-
token matmul layout [(p*p*C), inner] matching the pipeline's (dy, dx, c)
token feature order; the persisted sincos table ``pos_embed.pos_embed``
loads as-is and is center-cropped at runtime.
"""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.models.flux.loader import load_routed
from vllm_omni_tpu.models.sd3.transformer import (
    SD3DiTConfig,
    init_params,
)


def dit_config_from_diffusers(d: dict) -> SD3DiTConfig:
    in_ch = d.get("in_channels", 16)
    return SD3DiTConfig(
        in_channels=in_ch,
        out_channels=d.get("out_channels") or in_ch,
        patch_size=d.get("patch_size", 2),
        num_layers=d.get("num_layers", 24),
        num_heads=d.get("num_attention_heads", 24),
        head_dim=d.get("attention_head_dim", 64),
        joint_dim=d.get("joint_attention_dim", 4096),
        pooled_dim=d.get("pooled_projection_dim", 2048),
        pos_embed_max_size=d.get("pos_embed_max_size", 192),
        qk_norm=d.get("qk_norm") == "rms_norm",
        dual_attention_layers=tuple(
            d.get("dual_attention_layers", ())),
    )


def _routing(cfg: SD3DiTConfig) -> dict:
    r: dict[str, tuple] = {}

    def lin(hf, *path):
        r[f"{hf}.weight"] = ("direct", path + ("w",))
        r[f"{hf}.bias"] = ("direct", path + ("b",))

    lin("pos_embed.proj", "patch_proj")
    r["pos_embed.pos_embed"] = ("raw", ("pos_embed",))
    lin("context_embedder", "ctx_in")
    lin("time_text_embed.timestep_embedder.linear_1", "time_in1")
    lin("time_text_embed.timestep_embedder.linear_2", "time_in2")
    lin("time_text_embed.text_embedder.linear_1", "pooled_in1")
    lin("time_text_embed.text_embedder.linear_2", "pooled_in2")
    lin("norm_out.linear", "norm_out_mod")
    lin("proj_out", "proj_out")
    for i in range(cfg.num_layers):
        b = f"transformer_blocks.{i}"
        t = ("blocks", i)
        last = i == cfg.num_layers - 1
        lin(f"{b}.norm1.linear", *t, "img_mod")
        if last:
            lin(f"{b}.norm1_context.linear", *t, "ctx_ada")
        else:
            lin(f"{b}.norm1_context.linear", *t, "txt_mod")
        for hf, ours in (("to_q", "to_q"), ("to_k", "to_k"),
                         ("to_v", "to_v"), ("add_q_proj", "add_q"),
                         ("add_k_proj", "add_k"),
                         ("add_v_proj", "add_v")):
            lin(f"{b}.attn.{hf}", *t, ours)
        if cfg.qk_norm:
            for nm in ("norm_q", "norm_k", "norm_added_q",
                       "norm_added_k"):
                r[f"{b}.attn.{nm}.weight"] = ("direct", t + (nm, "w"))
        lin(f"{b}.attn.to_out.0", *t, "to_out")
        lin(f"{b}.ff.net.0.proj", *t, "img_mlp1")
        lin(f"{b}.ff.net.2", *t, "img_mlp2")
        if not last:
            lin(f"{b}.attn.to_add_out", *t, "to_add_out")
            lin(f"{b}.ff_context.net.0.proj", *t, "txt_mlp1")
            lin(f"{b}.ff_context.net.2", *t, "txt_mlp2")
        if i in cfg.dual_attention_layers:
            for hf, ours in (("to_q", "to_q2"), ("to_k", "to_k2"),
                             ("to_v", "to_v2")):
                lin(f"{b}.attn2.{hf}", *t, ours)
            if cfg.qk_norm:
                r[f"{b}.attn2.norm_q.weight"] = (
                    "direct", t + ("norm_q2", "w"))
                r[f"{b}.attn2.norm_k.weight"] = (
                    "direct", t + ("norm_k2", "w"))
            lin(f"{b}.attn2.to_out.0", *t, "to_out2")
    return r


def load_sd3_dit(model_dir: str, cfg: SD3DiTConfig = None,
                 dtype=jnp.bfloat16):
    if cfg is None:
        with open(os.path.join(model_dir, "config.json")) as f:
            cfg = dit_config_from_diffusers(json.load(f))
    shapes = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg, jnp.float32))
    p = cfg.patch_size

    def conv_to_packed(arr):
        # [inner, C, p, p] -> [(dy, dx, c), inner]
        return np.ascontiguousarray(
            arr.transpose(2, 3, 1, 0).reshape(p * p * arr.shape[1], -1))

    def pos_table(arr):
        # persisted [1, max*max, inner] -> [max*max, inner]
        return arr.reshape(arr.shape[-2], arr.shape[-1])

    tree = load_routed(
        model_dir, _routing(cfg), shapes, dtype,
        transforms={"pos_embed.proj.weight": conv_to_packed,
                    "pos_embed.pos_embed": pos_table})
    return tree, cfg
