"""Cross-stage wiring for the Qwen3-TTS pipeline: LM codec stream ->
speech-decoder prompt (reference: qwen3_tts stage wiring, SURVEY §2.8)."""

from __future__ import annotations

from vllm_omni_tpu.entrypoints.omni_stage import StageRequest
from vllm_omni_tpu.models.qwen3_tts.tts_lm import codec_ids_from_lm_tokens
from vllm_omni_tpu.models.stage_input_processors.qwen3_omni import (
    voice_info,
)


def lm_to_speech_decoder(config, upstream_outputs) -> list[StageRequest]:
    """Strip specials + the text-vocab offset from the LM's sampled stream;
    the pure codec ids become the one-shot vocoder prompt.  Voice
    conditioning rides additional_information across the hop.  The
    codec id range comes from the stage's engine_args
    (codec_offset/codec_vocab — real checkpoints put codec ids after
    the 151936-token text vocabulary); tiny defaults otherwise."""
    eng = getattr(config, "engine_args", None) or {}
    kw = {}
    if "codec_offset" in eng:
        kw["codec_offset"] = int(eng["codec_offset"])
    if "codec_vocab" in eng:
        kw["codec_vocab"] = int(eng["codec_vocab"])
    reqs = []
    for out in upstream_outputs:
        toks = out.outputs[0].token_ids if out.outputs else []
        codec = codec_ids_from_lm_tokens(toks, **kw)
        if not codec:
            # degenerate sample (no codec tokens): emit one silence code
            # rather than an empty prompt the scheduler would reject
            codec = [0]
        reqs.append(StageRequest(request_id=out.request_id,
                                 prompt_token_ids=codec,
                                 additional_information=voice_info(out)))
    return reqs
