"""Cross-stage tensor wiring for the Qwen2.5-Omni pipeline.

Reference: vllm_omni/model_executor/stage_input_processors/ (qwen2_5
variant).  The handoffs are structurally identical to Qwen3-Omni —
thinker hidden states ride prompt_embeds into the talker, codec tokens
become the one-shot token2wav prompt — so the shared implementations are
re-exported under this family's names.
"""

from vllm_omni_tpu.models.stage_input_processors.qwen3_omni import (
    thinker_to_talker,
    talker_to_code2wav as talker_to_token2wav,
)

__all__ = ["thinker_to_talker", "talker_to_token2wav"]
