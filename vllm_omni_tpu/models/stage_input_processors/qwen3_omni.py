"""Cross-stage tensor wiring for the Qwen3-Omni pipeline.

Reference: vllm_omni/model_executor/stage_input_processors/qwen3_omni.py —
``thinker2talker`` packs thinker hidden states + text tokens into talker
inputs; ``talker2code2wav`` turns codec tokens into the vocoder's input.
Registered in stage YAML via ``custom_process_input_func``.
"""

from __future__ import annotations

import numpy as np

from vllm_omni_tpu.entrypoints.omni_stage import StageRequest


def thinker_to_talker(config, upstream_outputs) -> list[StageRequest]:
    """Thinker hidden states ride the prompt_embeds path; placeholder token
    ids keep the scheduler's length accounting aligned with the embeds."""
    reqs = []
    for out in upstream_outputs:
        hidden = out.multimodal_output.get("hidden_states")
        if hidden is None:
            # thinker engine was not run with collect_hidden — degrade to
            # token-bridging so the pipeline still flows
            toks = out.outputs[0].token_ids if out.outputs else []
            reqs.append(StageRequest(request_id=out.request_id,
                                     prompt_token_ids=list(toks),
                                     additional_information=voice_info(
                                         out)))
            continue
        hidden = np.asarray(hidden)
        info = voice_info(out)
        info["thinker_token_ids"] = (list(out.outputs[0].token_ids)
                                     if out.outputs else [])
        reqs.append(StageRequest(
            request_id=out.request_id,
            prompt_token_ids=[0] * hidden.shape[0],
            prompt_embeds=hidden,
            additional_information=info,
        ))
    return reqs


# per-request conditioning keys a vocoder stage consumes (voice
# vectors / reference audio resolved upstream, e.g. by the serving
# layer's voice registry) — forwarded verbatim across EVERY stage hop
# so the final vocoder sees them regardless of pipeline depth
_VOICE_KEYS = ("voice", "speaker_embedding", "reference_mel")


def voice_info(out) -> dict:
    """The voice-conditioning subset of an upstream output's
    additional_information (empty when absent)."""
    info = getattr(out, "additional_information", None) or {}
    return {k: info[k] for k in _VOICE_KEYS if k in info}


def talker_to_code2wav(config, upstream_outputs) -> list[StageRequest]:
    """Codec tokens emitted by the talker become the vocoder's one-shot
    prompt (reference: talker2code2wav).  Voice-conditioning entries in
    the request's additional_information ride along."""
    return [
        StageRequest(
            request_id=out.request_id,
            prompt_token_ids=list(out.outputs[0].token_ids)
            if out.outputs else [],
            additional_information=voice_info(out),
        )
        for out in upstream_outputs
    ]
