"""CLIP text encoder (SD3 / Flux pooled-conditioning stack).

Checkpoint-schema implementation of the transformers ``CLIPTextModel``
tower the reference's SD3 (clip-L + OpenCLIP-bigG) and Flux (clip-L)
pipelines pool prompt embeddings from (diffusers loads them via
transformers).  Pre-LN causal transformer over learned positions;
``quick_gelu`` (CLIP-L) or ``gelu`` activations; the pooled vector is
the final-LN hidden at the EOS position.

TPU-first: pure functions over a param pytree, one jit per bucketed
sequence length; the causal bias is built inside the trace from static
shapes.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common import nn

logger = init_logger(__name__)


@dataclass(frozen=True)
class CLIPTextConfig:
    vocab_size: int = 49408
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_positions: int = 77
    eps: float = 1e-5
    act: str = "quick_gelu"  # "quick_gelu" (CLIP-L) | "gelu" (bigG)
    eos_token_id: int = 49407

    @staticmethod
    def tiny(vocab_size: int = 64) -> "CLIPTextConfig":
        return CLIPTextConfig(vocab_size=vocab_size, hidden_size=32,
                              num_layers=2, num_heads=4,
                              intermediate_size=64, max_positions=16,
                              eos_token_id=vocab_size - 1)

    @staticmethod
    def from_hf(d: dict) -> "CLIPTextConfig":
        return CLIPTextConfig(
            vocab_size=d.get("vocab_size", 49408),
            hidden_size=d.get("hidden_size", 768),
            num_layers=d.get("num_hidden_layers", 12),
            num_heads=d.get("num_attention_heads", 12),
            intermediate_size=d.get("intermediate_size", 3072),
            max_positions=d.get("max_position_embeddings", 77),
            eps=d.get("layer_norm_eps", 1e-5),
            act=d.get("hidden_act", "quick_gelu"),
            eos_token_id=d.get("eos_token_id", 49407),
        )


def init_params(key, cfg: CLIPTextConfig, dtype=jnp.float32):
    ki = iter(jax.random.split(key, 2 + 6 * cfg.num_layers))
    h = cfg.hidden_size
    p = {
        "token_embed": nn.embedding_init(next(ki), cfg.vocab_size, h,
                                         dtype),
        "pos_embed": nn.embedding_init(next(ki), cfg.max_positions, h,
                                       dtype),
        "final_norm": nn.layernorm_init(h, dtype=dtype),
        "layers": [],
    }
    for _ in range(cfg.num_layers):
        p["layers"].append({
            "norm1": nn.layernorm_init(h, dtype=dtype),
            "q_proj": nn.linear_init(next(ki), h, h, dtype=dtype),
            "k_proj": nn.linear_init(next(ki), h, h, dtype=dtype),
            "v_proj": nn.linear_init(next(ki), h, h, dtype=dtype),
            "out_proj": nn.linear_init(next(ki), h, h, dtype=dtype),
            "norm2": nn.layernorm_init(h, dtype=dtype),
            "fc1": nn.linear_init(next(ki), h, cfg.intermediate_size,
                                  dtype=dtype),
            "fc2": nn.linear_init(next(ki), cfg.intermediate_size, h,
                                  dtype=dtype),
        })
    return p


def _act(cfg: CLIPTextConfig, x):
    if cfg.act == "quick_gelu":
        return x * jax.nn.sigmoid(1.702 * x)
    return jax.nn.gelu(x, approximate=False)


def forward(params, cfg: CLIPTextConfig, token_ids: jax.Array,
            return_penultimate: bool = False):
    """token_ids [B, S] -> (last_hidden [B, S, h], pooled [B, h]).

    ``pooled`` is the final-LN hidden at each row's EOS position (the
    first occurrence of eos_token_id; transformers CLIPTextModel pooled
    output), projected by ``text_projection`` when the params carry one
    (CLIPTextModelWithProjection).  S must be <= max_positions; pad WITH
    eos/pad ids after the real eos like the CLIP tokenizer does.

    ``return_penultimate``: also return the raw hidden BEFORE the last
    layer (HF ``hidden_states[-2]`` — what SD3/SDXL condition on).
    """
    b, s = token_ids.shape
    x = nn.embedding(params["token_embed"], token_ids)
    x = x + nn.embedding(params["pos_embed"], jnp.arange(s))[None]
    causal = jnp.where(
        jnp.arange(s)[None, :] <= jnp.arange(s)[:, None], 0.0, -1e30)
    scale = 1.0 / math.sqrt(cfg.hidden_size // cfg.num_heads)
    penult = None
    for li, lp in enumerate(params["layers"]):
        if li == len(params["layers"]) - 1:
            penult = x
        h = nn.layernorm(lp["norm1"], x, eps=cfg.eps)
        q = nn.linear(lp["q_proj"], h).reshape(b, s, cfg.num_heads, -1)
        k = nn.linear(lp["k_proj"], h).reshape(b, s, cfg.num_heads, -1)
        v = nn.linear(lp["v_proj"], h).reshape(b, s, cfg.num_heads, -1)
        sc = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32),
                        precision=jax.lax.Precision.HIGHEST) * scale
        a = jax.nn.softmax(sc + causal[None, None], axis=-1).astype(
            x.dtype)
        o = jnp.einsum("bhqk,bkhd->bqhd", a, v,
                       precision=jax.lax.Precision.HIGHEST)
        x = x + nn.linear(lp["out_proj"], o.reshape(b, s, -1))
        h = nn.layernorm(lp["norm2"], x, eps=cfg.eps)
        x = x + nn.linear(lp["fc2"], _act(cfg, nn.linear(lp["fc1"], h)))
    out = nn.layernorm(params["final_norm"], x, eps=cfg.eps)
    if cfg.eos_token_id == 2:
        # transformers-legacy configs (the published CLIP-L/bigG
        # text_encoder config.json ships eos_token_id=2 while the real
        # EOS is the highest vocab id): pool at the max token id, the
        # CLIPTextModel legacy branch
        eos_pos = jnp.argmax(token_ids, axis=1)
    else:
        # first EOS per row (argmax of the == mask finds the first True)
        eos_pos = jnp.argmax(
            (token_ids == cfg.eos_token_id).astype(jnp.int32), axis=1)
    pooled = out[jnp.arange(b), eos_pos]
    if "text_proj" in params:
        pooled = pooled @ params["text_proj"]["w"]
    if return_penultimate:
        return out, pooled, penult
    return out, pooled


# ------------------------------------------------------- checkpoint load
def hf_flat_map(cfg: CLIPTextConfig,
                prefix: str = "text_model.") -> dict:
    m: dict[str, tuple] = {}
    m[f"{prefix}embeddings.token_embedding.weight"] = \
        ("token_embed", "w")
    m[f"{prefix}embeddings.position_embedding.weight"] = \
        ("pos_embed", "w")
    m[f"{prefix}final_layer_norm.weight"] = ("final_norm", "w")
    m[f"{prefix}final_layer_norm.bias"] = ("final_norm", "b")
    for i in range(cfg.num_layers):
        lp = f"{prefix}encoder.layers.{i}"
        tgt = ("layers", i)
        for hf, ours in (("layer_norm1", "norm1"),
                         ("layer_norm2", "norm2"),
                         ("self_attn.q_proj", "q_proj"),
                         ("self_attn.k_proj", "k_proj"),
                         ("self_attn.v_proj", "v_proj"),
                         ("self_attn.out_proj", "out_proj"),
                         ("mlp.fc1", "fc1"), ("mlp.fc2", "fc2")):
            m[f"{lp}.{hf}.weight"] = tgt + (ours, "w")
            m[f"{lp}.{hf}.bias"] = tgt + (ours, "b")
    return m


def hf_transform(name: str, arr):
    if arr.ndim == 2 and name.endswith("weight") \
            and "embedding" not in name:
        return arr.T
    return arr


def load_clip_text(model_dir: str, cfg: CLIPTextConfig = None,
                   dtype=jnp.float32, prefix: str = "text_model.",
                   hf_cfg: dict = None):
    """Stream a CLIP text tower out of a checkpoint directory."""
    from vllm_omni_tpu.model_loader.safetensors_loader import (
        load_checkpoint_tree,
    )

    if cfg is None:
        cfg = CLIPTextConfig.from_hf(hf_cfg or {})
    shapes = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg, jnp.float32))
    tree = jax.tree.map(lambda t: np.zeros(t.shape, np.float32), shapes)
    flat = hf_flat_map(cfg, prefix)
    # CLIPTextModelWithProjection (SD3/SDXL pooled towers) adds a
    # bias-free projection on the pooled output
    proj_shape = _ckpt_tensor_shape(model_dir, "text_projection.weight")
    if proj_shape is not None:
        # HF [proj, hidden]; hf_transform transposes to [hidden, proj]
        tree["text_proj"] = {
            "w": np.zeros((proj_shape[1], proj_shape[0]), np.float32)}
        flat["text_projection.weight"] = ("text_proj", "w")
    n, _ = load_checkpoint_tree(
        model_dir, flat.get, tree, dtype=np.float32,
        transform=hf_transform, name_filter=lambda nm: nm in flat,
    )
    n_leaves = len(jax.tree.leaves(tree))
    if n < n_leaves:
        raise ValueError(
            f"{model_dir} covered {n}/{n_leaves} CLIP text weights")
    return jax.tree.map(lambda a: jnp.asarray(a, dtype), tree), cfg


def _ckpt_tensor_shape(model_dir: str, tensor_name: str):
    import os

    from safetensors import safe_open

    for fn in sorted(os.listdir(model_dir)):
        if fn.endswith(".safetensors"):
            with safe_open(os.path.join(model_dir, fn), "np") as f:
                if tensor_name in f.keys():
                    return tuple(f.get_slice(tensor_name).get_shape())
    return None
