"""SigLIP vision tower, packed-NaViT style (understanding input).

Checkpoint-schema implementation of the transformers
``SiglipVisionModel`` encoder as Bagel consumes it (reference:
vllm_omni/diffusion/models/bagel/pipeline_bagel.py:121-149
``SiglipNaViTWrapper``): the conv patch embedding is applied as a
LINEAR over flattened patches, learned position embeddings are indexed
by flattened (possibly extrapolated) position ids, and the pre-LN
encoder runs over a PACKED multi-image sequence with a block-diagonal
per-image mask.  The pooling head is not used (Bagel takes the packed
last_hidden_state).

Shared across understanding towers: Bagel's und input; the
GLM-Image / Ovis understanding encoders are the same SigLIP family.

TPU-first: one packed [N, D] sequence per batch (static shapes from
bucketed packing), the per-image mask a static additive bias, exact
GELU-tanh MLPs on the MXU.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from vllm_omni_tpu.logger import init_logger
from vllm_omni_tpu.models.common import nn

logger = init_logger(__name__)


@dataclass(frozen=True)
class SigLIPConfig:
    hidden_size: int = 1152
    num_layers: int = 27
    num_heads: int = 16
    intermediate_size: int = 4304
    patch_size: int = 14
    num_positions: int = 1024     # (image_size // patch)^2 table rows
    num_channels: int = 3
    eps: float = 1e-6

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def patch_dim(self) -> int:
        return self.num_channels * self.patch_size * self.patch_size

    @staticmethod
    def tiny() -> "SigLIPConfig":
        return SigLIPConfig(hidden_size=32, num_layers=2, num_heads=4,
                            intermediate_size=64, patch_size=14,
                            num_positions=4)

    @staticmethod
    def from_hf(d: dict) -> "SigLIPConfig":
        img = d.get("image_size", 448)
        patch = d.get("patch_size", 14)
        return SigLIPConfig(
            hidden_size=d.get("hidden_size", 1152),
            num_layers=d.get("num_hidden_layers", 27),
            num_heads=d.get("num_attention_heads", 16),
            intermediate_size=d.get("intermediate_size", 4304),
            patch_size=patch,
            num_positions=(img // patch) ** 2,
            num_channels=d.get("num_channels", 3),
            eps=d.get("layer_norm_eps", 1e-6),
        )


def init_params(key, cfg: SigLIPConfig, dtype=jnp.float32):
    ki = iter(jax.random.split(key, 8 + 8 * cfg.num_layers))
    h = cfg.hidden_size
    p = {
        "patch_embed": nn.linear_init(next(ki), cfg.patch_dim, h,
                                      dtype=dtype),
        "pos_embed": nn.embedding_init(next(ki), cfg.num_positions, h,
                                       dtype),
        "post_norm": nn.layernorm_init(h, dtype=dtype),
        "layers": [],
    }
    for _ in range(cfg.num_layers):
        p["layers"].append({
            "norm1": nn.layernorm_init(h, dtype=dtype),
            "q_proj": nn.linear_init(next(ki), h, h, dtype=dtype),
            "k_proj": nn.linear_init(next(ki), h, h, dtype=dtype),
            "v_proj": nn.linear_init(next(ki), h, h, dtype=dtype),
            "out_proj": nn.linear_init(next(ki), h, h, dtype=dtype),
            "norm2": nn.layernorm_init(h, dtype=dtype),
            "fc1": nn.linear_init(next(ki), h, cfg.intermediate_size,
                                  dtype=dtype),
            "fc2": nn.linear_init(next(ki), cfg.intermediate_size, h,
                                  dtype=dtype),
        })
    return p


def patchify(image: np.ndarray, patch: int) -> np.ndarray:
    """[C, H, W] -> [n_patches, C*patch*patch] (reference ``patchify``:
    row-major patch grid, channel-first within a patch)."""
    c, h, w = image.shape
    ph, pw = h // patch, w // patch
    x = image.reshape(c, ph, patch, pw, patch)
    x = x.transpose(1, 3, 0, 2, 4).reshape(ph * pw, c * patch * patch)
    return x


def flattened_position_ids_extrapolate(img_h: int, img_w: int,
                                       patch: int,
                                       max_per_side: int) -> np.ndarray:
    """Row/col ids into the max_per_side^2 table (reference
    get_flattened_position_ids_extrapolate)."""
    ph, pw = img_h // patch, img_w // patch
    rows = np.arange(ph)[:, None] * max_per_side + np.arange(pw)[None, :]
    return rows.reshape(-1)


def forward_packed(params, cfg: SigLIPConfig, tokens, position_ids,
                   seqlens):
    """Packed NaViT forward.

    tokens [N, patch_dim] flattened patches of all images; position_ids
    [N] into the pos table; seqlens: python list/ints of per-image
    token counts (static — drives the block-diagonal mask).  Returns
    [N, hidden] post-layernormed features.
    """
    x = nn.linear(params["patch_embed"], tokens)
    x = x + nn.embedding(params["pos_embed"], position_ids)
    n = x.shape[0]
    img_of = np.repeat(np.arange(len(seqlens)), seqlens)
    assert img_of.shape[0] == n, (img_of.shape, n)
    same = img_of[:, None] == img_of[None, :]
    bias = jnp.where(jnp.asarray(same), 0.0, -1e30).astype(jnp.float32)

    scale = 1.0 / math.sqrt(cfg.head_dim)
    for lp in params["layers"]:
        h = nn.layernorm(lp["norm1"], x, eps=cfg.eps)
        q = nn.linear(lp["q_proj"], h).reshape(n, cfg.num_heads, -1)
        k = nn.linear(lp["k_proj"], h).reshape(n, cfg.num_heads, -1)
        v = nn.linear(lp["v_proj"], h).reshape(n, cfg.num_heads, -1)
        s = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                       k.astype(jnp.float32),
                       precision=jax.lax.Precision.HIGHEST) * scale
        a = jax.nn.softmax(s + bias[None], axis=-1).astype(x.dtype)
        o = jnp.einsum("hqk,khd->qhd", a, v,
                       precision=jax.lax.Precision.HIGHEST)
        x = x + nn.linear(lp["out_proj"], o.reshape(n, -1))
        h = nn.layernorm(lp["norm2"], x, eps=cfg.eps)
        h = nn.linear(lp["fc2"],
                      jax.nn.gelu(nn.linear(lp["fc1"], h),
                                  approximate=True))
        x = x + h
    return nn.layernorm(params["post_norm"], x, eps=cfg.eps)


# ------------------------------------------------------- checkpoint load
def hf_flat_map(cfg: SigLIPConfig,
                prefix: str = "vit_model.vision_model.") -> dict:
    m: dict[str, tuple] = {}
    m[f"{prefix}embeddings.patch_embedding.weight"] = ("patch_embed", "w")
    m[f"{prefix}embeddings.patch_embedding.bias"] = ("patch_embed", "b")
    m[f"{prefix}embeddings.position_embedding.weight"] = \
        ("pos_embed", "w")
    m[f"{prefix}post_layernorm.weight"] = ("post_norm", "w")
    m[f"{prefix}post_layernorm.bias"] = ("post_norm", "b")
    for i in range(cfg.num_layers):
        lp = f"{prefix}encoder.layers.{i}"
        tgt = ("layers", i)
        for hf, ours in (("layer_norm1", "norm1"),
                         ("layer_norm2", "norm2"),
                         ("self_attn.q_proj", "q_proj"),
                         ("self_attn.k_proj", "k_proj"),
                         ("self_attn.v_proj", "v_proj"),
                         ("self_attn.out_proj", "out_proj"),
                         ("mlp.fc1", "fc1"), ("mlp.fc2", "fc2")):
            m[f"{lp}.{hf}.weight"] = tgt + (ours, "w")
            m[f"{lp}.{hf}.bias"] = tgt + (ours, "b")
    return m


def hf_transform(name: str, arr):
    """Conv2d patch embedding [out, C, p, p] -> linear [C*p*p, out]
    (the NaViT wrapper flattens it the same way); linears [out, in] ->
    [in, out]; the position table stays [n, hidden]."""
    if arr.ndim == 4:
        return arr.reshape(arr.shape[0], -1).T
    if arr.ndim == 2 and name.endswith("weight") \
            and "position_embedding" not in name:
        return arr.T
    return arr


def load_siglip(model_dir: str, cfg: SigLIPConfig = None,
                dtype=jnp.float32,
                prefix: str = "vit_model.vision_model.",
                hf_cfg: dict = None):
    """Stream a SigLIP vision tower out of a (composite) checkpoint."""
    from vllm_omni_tpu.model_loader.safetensors_loader import (
        load_checkpoint_tree,
    )

    if cfg is None:
        cfg = SigLIPConfig.from_hf(hf_cfg or {})
    shapes = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg, jnp.float32))
    tree = jax.tree.map(lambda t: np.zeros(t.shape, np.float32), shapes)
    flat = hf_flat_map(cfg, prefix)
    n, _ = load_checkpoint_tree(
        model_dir, flat.get, tree, dtype=np.float32,
        transform=hf_transform, name_filter=lambda nm: nm in flat,
    )
    n_leaves = len(jax.tree.leaves(tree))
    if n != n_leaves:
        raise ValueError(
            f"{model_dir} covered {n}/{n_leaves} SigLIP weights")
    tree = jax.tree.map(lambda a: jnp.asarray(a, dtype), tree)
    return tree, cfg


def sincos_2d_pos_embed(dim: int, side: int) -> np.ndarray:
    """Frozen 2-D sin-cos table [side*side, dim] (reference
    PositionEmbedding / get_2d_sincos_pos_embed)."""
    def one_dim(d, pos):
        omega = 1.0 / 10000 ** (np.arange(d // 2, dtype=np.float64)
                                / (d / 2.0))
        out = np.einsum("m,d->md", pos.reshape(-1), omega)
        return np.concatenate([np.sin(out), np.cos(out)], axis=1)

    grid_h = np.arange(side, dtype=np.float32)
    grid_w = np.arange(side, dtype=np.float32)
    grid = np.meshgrid(grid_w, grid_h)  # w first (reference)
    grid = np.stack(grid, axis=0).reshape(2, side, side)
    emb_h = one_dim(dim // 2, grid[0])
    emb_w = one_dim(dim // 2, grid[1])
    return np.concatenate([emb_h, emb_w],
                          axis=1).astype(np.float32)
